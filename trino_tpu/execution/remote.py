"""Coordinator-side remote execution: worker processes over HTTP.

The process/network boundary of VERDICT round-3 item #3: the coordinator
spawns N worker processes (execution/worker.py), mirrors each task with an
:class:`HttpRemoteTask` (reference: server/remotetask/HttpRemoteTask.java:132
— create POST, status polling, cancel), and pages move worker->worker and
worker->coordinator through :class:`HttpExchangeClient` speaking the
pull-token results protocol (operator/HttpPageBufferClient.java:355,
operator/DirectExchangeClient.java:56).

``ProcessDistributedQueryRunner`` keeps the in-process
``DistributedQueryRunner`` planning/DDL surface and swaps the execution
backend: every fragment task runs in a real worker process; killing a
worker kills its tasks for real (the FTE recovery story becomes testable).
"""

from __future__ import annotations

import json
import os
import struct
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

from ..runner import QueryResult, Session
from ..spi.batch import ColumnBatch
from ..spi.errors import (
    GENERIC_INTERNAL_ERROR,
    GENERIC_USER_ERROR,
    NO_NODES_AVAILABLE,
    PAGE_TRANSPORT_TIMEOUT,
    REMOTE_HOST_GONE,
    Backoff,
    TrinoError,
    classify,
    lookup_code,
)
from .distributed_runner import DistributedQueryRunner
from .failure_detector import GONE, NodeGoneError, WorkerFailureDetector
from .failure_injector import GET_RESULTS_FAILURE
from .fragmenter import SubPlan
from .serde import deserialize_batch
from .worker import encode_descriptor

__all__ = ["HttpExchangeClient", "HttpRemoteTask",
           "ProcessDistributedQueryRunner", "WorkerProcess"]


def _http(method: str, url: str, data: Optional[bytes] = None,
          timeout: float = 30.0, headers: Optional[dict] = None):
    req = urllib.request.Request(url, data=data, method=method)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    # per-spawn internal shared secret (reference: server/
    # InternalCommunicationConfig.java:33 sharedSecret) — every node in the
    # cluster process tree carries it via env; the worker rejects mutating
    # or descriptor-decoding requests without it
    secret = os.environ.get("TRINO_TPU_INTERNAL_SECRET")
    if secret:
        req.add_header("X-Trino-Internal-Bearer", secret)
    return urllib.request.urlopen(req, timeout=timeout)


class HttpExchangeClient:
    """Pulls one partition from many upstream task result URIs; same
    poll/is_finished surface as the in-process ExchangeClient so operators
    are transport-agnostic.

    Each source carries a deterministic :class:`Backoff`
    (HttpPageBufferClient.java:355's role): transient fetch failures skip
    the source until its delay gate reopens, and once failures persist past
    ``max_failure_duration_s`` the source surfaces as a classified EXTERNAL
    :class:`TrinoError` instead of spinning silently until the query
    deadline.  ``backoff`` is a config dict
    (min_delay_s / max_delay_s / max_failure_duration_s) so it travels in
    task descriptors."""

    def __init__(self, task_uris: list[str], partition: int,
                 backoff: Optional[dict] = None,
                 traceparent: Optional[str] = None):
        # trace context rides every results fetch (the reference propagates
        # OTel context on all task calls); servers are free to ignore it
        self._traceparent = traceparent
        cfg = backoff or {}
        # [uri, token, done, Backoff]
        self._sources = [[u, 0, False, Backoff(
            min_delay_s=cfg.get("min_delay_s", 0.05),
            max_delay_s=cfg.get("max_delay_s", 2.0),
            max_failure_duration_s=cfg.get("max_failure_duration_s", 120.0),
        )] for u in task_uris]
        self.partition = partition
        self._ready: list[ColumnBatch] = []
        # per-client counters, folded into ResilienceStats by the runner
        self.stats = {"fetch_failures": 0, "backoff_skips": 0,
                      "backoff_trips": 0,
                      "failures_by_source": {u: 0 for u in task_uris}}

    @staticmethod
    def _host_of(uri: str) -> str:
        # ".../v1/task/<id>" -> worker base URL, the blacklist key
        return uri.split("/v1/", 1)[0]

    def _fetch(self, s, timeout: float) -> int:
        uri, token, _done, backoff = s
        # the server bounds its long-poll to maxwait (worker.py honors it),
        # so a short poll really IS short; the socket timeout only needs a
        # small grace on top for page serialization + transfer
        maxwait = min(max(timeout, 0.0), 5.0)
        url = f"{uri}/results/{self.partition}/{token}?maxwait={maxwait:g}"
        t0 = time.perf_counter()
        hdrs = ({"traceparent": self._traceparent}
                if self._traceparent else None)
        try:
            with _http("GET", url, timeout=maxwait + 5.0,
                       headers=hdrs) as resp:
                body = resp.read()
                next_token = int(resp.headers.get("X-Next-Token", token))
                done = bool(int(resp.headers.get("X-Done", 0)))
        except urllib.error.HTTPError as e:
            if e.code == 404:  # task not created yet: transient
                return 0
            # a FAILED task's 500 body carries its own classification
            # (worker.py status JSON) — keep it, so a worker-side USER
            # error stays USER (fail-fast) instead of degrading to a
            # retryable transport error
            detail = e.read()[:500]
            code_name = error_type = None
            try:
                info = json.loads(detail)
                code_name = info.get("error_code")
                error_type = info.get("error_type")
                detail = info.get("error") or detail
            # tpulint: disable=error-taxonomy -- best-effort payload parse; re-raised classified below
            except Exception:
                pass
            raise TrinoError(
                lookup_code(code_name or "REMOTE_TASK_ERROR", error_type),
                f"exchange fetch failed ({e.code}): {detail!r}",
                remote_host=self._host_of(uri)) from e
        except (urllib.error.URLError, ConnectionError, TimeoutError) as e:
            # worker unreachable: back off; once failures persist past the
            # failure-duration budget this producer is DECLARED failed
            self.stats["fetch_failures"] += 1
            self.stats["failures_by_source"][uri] += 1
            if backoff.failure():
                self.stats["backoff_trips"] += 1
                raise TrinoError(
                    PAGE_TRANSPORT_TIMEOUT,
                    f"producer {uri} unreachable for "
                    f"{backoff.failure_duration_s:.1f}s "
                    f"({backoff.failure_count} attempts): "
                    f"{type(e).__name__}: {e}",
                    remote_host=self._host_of(uri)) from e
            return 0
        backoff.success()
        count = 0
        pos = 0
        while pos + 4 <= len(body):
            (n,) = struct.unpack("<I", body[pos:pos + 4])
            pos += 4
            self._ready.append(deserialize_batch(body[pos:pos + n]))
            pos += n
            count += 1
        s[1] = next_token
        s[2] = done
        from ..telemetry.metrics import observe_exchange

        observe_exchange(len(body), count, time.perf_counter() - t0)
        from ..telemetry import profiler

        if count and profiler.enabled():
            # one event per non-empty fetch: the wall time covers the
            # long-poll wait plus page transfer for this source
            wall = time.perf_counter() - t0
            profiler.event(profiler.EXCHANGE, "http-exchange.fetch",
                           profiler.now() - wall, pages=count,
                           bytes=len(body))
        return count

    def poll(self, timeout: float = 0.05) -> Optional[ColumnBatch]:
        if self._ready:
            return self._ready.pop(0)
        for s in self._sources:
            if s[2]:
                continue
            if not s[3].ready():  # delay gate closed: skip this round
                self.stats["backoff_skips"] += 1
                continue
            if self._fetch(s, timeout):
                return self._ready.pop(0)
        return None

    def is_finished(self) -> bool:
        return not self._ready and all(s[2] for s in self._sources)


class HttpRemoteTask:
    """Coordinator-side mirror of one worker task."""

    def __init__(self, worker_url: str, task_id: str):
        self.worker_url = worker_url
        self.task_id = task_id
        self.uri = f"{worker_url}/v1/task/{task_id}"

    def create(self, descriptor: dict,
               traceparent: Optional[str] = None) -> None:
        headers = {"traceparent": traceparent} if traceparent else None
        with _http("POST", self.uri, encode_descriptor(descriptor),
                   timeout=60.0, headers=headers) as resp:
            assert resp.status == 200

    def status(self) -> dict:
        try:
            with _http("GET", f"{self.uri}/status", timeout=10.0) as resp:
                return json.loads(resp.read())
        except (urllib.error.URLError, ConnectionError) as e:
            return {"state": "GONE", "error": str(e),
                    "error_type": "EXTERNAL",
                    "error_code": "REMOTE_HOST_GONE"}

    def cancel(self) -> None:
        try:
            _http("DELETE", self.uri, timeout=5.0).read()
        # tpulint: disable=error-taxonomy -- best-effort cancel of a task that may already be gone
        except Exception:
            pass


_SECRET_LOCK = threading.Lock()


class WorkerProcess:
    """One spawned worker (python -m trino_tpu.execution.worker).

    Boot is bounded: a worker that dies (or wedges) before printing
    ``LISTENING`` raises within ``boot_timeout_s`` with its captured stderr
    in the message, instead of blocking the coordinator forever on
    ``stdout.readline()``."""

    def __init__(self, env_overrides: Optional[dict] = None,
                 boot_timeout_s: float = 60.0):
        import tempfile

        # one shared secret per cluster process tree: minted on first spawn,
        # inherited by every worker and by worker->worker exchange fetches
        with _SECRET_LOCK:
            if "TRINO_TPU_INTERNAL_SECRET" not in os.environ:
                import secrets

                os.environ["TRINO_TPU_INTERNAL_SECRET"] = secrets.token_hex(16)
        env = dict(os.environ)
        env.update(env_overrides or {})
        self._stderr = tempfile.TemporaryFile(mode="w+")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "trino_tpu.execution.worker", "--port", "0"],
            stdout=subprocess.PIPE, stderr=self._stderr,
            text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        box: list[str] = []
        reader = threading.Thread(
            target=lambda: box.append(self.proc.stdout.readline() or ""),
            daemon=True)
        reader.start()
        reader.join(timeout=boot_timeout_s)
        line = box[0] if box else None
        if line is None or not line.startswith("LISTENING"):
            try:
                self.proc.kill()
                self.proc.wait(timeout=10)
            # tpulint: disable=error-taxonomy -- cleanup before the classified boot-failure raise below
            except Exception:
                pass
            reader.join(timeout=5)
            why = ("timed out after "
                   f"{boot_timeout_s}s" if line is None else f"got {line!r}")
            raise TrinoError(
                REMOTE_HOST_GONE,
                f"worker failed to boot ({why}); stderr: "
                f"{self.stderr_tail()!r}")
        self.port = int(line.split()[1])
        self.url = f"http://127.0.0.1:{self.port}"

    def stderr_tail(self, limit: int = 2000) -> str:
        try:
            self._stderr.flush()
            self._stderr.seek(0, os.SEEK_END)
            size = self._stderr.tell()
            self._stderr.seek(max(0, size - limit))
            return self._stderr.read()
        except Exception:
            return "<unavailable>"

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        self.proc.kill()
        self.proc.wait(timeout=10)

    def shutdown(self) -> None:
        try:
            _http("PUT", f"{self.url}/v1/shutdown", timeout=5.0).read()
        # tpulint: disable=error-taxonomy -- best-effort graceful stop; kill() below is the backstop
        except Exception:
            pass
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.kill()


class ProcessDistributedQueryRunner(DistributedQueryRunner):
    """DistributedQueryRunner whose tasks run in real worker processes.

    ``catalog_spec`` = {"factory": "module:callable", "kwargs": {...}}
    reconstructs the catalog inside each worker (split generation is
    worker-side; only plan fragments and pages cross the wire)."""

    def __init__(self, catalog_spec: dict, worker_count: int = 2,
                 session: Optional[Session] = None,
                 env_overrides: Optional[dict] = None):
        from .worker import build_catalog

        super().__init__(build_catalog(catalog_spec),
                         worker_count=worker_count, session=session)
        self.catalog_spec = catalog_spec
        self._env_overrides = env_overrides
        self.workers = [WorkerProcess(env_overrides)
                        for _ in range(worker_count)]
        self._query_seq = 0
        # replace the base in-process pinger with the real heartbeat sweep
        # over worker /v1/status (execution/failure_detector.py); shares the
        # resilience event log so transitions land in the same timeline as
        # retries and replacements
        sess = self.session
        self.failure_detector = WorkerFailureDetector(
            heartbeat_interval_s=sess.heartbeat_interval_s,
            failure_threshold=sess.heartbeat_failure_threshold,
            events=self.resilience_events)
        for w in self.workers:
            self._monitor_worker(w)
        self._replacements_used = 0

    def _monitor_worker(self, w: WorkerProcess) -> None:
        def probe() -> dict:
            if not w.alive():
                raise NodeGoneError(
                    f"worker process exited rc={w.proc.poll()}")
            with _http("GET", f"{w.url}/v1/status", timeout=2.0) as resp:
                return json.loads(resp.read())

        self.failure_detector.monitor(w.url, probe)

    def _placement_workers(self, blacklist: frozenset = frozenset()
                           ) -> list[WorkerProcess]:
        """Task placement targets: live worker processes whose heartbeat
        state is ACTIVE (draining and unresponsive nodes get no new tasks),
        minus the query's blacklist, minus workers the cross-query
        ClusterBlacklist currently scores past its threshold.  Falls back to
        progressively ignoring the cluster then the query blacklist rather
        than returning nothing (a 1-worker cluster must still place after a
        blacklisting retry)."""
        self.failure_detector.maybe_sweep()
        states = self.failure_detector.states()
        live = [w for w in self.workers
                if w.alive() and states.get(w.url, "ACTIVE") == "ACTIVE"]
        placeable = [w for w in live if w.url not in blacklist]
        cluster_bl = self.cluster_blacklist.blacklisted()
        preferred = [w for w in placeable if w.url not in cluster_bl]
        return preferred or placeable or live

    @property
    def active_worker_count(self) -> int:
        """Heartbeat-gated worker count (overrides the base property, which
        consults the in-process control-plane pinger)."""
        return len(self._placement_workers()) or self.worker_count

    def _replace_gone_workers(self) -> None:
        """Self-heal cluster capacity: respawn a WorkerProcess for every
        GONE node, bounded by ``Session.max_worker_replacements`` over the
        runner's lifetime."""
        self.failure_detector.sweep_once()
        for i, w in enumerate(self.workers):
            if self.failure_detector.state_of(w.url) != GONE:
                continue
            if self._replacements_used >= self.session.max_worker_replacements:
                self.resilience_events.append(
                    ("replacement_cap", w.url,
                     self.session.max_worker_replacements))
                continue
            replacement = WorkerProcess(self._env_overrides)
            self._replacements_used += 1
            self.resilience.worker_replacements += 1
            self.resilience_events.append(
                ("worker_replaced", w.url, replacement.url))
            self.failure_detector.unmonitor(w.url)
            self._monitor_worker(replacement)
            self.workers[i] = replacement
            try:
                if w.alive():
                    w.kill()
            # tpulint: disable=error-taxonomy -- replaced worker teardown is best-effort
            except Exception:
                pass

    def _prepare_retry(self) -> None:
        """Between query-retry attempts: sweep heartbeats and respawn GONE
        workers so the re-run sees healed capacity."""
        self._replace_gone_workers()

    # --------------------------------------------------------------- drain
    def drain_worker(self, worker, timeout_s: Optional[float] = None,
                     replace: bool = True) -> dict:
        """Coordinator-driven graceful drain of one worker process.

        Protocol: PUT /v1/shutdown?timeout_s=N flips the worker to
        SHUTTING_DOWN (it refuses new tasks with 503; the next heartbeat
        sweep + placement stop scheduling to it — a 503 on task create
        surfaces as a retryable classified error, so retry_policy=QUERY
        migrates not-yet-started work automatically).  The worker exits on
        its own once every running task is terminal AND its output buffers
        are fully drained; past the budget it abandons the stragglers (exit
        code 9) and, if even the process lingers, the coordinator escalates
        with a hard kill.  The failure detector is swept synchronously
        before any replacement boots so in-flight queries observe
        REMOTE_HOST_GONE (and retry) instead of spinning on exchange
        backoff.  Operator-initiated: the replacement does NOT count
        against ``max_worker_replacements``."""
        import subprocess as _subprocess

        from ..telemetry import metrics as tm
        from .speculation import drain_timeout_s as _drain_budget

        if isinstance(worker, str):
            matches = [w for w in self.workers if w.url == worker]
            if not matches:
                raise TrinoError(GENERIC_USER_ERROR,
                                 f"no such worker: {worker}")
            w = matches[0]
        else:
            w = worker
        budget = (float(timeout_s) if timeout_s is not None
                  else _drain_budget(self.session, 30.0))
        tm.DRAINS.inc()
        self.resilience_events.append(("drain", w.url, "started"))
        try:
            _http("PUT", f"{w.url}/v1/shutdown?timeout_s={budget:g}",
                  timeout=5.0).read()
        # tpulint: disable=error-taxonomy -- already dead: the sweeps below classify it
        except Exception:
            pass
        # observe SHUTTING_DOWN promptly so placement excludes the worker
        # from this moment on, not from the next opportunistic sweep
        self.failure_detector.sweep_once()
        escalated = False
        try:
            w.proc.wait(timeout=budget + 5.0)
        except _subprocess.TimeoutExpired:
            escalated = True
            self.resilience_events.append(("drain", w.url, "escalated"))
            w.kill()
        # the process is gone: land GONE in the detector BEFORE a
        # replacement exists, so concurrent queries classify and retry
        self.failure_detector.sweep_once()
        summary = {"worker": w.url, "escalated": escalated,
                   "exit_code": w.proc.poll(), "replacement": None}
        if replace:
            slot = self.workers.index(w)
            replacement = WorkerProcess(self._env_overrides)
            self.failure_detector.unmonitor(w.url)
            self._monitor_worker(replacement)
            self.workers[slot] = replacement
            self.failure_detector.sweep_once()
            self.resilience_events.append(
                ("drain", w.url, "replaced", replacement.url))
            summary["replacement"] = replacement.url
        self.resilience_events.append(("drain", w.url, "drained"))
        return summary

    # --------------------------------------------------------- elasticity
    def add_worker(self) -> WorkerProcess:
        """Grow the fleet by one worker process (autoscaler scale-up).
        Placement picks it up on the next heartbeat sweep; running FTE
        stages keep their recorded task fan-out (shape_matches), new
        queries fan out wider."""
        w = WorkerProcess(self._env_overrides)
        self._monitor_worker(w)
        self.workers.append(w)
        self.failure_detector.sweep_once()
        self.resilience_events.append(("scale", w.url, "added"))
        return w

    def remove_worker(self, timeout_s: Optional[float] = None
                      ) -> Optional[str]:
        """Shrink the fleet by one worker (autoscaler scale-down): drain
        the last slot through the zero-loss shutdown protocol WITHOUT a
        replacement, then drop it from the fleet.  Returns the removed
        worker's url, or None when only one worker remains."""
        live = [w for w in self.workers if w.alive()]
        if len(live) <= 1:
            return None
        w = live[-1]
        self.drain_worker(w, timeout_s=timeout_s, replace=False)
        self.failure_detector.unmonitor(w.url)
        self.workers.remove(w)
        self.resilience_events.append(("scale", w.url, "removed"))
        return w.url

    def rolling_restart(self, timeout_s: Optional[float] = None
                        ) -> list[dict]:
        """Drain + replace every worker slot, one at a time — the rolling
        restart drill.  Under retry_policy=QUERY this loses zero queries:
        capacity shrinks by one worker per step, never to zero."""
        return [self.drain_worker(self.workers[i], timeout_s=timeout_s,
                                  replace=True)
                for i in range(len(self.workers))]

    def close(self) -> None:
        self.failure_detector.stop()
        for w in self.workers:
            w.shutdown()

    def __del__(self):  # best effort
        try:
            for w in self.workers:
                if w.alive():
                    w.proc.kill()
        # tpulint: disable=error-taxonomy -- interpreter-teardown kill; nothing to classify to
        except Exception:
            pass

    def fte_run_attempt(self, fragment, task_index: int, task_count: int,
                        nparts: int, upstream: dict, spool_root: str,
                        attempt: int, stats_sink: Optional[list],
                        memory_multiplier: float = 1.0) -> str:
        """Dispatch ONE FTE task attempt to a live worker PROCESS; the
        worker writes the durable spool (shared filesystem) and commits
        atomically.  A worker death mid-attempt surfaces here as GONE and
        the FTE retry loop re-dispatches to a surviving worker — recovery
        from real process loss, off the committed on-disk spools."""
        import os as _os

        from .fte import fte_task_dir

        alive = self._placement_workers()
        if not alive:
            raise TrinoError(NO_NODES_AVAILABLE, "no live workers")
        w = alive[(fragment.id * 31 + task_index + attempt) % len(alive)]
        self._query_seq += 1
        task_dir = fte_task_dir(spool_root, fragment.id, task_index)
        _os.makedirs(task_dir, exist_ok=True)
        injector = getattr(self.session, "failure_injector", None)
        desc = {
            "fragment": fragment,
            "task_index": task_index,
            "task_count": task_count,
            "num_partitions": nparts,
            "upstream": {},
            "catalog": self.catalog_spec,
            "splits_per_node": self.session.splits_per_node,
            "node_count": self.worker_count,
            "dynamic_filtering": self.session.dynamic_filtering,
            "hbm_limit_bytes": int(
                self.session.hbm_limit_bytes * memory_multiplier),
            "spool": {"task_dir": task_dir, "attempt": attempt,
                      "num_partitions": nparts},
            "spool_upstream": upstream,
            "failure_rules": (
                injector.consume_for(
                    fragment.id, task_index, attempt,
                    # a leaf attempt (no upstream) never reaches the
                    # results-read injection point; new kinds export by
                    # default
                    unreachable=(set() if upstream
                                 else {GET_RESULTS_FAILURE}))
                if injector is not None else []),
        }
        rt = HttpRemoteTask(
            w.url, f"fte{self._query_seq}_f{fragment.id}_t{task_index}"
                   f"_a{attempt}")
        rt.create(desc)
        deadline = time.monotonic() + 600
        while True:
            st = rt.status()
            if st["state"] == "FINISHED":
                break
            if st["state"] in ("FAILED", "GONE", "CANCELED"):
                # classified so the FTE retry chain can fail fast on USER
                # errors and keep retrying EXTERNAL/INTERNAL ones
                raise TrinoError(
                    lookup_code(st.get("error_code"), st.get("error_type")),
                    f"attempt failed ({st['state']}): {st.get('error')}",
                    remote_host=w.url)
            if time.monotonic() > deadline:
                rt.cancel()
                raise TimeoutError("fte attempt stalled")
            time.sleep(0.05)
        expected = _os.path.join(task_dir, f"attempt-{attempt}")
        if not _os.path.isdir(expected):
            raise TrinoError(GENERIC_INTERNAL_ERROR,
                             "attempt reported FINISHED but no committed "
                             "spool found")
        if stats_sink is not None:
            from ..exec.stats import QueryStats

            stats_sink.append(QueryStats(
                label=f"fragment {fragment.id} task {task_index}: "
                      f"(remote worker {w.url})"))
        return expected

    # ------------------------------------------------------------- execution
    def _run_streaming(self, subplan: SubPlan, stats_sink: Optional[list],
                       attempt: int = 0,
                       blacklist: frozenset = frozenset()) -> QueryResult:
        # cluster-state system tables (system.runtime.workers / queries /
        # metrics.counters) are coordinator-fed: the attached runner and
        # failure detector live in THIS process, not in any worker, so a
        # subplan whose scans all read catalog "system" executes in-process
        # — the analogue of Trino's coordinator-only system splits
        if self._scans_system_only(subplan):
            return super()._run_streaming(subplan, stats_sink,
                                          attempt=attempt,
                                          blacklist=blacklist)
        # the base class dispatches retry_policy (TASK -> fte, QUERY -> the
        # query-retry loop); both land here for the actual remote run
        return self._run_remote(subplan, attempt=attempt,
                                blacklist=blacklist)

    @staticmethod
    def _scans_system_only(subplan: SubPlan) -> bool:
        from ..planner.plan import TableScan

        scans: list = []

        def walk(n) -> None:
            if isinstance(n, TableScan):
                scans.append(n)
            for c in n.children:
                walk(c)

        for f in subplan.all_fragments():
            walk(f.root)
        return bool(scans) and all(s.catalog == "system" for s in scans)

    def _exchange_backoff_cfg(self) -> dict:
        sess = self.session
        return {"min_delay_s": sess.exchange_backoff_min_s,
                "max_delay_s": sess.exchange_backoff_max_s,
                "max_failure_duration_s":
                    sess.exchange_max_failure_duration_s}

    def _check_workers(self, by_worker: dict) -> None:
        """One heartbeat-cadence sweep: a single cached /v1/status per
        WORKER (not per task) decides node death and task failure — the old
        per-task loop made the sweep itself the stall (10 s status timeout
        x N tasks against one hung worker)."""
        self.failure_detector.sweep_once()
        for wurl, owned in by_worker.items():
            # state None means the worker was unmonitored mid-query (a
            # drain replaced it) — without this an in-flight query would
            # spin on exchange backoff against a vanished process until the
            # query deadline instead of retrying promptly
            if self.failure_detector.state_of(wurl) in (GONE, None):
                raise TrinoError(
                    REMOTE_HOST_GONE,
                    f"worker {wurl} ({len(owned)} tasks): "
                    f"{self.failure_detector.last_error(wurl) or 'replaced'}",
                    remote_host=wurl)
            status = self.failure_detector.last_status(wurl) or {}
            # the same cached status JSON feeds the cluster memory view:
            # per-task query_id + memory_reserved_bytes aggregate on the
            # coordinator (ClusterMemoryManager.update_worker)
            self.memory_manager.update_worker(wurl, status)
            task_states = status.get("tasks", {})
            for fid, t, task_id in owned:
                st = task_states.get(task_id)
                if st is not None and st["state"] == "FAILED":
                    raise TrinoError(
                        lookup_code(st.get("error_code"),
                                    st.get("error_type")),
                        f"task f{fid}.t{t} FAILED: {st.get('error')}",
                        remote_host=wurl)

    def _collect_task_spans(self, tasks: dict, parent_span) -> None:
        """Re-attach every worker task's finished span subtree under the
        coordinator's query span — one distributed trace tree per query.
        Workers publish the span BEFORE the terminal state, but the client
        drain can observe the last page slightly before the producer flips
        state, hence the short bounded re-poll.  Scan totals travel as
        ``trino.scan.*`` span attributes and fold into the coordinator's
        query record (worker processes keep their own metric registries)."""
        if parent_span is None:
            return
        from ..telemetry import runtime as rt
        from .tracing import Span

        rec = rt.current_record()
        budget = time.monotonic() + 5.0
        for remote_task in tasks.values():
            d = None
            while True:
                st = remote_task.status()
                d = st.get("span")
                if d is not None or st.get("state") != "RUNNING" \
                        or time.monotonic() > budget:
                    break
                time.sleep(0.05)
            prof = st.get("profile") if st else None
            if prof and rec is not None:
                # worker rings are keyed by the worker-visible pq{N} id;
                # re-tag onto the engine query id so the coordinator's
                # chrome_trace merges both processes into one timeline
                from ..telemetry import profiler

                profiler.add_remote_events(
                    rec.query_id, prof,
                    process_name=f"worker:{remote_task.worker_url}")
            if not d:
                continue
            sub = Span.from_dict(d)
            parent_span.children.append(sub)
            if rec is not None:
                rt.add_input(rec,
                             int(sub.attributes.get("trino.scan.rows", 0)),
                             int(sub.attributes.get("trino.scan.bytes", 0)))

    def _run_remote(self, subplan: SubPlan, attempt: int = 0,
                    blacklist: frozenset = frozenset()) -> QueryResult:
        from ..telemetry import runtime as _rtl
        from .resource_manager import find_group
        from .tracing import traceparent as _traceparent

        self._query_seq += 1
        qid = f"pq{self._query_seq}"
        # cluster memory accounting is keyed by the WORKER-visible query id
        # (worker status payloads carry it per task), so register under qid
        qrec = _rtl.current_record()
        max_mem = (self.session.query_max_memory_bytes
                   or int(os.environ.get("TRINO_TPU_QUERY_MAX_MEMORY",
                                         "0") or 0) or None)
        handle = self.memory_manager.register_query(
            qid, priority=self.session.query_priority,
            group=find_group(self.dispatcher.root,
                             qrec.resource_group if qrec is not None else ""),
            max_memory=max_mem)
        # the open trino.query span (run_with_query_events) becomes the
        # remote parent of every worker task span for this attempt
        parent_span = self.tracer.current()
        tp = _traceparent(parent_span) if parent_span is not None else None
        fragments = subplan.all_fragments()
        task_counts, consumer_tasks = self.stage_task_counts(fragments)
        alive = self._placement_workers(blacklist)
        if not alive:
            raise TrinoError(NO_NODES_AVAILABLE, "no live workers")
        injector = getattr(self.session, "failure_injector", None)

        # deterministic placement: task t of fragment f -> alive worker
        # (f*31 + t) % n  (UniformNodeSelector's role, minus locality)
        tasks: dict[tuple[int, int], HttpRemoteTask] = {}
        by_worker: dict[str, list] = {}
        for f in fragments:
            for t in range(task_counts[f.id]):
                w = alive[(f.id * 31 + t) % len(alive)]
                rt = HttpRemoteTask(w.url, f"{qid}_f{f.id}_t{t}")
                tasks[(f.id, t)] = rt
                by_worker.setdefault(w.url, []).append((f.id, t, rt.task_id))

        by_id = {f.id: f for f in fragments}
        client = None
        try:
            for f in fragments:
                tc = task_counts[f.id]
                for t in range(tc):
                    upstream = {}
                    for src in f.source_fragments:
                        src_tasks = [tasks[(src, i)].uri
                                     for i in range(task_counts[src])]
                        upstream[src] = {
                            "uris": src_tasks,
                            "merge": by_id[src].output_kind == "MERGE",
                        }
                    desc = {
                        "fragment": f,
                        "task_index": t,
                        "task_count": tc,
                        "num_partitions": consumer_tasks.get(f.id, 1),
                        "attempt": attempt,
                        "query_id": qid,
                        "upstream": upstream,
                        "catalog": self.catalog_spec,
                        "splits_per_node": self.session.splits_per_node,
                        "node_count": self.worker_count,
                        "dynamic_filtering": self.session.dynamic_filtering,
                        "hbm_limit_bytes": self.session.hbm_limit_bytes,
                        "exchange_backoff": self._exchange_backoff_cfg(),
                        "failure_rules": (
                            injector.consume_for(
                                f.id, t, attempt,
                                # leaves never reach the results-read
                                # injection point
                                unreachable=(set() if upstream
                                             else {GET_RESULTS_FAILURE}))
                            if injector is not None else []),
                    }
                    rt = tasks[(f.id, t)]
                    try:
                        rt.create(desc, traceparent=tp)
                    except BaseException as e:  # noqa: BLE001
                        te = classify(e)
                        te.remote_host = te.remote_host or \
                            HttpExchangeClient._host_of(rt.uri)
                        raise te from e

            # drain the root fragment's partition 0 as the client; ONE
            # status poll per worker at heartbeat cadence decides failure
            root = subplan.fragment
            root_uris = [tasks[(root.id, t)].uri
                         for t in range(task_counts[root.id])]
            client = HttpExchangeClient(root_uris, 0,
                                        backoff=self._exchange_backoff_cfg(),
                                        traceparent=tp)
            batches: list[ColumnBatch] = []
            deadline = time.monotonic() + 600
            last_status = 0.0
            while not client.is_finished():
                b = client.poll(timeout=0.2)
                if b is not None:
                    batches.append(b)
                    continue
                now = time.monotonic()
                if now - last_status > self.session.heartbeat_interval_s:
                    last_status = now
                    self._check_workers(by_worker)
                    # worker snapshots just refreshed: give the low-memory
                    # killer a chance, then surface a verdict against US
                    handle.poll()
                handle.check()
                if now > deadline:
                    raise TimeoutError("remote query stalled")
            self._collect_task_spans(tasks, parent_span)
            return self._to_result(subplan, batches)
        except BaseException:
            for rt in tasks.values():
                rt.cancel()
            raise
        finally:
            self.memory_manager.unregister_query(qid)
            if client is not None:
                self.resilience.exchange_fetch_failures += \
                    client.stats["fetch_failures"]
                self.resilience.exchange_backoff_trips += \
                    client.stats["backoff_trips"]
            self.resilience.heartbeat_transitions = \
                self.failure_detector.transitions
