"""Whole-stage GSPMD compilation: one jitted program per TPU-resident stage.

The fragmenter marks REPARTITION seams whose producer is an
``Aggregate(PARTIAL)`` over a Filter/Project chain and whose consumer
FINAL-aggregates that edge (execution/fragmenter.py: ``FusedSeam``).  This
module compiles each marked seam into exactly TWO jitted programs instead
of a per-batch operator chain plus an explicit collective rendezvous:

1. **Accumulate** (one call per input batch, per task): the Filter/Project
   chain, the static grouped partial aggregation, and the merge into a
   cap-slot carried state run as ONE ``jax.jit`` program with the state
   pytree DONATED (the state buffers are exclusively owned, so XLA updates
   them in place).  Batches are padded to power-of-two buckets first, so
   the program retraces O(#buckets), never O(#batches) — the shape-bucket
   compile cache of SURVEY §7.

2. **Seam merge** (one call per stage): the deposited per-task states ride
   a ``shard_map`` over the named mesh — hash-route group slots to owner
   devices, ``jax.lax.all_to_all`` fused inside the program, FINAL combine
   and finalize — subsuming ``collective_exchange._shuffle_program`` for
   fused stages.  In/out specs are both ``P("x")`` on dim 0 (the seam
   PartitionSpec contract recorded on the FusedSeam): producer deposit and
   consumer take agree on sharding, so no resharding happens on the seam.

Overflow contract: the carried state holds ``cap`` group slots per task
(``TRINO_TPU_FUSED_CAP``); if a task sees more distinct groups the device
overflow scalar trips at finish and the runner re-runs the subplan on the
legacy per-operator path (FusedStageOverflow).  The seam merge itself can
never overflow: its capacity is ``n_tasks * cap`` which bounds the distinct
groups that can arrive.

``TRINO_TPU_FUSED_STAGE={auto,1,0}``: 0 restores today's per-operator +
collective-exchange path bit-for-bit (same knob pattern as
TRINO_TPU_SYNC_FREE / TRINO_TPU_HASH_IMPL).
"""

from __future__ import annotations

import os
import threading
import warnings
from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..caching.executable_cache import jit_memo, register_external

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..exec import kernels as K
from ..exec import syncguard as SG
from ..exec.operators import Operator
from ..exec.stats import FusedStageStats
from ..ops.expr import compile_expression
from ..parallel.compat import shard_map
from ..parallel.static_agg import AggSpec, combine_partials, static_grouped_agg
from ..planner import plan as PL
from ..spi.batch import Column, ColumnBatch
from ..spi.errors import (GENERIC_INTERNAL_ERROR, PAGE_TRANSPORT_TIMEOUT,
                          TrinoError)
from ..spi.types import DOUBLE, DecimalType

__all__ = ["FusedStageExec", "FusedStageOverflow", "FusedStageSinkOperator",
           "FusedStageSourceOperator", "FusedStageSpec", "build_fused_spec",
           "plan_fused_stages", "fused_stage_mode", "fused_cap"]

_AXIS = "x"

# CPU meshes can't honor buffer donation; the fallback is correct (copy),
# the warning is per-call noise on the hot path.
warnings.filterwarnings("ignore", message="Some donated buffers were not usable")


def fused_stage_mode() -> str:
    """TRINO_TPU_FUSED_STAGE: auto (default, fuse eligible seams), 1 (same),
    0 (legacy per-operator + collective-exchange path, bit-for-bit)."""
    v = os.environ.get("TRINO_TPU_FUSED_STAGE", "auto").strip().lower()
    return v if v in ("auto", "1", "0") else "auto"


def fused_cap() -> int:
    """Carried-state group-slot capacity per task (TRINO_TPU_FUSED_CAP)."""
    return int(os.environ.get("TRINO_TPU_FUSED_CAP", "8192"))


class FusedStageOverflow(RuntimeError):
    """A task saw more distinct groups than the fused state cap; the runner
    falls back to the legacy per-operator path for this subplan."""


# ---------------------------------------------------------------------------
# stage spec: what the fragmenter's FusedSeam lowers to


@dataclass(frozen=True)
class _StateSpec:
    """One mergeable state column of the carried aggregation state
    (mirrors HashAggregationOperator._agg_spec + the PARTIAL avg
    expansion of add_exchanges.partial_agg_layout)."""

    fn: str           # sum | count | count_star | min | max
    arg: int          # chain-output channel (-1 for count_star)
    dtype: str        # numpy dtype str of the state lane
    scale: int = 0    # decimal scale folded into the avg sum state
    has_valid: bool = True  # state carries a validity lane


@dataclass
class FusedStageSpec:
    producer_fid: int
    consumer_fid: int
    n_tasks: int
    feed: PL.PlanNode              # runs as the legacy operator pipeline
    chain: tuple                   # Filter|Project nodes, application order
    partial: PL.Aggregate
    final: PL.Aggregate
    nk: int
    cap: int
    state_specs: tuple = ()        # tuple[tuple[_StateSpec, ...], ...]

    @property
    def key_types(self):
        src = self.partial.source.output_types
        return tuple(src[c] for c in self.partial.group_keys)

    @property
    def flat_states(self) -> tuple:
        return tuple(s for group in self.state_specs for s in group)

    def cache_key(self) -> tuple:
        return (self.partial, tuple(self.chain),
                tuple(self.feed.output_types), self.cap)


def _derive_state_specs(partial: PL.Aggregate) -> tuple:
    src_types = partial.source.output_types
    out = []
    for a in partial.aggregates:
        if a.fn == "count" and a.arg < 0:
            out.append((_StateSpec("count_star", -1, "<i8", 0, False),))
        elif a.fn == "avg":
            t = src_types[a.arg]
            scale = t.scale if isinstance(t, DecimalType) else 0
            out.append((_StateSpec("sum", a.arg, "<f8", scale, True),
                        _StateSpec("count", a.arg, "<i8", 0, False)))
        elif a.fn == "sum":
            if a.type == DOUBLE:
                dt = "<f8"
            elif a.type.name == "real":
                dt = "<f4"
            else:
                dt = "<i8"
            out.append((_StateSpec("sum", a.arg, dt, 0, True),))
        elif a.fn == "count":
            out.append((_StateSpec("count", a.arg, "<i8", 0, False),))
        else:  # min | max
            dt = np.dtype(src_types[a.arg].storage_dtype).str
            out.append((_StateSpec(a.fn, a.arg, dt, 0, True),))
    return tuple(out)


def build_fused_spec(producer, consumer, n_tasks: int,
                     cap: int) -> "FusedStageSpec":
    """Lower a fragmenter-marked FusedSeam into the executable spec."""
    from .fragmenter import _walk

    root = producer.root  # Aggregate(PARTIAL), checked by the fragmenter
    chain = []
    node = root.source
    while isinstance(node, (PL.Filter, PL.Project)):
        chain.append(node)
        node = node.source
    chain.reverse()
    final = next(n for n in _walk(consumer.root)
                 if isinstance(n, PL.Aggregate) and n.step == "FINAL"
                 and isinstance(n.source, PL.RemoteSource)
                 and n.source.fragment_id == producer.id)
    spec = FusedStageSpec(
        producer_fid=producer.id, consumer_fid=consumer.id, n_tasks=n_tasks,
        feed=node, chain=tuple(chain), partial=root, final=final,
        nk=len(root.group_keys), cap=cap,
        state_specs=_derive_state_specs(root))
    n_states = len(spec.flat_states)
    assert n_states == len(root.output_types) - spec.nk, \
        "fused state layout disagrees with partial_agg_layout"
    return spec


def plan_fused_stages(fragments, session, task_counts: dict,
                      consumer_tasks: dict) -> dict:
    """Runtime gate over fragmenter-marked seams: returns {producer_fid:
    FusedStageExec} for seams where the mesh exists and producer/consumer
    task counts line up (same conditions as the collective exchange)."""
    if fused_stage_mode() == "0" or not getattr(session, "use_collectives", True):
        return {}
    from .collective_exchange import collectives_available

    by_id = {f.id: f for f in fragments}
    out: dict = {}
    for f in fragments:
        seam = getattr(f, "fused_seam", None)
        if seam is None or not getattr(f, "device_resident", False):
            continue
        tc = task_counts.get(f.id)
        if (tc is None or consumer_tasks.get(f.id) != tc
                or task_counts.get(seam.consumer_fid) != tc
                or not collectives_available(tc)):
            continue
        spec = build_fused_spec(f, by_id[seam.consumer_fid], tc, fused_cap())
        out[f.id] = FusedStageExec(spec)
    return out


# ---------------------------------------------------------------------------
# the accumulate program: chain -> partial agg -> state merge, ONE jit call


_ACCUM_CACHE: dict = {}
_ACCUM_LOCK = threading.Lock()
_ACCUM_STATS = {"hits": 0, "misses": 0, "evictions": 0}
_TRACE_SIGS: set = set()  # (program id, bucket signature) — compile counting


def _accum_cache_stats() -> dict:
    """system.runtime.caches row for the id()-keyed accumulate memo — it
    cannot live in the registry proper (keys are object identities, not
    replayable values) but must show up in the observability plane."""
    with _ACCUM_LOCK:
        return {"tier": "exec", "name": "stage._accumulate_program",
                "entries": len(_ACCUM_CACHE), "bytes": 0,
                "hits": _ACCUM_STATS["hits"],
                "misses": _ACCUM_STATS["misses"],
                "evictions": _ACCUM_STATS["evictions"], "invalidations": 0}


register_external("stage._accumulate_program", _accum_cache_stats)


class _AccumulateProgram:
    """One fused accumulate program: compiled expressions + static agg +
    carried-state combine under a single ``jax.jit`` with the state pytree
    donated.  Cached per (stage spec, feed dictionary identity); jax.jit
    itself buckets retraces by the padded batch shape."""

    def __init__(self, spec: FusedStageSpec, in_types, in_dicts):
        self.spec = spec
        self._compile_chain(in_types, in_dicts)
        self._fn = jax.jit(self._run, donate_argnums=(0,))
        # one launch for the whole zero pytree (it is immediately donated to
        # the first accumulate call, so every task needs fresh buffers)
        self._init_fn = jax.jit(self._initial_state)

    def _compile_chain(self, in_types, in_dicts):
        spec = self.spec
        types = list(in_types)
        dicts = list(in_dicts)
        steps = []
        for node in spec.chain:
            if isinstance(node, PL.Filter):
                steps.append(("filter",
                              compile_expression(node.predicate, types, dicts),
                              None))
            else:
                ces = [compile_expression(e, types, dicts)
                       for e in node.expressions]
                steps.append(("project", ces,
                              [t.storage_dtype for t in node.output_types]))
                types = list(node.output_types)
                dicts = [ce.dictionary for ce in ces]
        self.steps = steps
        self.out_types = types
        # chain-output dictionaries: what the carried state's key codes mean
        self.key_dicts = [dicts[c] for c in spec.partial.group_keys]

    def initial_state(self) -> dict:
        return self._init_fn()

    def _initial_state(self) -> dict:
        spec = self.spec
        cap = spec.cap
        kd = tuple(jnp.zeros(cap, t.storage_dtype) for t in spec.key_types)
        kv = tuple(jnp.zeros(cap, jnp.bool_) for _ in spec.key_types)
        sd = tuple(jnp.zeros(cap, np.dtype(s.dtype)) for s in spec.flat_states)
        sv = tuple(jnp.zeros(cap, jnp.bool_) if s.has_valid else None
                   for s in spec.flat_states)
        return {"kd": kd, "kv": kv, "sd": sd, "sv": sv,
                "used": jnp.zeros(cap, jnp.bool_),
                "err": jnp.zeros((), jnp.int32),
                "ovf": jnp.zeros((), jnp.int32)}

    def __call__(self, state, cols, live, batch_remaps, state_remaps):
        return self._fn(state, cols, live, batch_remaps, state_remaps)

    # -- traced body --------------------------------------------------------
    def _run(self, state, cols, live, batch_remaps, state_remaps):
        n = cols[0][0].shape[0]
        cols, live, batch_err = self._apply_chain(cols, live, n)
        return self._agg_merge(state, cols, live, batch_remaps,
                               state_remaps, n, batch_err)

    def _apply_chain(self, cols, live, n):
        from ..ops.expr import (
            expr_condition_mask,
            expr_error_scope,
            reduce_error_lanes,
        )

        # ---- Filter/Project chain (mirrors FilterProjectOperator.run) -----
        with expr_error_scope() as errs:
            for kind, compiled, out_dtypes in self.steps:
                if kind == "filter":
                    with expr_condition_mask(live):
                        data, valid = compiled(cols)
                    mask = data if valid is None else data & valid
                    if getattr(mask, "ndim", 1) == 0:
                        mask = jnp.broadcast_to(mask, (n,))
                    live = live & mask
                else:
                    outs = []
                    with expr_condition_mask(live):
                        for ce, dt in zip(compiled, out_dtypes):
                            d, v = ce(cols)
                            d = jnp.asarray(d)
                            if d.ndim == 0:
                                d = jnp.broadcast_to(d, (n,))
                            d = d.astype(dt)
                            if v is not None:
                                v = jnp.asarray(v)
                                if v.ndim == 0:
                                    v = jnp.broadcast_to(v, (n,))
                            outs.append((d, v))
                    cols = outs
            err = reduce_error_lanes(errs, (n,))
        batch_err = (jnp.zeros((), jnp.int32) if err is None
                     else jnp.max(err).astype(jnp.int32))
        return cols, live, batch_err

    def _agg_merge(self, state, cols, live, batch_remaps, state_remaps,
                   n, batch_err):
        spec = self.spec
        cap = spec.cap
        # ---- partial aggregation of this batch ----------------------------
        keys, kvalids = [], []
        for j, ch in enumerate(spec.partial.group_keys):
            d, v = cols[ch]
            if batch_remaps[j] is not None:  # codes -> merged dict space
                d = batch_remaps[j][d]
            keys.append(d)
            kvalids.append(v if v is not None else jnp.ones(n, jnp.bool_))
        agg_inputs = []
        for ss in spec.flat_states:
            if ss.fn == "count_star":
                agg_inputs.append((AggSpec("count_star", jnp.int64),
                                   None, None))
                continue
            d, v = cols[ss.arg]
            if ss.fn == "sum" and ss.scale:
                d = d.astype(jnp.float64) / (10.0 ** ss.scale)
            agg_inputs.append((AggSpec(ss.fn, np.dtype(ss.dtype)), d, v))
        part = static_grouped_agg(keys, kvalids, agg_inputs, cap,
                                  row_mask=live)

        # ---- merge with the carried state ---------------------------------
        skd = list(state["kd"])
        for j in range(spec.nk):
            if state_remaps[j] is not None:
                skd[j] = state_remaps[j][skd[j]]
        ckd = [jnp.concatenate([skd[j], part.keys[j]])
               for j in range(spec.nk)]
        ckv = [jnp.concatenate([state["kv"][j],
                                part.key_valids[j]
                                if part.key_valids[j] is not None
                                else jnp.ones(cap, jnp.bool_)])
               for j in range(spec.nk)]
        cused = jnp.concatenate([state["used"], part.slot_used])
        partial_inputs = []
        for si, ss in enumerate(spec.flat_states):
            vals = jnp.concatenate([state["sd"][si], part.values[si]])
            if ss.has_valid:
                pv = part.value_valids[si]
                if pv is None:
                    pv = part.slot_used
                valid = jnp.concatenate([state["sv"][si], pv])
            else:
                valid = None
            partial_inputs.append(
                (AggSpec(ss.fn if ss.fn != "count_star" else "count",
                         np.dtype(ss.dtype)), vals, valid))
        merged = combine_partials(ckd, ckv, partial_inputs, cused, cap)

        new_sd, new_sv = [], []
        for si, ss in enumerate(spec.flat_states):
            new_sd.append(merged.values[si])
            if ss.has_valid:
                mv = merged.value_valids[si]
                new_sv.append(mv if mv is not None else merged.slot_used)
            else:
                new_sv.append(None)
        ovf = jnp.maximum(
            state["ovf"],
            jnp.maximum(part.num_groups, merged.num_groups).astype(jnp.int32))
        return {
            "kd": tuple(merged.keys),
            "kv": tuple(v if v is not None else merged.slot_used
                        for v in merged.key_valids),
            "sd": tuple(new_sd),
            "sv": tuple(new_sv),
            "used": merged.slot_used,
            "err": jnp.maximum(state["err"], batch_err),
            "ovf": ovf,
        }


@jit_memo("stage._ingest_program", maxsize=256)
def _ingest_program(n_out: int, miss_valid: tuple, has_live: bool):
    """ONE jitted pad-to-bucket program per pad pattern (jax's own cache
    keys the raw input shapes): pads every column to the power-of-two
    bucket, fills absent valid masks, and extends ``live`` as dead over the
    pad rows — the same semantics as spi.batch.pad_to_bucket plus the
    per-column mask fill, collapsed from ~3x #columns eager dispatches per
    batch into a single launch ahead of the accumulate call."""

    @jax.jit
    def run(cols, live):
        n_in = cols[0][0].shape[0]
        pad = n_out - n_in
        outs = []
        for (d, v), miss in zip(cols, miss_valid):
            if pad:
                d = jnp.concatenate([d, jnp.zeros(pad, d.dtype)])
            if miss:
                v = jnp.ones(n_out, jnp.bool_)
            elif pad:
                v = jnp.concatenate([v, jnp.zeros(pad, jnp.bool_)])
            outs.append((d, v))
        if not has_live:
            live = jnp.concatenate(
                [jnp.ones(n_in, jnp.bool_), jnp.zeros(pad, jnp.bool_)])
        return tuple(outs), live

    return run


def _accumulate_program(spec: FusedStageSpec, in_types,
                        in_dicts) -> _AccumulateProgram:
    key = (spec.cache_key(), tuple(in_types),
           tuple(id(d) if d is not None else None for d in in_dicts))
    with _ACCUM_LOCK:
        hit = _ACCUM_CACHE.get(key)
        if hit is not None:
            _ACCUM_STATS["hits"] += 1
            return hit[0]
        _ACCUM_STATS["misses"] += 1
        if len(_ACCUM_CACHE) >= 256:
            _ACCUM_CACHE.pop(next(iter(_ACCUM_CACHE)))
            _ACCUM_STATS["evictions"] += 1
    prog = _AccumulateProgram(spec, in_types, in_dicts)
    with _ACCUM_LOCK:
        # dict refs held in the value keep the id()-keyed entries stable
        _ACCUM_CACHE.setdefault(key, (prog, list(in_dicts)))
    return prog


# ---------------------------------------------------------------------------
# the seam merge program: route -> all_to_all -> FINAL combine -> finalize


@jit_memo("stage._merge_program")
def _merge_program(n_dev: int, cap: int, key_dtypes: tuple, dict_flags: tuple,
                   state_sig: tuple, final_sig: tuple, table_buckets: tuple):
    """One jitted shard_map over the stage mesh: remap state key codes into
    the unified dictionaries, hash-route group slots to owner devices
    (VALUE hashes for dictionary keys — same _dict_value_hashes contract as
    the host and collective exchanges), all_to_all every state lane, FINAL
    combine at capacity ``n_dev*cap`` (which can never overflow), and
    finalize the aggregate outputs.  All in/out specs are P(_AXIS) on dim 0
    — the seam PartitionSpec contract."""
    mesh = Mesh(jax.devices()[:n_dev], (_AXIS,))
    nk = len(key_dtypes)
    n_states = len(state_sig)
    fcap = n_dev * cap
    n_dict = sum(dict_flags)

    def local(*flat):
        i = 0
        kds = list(flat[i:i + nk]); i += nk
        kvs = list(flat[i:i + nk]); i += nk
        sds = list(flat[i:i + n_states]); i += n_states
        svs = []
        for fn, dt, has_valid in state_sig:
            if has_valid:
                svs.append(flat[i]); i += 1
            else:
                svs.append(None)
        used = flat[i]; i += 1
        remaps, vhs = {}, {}
        for j in range(nk):
            if dict_flags[j]:
                remaps[j] = flat[i]; i += 1
                vhs[j] = flat[i]; i += 1
        # ---- unify: task-local codes -> merged dictionary space -----------
        for j in remaps:
            kds[j] = remaps[j][kds[j]]
        # ---- destination by key-value hash (NULL keys -> device 0) --------
        route_keys = [vhs[j][kds[j]] if dict_flags[j] else kds[j]
                      for j in range(nk)]
        h = K.hash_combine(route_keys)
        dest = (h % jnp.uint64(n_dev)).astype(jnp.int32)
        null_key = None
        for j in range(nk):
            nkv = ~kvs[j]
            null_key = nkv if null_key is None else (null_key | nkv)
        if null_key is not None:
            dest = jnp.where(null_key, 0, dest)
        lane_live = used[None, :] & (
            dest[None, :] == jnp.arange(n_dev, dtype=jnp.int32)[:, None])

        def shuffle(x):
            lanes = jnp.broadcast_to(x[None, :], (n_dev, cap))
            out = jax.lax.all_to_all(lanes, _AXIS, 0, 0, tiled=False)
            return out.reshape(fcap)

        rkd = [shuffle(k) for k in kds]
        rkv = [shuffle(v) for v in kvs]
        rlive = jax.lax.all_to_all(lane_live, _AXIS, 0, 0,
                                   tiled=False).reshape(fcap)
        partial_inputs = []
        for (fn, dt, has_valid), sd, sv in zip(state_sig, sds, svs):
            partial_inputs.append(
                (AggSpec(fn, np.dtype(dt)), shuffle(sd),
                 shuffle(sv) if sv is not None else None))
        fin = combine_partials(rkd, rkv, partial_inputs, rlive, fcap)

        # ---- FINAL finalize (HashAggregationOperator FINAL semantics) -----
        outs = []
        si = 0
        for fn, out_dt, width in final_sig:
            if fn == "avg":
                s, sv_ = fin.values[si], fin.value_valids[si]
                c = fin.values[si + 1]
                cnt = jnp.maximum(c, 1)
                vals = (s / cnt).astype(out_dt)
                valid = (c > 0)
                if sv_ is not None:
                    valid = valid & sv_
                outs.append((vals, valid))
            elif fn == "count":
                outs.append((fin.values[si].astype(jnp.int64), None))
            else:  # sum | min | max
                outs.append((fin.values[si].astype(out_dt),
                             fin.value_valids[si]))
            si += width
        flat_out = list(fin.keys)
        flat_out += [v if v is not None else fin.slot_used
                     for v in fin.key_valids]
        flat_out += [d for d, _ in outs]
        flat_out += [v for _, v in outs if v is not None]
        flat_out.append(fin.slot_used)
        return tuple(flat_out)

    n_in = 2 * nk + n_states + sum(1 for s in state_sig if s[2]) + 1 + 2 * n_dict
    n_out = 2 * nk + len(final_sig) \
        + sum(1 for f in final_sig if f[0] not in ("count",)) + 1
    return mesh, jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=tuple([P(_AXIS)] * n_in),
        out_specs=tuple([P(_AXIS)] * n_out),
        check_vma=False,
    ))


# ---------------------------------------------------------------------------
# rendezvous + operators


class FusedStageExec:
    """Rendezvous for one fused seam: ``n_tasks`` producer sinks deposit
    their carried states; the last depositor runs the seam merge program
    inside a SyncGuard hot region (zero host syncs between deposit and
    take); consumer sources take their device shard."""

    def __init__(self, spec: FusedStageSpec):
        self.spec = spec
        n = spec.n_tasks
        self._deposits: list = [None] * n
        self._dicts: list = [None] * n
        self._count = 0
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._results: list = [None] * n
        self._error: Optional[BaseException] = None
        self.stats = FusedStageStats(stages=1)

    # ------------------------------------------------------------ producers
    def deposit(self, task_index: int, state, key_dicts,
                sink_stats: FusedStageStats) -> None:
        run_it = False
        with self._lock:
            self._deposits[task_index] = state
            self._dicts[task_index] = key_dicts
            self.stats.merge(sink_stats)
            self._count += 1
            run_it = self._count == self.spec.n_tasks
        if run_it:
            from ..telemetry import profiler

            t0 = profiler.now() if profiler.enabled() else 0.0
            try:
                with SG.hot_region():
                    self._run_merge()
                self.stats.merges += 1
            except BaseException as e:  # surfaced to every waiting consumer
                self._error = e
            if t0:
                profiler.event(
                    profiler.FUSED,
                    f"fused-merge[f{self.spec.producer_fid}->"
                    f"f{self.spec.consumer_fid}]", t0,
                    tasks=self.spec.n_tasks)
            self._done.set()

    def abort(self) -> None:
        self._error = RuntimeError("fused stage aborted")
        self._done.set()

    # ------------------------------------------------------------- the merge
    def _run_merge(self) -> None:
        from .task import _dict_value_hashes

        spec = self.spec
        n, cap, nk = spec.n_tasks, spec.cap, spec.nk
        fcap = n * cap
        key_types = spec.key_types
        dict_flags = tuple(t.is_dictionary_encoded for t in key_types)
        states = [st if st is not None else self._empty_state_host()
                  for st in self._deposits]

        # unify each dictionary key column across tasks (host work over the
        # tiny dictionaries only; codes remap with a device gather inside
        # the merge program)
        empty = np.array([], dtype=object)
        merged_dicts: list = [None] * nk
        remap_tables: list = [None] * nk  # per key: [n] padded tables
        vh_tables: list = [None] * nk
        r_buckets: list = [0] * nk
        v_buckets: list = [0] * nk
        for j in range(nk):
            if not dict_flags[j]:
                continue
            task_dicts = [
                (self._dicts[i][j] if self._dicts[i] is not None
                 and self._dicts[i][j] is not None else empty)
                for i in range(n)]
            first = task_dicts[0]
            if all(d is first or (d.shape == first.shape and (d == first).all())
                   for d in task_dicts):
                merged = first
                remaps = [np.arange(max(len(first), 1), dtype=np.int32)
                          for _ in range(n)]
            else:
                merged = np.unique(np.concatenate(task_dicts))
                remaps = [np.searchsorted(merged, d).astype(np.int32)
                          if len(d) else np.zeros(1, np.int32)
                          for d in task_dicts]
            merged_dicts[j] = merged
            R = K.bucket(max(max(len(r) for r in remaps), 1))
            remap_tables[j] = [
                np.concatenate([r, np.zeros(R - len(r), np.int32)])
                for r in remaps]
            r_buckets[j] = R
            vh = _dict_value_hashes(merged) if len(merged) else \
                np.zeros(1, np.int64)
            V = K.bucket(max(len(vh), 1))
            vh_tables[j] = np.concatenate([vh, np.zeros(V - len(vh), np.int64)])
            v_buckets[j] = V

        state_sig = tuple((s.fn if s.fn != "count_star" else "count",
                           s.dtype, s.has_valid) for s in spec.flat_states)
        final_sig = tuple(
            (a.fn if not (a.fn == "count" and a.arg < 0) else "count",
             np.dtype(t.storage_dtype).str, len(group))
            for a, t, group in zip(spec.final.aggregates,
                                   spec.final.output_types[nk:],
                                   spec.state_specs))
        mesh, prog = _merge_program(
            n, cap, tuple(np.dtype(t.storage_dtype).str for t in key_types),
            dict_flags, state_sig, final_sig,
            (tuple(r_buckets), tuple(v_buckets)))

        srcs: list = []  # [flat][task] host or device arrays
        sizes: list = []

        def add_global(per_task, size):
            srcs.append(list(per_task))
            sizes.append(size)

        for j in range(nk):
            add_global([states[i]["kd"][j] for i in range(n)], cap)
        for j in range(nk):
            add_global([states[i]["kv"][j] for i in range(n)], cap)
        for si, ss in enumerate(spec.flat_states):
            add_global([states[i]["sd"][si] for i in range(n)], cap)
        for si, ss in enumerate(spec.flat_states):
            if ss.has_valid:
                add_global([states[i]["sv"][si] for i in range(n)], cap)
        add_global([states[i]["used"] for i in range(n)], cap)
        for j in range(nk):
            if dict_flags[j]:
                add_global(remap_tables[j], r_buckets[j])
                add_global([vh_tables[j]] * n, v_buckets[j])

        # ONE batched transfer for every shard of every flat input (instead
        # of a device_put launch per shard), then metadata-only global
        # array assembly
        moved = jax.device_put(
            srcs, [[mesh.devices[i] for i in range(n)] for _ in srcs])
        flat = [
            jax.make_array_from_single_device_arrays(
                (n * size,), NamedSharding(mesh, P(_AXIS)), shards)
            for shards, size in zip(moved, sizes)]

        outs = prog(*flat)

        def shards_of(garr):
            by_dev = {s.device: s.data for s in garr.addressable_shards}
            return [by_dev[mesh.devices[i]] for i in range(n)]

        i = 0
        kd_shards = [shards_of(outs[i + j]) for j in range(nk)]; i += nk
        kv_shards = [shards_of(outs[i + j]) for j in range(nk)]; i += nk
        data_shards = [shards_of(outs[i + j]) for j in range(len(final_sig))]
        i += len(final_sig)
        valid_shards: list = []
        for fn, _, _ in final_sig:
            if fn == "count":
                valid_shards.append(None)
            else:
                valid_shards.append(shards_of(outs[i])); i += 1
        live_shards = shards_of(outs[i])

        fin = spec.final
        for t in range(n):
            cols = []
            for j in range(nk):
                cols.append(Column(fin.output_types[j], kd_shards[j][t],
                                   kv_shards[j][t], merged_dicts[j]))
            for a in range(len(final_sig)):
                cols.append(Column(
                    fin.output_types[nk + a], data_shards[a][t],
                    None if valid_shards[a] is None else valid_shards[a][t]))
            self._results[t] = ColumnBatch(list(fin.output_names), cols,
                                           live_shards[t])

    def _empty_state_host(self) -> dict:
        """Zero state for a task that saw no input (numpy: built outside
        any jit, moved by the make_global device_puts)."""
        spec = self.spec
        cap = spec.cap
        return {
            "kd": tuple(np.zeros(cap, t.storage_dtype)
                        for t in spec.key_types),
            "kv": tuple(np.zeros(cap, np.bool_) for _ in spec.key_types),
            "sd": tuple(np.zeros(cap, np.dtype(s.dtype))
                        for s in spec.flat_states),
            "sv": tuple(np.zeros(cap, np.bool_) if s.has_valid else None
                        for s in spec.flat_states),
            "used": np.zeros(cap, np.bool_),
        }

    # ------------------------------------------------------------- consumers
    def take(self, task_index: int,
             timeout: Optional[float] = None) -> ColumnBatch:
        """Blocking take with the PR-5 timeout policy: default from
        TRINO_TPU_EXCHANGE_STALL_S, stall raises a retryable
        PAGE_TRANSPORT_TIMEOUT (same contract as CollectiveRepartitionExchange
        and the HTTP exchange client)."""
        if timeout is None:
            from .task import STALL_TIMEOUT_S

            timeout = STALL_TIMEOUT_S
        from ..telemetry import profiler

        t0 = profiler.now() if profiler.enabled() else 0.0
        ok = self._done.wait(timeout)
        if t0:
            profiler.event(
                profiler.EXCHANGE,
                f"fused-take[f{self.spec.producer_fid}->"
                f"f{self.spec.consumer_fid}]", t0, stalled=not ok)
        if not ok:
            raise TrinoError(
                PAGE_TRANSPORT_TIMEOUT,
                f"fused stage seam f{self.spec.producer_fid}->"
                f"f{self.spec.consumer_fid} stalled after {timeout:.0f}s")
        if self._error is not None:
            if isinstance(self._error, (FusedStageOverflow, TrinoError)):
                raise self._error
            raise TrinoError(
                GENERIC_INTERNAL_ERROR,
                f"fused stage failed: {self._error}") from self._error
        return self._results[task_index]


class FusedStageSinkOperator(Operator):
    """Producer-side terminal of a fused stage: absorbs the feed's device
    batches with ONE jitted accumulate call each (SyncGuard hot region —
    zero host syncs), checks the overflow scalar once at finish, then
    deposits the carried state into the seam rendezvous."""

    def __init__(self, exchange: FusedStageExec, task_index: int):
        self.exchange = exchange
        self.task_index = task_index
        self.spec = exchange.spec
        self._prog: Optional[_AccumulateProgram] = None
        self._state: Optional[dict] = None
        self._key_dicts: Optional[list] = None
        self._remap_cache: dict = {}
        self.stats = FusedStageStats()
        self.pending_errors: list = []

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_rows == 0:
            return
        from ..telemetry import profiler

        t0 = profiler.now() if profiler.enabled() else 0.0
        with SG.hot_region():
            self._accumulate(batch)
        if t0:
            profiler.event(
                profiler.FUSED,
                f"fused-accumulate[f{self.spec.producer_fid}]", t0,
                rows=batch.num_rows)

    def _accumulate(self, batch: ColumnBatch) -> None:
        spec = self.spec
        raw_n = batch.num_rows
        # a live-carrying batch is already bucket-shaped (jitted pipeline
        # output) — same pass-through rule as spi.batch.pad_to_bucket
        n = raw_n if batch.live is not None else K.bucket(raw_n)
        in_types = [c.type for c in batch.columns]
        in_dicts = [c.dictionary for c in batch.columns]
        prog = _accumulate_program(spec, in_types, in_dicts)
        if self._state is None:
            self._state = prog.initial_state()
            self._key_dicts = list(prog.key_dicts)
        # dictionary drift: lift carried-state codes and batch codes into a
        # merged dictionary before the (donated) state combine
        batch_remaps: list = [None] * spec.nk
        state_remaps: list = [None] * spec.nk
        for j in range(spec.nk):
            bd, cur = prog.key_dicts[j], self._key_dicts[j]
            if bd is None or cur is None or bd is cur:
                continue
            ck = (id(bd), id(cur))
            hit = self._remap_cache.get(ck)
            if hit is None:
                if bd.shape == cur.shape and (bd == cur).all():
                    hit = (None, None, cur)
                else:
                    merged = np.unique(np.concatenate([cur, bd]))
                    hit = (_pad_table(np.searchsorted(merged, bd)),
                           _pad_table(np.searchsorted(merged, cur)), merged)
                self._remap_cache[ck] = hit
            batch_remaps[j], state_remaps[j], merged = hit
            self._key_dicts[j] = merged
        ingest = _ingest_program(
            n, tuple(c.valid is None for c in batch.columns),
            batch.live is not None)
        cols, live = ingest(
            tuple((c.data, c.valid) for c in batch.columns), batch.live)
        sig = (id(prog), raw_n, n,
               tuple(None if r is None else len(r) for r in batch_remaps),
               tuple(None if r is None else len(r) for r in state_remaps))
        with _ACCUM_LOCK:
            if sig in _TRACE_SIGS:
                fresh = False
                self.stats.cache_hits += 1
            else:
                fresh = True
                _TRACE_SIGS.add(sig)
                self.stats.compiles += 1
        if fresh:
            # a fresh (prog, shape-bucket) signature means this call traces
            # + compiles; its wall time goes to the compile histogram
            import time as _time

            from ..telemetry import metrics as tm

            t0 = _time.perf_counter()
            self._state = prog(self._state, cols, live,
                               tuple(batch_remaps), tuple(state_remaps))
            tm.FUSED_COMPILES.inc()
            tm.FUSED_COMPILE_SECONDS.record(_time.perf_counter() - t0)
        else:
            self._state = prog(self._state, cols, live,
                               tuple(batch_remaps), tuple(state_remaps))
        self._prog = prog
        self.stats.jit_calls += 1
        self.stats.batches += 1
        self.stats.input_rows += n

    def finish_input(self) -> None:
        super().finish_input()
        if self._state is not None:
            # the one data-dependent scalar of the stage, pulled OUTSIDE the
            # hot region, once per task (not per batch)
            ovf = int(SG.fetch(self._state["ovf"], "fused.overflow"))
            if ovf > self.spec.cap:
                raise FusedStageOverflow(
                    f"fused stage f{self.spec.producer_fid}: {ovf} groups "
                    f"exceed the {self.spec.cap}-slot state "
                    f"(TRINO_TPU_FUSED_CAP); falling back to the legacy path")
            self.pending_errors.append(self._state["err"])
        self.exchange.deposit(self.task_index, self._state, self._key_dicts,
                              self.stats)

    def is_finished(self) -> bool:
        return self.input_done


def _pad_table(t: np.ndarray) -> np.ndarray:
    t = t.astype(np.int32)
    R = K.bucket(max(len(t), 1))
    return np.concatenate([t, np.zeros(R - len(t), np.int32)])


class FusedStageSourceOperator(Operator):
    """Consumer-side source: emits this task's device shard of the fused
    FINAL aggregation once (replaces RemoteSource + HashAggregation(FINAL)
    in the consumer pipeline)."""

    blocking = True  # see RemoteExchangeSourceOperator

    def __init__(self, exchange: FusedStageExec, task_index: int):
        self.exchange = exchange
        self.task_index = task_index
        self.input_done = True
        self._emitted = False

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[ColumnBatch]:
        if self._emitted or self._closed:
            return None
        if not self.blocking and not self.exchange._done.is_set():
            return None  # park; the executor reschedules us
        self._emitted = True
        batch = self.exchange.take(self.task_index)
        return batch if batch.num_rows else None

    def is_finished(self) -> bool:
        return self._emitted or self._closed
