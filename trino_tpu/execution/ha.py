"""Horizontally-scaled HA control plane: the coordinator fleet.

PR 15 made ONE coordinator restartable: the per-query write-ahead state
log (execution/query_state.py) lets a rebooted process resume in-flight
``retry_policy="TASK"`` queries under their original ids.  This module
makes the control plane *horizontal* — the production shape of the
reference's dispatcher/coordinator split (dispatcher/
QueuedStatementResource behind ``POST /v1/statement``):

- **Cluster directory** (``TRINO_TPU_HA_DIR``): every coordinator
  registers a lease file ``coordinators/<node>.json`` renewed by a
  heartbeat thread.  A lease not renewed within
  ``TRINO_TPU_HA_LEASE_TTL_S`` is dead, and any peer may claim it.
- **Consistent-hash ownership**: query ids map to coordinators by
  rendezvous (highest-random-weight) hashing — removing a member remaps
  ONLY that member's queries, so a failover never reshuffles the healthy
  fleet.  The stateless front tier (server/front_tier.py) routes by the
  same function.
- **Lease-based failover**: each coordinator watches for expired leases.
  The claim primitive is one atomic ``os.rename`` of the dead lease file
  into ``claims/`` — exactly one racing peer wins — after which the winner
  renames the dead coordinator's WAL directory into its own custody and
  adopts every in-flight query in it through the PR 15 recovery machinery
  (``query_state.pending`` → dispatcher adopt → ``resume_fte_query``),
  cross-process: committed attempts are never re-executed, and clients
  polling the original query id through the front tier never notice.
- **Elastic worker autoscaling**: :class:`WorkerAutoscaler` watches the
  ``trino_admission_queued_seconds`` distribution and the cluster memory
  gauges and grows the worker fleet, or drains one worker at a time
  through the zero-loss ``PUT /v1/shutdown`` protocol (PR 9), between a
  configured floor and ceiling.

Everything is behind ``TRINO_TPU_HA`` (default 0 = bit-for-bit
single-coordinator legacy: no lease files, no threads, no directory I/O).
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import threading
import time
from typing import Callable, Optional

__all__ = [
    "ha_enabled", "ha_dir", "node_id", "lease_ttl_s", "heartbeat_s",
    "coordinators_dir", "claims_dir", "wal_root", "node_wal_dir",
    "CoordinatorInfo", "read_members", "live_members", "owner_of",
    "CoordinatorLease", "claim_dead", "claimed_wal_dirs", "HACoordinator",
    "WorkerAutoscaler",
]


# --------------------------------------------------------------- knobs

def ha_enabled() -> bool:
    from ..spi.knobs import get_bool

    return get_bool("TRINO_TPU_HA")


def ha_dir() -> str:
    from ..spi.knobs import get_str

    return get_str("TRINO_TPU_HA_DIR")


def node_id() -> str:
    from ..spi.knobs import get_str

    nid = get_str("TRINO_TPU_HA_NODE_ID").strip()
    if nid:
        return nid
    return f"coord-{socket.gethostname()}-{os.getpid()}"


def lease_ttl_s() -> float:
    from ..spi.knobs import get_float

    return get_float("TRINO_TPU_HA_LEASE_TTL_S") or 10.0


def heartbeat_s() -> float:
    from ..spi.knobs import get_float

    return get_float("TRINO_TPU_HA_HEARTBEAT_S") or 2.0


# -------------------------------------------------------------- layout

def coordinators_dir(root: Optional[str] = None) -> str:
    return os.path.join(root or ha_dir(), "coordinators")


def claims_dir(root: Optional[str] = None) -> str:
    return os.path.join(root or ha_dir(), "claims")


def wal_root(root: Optional[str] = None) -> str:
    return os.path.join(root or ha_dir(), "wal")


def node_wal_dir(nid: str, root: Optional[str] = None) -> str:
    return os.path.join(wal_root(root), nid)


def _lease_path(nid: str, root: Optional[str] = None) -> str:
    return os.path.join(coordinators_dir(root), nid + ".json")


# ----------------------------------------------------------- directory

class CoordinatorInfo:
    """One parsed lease file."""

    __slots__ = ("node_id", "url", "pid", "epoch", "ts", "state",
                 "in_flight", "age_s")

    def __init__(self, node_id: str, url: str = "", pid: int = 0,
                 epoch: float = 0.0, ts: float = 0.0, state: str = "ACTIVE",
                 in_flight: int = 0, age_s: float = 0.0):
        self.node_id = node_id
        self.url = url
        self.pid = pid
        self.epoch = epoch
        self.ts = ts
        self.state = state
        self.in_flight = in_flight
        self.age_s = age_s


def read_members(root: Optional[str] = None,
                 ttl: Optional[float] = None) -> list[CoordinatorInfo]:
    """Every registered coordinator, lease-age annotated; ``state`` becomes
    ``EXPIRED`` past the TTL.  Sorted by node id for determinism."""
    d = coordinators_dir(root)
    ttl = lease_ttl_s() if ttl is None else ttl
    now = time.time()
    out = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return []
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(d, name)
        try:
            with open(path, encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue  # torn write or concurrent claim: skip this round
        info = CoordinatorInfo(
            node_id=rec.get("node_id", name[:-len(".json")]),
            url=rec.get("url", ""), pid=int(rec.get("pid", 0) or 0),
            epoch=float(rec.get("epoch", 0.0) or 0.0),
            ts=float(rec.get("ts", 0.0) or 0.0),
            state=rec.get("state", "ACTIVE"),
            in_flight=int(rec.get("in_flight", 0) or 0))
        info.age_s = max(0.0, now - info.ts)
        if info.state == "ACTIVE" and info.age_s > ttl:
            info.state = "EXPIRED"
        out.append(info)
    return out


def live_members(root: Optional[str] = None,
                 ttl: Optional[float] = None) -> list[CoordinatorInfo]:
    return [m for m in read_members(root, ttl) if m.state == "ACTIVE"]


def owner_of(key: str, member_ids: list[str]) -> Optional[str]:
    """Rendezvous-hash owner of ``key`` among ``member_ids``: every party
    (front tier, every coordinator) computes the same owner from the same
    membership, with no shared ring state to repair on failover."""
    if not member_ids:
        return None
    return max(
        member_ids,
        key=lambda m: hashlib.sha256(
            f"{m}|{key}".encode("utf-8")).digest())


# --------------------------------------------------------------- lease

class CoordinatorLease:
    """This coordinator's heartbeated lease file.

    ``register()`` writes the lease (atomic tmp+rename) and starts the
    renewal thread.  A renewal that finds the file missing, or carrying a
    different epoch, means a peer claimed us while we were wedged — the
    lease flips ``deposed`` and stops renewing, so a zombie coordinator
    can never resurrect its lease and fight its successor for queries."""

    def __init__(self, nid: Optional[str] = None, url: str = "",
                 root: Optional[str] = None,
                 ttl: Optional[float] = None,
                 interval: Optional[float] = None,
                 info_fn: Optional[Callable[[], dict]] = None):
        self.node_id = nid or node_id()
        self.url = url
        self.root = root or ha_dir()
        self.ttl = lease_ttl_s() if ttl is None else ttl
        self.interval = heartbeat_s() if interval is None else interval
        self.epoch = time.time()
        self.path = _lease_path(self.node_id, self.root)
        self.deposed = False
        self._info_fn = info_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _payload(self) -> dict:
        rec = {
            "node_id": self.node_id, "url": self.url, "pid": os.getpid(),
            "epoch": self.epoch, "ts": time.time(), "state": "ACTIVE",
        }
        if self._info_fn is not None:
            try:
                rec.update(self._info_fn())
            # tpulint: disable=error-taxonomy -- optional enrichment must never kill the heartbeat
            except Exception:
                pass
        return rec

    def _write(self) -> None:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + f".tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self._payload(), f)
        os.replace(tmp, self.path)

    def register(self) -> "CoordinatorLease":
        self._write()
        self._thread = threading.Thread(
            target=self._run, name=f"ha-lease-{self.node_id}", daemon=True)
        self._thread.start()
        return self

    def renew(self) -> bool:
        """One renewal; False (and ``deposed``) when the lease was claimed
        out from under us."""
        if self.deposed:
            return False
        try:
            with open(self.path, encoding="utf-8") as f:
                rec = json.load(f)
            if float(rec.get("epoch", 0.0) or 0.0) != self.epoch:
                self.deposed = True
                return False
        except OSError:
            # lease file gone: a peer claimed it (rename) — we are deposed
            self.deposed = True
            return False
        except ValueError:
            pass  # torn concurrent read of our own write: rewrite below
        self._write()
        return True

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            if not self.renew():
                break

    def release(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if not self.deposed:
            try:
                os.remove(self.path)
            except OSError:
                pass


# ------------------------------------------------------------ failover

def claim_dead(claimant: str, root: Optional[str] = None,
               ttl: Optional[float] = None) -> list[tuple[str, str]]:
    """Claim every expired peer lease.  Returns ``(dead_node_id,
    claimed_wal_dir)`` per win (claimed_wal_dir may not exist if the dead
    coordinator never ran an FTE query).

    The atomic primitive is ``os.rename`` of the lease file into
    ``claims/``: of N racing peers exactly one rename succeeds, the rest
    get ENOENT and walk away.  Only the winner then renames the dead WAL
    directory into its custody (``<wal>/<dead>.claimed-<claimant>``), so a
    restarted dead coordinator boots with an empty WAL dir and cannot
    double-resume queries its successor already owns."""
    root = root or ha_dir()
    ttl = lease_ttl_s() if ttl is None else ttl
    wins = []
    for m in read_members(root, ttl):
        if m.node_id == claimant or m.state != "EXPIRED":
            continue
        cdir = claims_dir(root)
        os.makedirs(cdir, exist_ok=True)
        claim = os.path.join(
            cdir, f"{m.node_id}-{m.epoch:.6f}.lease")
        try:
            os.rename(_lease_path(m.node_id, root), claim)
        except OSError:
            continue  # a peer won the race (or the lease re-appeared)
        src = node_wal_dir(m.node_id, root)
        dst = src + f".claimed-{claimant}-{m.epoch:.6f}"
        try:
            os.rename(src, dst)
        except OSError:
            dst = ""  # no WAL dir: nothing in flight to adopt
        wins.append((m.node_id, dst))
    return wins


def claimed_wal_dirs(claimant: str,
                     root: Optional[str] = None) -> list[str]:
    """WAL directories this claimant has custody of (boot-time re-scan: a
    claimant that crashed mid-adoption re-adopts from its claimed dirs)."""
    marker = f".claimed-{claimant}-"
    try:
        names = sorted(os.listdir(wal_root(root)))
    except OSError:
        return []
    return [os.path.join(wal_root(root), n) for n in names if marker in n]


class HACoordinator:
    """One fleet member: lease + failover watcher around a running
    :class:`~trino_tpu.server.protocol.TrinoTpuServer`.

    Boot order matters: the server's dispatcher first recovers this node's
    OWN WAL dir (the PR 15 restart path — the child process points
    ``TRINO_TPU_QUERY_STATE_DIR`` at ``<ha>/wal/<node>``), then the lease
    registers, then the watcher starts claiming dead peers."""

    def __init__(self, server, nid: Optional[str] = None,
                 root: Optional[str] = None,
                 ttl: Optional[float] = None,
                 interval: Optional[float] = None):
        self.server = server
        self.node_id = nid or node_id()
        self.root = root or ha_dir()
        self.ttl = lease_ttl_s() if ttl is None else ttl
        self.interval = heartbeat_s() if interval is None else interval
        host, port = server.address
        self.lease = CoordinatorLease(
            self.node_id, url=f"http://{host}:{port}", root=self.root,
            ttl=self.ttl, interval=self.interval, info_fn=self._lease_info)
        self.takeovers: list[str] = []
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None

    def _lease_info(self) -> dict:
        return {"in_flight": self.server.dispatcher.in_flight()}

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "HACoordinator":
        from ..telemetry import metrics as tm

        # custody from a previous generation of THIS node (claimant crash
        # mid-adoption): re-adopt before accepting new work
        for d in claimed_wal_dirs(self.node_id, self.root):
            self._adopt_dir(d)
        self.lease.register()
        tm.HA_LEASES_HELD.set(1)
        self._watcher = threading.Thread(
            target=self._watch, name=f"ha-watch-{self.node_id}",
            daemon=True)
        self._watcher.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=5)
        self.lease.release()

    # ------------------------------------------------------------- failover
    def _watch(self) -> None:
        from ..telemetry import metrics as tm

        while not self._stop.wait(self.interval):
            if self.lease.deposed:
                break
            try:
                tm.HA_FLEET_COORDINATORS.set(
                    len(live_members(self.root, self.ttl)))
                self.step()
            # tpulint: disable=error-taxonomy -- the watcher must survive any one bad round
            except Exception:
                pass

    def step(self) -> list[str]:
        """One failover round (exposed for deterministic tests): claim
        expired peers, adopt their in-flight queries.  Returns the node
        ids claimed this round."""
        from ..telemetry import metrics as tm

        claimed = []
        for dead, wal_dir in claim_dead(self.node_id, self.root, self.ttl):
            tm.HA_TAKEOVERS.inc()
            self.takeovers.append(dead)
            claimed.append(dead)
            if wal_dir:
                self._adopt_dir(wal_dir)
        if claimed:
            tm.HA_LEASES_HELD.set(1 + len(self.takeovers))
        return claimed

    def _adopt_dir(self, wal_dir: str) -> None:
        from ..telemetry import metrics as tm
        from . import query_state

        try:
            query_state.prune_ended(wal_dir)
        except OSError:
            pass
        for pq in query_state.pending(wal_dir):
            if self.server.dispatcher.adopt(pq):
                tm.HA_ADOPTED_QUERIES.inc()


# ----------------------------------------------------------- autoscaler

class WorkerAutoscaler:
    """Elastic worker fleet controller.

    Each round reads the pressure signals — admission queued-seconds
    accumulated since the previous round (the
    ``trino_admission_queued_seconds`` distribution) and the cluster
    memory gauges — and applies at most one action:

    - **pressure** and below the ceiling → grow the fleet by one worker
      (``runner.add_worker()``, or restore a slot this controller drained
      on the in-process runner);
    - **no pressure** for ``idle_rounds`` consecutive rounds and above the
      floor → drain one worker through the zero-loss ``PUT /v1/shutdown``
      protocol (``runner.remove_worker()`` / logical drain in-process).

    One action per round keeps the loop stable (no flapping between
    observations of the same backlog)."""

    def __init__(self, runner, min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 queue_s: Optional[float] = None,
                 idle_rounds: Optional[int] = None,
                 interval_s: Optional[float] = None,
                 low_memory_frac: float = 0.1,
                 on_scale: Optional[Callable[[str, int], None]] = None):
        from ..spi import knobs

        self.runner = runner
        self.min_workers = (knobs.get_int("TRINO_TPU_AUTOSCALE_MIN_WORKERS")
                            or 1) if min_workers is None else min_workers
        self.max_workers = (knobs.get_int("TRINO_TPU_AUTOSCALE_MAX_WORKERS")
                            or 4) if max_workers is None else max_workers
        self.queue_s = (knobs.get_float("TRINO_TPU_AUTOSCALE_QUEUE_S")
                        or 0.5) if queue_s is None else queue_s
        self.idle_rounds = (knobs.get_int("TRINO_TPU_AUTOSCALE_IDLE_ROUNDS")
                            or 3) if idle_rounds is None else idle_rounds
        self.interval_s = (knobs.get_float("TRINO_TPU_AUTOSCALE_INTERVAL_S")
                           or 5.0) if interval_s is None else interval_s
        self.low_memory_frac = low_memory_frac
        self.on_scale = on_scale
        self.events: list[tuple] = []
        self._idle = 0
        self._drained: list[str] = []  # in-process logical drains to undo
        self._last_queued_sum = self._queued_sum()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -------------------------------------------------------------- signals
    @staticmethod
    def _queued_sum() -> float:
        from ..telemetry import metrics as tm

        return float(tm.ADMISSION_QUEUED_SECONDS.snapshot()["sum"])

    def queued_delta(self) -> float:
        now = self._queued_sum()
        delta = max(0.0, now - self._last_queued_sum)
        self._last_queued_sum = now
        return delta

    def memory_low(self) -> bool:
        mm = getattr(self.runner, "memory_manager", None)
        cap = getattr(mm, "capacity_bytes", None)
        if not cap:
            return False
        free = mm.cluster_free_bytes()
        return free <= cap * self.low_memory_frac

    def worker_count(self) -> int:
        workers = getattr(self.runner, "workers", None)
        if workers is not None:  # process runner: live processes
            return sum(1 for w in workers if w.alive())
        return int(self.runner.active_worker_count)

    # -------------------------------------------------------------- actions
    def _scale_up(self) -> bool:
        if self._drained and hasattr(self.runner, "restore_worker"):
            self.runner.restore_worker(self._drained.pop())
            return True
        add = getattr(self.runner, "add_worker", None)
        if add is None:
            return False
        add()
        return True

    def _scale_down(self) -> bool:
        remove = getattr(self.runner, "remove_worker", None)
        if remove is not None:
            return remove() is not None
        # in-process runner: logical drain of the highest live slot
        nodes = getattr(self.runner, "nodes", None)
        if nodes is None:
            return False
        active = [n for n in nodes.active_workers()
                  if n not in self._drained]
        if not active:
            return False
        victim = sorted(active)[-1]
        self.runner.drain_worker(victim)
        self._drained.append(victim)
        return True

    # --------------------------------------------------------------- policy
    def step(self, queued_delta_s: Optional[float] = None) -> Optional[str]:
        """One controller round; returns \"up\", \"down\", or None."""
        from ..telemetry import metrics as tm

        with self._lock:
            delta = (self.queued_delta() if queued_delta_s is None
                     else queued_delta_s)
            pressure = delta >= self.queue_s or self.memory_low()
            count = self.worker_count()
            if pressure:
                self._idle = 0
                if count < self.max_workers and self._scale_up():
                    tm.HA_AUTOSCALE_EVENTS.inc()
                    self.events.append(("up", count + 1, round(delta, 4)))
                    if self.on_scale is not None:
                        self.on_scale("up", count + 1)
                    return "up"
                return None
            self._idle += 1
            if self._idle >= self.idle_rounds and count > self.min_workers:
                if self._scale_down():
                    self._idle = 0
                    tm.HA_AUTOSCALE_EVENTS.inc()
                    self.events.append(("down", count - 1, round(delta, 4)))
                    if self.on_scale is not None:
                        self.on_scale("down", count - 1)
                    return "down"
            return None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "WorkerAutoscaler":
        self._thread = threading.Thread(
            target=self._run, name="ha-autoscaler", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            # tpulint: disable=error-taxonomy -- the controller must survive any one bad round
            except Exception:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
