"""Tracing: per-query span trees (the OpenTelemetry role).

Mirrors the reference's tracing layer (tracing/TracingMetadata.java:121
decorators, tracing/TrinoAttributes.java span vocabulary, spans per
query/stage/task propagated into workers) without the OTel SDK dependency:
spans are plain objects collected per query; an exporter hook receives
finished root spans (plug an OTLP exporter there in a deployment).  The
attribute names follow the reference's ``trino.*`` vocabulary."""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = ["Span", "Tracer", "traceparent", "parse_traceparent",
           "annotate_scan_span", "annotate_sync_span",
           "annotate_resilience_span", "annotate_fused_span",
           "annotate_resident_span"]


def _new_trace_id() -> str:
    return uuid.uuid4().hex  # 32 hex chars (the W3C trace-id width)


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]  # 16 hex chars (the W3C span-id width)


def traceparent(span: "Span") -> str:
    """W3C-traceparent-style header value for propagating ``span`` as the
    remote parent across the HTTP plane (reference:
    tracing/TracingMetadata.java:121 injecting context into task calls)."""
    if not span.trace_id:
        span.trace_id = _new_trace_id()
    if not span.span_id:
        span.span_id = _new_span_id()
    return f"00-{span.trace_id}-{span.span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[tuple[str, str]]:
    """``"00-<trace>-<span>-01"`` -> (trace_id, parent_span_id), or None on
    anything malformed (propagation is best-effort, never a failure)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    return parts[1], parts[2]


def annotate_fused_span(span: "Span", fs) -> None:
    """Set the ``trino.fused.*`` attributes from a FusedStageStats roll-up
    (exec/stats.py): whole-stage compile counts, shape-bucket cache hits and
    per-batch dispatch counts next to the query wall time."""
    if fs is None or not fs.any:
        return
    span.set("trino.fused.stages", fs.stages)
    span.set("trino.fused.batches", fs.batches)
    span.set("trino.fused.input-rows", fs.input_rows)
    span.set("trino.fused.jit-calls", fs.jit_calls)
    span.set("trino.fused.compiles", fs.compiles)
    span.set("trino.fused.cache-hits", fs.cache_hits)
    span.set("trino.fused.seam-merges", fs.merges)
    span.set("trino.fused.fallbacks", fs.fallbacks)


def annotate_resident_span(span: "Span", rs) -> None:
    """Set the ``trino.resident.*`` attributes from a ResidentPlanStats
    roll-up (exec/stats.py): whole-plan program counts, in-program seam
    fusion and the launches/batch figure next to the query wall time."""
    if rs is None or not rs.any:
        return
    span.set("trino.resident.plans", rs.plans)
    span.set("trino.resident.seams", rs.seams)
    span.set("trino.resident.batches", rs.batches)
    span.set("trino.resident.input-rows", rs.input_rows)
    span.set("trino.resident.jit-calls", rs.jit_calls)
    span.set("trino.resident.programs", rs.programs)
    span.set("trino.resident.cache-hits", rs.cache_hits)
    span.set("trino.resident.launches-per-batch",
             round(rs.launches_per_batch, 3))
    span.set("trino.resident.code-seam-columns", rs.code_seam_columns)
    span.set("trino.resident.merges", rs.merges)
    span.set("trino.resident.fallbacks", rs.fallbacks)


def annotate_resilience_span(span: "Span", res) -> None:
    """Set the ``trino.exec.*`` resilience attributes from a ResilienceStats
    delta (exec/stats.py) so exporters see retries, backoff waits, worker
    replacements and heartbeat churn next to the query wall time."""
    if res is None or not res.any:
        return
    span.set("trino.exec.query-retries", res.query_retries)
    span.set("trino.exec.backoff-waits", res.backoff_waits)
    span.set("trino.exec.backoff-wait-ms", round(res.backoff_wait_s * 1e3, 1))
    span.set("trino.exec.blacklisted-workers", res.blacklisted_workers)
    span.set("trino.exec.worker-replacements", res.worker_replacements)
    span.set("trino.exec.heartbeat-transitions", res.heartbeat_transitions)
    span.set("trino.exec.exchange-fetch-failures", res.exchange_fetch_failures)
    span.set("trino.exec.exchange-backoff-trips", res.exchange_backoff_trips)


def annotate_sync_span(span: "Span", sync) -> None:
    """Set the ``trino.exec.*`` host-transfer attributes from a SyncGuard
    SyncStats delta (exec/syncguard.py), so exporters see how many times the
    operator hot path crossed the device boundary next to the wall time."""
    if sync is None or not sync.host_syncs:
        return
    span.set("trino.exec.host-syncs", sync.host_syncs)
    span.set("trino.exec.blocking-syncs", sync.blocking_syncs)
    span.set("trino.exec.hot-loop-syncs", sync.hot_loop_syncs)
    span.set("trino.exec.async-polls", sync.async_polls)
    span.set("trino.exec.async-poll-hits", sync.poll_hits)
    span.set("trino.exec.expand-overflows", sync.expand_overflows)
    span.set("trino.exec.expand-retries", sync.expand_retries)


def annotate_scan_span(span: "Span", ingest) -> None:
    """Set the ``trino.scan.*`` attributes from a ScanIngestStats roll-up
    (exec/stats.py) on an execution span, so exporters see scan throughput,
    queue depth and transfer/compute overlap next to the wall time."""
    if ingest is None or not ingest.scan_batches:
        return
    span.set("trino.scan.bytes", ingest.scan_bytes)
    span.set("trino.scan.rows", ingest.scan_rows)
    span.set("trino.scan.batches", ingest.scan_batches)
    span.set("trino.scan.coalesced-batches", ingest.coalesced_batches)
    span.set("trino.scan.gb-per-s", round(ingest.gbps, 3))
    span.set("trino.scan.queue-depth-avg", round(ingest.queue_depth_avg, 2))
    span.set("trino.scan.queue-depth-max", ingest.queue_depth_max)
    span.set("trino.scan.source-read-ms", round(ingest.source_read_s * 1e3, 1))
    span.set("trino.scan.consumer-wait-ms",
             round(ingest.consumer_wait_s * 1e3, 1))
    span.set("trino.scan.stage-ms", round(ingest.stage_s * 1e3, 1))
    span.set("trino.scan.prefetch", ingest.prefetch_enabled)


@dataclass
class Span:
    name: str
    attributes: dict = field(default_factory=dict)
    start: float = 0.0
    end: Optional[float] = None
    children: list["Span"] = field(default_factory=list)
    # distributed identity: trace_id is shared by the whole query tree,
    # parent_id links a child to its parent across process boundaries
    trace_id: str = ""
    span_id: str = ""
    parent_id: Optional[str] = None

    @property
    def duration_ms(self) -> float:
        return ((self.end or time.perf_counter()) - self.start) * 1e3

    def set(self, key: str, value) -> "Span":
        self.attributes[key] = value
        return self

    def text(self, indent: int = 0) -> str:
        attrs = " ".join(f"{k}={v}" for k, v in self.attributes.items())
        lines = ["  " * indent
                 + f"- {self.name} {self.duration_ms:.1f}ms"
                 + (f" [{attrs}]" if attrs else "")]
        for c in self.children:
            lines.append(c.text(indent + 1))
        return "\n".join(lines)

    def to_dict(self) -> dict:
        """JSON-safe subtree for shipping finished spans across processes
        (worker -> coordinator with task completion).  Durations travel as
        milliseconds: perf_counter timestamps are not comparable across
        processes, so absolute start/end stay process-local."""
        return {
            "name": self.name,
            "attributes": dict(self.attributes),
            "duration_ms": self.duration_ms,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "children": [c.to_dict() for c in self.children],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        s = cls(d["name"], dict(d.get("attributes", {})),
                start=0.0, end=d.get("duration_ms", 0.0) / 1e3,
                trace_id=d.get("trace_id", ""),
                span_id=d.get("span_id", ""),
                parent_id=d.get("parent_id"))
        s.children = [cls.from_dict(c) for c in d.get("children", [])]
        return s


class _SpanCtx:
    def __init__(self, tracer: "Tracer", span: Span):
        self.tracer = tracer
        self.span = span

    def __enter__(self) -> Span:
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        self.span.end = time.perf_counter()
        if exc is not None:
            self.span.set("error", type(exc).__name__)
        self.tracer._pop(self.span)


class Tracer:
    """Thread-aware span collector.  ``span(name)`` nests under the current
    thread's open span; finished ROOT spans go to ``exporter`` and the
    bounded ``finished`` ring (introspection / tests)."""

    def __init__(self, exporter: Optional[Callable[[Span], None]] = None,
                 keep: int = 50):
        self._local = threading.local()
        self._exporter = exporter
        # deque(maxlen=keep): O(1) ring eviction (list.pop(0) was O(n) per
        # finished root, under the lock)
        self.finished: deque = deque(maxlen=keep)
        self._lock = threading.Lock()

    def _stack(self) -> list:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    def span(self, name: str, parent: Optional[Span] = None,
             remote: Optional[tuple[str, str]] = None,
             **attributes) -> _SpanCtx:
        """Open a span.  Default parenting is the current thread's open
        span.  ``parent=`` attaches to an explicit span on ANOTHER thread
        (task threads nesting under the query span).  ``remote=`` is a
        (trace_id, parent_span_id) pair from ``parse_traceparent``: the
        span becomes a local root carrying the remote identity, so the
        coordinator can re-attach the shipped subtree."""
        s = Span(name, dict(attributes), time.perf_counter(),
                 span_id=_new_span_id())
        stack = self._stack()
        if parent is not None:
            if not parent.trace_id:
                parent.trace_id = _new_trace_id()
            if not parent.span_id:
                parent.span_id = _new_span_id()
            s.trace_id = parent.trace_id
            s.parent_id = parent.span_id
            parent.children.append(s)  # list.append: thread-safe
        elif remote is not None:
            s.trace_id, s.parent_id = remote
            s._remote_root = True
        elif stack:
            s.trace_id = stack[-1].trace_id
            s.parent_id = stack[-1].span_id
            stack[-1].children.append(s)
        else:
            s.trace_id = _new_trace_id()
        stack.append(s)
        return _SpanCtx(self, s)

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def _pop(self, span: Span) -> None:
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        if stack:
            return
        # the thread's outermost span closed.  A span attached to an
        # explicit cross-thread parent is NOT a root (it already lives in
        # its parent's subtree); remote-parented spans ARE local roots.
        if span.parent_id is not None and \
                not getattr(span, "_remote_root", False):
            return
        with self._lock:
            self.finished.append(span)
        if self._exporter is not None:
            self._exporter(span)
