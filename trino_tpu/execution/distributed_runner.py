"""DistributedQueryRunner: coordinator + N workers in one process.

The reference's central testing trick (testing/trino-testing/.../
DistributedQueryRunner.java:101 boots a real coordinator + N workers in one
JVM) and its pipelined scheduler in miniature (execution/scheduler/
PipelinedQueryScheduler.java:157 all-at-once stage activation): every
fragment is scheduled as ``task_count`` concurrent tasks up front; tasks
stream pages to each other through pull-token OutputBuffers; the root
(OUTPUT) fragment's buffer feeds the client.

Task threads model worker task executors (a thread per task stands in for
TimeSharingTaskExecutor quanta; numpy/XLA release the GIL in the kernels,
so scans/joins on different tasks genuinely overlap).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..connectors.catalog import Catalog, default_catalog
from ..exec.driver import run_pipelines
from ..exec.local_planner import LocalPlanner
from ..exec.stats import QueryStats
from ..planner.add_exchanges import add_exchanges
from ..planner.logical import LogicalPlanner
from ..planner.optimizer import optimize
from ..planner.plan import PlanNode
from ..runner import QueryResult, Session, text_result
from ..spi.batch import Column, ColumnBatch
from ..sql import ast
from ..sql.parser import parse_statement
from .exchange import ExchangeClient, OutputBuffer
from .fragmenter import PlanFragment, SubPlan, fragment_plan
from .task import PartitionedOutputSink

__all__ = ["DistributedQueryRunner"]


@dataclass
class _Stage:
    fragment: PlanFragment
    task_count: int
    buffers: list[OutputBuffer]  # one per task


class DistributedQueryRunner:
    def __init__(self, catalog: Optional[Catalog] = None,
                 worker_count: int = 3,
                 session: Optional[Session] = None):
        from .control import HeartbeatFailureDetector, NodeManager
        from .resource_manager import (
            ClusterMemoryManager,
            build_dispatch_manager,
        )

        self.catalog = catalog if catalog is not None else default_catalog()
        self.worker_count = worker_count
        self.session = session if session is not None else Session(
            node_count=worker_count)
        # control plane: discovery (in-process workers announce at boot),
        # heartbeat-gated membership, resource-group admission + query FSM
        self.nodes = NodeManager()
        self.nodes.announce("coordinator", coordinator=True)
        for i in range(worker_count):
            self.nodes.announce(f"worker-{i}")
        self.failure_detector = HeartbeatFailureDetector(self.nodes)
        for i in range(worker_count):
            self.failure_detector.monitor(f"worker-{i}", lambda: True)
        # admission: the TRINO_TPU_RESOURCE_GROUPS tree when configured,
        # else the flat global group sized from the session knobs — plus the
        # coordinator's cluster memory view + low-memory killer
        self.dispatcher = build_dispatch_manager(self.session)
        self.memory_manager = ClusterMemoryManager()
        import itertools

        from ..spi.eventlistener import EventListenerManager
        from ..spi.security import AccessControlManager
        from .tracing import Tracer

        self.tracer = Tracer()
        self.event_listeners = EventListenerManager()
        self.access_control = AccessControlManager()
        self._qids = itertools.count(1)
        from ..telemetry import journal as _journal

        j = _journal.get_journal()
        if j is not None:
            self.event_listeners.add(j)
        # query-level resilience surface (retry_policy=QUERY): cumulative
        # counters + an append-only event log of retries / blacklists /
        # heartbeat transitions / replacements, shared with the process
        # runner's WorkerFailureDetector
        from ..exec.stats import ResilienceStats

        self.resilience = ResilienceStats()
        self.resilience_events: list = []
        # cross-query worker blacklist: per-query blacklists die with their
        # query, so a flaky worker would get a task from every new query —
        # this one is coordinator-held, TTL-decayed, and consulted by task
        # placement (remote) / speculation stats across queries
        from .speculation import ClusterBlacklist

        # persist=True: strikes are journaled (telemetry/journal.py) and the
        # TTL-decayed remainder re-seeds this blacklist after a restart —
        # a flaky worker does not get a clean slate from a coordinator bounce
        self.cluster_blacklist = ClusterBlacklist(
            ttl_s=self.session.blacklist_ttl_s,
            threshold=self.session.blacklist_threshold,
            persist=True)
        # cumulative speculation outcome counters (per-query details go to
        # resilience_events)
        self.speculative_starts = 0
        self.speculative_wins = 0
        # cumulative count of fused-stage overflow fallbacks (whole-stage
        # compilation re-running a subplan on the legacy per-operator path)
        self.fused_fallbacks = 0
        # cumulative count of resident-plan fallbacks (whole-query GSPMD
        # compilation bailing to the task-per-worker path: state overflow,
        # duplicate build keys, build failures)
        self.resident_fallbacks = 0
        # system catalog (connectors/system.py): bind this runner so
        # dispatcher-tracked query state shows up in system.runtime.queries
        sysconn = self.catalog._connectors.get("system")
        if sysconn is not None and hasattr(sysconn, "attach"):
            sysconn.attach(self)

    # ------------------------------------------------------------------ plan
    def create_plan(self, sql: str) -> PlanNode:
        return self._plan_stmt(parse_statement(sql))

    def _plan_stmt(self, stmt: ast.Statement) -> PlanNode:
        from ..runner import check_select_access

        with self.tracer.span("trino.planner"):
            plan = LogicalPlanner(
                self.catalog, self.session.default_catalog).plan(stmt)
            plan = optimize(plan, self.catalog)
        check_select_access(plan, self.access_control, self.session.user)
        writer_tasks = 1
        if self.session.scale_writers:
            writer_tasks = max(1, min(self.session.writer_task_limit,
                                      self.worker_count))
        return add_exchanges(plan, writer_tasks=writer_tasks)

    def create_subplan(self, sql: str) -> SubPlan:
        return fragment_plan(self.create_plan(sql))

    def explain(self, sql: str) -> str:
        return self.create_subplan(sql).text()

    # --------------------------------------------------------------- execute
    def execute(self, sql: str,
                query_id: Optional[str] = None) -> QueryResult:
        from ..runner import run_with_query_events

        return run_with_query_events(
            query_id or f"dq_{next(self._qids)}", sql, self.session.user,
            self.event_listeners, self.tracer, lambda: self._execute(sql))

    def profile(self, query_id: str) -> Optional[dict]:
        """Chrome trace_event JSON of a profiled query's merged
        coordinator+worker timeline, or None when unknown."""
        from ..telemetry import profiler

        return profiler.chrome_trace(query_id)

    def _execute(self, sql: str) -> QueryResult:
        from ..caching import plan_cache, result_cache
        from ..runner import check_ddl_access, check_select_access

        # Tier A fast path (see runner.py): a hit skips parse → analyze →
        # plan → optimize → add_exchanges; only statements that reached
        # _plan_stmt were ever stored, so non-SELECT texts always miss
        entry = plan_cache.lookup(sql, self.session, self.catalog,
                                  flavor="fragmented")
        if entry is not None:
            check_select_access(entry.plan, self.access_control,
                                self.session.user)
            versions = result_cache.version_vector(entry.tables,
                                                   self.catalog)
            key = result_cache.result_key(entry, versions)
            cached = result_cache.lookup(key)
            if cached is not None:
                return cached

            def run_cached(fsm):
                fsm.set("PLANNING")
                subplan = fragment_plan(plan_cache.clone(entry.plan))
                fsm.set("STARTING")
                fsm.set("RUNNING")
                out = self._execute_subplan(subplan, None)
                fsm.set("FINISHING")
                return out

            out = self.dispatcher.submit(sql, self.session, run_cached)
            result_cache.store(key, out, entry.tables)
            return out
        stmt = parse_statement(sql)
        from .transaction import handle_transaction_stmt

        txn = handle_transaction_stmt(stmt, self.session, self.catalog)
        if txn is not None:
            return txn
        check_ddl_access(stmt, self.access_control, self.session.user,
                         self.session.default_catalog)
        from ..runner import execute_session_stmt

        sess = execute_session_stmt(stmt, self.session)
        if sess is not None:
            return sess
        if isinstance(stmt, ast.Explain):
            subplan = fragment_plan(self._plan_stmt(stmt.statement))
            lines = subplan.text().splitlines()
            if stmt.analyze:
                stats: list[QueryStats] = []
                self._execute_subplan(subplan, stats)
                for s in sorted(stats, key=lambda s: s.label):
                    lines.extend(s.text().splitlines())
            return text_result("Query Plan", lines)
        if isinstance(stmt, ast.ShowTables):
            conn = self.catalog.connector(self.session.default_catalog)
            return text_result("Table", conn.list_tables())
        if isinstance(stmt, ast.ShowColumns):
            cat, table, schema = self.catalog.resolve_table(
                stmt.table, self.session.default_catalog)
            return text_result(
                "Column", [f"{c.name} {c.type}" for c in schema.columns])
        from ..runner import execute_ddl

        ddl = execute_ddl(
            stmt, self.catalog, self.session.default_catalog,
            lambda st: self._execute_subplan(
                fragment_plan(self._plan_stmt(st)), None))
        if ddl is not None:
            return ddl

        store_ctx = {}

        def run(fsm):
            fsm.set("PLANNING")
            plan = self._plan_stmt(stmt)
            new_entry = plan_cache.store(sql, self.session, self.catalog,
                                         plan, flavor="fragmented")
            # version vector read BEFORE execution (see runner.py: a
            # racing mutation strands the entry, never serves stale)
            store_ctx["key"] = result_cache.result_key(
                new_entry,
                result_cache.version_vector(new_entry.tables, self.catalog))
            store_ctx["tables"] = new_entry.tables
            subplan = fragment_plan(plan)
            fsm.set("STARTING")
            fsm.set("RUNNING")
            out = self._execute_subplan(subplan, None)
            fsm.set("FINISHING")
            return out

        out = self.dispatcher.submit(sql, self.session, run)
        if store_ctx.get("key") is not None:
            result_cache.store(store_ctx["key"], out, store_ctx["tables"])
        return out

    def _execute_subplan(self, subplan: SubPlan,
                         stats_sink: Optional[list]) -> QueryResult:
        if self.session.retry_policy == "TASK":
            from .fte import run_fte_query

            return self._to_result(subplan, run_fte_query(self, subplan,
                                                          stats_sink))
        if self.session.retry_policy == "QUERY":
            return self._run_query_retry(subplan, stats_sink)
        return self._run_streaming(subplan, stats_sink)

    def _run_query_retry(self, subplan: SubPlan,
                         stats_sink: Optional[list]) -> QueryResult:
        """retry_policy=QUERY: streaming execution with coordinator-level
        retry (reference: coordinator query retries — the pipelined overlap
        is kept; the recovery unit is the whole query).  On a retryable
        failure: blacklist the implicated worker for this query, replace
        GONE workers (``_prepare_retry``), back off deterministically, and
        re-run the subplan.  USER-classified errors fail fast, always."""
        import time as _time

        from ..exec.stats import ResilienceStats
        from ..spi.errors import Backoff, classify

        sess = self.session
        before = ResilienceStats()
        before.merge(self.resilience)
        backoff = Backoff(min_delay_s=sess.retry_initial_delay_s,
                          max_delay_s=sess.retry_max_delay_s,
                          max_failure_duration_s=float("inf"))
        blacklist: set = set()
        attempts = 1 + max(0, int(sess.query_retry_attempts))
        try:
            for attempt in range(attempts):
                try:
                    return self._run_streaming(
                        subplan, stats_sink, attempt=attempt,
                        blacklist=frozenset(blacklist))
                except BaseException as e:  # noqa: BLE001 — classified below
                    te = classify(e)
                    if not te.is_retryable() or attempt == attempts - 1:
                        raise
                    if te.remote_host and te.remote_host not in blacklist:
                        blacklist.add(te.remote_host)
                        self.resilience.blacklisted_workers += 1
                        self.resilience_events.append(
                            ("blacklist", te.remote_host, te.code.name))
                    if te.remote_host:
                        # score the failure cross-query too: enough strikes
                        # within the TTL and the worker stops receiving
                        # tasks from NEW queries as well
                        from ..telemetry import runtime as _rt2

                        _rec = _rt2.current_record()
                        self.cluster_blacklist.record_failure(
                            te.remote_host, reason=te.code.name,
                            query_id=_rec.query_id if _rec else "")
                    self._prepare_retry()
                    backoff.failure()
                    delay = backoff.delay_s
                    self.resilience.query_retries += 1
                    self.resilience.backoff_waits += 1
                    self.resilience.backoff_wait_s += delay
                    self.resilience_events.append(
                        ("query_retry", attempt + 1, te.code.name, delay))
                    _time.sleep(delay)
            raise AssertionError("unreachable: retry loop exhausted")
        finally:
            delta = ResilienceStats.delta(self.resilience, before)
            if delta.any:
                from ..telemetry import metrics as tm
                from ..telemetry import runtime as rt
                from .tracing import annotate_resilience_span

                tm.observe_resilience(delta)
                rec = rt.current_record()
                if rec is not None:
                    rt.add_retries(rec, delta.query_retries)
                span = self.tracer.current()
                if span is not None:
                    annotate_resilience_span(span, delta)
                if stats_sink is not None:
                    stats_sink.append(QueryStats(label="resilience:",
                                                 resilience=delta))

    def _prepare_retry(self) -> None:
        """Hook run between query-retry attempts; the process runner
        overrides it to sweep heartbeats and replace GONE workers."""

    def _run_streaming(self, subplan: SubPlan, stats_sink: Optional[list],
                       attempt: int = 0,
                       blacklist: frozenset = frozenset(),
                       use_fused: bool = True) -> QueryResult:
        from ..telemetry import runtime as _rt
        from .resource_manager import find_group

        # register with the cluster memory manager: the handle carries the
        # OOM-killer kill flag the scheduling/drain loops below poll, and
        # every task's memory pool is booked under this query id
        qrec = _rt.current_record()
        mem_qid = qrec.query_id if qrec is not None else f"q@{id(subplan):x}"
        max_mem = (self.session.query_max_memory_bytes
                   or int(os.environ.get("TRINO_TPU_QUERY_MAX_MEMORY",
                                         "0") or 0) or None)
        handle = self.memory_manager.register_query(
            mem_qid, priority=self.session.query_priority,
            group=find_group(self.dispatcher.root,
                             qrec.resource_group if qrec is not None else ""),
            max_memory=max_mem)
        try:
            return self._run_streaming_inner(
                subplan, stats_sink, attempt, blacklist, use_fused,
                handle, mem_qid)
        finally:
            self.memory_manager.unregister_query(mem_qid)

    def _run_streaming_inner(self, subplan: SubPlan,
                             stats_sink: Optional[list], attempt: int,
                             blacklist: frozenset, use_fused: bool,
                             handle, mem_qid: str) -> QueryResult:
        from .collective_exchange import (
            CollectiveRepartitionExchange,
            collectives_available,
        )
        from .stage_compiler import FusedStageOverflow, plan_fused_stages

        fragments = subplan.all_fragments()
        task_counts, consumer_tasks = self.stage_task_counts(fragments)
        stages: dict[int, _Stage] = {
            f.id: _Stage(f, task_counts[f.id], []) for f in fragments
        }
        # One byte budget for every scheduler: the time-sharing executor
        # flips sinks non-blocking, whose drivers then park via
        # ``needs_input`` until consumer acks free capacity — no quantum is
        # ever pinned inside ``enqueue``, so the old 1 GiB escape cap is
        # gone.  TRINO_TPU_SINK_MAX_BYTES overrides.
        env_cap = os.environ.get("TRINO_TPU_SINK_MAX_BYTES")
        sink_cap = max(int(env_cap), 1 << 20) if env_cap else 256 << 20
        for f in fragments:
            tc = stages[f.id].task_count
            nparts = consumer_tasks.get(f.id, 1)
            stages[f.id].buffers = [
                OutputBuffer(nparts, max_bytes=sink_cap)
                for _ in range(tc)
            ]

        # whole-stage compilation (execution/stage_compiler.py): fragmenter-
        # marked PARTIAL->shuffle->FINAL seams run as one jitted program per
        # batch-bucket plus one seam merge; the collective exchange and the
        # host buffers cover every remaining edge
        fused_edges: dict = {}
        resident_edges: dict = {}
        if use_fused:
            fused_edges = plan_fused_stages(
                fragments, self.session, task_counts, consumer_tasks)
            # whole-query compilation (execution/plan_compiler.py): maximal
            # device-resident subtrees — broadcast join spine + agg seam —
            # run as ONE program per batch; a coalesced core fragment's
            # plain fused seam is subsumed by its resident plan
            from .plan_compiler import plan_resident_plans

            resident_edges = plan_resident_plans(
                fragments, self.session, task_counts, consumer_tasks)
            for fid in resident_edges:
                fused_edges.pop(fid, None)
        # device-collective REPARTITION edges (all_to_all over the mesh)
        # where producer/consumer task counts line up; host buffers remain
        # the fallback for every other edge
        collective_edges: dict[int, CollectiveRepartitionExchange] = {}
        if self.session.use_collectives:
            for f in fragments:
                tc = stages[f.id].task_count
                if (f.id not in fused_edges
                        and f.id not in resident_edges
                        and f.output_kind == "REPARTITION"
                        and consumer_tasks.get(f.id) == tc
                        and collectives_available(tc)):
                    collective_edges[f.id] = CollectiveRepartitionExchange(
                        tc, f.output_keys,
                        f.root.output_names, f.root.output_types)
        # kept as attributes for observability/tests; tasks receive the
        # dict as an argument so concurrent queries cannot cross-wire
        self._collective_edges = collective_edges
        self._fused_edges = fused_edges
        self._resident_edges = resident_edges
        edges = {**collective_edges, **fused_edges, **resident_edges}

        errors: list[BaseException] = []
        adaptive = None
        if self.session.task_scheduler == "TIME_SHARING":
            hung = self._run_time_sharing(
                fragments, stages, errors, stats_sink, edges,
                attempt, handle=handle, memory_owner=mem_qid)
        else:
            from ..telemetry import runtime as _rt

            # task spans nest under the coordinator thread's open query
            # span via explicit cross-thread parenting (tracing.py parent=)
            parent_span = self.tracer.current()
            qrec = _rt.current_record()
            # streaming straggler speculation (leaf stages only): a leaf
            # twin re-reads its splits from the connector; a non-leaf twin
            # would need its producers' pages back, but the streaming
            # exchange frees them on ack — that retention is what FTE's
            # durable spool provides, so non-leaf speculation stays with
            # retry_policy=TASK (see execution/speculation.py)
            from .speculation import (
                SPECULATIVE,
                STANDARD,
                StreamingSpeculation,
                StreamingSpoolTee,
                nonleaf_speculation_enabled,
                speculation_enabled,
            )

            # adaptive execution plane (execution/adaptive.py): phased
            # activation + runtime join-distribution decisions.  ``0`` is
            # bit-for-bit legacy; ``auto`` engages only when the plan has
            # decision edges; ``1`` forces phased scheduling regardless.
            from .adaptive import AdaptiveExec, adaptive_mode

            mode = adaptive_mode(self.session)
            if mode != "0":
                adaptive = AdaptiveExec(stages, fragments, edges,
                                        sink_cap, self.session, errors)
                if mode == "auto" and not adaptive.sites:
                    adaptive = None
            spec: Optional[StreamingSpeculation] = None
            spec_gates: dict = {}
            if speculation_enabled(self.session):
                from ..planner.plan import TableWriter

                def _writes(node) -> bool:
                    return isinstance(node, TableWriter) or any(
                        _writes(c) for c in node.children)

                spec = StreamingSpeculation(
                    lag_multiplier=self.session.speculation_lag_multiplier,
                    min_delay_s=self.session.speculation_min_delay_s,
                    events=self.resilience_events)
                for f in fragments:
                    if (f.source_fragments or f.id in edges
                            or stages[f.id].task_count < 2
                            or _writes(f.root)
                            or (adaptive is not None
                                and adaptive.is_deferred_producer(f.id))):
                        continue  # twin needs re-readable, side-effect-free
                        # (deferred producers also feed barrier statistics:
                        # a twin would double-count the staging sketch)
                    spec.register_stage(f.id, stages[f.id].task_count)
                    for t in range(stages[f.id].task_count):
                        spec_gates[(f.id, t)] = spec.register_task(f.id, t)

            tee: Optional[StreamingSpoolTee] = None
            if (spec is not None and adaptive is None
                    and nonleaf_speculation_enabled(self.session)):
                # non-leaf twin eligibility (r15): a stage whose sources
                # all land in plain OutputBuffers can speculate too — its
                # producers tee winner pages into a durable per-task spool
                # (SpoolTeeBuffer), and the twin re-reads committed tee
                # dirs once EVERY source task has committed.  Collective/
                # fused edges and adaptive routing bypass stage.buffers,
                # so those fragments stay leaf-only.
                nonleaf = [
                    f for f in fragments
                    if f.source_fragments and f.id not in edges
                    and stages[f.id].task_count >= 2
                    and not _writes(f.root)
                    and all(src not in edges for src in f.source_fragments)
                ]
                if nonleaf:
                    from .durable_spool import make_spool_root

                    from . import spool_gc

                    tee = StreamingSpoolTee(make_spool_root(
                        getattr(self.session, "fte_spool_dir", None)))
                    spool_gc.acquire(
                        tee.root, qrec.query_id if qrec is not None
                        else "adhoc")
                    for f in nonleaf:
                        srcs = tuple(f.source_fragments)
                        spec.register_stage(
                            f.id, stages[f.id].task_count,
                            eligible=lambda _s=srcs: tee.ready(_s))
                        for t in range(stages[f.id].task_count):
                            spec_gates[(f.id, t)] = \
                                spec.register_task(f.id, t)
                        for src in srcs:
                            tee.want(src, stages[src].task_count)

            def _spawn_stage(fid: int) -> list[threading.Thread]:
                stage = stages[fid]
                out = []
                for t in range(stage.task_count):
                    ctx = None
                    if (fid, t) in spec_gates:
                        ctx = {"gate": spec_gates[(fid, t)],
                               "kind": STANDARD,
                               "cancel": spec.cancel_event(fid, t, STANDARD)}
                    th = threading.Thread(
                        target=self._run_task,
                        args=(stage, t, stages, errors, stats_sink,
                              edges, attempt, parent_span, qrec, mem_qid,
                              ctx, adaptive, tee),
                        name=f"task-{fid}.{t}",
                        daemon=True,
                    )
                    th.start()
                    out.append(th)
                return out

            if adaptive is None:
                threads: list[threading.Thread] = []
                for f in fragments:
                    threads.extend(_spawn_stage(f.id))
            else:
                # phased activation: only groups with no unresolved
                # decision sites upstream get tasks now; the rest hold no
                # threads or buffers' worth of pages and stay rewritable
                threads = adaptive.start(_spawn_stage)

            def _spawn_twin(fid: int, t: int) -> threading.Thread:
                # twin attempts use attempt+1000 (mirrors fte.py's
                # SPECULATIVE attempt base) so attempt-scoped injector
                # rules do not refire on the twin
                twin_ctx = {"gate": spec_gates[(fid, t)],
                            "kind": SPECULATIVE,
                            "cancel": spec.cancel_event(fid, t, SPECULATIVE)}
                tw = threading.Thread(
                    target=self._run_task,
                    args=(stages[fid], t, stages, errors, stats_sink,
                          edges, attempt + 1000, parent_span, qrec,
                          mem_qid, twin_ctx, adaptive, tee),
                    name=f"task-{fid}.{t}-speculative",
                    daemon=True,
                )
                tw.start()
                return tw

            from .task import STALL_TIMEOUT_S

            # polled join (not a plain join) so an OOM-killer verdict can
            # unblock tasks parked on full/empty buffers mid-query
            deadline = time.monotonic() + 2 * STALL_TIMEOUT_S
            pending = list(threads)
            aborted = False
            while ((pending
                    or (adaptive is not None and not adaptive.done()))
                   and time.monotonic() < deadline):
                if pending:
                    pending[0].join(timeout=0.1)
                else:
                    time.sleep(0.02)
                pending = [th for th in pending if th.is_alive()]
                if adaptive is not None:
                    if errors or aborted:
                        # a failed task already aborted the buffers; force
                        # the plane done so un-activated groups never spawn
                        adaptive.abort()
                    else:
                        pending.extend(adaptive.advance(_spawn_stage))
                if spec is not None and not errors and not aborted:
                    pending.extend(spec.tick(_spawn_twin))
                if not aborted and handle.poll() is not None:
                    aborted = True
                    for s in stages.values():
                        for b in s.buffers:
                            b.abort()
                    for ex in edges.values():
                        ex.abort()
                    if adaptive is not None:
                        adaptive.abort()
            hung = [th.name for th in pending if th.is_alive()]
            if adaptive is not None and not errors:
                hung += adaptive.unactivated()
            if tee is not None:
                # all tasks (and any twins) are done or hung: the tee spool
                # served its purpose.  A coordinator killed before this
                # line leaks the root to the boot-time spool_gc sweep.
                from . import spool_gc

                spool_gc.release(tee.root)
            if spec is not None:
                self.speculative_starts += spec.starts
                self.speculative_wins += spec.wins
                if spec.wins:
                    from ..telemetry import runtime as _rt

                    qrec = _rt.current_record()
                    if qrec is not None:
                        qrec.speculative_wins += spec.wins
        kerr = handle.killed_error()
        if errors or hung or kerr is not None:
            for s in stages.values():
                for b in s.buffers:
                    b.abort()
            for ex in edges.values():
                ex.abort()
            if adaptive is not None:
                adaptive.abort()
            if kerr is not None:
                # the kill verdict wins over secondary task errors: aborted
                # buffers make tasks fail with cascade exceptions that would
                # otherwise mask the CLUSTER_OUT_OF_MEMORY cause
                raise kerr
            if errors:
                if use_fused and any(isinstance(e, FusedStageOverflow)
                                     for e in errors):
                    # a task saw more groups than the fused state cap (or a
                    # resident plan couldn't hold): the legacy per-operator
                    # path has no such limit — re-run this subplan on it
                    # (stats surface the event; raise TRINO_TPU_FUSED_CAP /
                    # fix the plan shape to avoid it)
                    from .plan_compiler import ResidentPlanOverflow

                    res = [e for e in errors
                           if isinstance(e, ResidentPlanOverflow)]
                    if res:
                        self.resident_fallbacks += 1
                        from ..telemetry import metrics as _tm

                        _tm.RESIDENT_FALLBACKS.inc()
                        if stats_sink is not None:
                            from ..exec.stats import ResidentPlanStats

                            stats_sink.append(QueryStats(
                                label="resident plans:",
                                resident=ResidentPlanStats(
                                    fallbacks=1,
                                    fallback_reasons=[str(res[0])[:120]])))
                    else:
                        self.fused_fallbacks += 1
                        if stats_sink is not None:
                            from ..exec.stats import FusedStageStats

                            stats_sink.append(QueryStats(
                                label="fused stages:",
                                fused=FusedStageStats(fallbacks=1)))
                    return self._run_streaming(subplan, stats_sink, attempt,
                                               blacklist, use_fused=False)
                raise errors[0]
            raise TimeoutError(f"tasks did not complete: {hung}")

        if fused_edges:
            from ..exec.stats import FusedStageStats

            from .tracing import annotate_fused_span

            roll = FusedStageStats()
            for ex in fused_edges.values():
                roll.merge(ex.stats)
            from ..telemetry.metrics import observe_fused

            observe_fused(roll)
            span = self.tracer.current()
            if span is not None:
                annotate_fused_span(span, roll)
            if stats_sink is not None:
                stats_sink.append(QueryStats(label="fused stages:",
                                             fused=roll))

        if resident_edges:
            from ..exec.stats import ResidentPlanStats

            from .plan_compiler import ResidentPlanExec
            from .tracing import annotate_resident_span

            rroll = ResidentPlanStats()
            for ex in resident_edges.values():
                if isinstance(ex, ResidentPlanExec):
                    rroll.merge(ex.rstats)
            from ..telemetry.metrics import observe_resident

            observe_resident(rroll)
            span = self.tracer.current()
            if span is not None:
                annotate_resident_span(span, rroll)
            if stats_sink is not None:
                stats_sink.append(QueryStats(label="resident plans:",
                                             resident=rroll))

        if adaptive is not None and adaptive.stats.any:
            from ..telemetry.metrics import observe_adaptive

            observe_adaptive(adaptive.stats)
            if stats_sink is not None:
                stats_sink.append(QueryStats(label="adaptive:",
                                             adaptive=adaptive.stats))

        # close the runtime-truth loop: journal per-fingerprint observed
        # stats so the NEXT run of this (or any row-equivalent) plan shape
        # costs joins/aggregations from reality (planner/history.py)
        try:
            from ..planner.history import record_query_stats
            from ..telemetry import runtime as _rt

            qrec = _rt.current_record()
            skip = (set(fused_edges) | set(resident_edges)
                    | set(collective_edges))
            n = record_query_stats(
                fragments, stages, skip, adaptive,
                qrec.query_id if qrec is not None else mem_qid,
                qrec.fingerprint if qrec is not None else "")
            if n:
                from ..telemetry.metrics import HBO_RECORDS

                HBO_RECORDS.inc(n)
        except Exception:
            from ..telemetry.metrics import HBO_RECORD_ERRORS

            HBO_RECORD_ERRORS.inc()

        # drain the root stage's buffer as the client
        from .task import maybe_deserialize

        root = stages[subplan.fragment.id]
        client = ExchangeClient(root.buffers, 0)
        batches = []
        while not client.is_finished():
            handle.check()
            b = client.poll(timeout=0.2)
            if b is not None:
                batches.append(maybe_deserialize(b))
        # a kill that lands during FINISHING still fails the query: the
        # victim must always observe its own kill or the killer's
        # capacity projection (total -= victim bytes) goes stale
        handle.check()
        return self._to_result(subplan, batches)

    def fte_run_attempt(self, fragment, task_index: int, task_count: int,
                        nparts: int, upstream: dict, spool_root: str,
                        attempt: int, stats_sink: Optional[list],
                        memory_multiplier: float = 1.0) -> str:
        """Run ONE task attempt against the durable spool; returns the
        committed attempt directory.  In-process execution here; the
        process runner overrides this with a worker-process dispatch.
        ``memory_multiplier`` scales the task's HBM budget — the FTE
        scheduler grows it exponentially after a memory failure
        (ExponentialGrowthPartitionMemoryEstimator.java:55)."""
        import os as _os

        from .durable_spool import DurableSpoolClient, DurableSpoolWriter
        from .failure_injector import GET_RESULTS_FAILURE, TASK_FAILURE
        from .fte import fte_task_dir
        from .task import PartitionedOutputSink as _Sink

        injector = getattr(self.session, "failure_injector", None)
        if injector is not None:
            injector.maybe_stall(fragment.id, task_index, attempt)
            injector.maybe_fail(TASK_FAILURE, fragment.id, task_index,
                                attempt)

        def on_read(_d, _fid=fragment.id, _t=task_index, _a=attempt):
            if injector is not None:
                injector.maybe_fail(GET_RESULTS_FAILURE, _fid, _t, _a)
                injector.maybe_corrupt_spool(_d, _fid, _t, _a)

        clients = {}
        for src, info in upstream.items():
            if info["merge"]:
                clients[src] = [
                    DurableSpoolClient([d], task_index, on_read)
                    for d in info["dirs"]
                ]
            else:
                clients[src] = DurableSpoolClient(
                    info["dirs"], task_index, on_read)
        planner = LocalPlanner(
            self.catalog,
            splits_per_node=self.session.splits_per_node,
            node_count=self.worker_count,
            task_index=task_index,
            task_count=task_count,
            remote_clients=clients,
            dynamic_filtering=self.session.dynamic_filtering,
            hbm_limit_bytes=int(
                self.session.hbm_limit_bytes * memory_multiplier),
        )
        local = planner.plan(fragment.root)
        task_dir = fte_task_dir(spool_root, fragment.id, task_index)
        _os.makedirs(task_dir, exist_ok=True)
        writer = DurableSpoolWriter(task_dir, attempt, nparts)
        sink = _Sink(
            writer,
            fragment.output_kind if fragment.output_kind != "OUTPUT"
            else "GATHER",
            fragment.output_keys, serde=True)
        local.pipelines[-1][-1] = sink
        stats = None
        if stats_sink is not None:
            stats = QueryStats(
                label=f"fragment {fragment.id} task {task_index}:")
        try:
            run_pipelines(local.pipelines, stats)
        except BaseException:
            writer.abort()
            raise
        writer.set_finished()
        if stats is not None:
            stats_sink.append(stats)
        return writer.committed

    # -------------------------------------------------------------- recovery
    def pending_fte_recoveries(self) -> list:
        """In-flight ``retry_policy="TASK"`` queries a dead coordinator
        left in the query-state WAL (execution/query_state.py) — the boot
        recovery work list the protocol dispatcher drains."""
        from . import query_state

        if not query_state.enabled():
            return []
        return query_state.pending()

    def resume_fte_query(self, pq) -> QueryResult:
        """Rehydrate one recovered query: decode the WAL's plan snapshot
        and re-enter the FTE loop with its committed-attempt map seeded —
        committed attempts are never re-executed (run_fte_query skips
        them; the WAL's attempt counters make that assertable).  Runs
        under the ORIGINAL query id so a reattaching client's
        ``GET /v1/statement/{id}`` polling resolves."""
        from ..runner import run_with_query_events
        from ..telemetry import metrics as tm
        from ..telemetry import profiler
        from . import query_state
        from .fte import run_fte_query

        subplan = query_state.decode_plan(pq.plan_b64)
        tm.FTE_QUERY_RECOVERIES.inc()
        profiler.instant(profiler.RECOVERY, "query-resume",
                         query_id=pq.query_id,
                         committed=len(pq.committed),
                         fingerprint=pq.fingerprint)

        def thunk():
            return self._to_result(
                subplan, run_fte_query(self, subplan, None, resume=pq))

        return run_with_query_events(
            pq.query_id, pq.sql, self.session.user, self.event_listeners,
            self.tracer, thunk)

    # ----------------------------------------------------------------- drain
    def drain_worker(self, node_id: str) -> dict:
        """Coordinator-driven graceful drain of an in-process worker slot:
        mark it draining in discovery so ``active_worker_count`` (and hence
        every NEW query's task placement) stops using it.  In-process tasks
        share the coordinator's address space, so running work simply
        completes; there is no process to wait on or replace."""
        from ..telemetry import metrics as tm

        tm.DRAINS.inc()
        self.resilience_events.append(("drain", node_id, "started"))
        self.nodes.drain(node_id)
        self.resilience_events.append(("drain", node_id, "drained"))
        return {"worker": node_id, "escalated": False}

    def restore_worker(self, node_id: str) -> None:
        """Undo an in-process drain (the rolling-restart drill's stand-in
        for booting a replacement process)."""
        self.nodes.restore(node_id)
        self.resilience_events.append(("drain", node_id, "restored"))

    @property
    def active_worker_count(self) -> int:
        """Live, non-draining workers per discovery + failure detection;
        falls back to the static count if the control plane sees none
        (mirrors NodeScheduler consulting the FailureDetector)."""
        # on-demand heartbeat round (deterministic without the background
        # pinger thread; start() enables continuous monitoring)
        self.failure_detector.ping_once()
        alive = [w for w in self.nodes.active_workers()
                 if w not in self.failure_detector.failed_nodes()]
        return len(alive) or self.worker_count

    def stage_task_counts(self, fragments) -> tuple[dict, dict]:
        """(fragment -> task count, fragment -> consumer task count); the
        output-buffer partition count of a fragment is its consumer's task
        count (the root's consumer is the client: 1)."""
        workers = self.active_worker_count
        writer_cap = max(1, min(self.session.writer_task_limit, workers))
        task_counts = {}
        for f in fragments:
            if f.partitioning == "SINGLE":
                task_counts[f.id] = 1
            elif f.partitioning == "ARBITRARY":
                # scaled-writer fragments honor the configured writer limit
                task_counts[f.id] = writer_cap
            else:
                task_counts[f.id] = workers
        self._history_fanout(fragments, task_counts, workers)
        consumer_tasks: dict[int, int] = {}
        for f in fragments:
            for src in f.source_fragments:
                consumer_tasks[src] = task_counts[f.id]
        return task_counts, consumer_tasks

    def _history_fanout(self, fragments, task_counts: dict,
                        workers: int) -> None:
        """Shrink a hash stage's task count when history says its input is
        small: N tasks each jitting a program over a trickle of rows costs
        more than the parallelism buys.  Only ever shrinks — an
        underestimate here cannot break correctness, just parallelism —
        and only for intermediate (non-scan, non-SINGLE) stages."""
        try:
            from ..planner.history import (
                fragment_fingerprints,
                hbo_enabled,
                _stats_table,
            )
            from ..spi import knobs

            if not hbo_enabled():
                return
            per_task = knobs.get_int("TRINO_TPU_HBO_ROWS_PER_TASK") or 0
            if per_task <= 0:
                return
            table, _ = _stats_table()
            if not table:
                return
            fps = fragment_fingerprints(fragments)
            by_id = {f.id: f for f in fragments}
            for f in fragments:
                if task_counts.get(f.id, 1) <= 1 or f.partitioning != "HASH":
                    continue
                rows = 0
                for src in f.source_fragments:
                    st = table.get(fps.get(src, ""))
                    n = None if st is None else (
                        st.rows if st.rows is not None else st.groups)
                    if n is None or src not in by_id:
                        rows = None
                        break
                    rows += n
                if rows is None:
                    continue
                t = max(1, min(workers, -(-rows // per_task)))
                if t < task_counts[f.id]:
                    task_counts[f.id] = t
                    from ..telemetry import runtime as _rt
                    from ..telemetry.metrics import HBO_FANOUT_ADJUSTED

                    HBO_FANOUT_ADJUSTED.inc()
                    qrec = _rt.current_record()
                    if qrec is not None:
                        _rt.add_adaptive(qrec, f"hbo_fanout:f{f.id}:{t}")
        except Exception:
            # advisory only: a failed adjustment must never fail scheduling
            from ..telemetry.metrics import HBO_RECORD_ERRORS

            HBO_RECORD_ERRORS.inc()

    def _to_result(self, subplan: SubPlan, batches: list) -> QueryResult:
        names = list(subplan.fragment.root.output_names)
        types = list(subplan.fragment.root.output_types)
        if batches:
            return QueryResult(names, ColumnBatch.concat(batches))
        import numpy as np

        return QueryResult(names, ColumnBatch(names, [
            Column(t, np.empty(0, t.storage_dtype)) for t in types]))

    def _build_task(self, stage: _Stage, task_index: int,
                    stages: dict[int, "_Stage"],
                    stats_sink: Optional[list],
                    collective: dict,
                    attempt: int = 0,
                    memory_owner: Optional[str] = None,
                    spec_ctx: Optional[dict] = None,
                    adaptive=None,
                    tee=None,
                    ) -> tuple[list, Optional[QueryStats]]:
        from .speculation import SPECULATIVE, SpeculationLost

        f = stage.fragment
        # engine-level fault injection on the in-process streaming path,
        # keyed by (fragment, task, attempt) exactly like the FTE path —
        # this is what makes retry_policy=QUERY deterministically testable
        injector = getattr(self.session, "failure_injector", None)
        if injector is not None:
            from .failure_injector import TASK_FAILURE

            cancel = spec_ctx["cancel"] if spec_ctx is not None else None
            injector.maybe_stall(
                f.id, task_index, attempt,
                # an injected stall must not outlive its query: bail as soon
                # as the task's buffer is aborted (query failed / OOM-killed)
                # or a speculative twin won the race
                should_cancel=lambda: (
                    stage.buffers[task_index].aborted
                    or (cancel is not None and cancel.is_set())))
            if cancel is not None and cancel.is_set():
                raise SpeculationLost(spec_ctx["kind"])
            injector.maybe_fail(TASK_FAILURE, f.id, task_index, attempt)
        clients = {}
        for src in f.source_fragments:
            if (tee is not None and spec_ctx is not None
                    and spec_ctx["kind"] == SPECULATIVE):
                # non-leaf twin: the streaming exchange already freed the
                # pages its primary consumed — re-read the committed tee
                # spool instead (eligibility guaranteed every source task
                # committed before this twin launched)
                from .durable_spool import DurableSpoolClient

                dirs = tee.committed_dirs(src)
                if dirs is None:
                    raise SpeculationLost(spec_ctx["kind"])
                if stages[src].fragment.output_kind == "MERGE":
                    clients[src] = [DurableSpoolClient([d], task_index)
                                    for d in dirs]
                else:
                    clients[src] = DurableSpoolClient(dirs, task_index)
                continue
            routed = (adaptive.routed_buffer(src)
                      if adaptive is not None else None)
            if routed is not None:
                # deferred edge: consume the router's re-distributed pages,
                # not the producer's staging buffers
                clients[src] = ExchangeClient([routed], task_index)
            elif src in collective:
                clients[src] = collective[src]
            elif stages[src].fragment.output_kind == "MERGE":
                # order-preserving gather: one client PER producer so the
                # merge operator sees each task's sorted stream separately
                clients[src] = [ExchangeClient([b], task_index)
                                for b in stages[src].buffers]
            else:
                clients[src] = ExchangeClient(stages[src].buffers, task_index)
        planner = LocalPlanner(
            self.catalog,
            splits_per_node=self.session.splits_per_node,
            node_count=self.worker_count,
            task_index=task_index,
            task_count=stage.task_count,
            remote_clients=clients,
            dynamic_filtering=self.session.dynamic_filtering,
            hbm_limit_bytes=self.session.hbm_limit_bytes,
            task_concurrency=self.session.task_concurrency,
        )
        if memory_owner is not None:
            # book this task's HBM pool under the query id so the cluster
            # memory manager sees in-process reservations too
            self.memory_manager.register_pool(memory_owner,
                                              planner.memory.pool)
        # swap the collector for the task's output sink; a fused producer
        # fragment plans only its FEED subtree — the Filter/Project chain,
        # the PARTIAL aggregation and the seam shuffle run inside the fused
        # sink's jitted programs (execution/stage_compiler.py)
        from .plan_compiler import (
            ResidentBuildHandle,
            ResidentBuildSinkOperator,
            ResidentPlanExec,
            ResidentPlanSinkOperator,
        )
        from .stage_compiler import FusedStageExec, FusedStageSinkOperator

        ex = collective.get(f.id)
        if isinstance(ex, ResidentPlanExec):
            # a resident core fragment plans only the scan FEED below the
            # join spine — joins, chain, PARTIAL agg and the interior seams
            # all run inside the whole-plan program
            local = planner.plan(ex.spec.feed)
            sink = ResidentPlanSinkOperator(ex, task_index)
        elif isinstance(ex, ResidentBuildHandle):
            local = planner.plan(f.root)
            sink = ResidentBuildSinkOperator(ex, task_index)
        elif isinstance(ex, FusedStageExec):
            local = planner.plan(ex.spec.feed)
            sink = FusedStageSinkOperator(ex, task_index)
        elif ex is not None:
            from .collective_exchange import CollectiveOutputSink

            local = planner.plan(f.root)
            sink = CollectiveOutputSink(ex, task_index)
        else:
            local = planner.plan(f.root)
            out = stage.buffers[task_index]
            if spec_ctx is not None:
                # racing attempts write through the task's gate: the first
                # page (or empty finish) claims the stream, the loser's
                # first write raises SpeculationLost — downstream consumers
                # only ever see one attempt's pages
                from .speculation import GatedBuffer

                out = GatedBuffer(out, spec_ctx["gate"], spec_ctx["kind"])
            if tee is not None and tee.wants(f.id):
                # this fragment feeds a speculation-eligible non-leaf
                # stage: tee winner pages into the durable spool so a
                # straggling consumer's twin can re-read them.  Outside
                # the gate — a losing attempt never reaches the tee.
                from .speculation import SpoolTeeBuffer

                out = SpoolTeeBuffer(
                    out,
                    tee.writer(f.id, task_index,
                               stage.buffers[task_index].num_partitions,
                               attempt=attempt),
                    on_commit=lambda d, _f=f.id, _t=task_index:
                        tee.mark_committed(_f, _t, d))
            kind = f.output_kind if f.output_kind != "OUTPUT" else "GATHER"
            sketch, sketch_keys = None, ()
            if adaptive is not None:
                ov = adaptive.sink_override(f.id, task_index)
                if ov is not None:
                    # deferred producer: land everything in the single-
                    # partition staging buffer (already swapped into
                    # stage.buffers) and feed the heavy-hitter sketch
                    kind = "GATHER"
                    sketch, sketch_keys = ov
            sink = PartitionedOutputSink(
                out, kind,
                f.output_keys, serde=self.session.exchange_serde,
                sketch=sketch, sketch_keys=sketch_keys,
                coalesce_rows=f.sink_coalesce_rows)
        local.pipelines[-1][-1] = sink
        stats = None
        if stats_sink is not None:
            stats = QueryStats(label=f"fragment {f.id} task {task_index}:")
            stats_sink.append(stats)  # list.append is thread-safe
        return local.pipelines, stats

    def _run_time_sharing(self, fragments, stages, errors, stats_sink,
                          collective, attempt: int = 0, handle=None,
                          memory_owner=None) -> list[str]:
        """Schedule every task on a bounded MLFQ executor
        (exec/executor.py); returns the names of tasks that never finished."""
        import time as _time

        from ..exec.executor import TimeSharingTaskExecutor

        executor = TimeSharingTaskExecutor(self.session.executor_workers)
        try:
            handles = []
            try:
                for f in fragments:
                    stage = stages[f.id]
                    for t in range(stage.task_count):
                        pipelines, stats = self._build_task(
                            stage, t, stages, stats_sink, collective, attempt,
                            memory_owner=memory_owner)
                        handles.append(
                            (f, t, executor.submit(pipelines, stats),
                             pipelines))
            except BaseException:
                # a task that failed to BUILD (e.g. injected fault) must not
                # leave already-submitted siblings blocked on its buffers
                for s in stages.values():
                    for b in s.buffers:
                        b.abort()
                for ex in collective.values():
                    ex.abort()
                raise
            # poll every handle so the FIRST failure aborts all buffers
            # immediately (matching THREADS-mode fail-fast)
            from .task import STALL_TIMEOUT_S

            deadline = _time.monotonic() + 2 * STALL_TIMEOUT_S
            pending = list(range(len(handles)))
            aborted = False
            while pending and _time.monotonic() < deadline:
                if (not aborted and handle is not None
                        and handle.poll() is not None):
                    # OOM-killer verdict: unblock everything now; the caller
                    # raises the CLUSTER_OUT_OF_MEMORY error
                    aborted = True
                    for s in stages.values():
                        for b in s.buffers:
                            b.abort()
                    for ex in collective.values():
                        ex.abort()
                still = []
                for i in pending:
                    f, t, h, pipelines = handles[i]
                    if not h.done.is_set():
                        still.append(i)
                        continue
                    if h.error is None:
                        # deferred expression errors (ops/expr.py channel):
                        # checked per finished task, same as run_pipelines
                        from ..ops.expr import check_error_scalars

                        try:
                            check_error_scalars([
                                e for p in pipelines for op in p
                                for e in getattr(op, "pending_errors", ())
                            ])
                        except Exception as err:  # noqa: BLE001
                            h.error = err
                    if h.error is not None:
                        errors.append(h.error)
                        for s in stages.values():
                            for b in s.buffers:
                                b.abort()
                        for ex in collective.values():
                            ex.abort()
                if len(still) == len(pending):
                    _time.sleep(0.02)
                pending = still
            return [f"task-{handles[i][0].id}.{handles[i][1]}"
                    for i in pending]
        finally:
            executor.shutdown()

    def _run_task(self, stage: _Stage, task_index: int,
                  stages: dict[int, "_Stage"], errors: list,
                  stats_sink: Optional[list] = None,
                  collective: Optional[dict] = None,
                  attempt: int = 0, parent_span=None,
                  query_record=None, memory_owner=None,
                  spec_ctx: Optional[dict] = None,
                  adaptive=None, tee=None) -> None:
        import time as _time

        from ..exec.driver import collect_encoding_stats, collect_scan_stats
        from ..telemetry import metrics as tm
        from ..telemetry import runtime as rt
        from .speculation import SpeculationLost
        from .tracing import annotate_scan_span

        tm.TASKS_CREATED.inc()
        trec = rt.task_started(
            query_record.query_id if query_record is not None else "",
            f"f{stage.fragment.id}.t{task_index}", stage.fragment.id,
            task_index, "local")
        from ..telemetry import profiler

        # task threads are fresh per task: stamp the query/task identity so
        # every driver/exchange event this thread (and its pipeline group
        # threads, via run_pipelines context inheritance) records attributes
        profiler.set_context(trec.query_id, trec.task_id)
        pt0 = profiler.now()
        t0 = _time.perf_counter()
        pipelines = None
        state = "FINISHED"
        err = None
        with self.tracer.span(
                "trino.task", parent=parent_span,
                **{"trino.task.id": trec.task_id,
                   "trino.task.worker": "local"}) as sp:
            try:
                pipelines, stats = self._build_task(
                    stage, task_index, stages, stats_sink, collective or {},
                    attempt, memory_owner=memory_owner, spec_ctx=spec_ctx,
                    adaptive=adaptive, tee=tee)
                run_pipelines(pipelines, stats)
            except SpeculationLost:
                # this attempt lost the first-commit race — its twin owns
                # the output stream; unwind without touching the query
                state = "CANCELED"
                sp.set("speculation.lost", True)
            except BaseException as e:  # noqa: BLE001 — surfaced to
                # coordinator
                gate = spec_ctx["gate"] if spec_ctx is not None else None
                if gate is not None and gate.owner is not None \
                        and gate.owner != spec_ctx["kind"]:
                    # a loser failing for real changes nothing: the other
                    # attempt owns the stream and is still healthy
                    state = "CANCELED"
                    sp.set("speculation.lost", True)
                    self.resilience_events.append(
                        ("speculative_loser_error", stage.fragment.id,
                         task_index, type(e).__name__))
                else:
                    errors.append(e)
                    state = "FAILED"
                    err = f"{type(e).__name__}: {e}"
                    sp.set("error", type(e).__name__)
                    # unblock every sibling immediately: producers stuck in
                    # enqueue backpressure, consumers polling this (now
                    # dead) task, and partners parked at a collective
                    # all_to_all barrier would otherwise wait out the full
                    # join timeout before the real error surfaces
                    for s in stages.values():
                        for b in s.buffers:
                            b.abort()
                    for ex in (collective or {}).values():
                        ex.abort()
                    if adaptive is not None:
                        adaptive.abort()
            ingest = collect_scan_stats(pipelines) if pipelines else None
            if pipelines:
                tm.observe_encoding(collect_encoding_stats(pipelines))
            if ingest is not None:
                annotate_scan_span(sp, ingest)
                tm.observe_scan(ingest)
                if query_record is not None:
                    rt.add_input(query_record, ingest.scan_rows,
                                 ingest.scan_bytes)
        tm.TASK_WALL_SECONDS.record(_time.perf_counter() - t0)
        profiler.event(profiler.TASK, trec.task_id, pt0, state=state)
        if state == "FAILED":
            tm.TASKS_FAILED.inc()
        rt.task_finished(trec, state, error=err)
