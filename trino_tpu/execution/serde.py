"""Page serialization + compression for real network boundaries.

The wire format role of the reference's PagesSerde stack
(core/trino-main/src/main/java/io/trino/execution/buffer/PageSerializer.
java:58, PagesSerdeUtil, CompressionCodec.java LZ4/ZSTD options): a
ColumnBatch becomes one length-prefixed binary page — schema header, then
per column dtype + data + validity + dictionary — optionally compressed
(stdlib zlib stands in for lz4; the codec byte leaves room for more).

Batches are compacted before serialization (a network boundary is a host
boundary; live masks never cross it).
"""

from __future__ import annotations

import struct
import zlib
from typing import Optional

import numpy as np

from ..spi.batch import Column, ColumnBatch
from ..spi.errors import PAGE_TRANSPORT_ERROR, TrinoError
from ..spi.types import Type, parse_type

__all__ = ["serialize_batch", "deserialize_batch", "write_frame",
           "iter_frames", "CODEC_NONE", "CODEC_ZLIB",
           "SPOOL_STREAM_MAGIC", "SpoolCorruptionError",
           "write_stream_header", "write_frame_crc"]

# v2 spool-stream header: a file starting with these 4 bytes carries
# CRC-checked frames ([u32 len][u32 crc32][payload]); any other first word
# is a v1 length prefix ([u32 len][payload]) — as a length it would mean an
# ~844 MB frame, far past any page the engine writes, so the two formats
# cannot collide and old spool/spill/connector files stay readable.
SPOOL_STREAM_MAGIC = b"TTS2"


class SpoolCorruptionError(TrinoError):
    """A spool frame failed its CRC32 (bit flip) or ended mid-frame (torn
    write that slipped past the atomic-rename commit, e.g. disk-level
    corruption after commit).  EXTERNAL/retryable: the FTE loop discards
    the corrupt attempt and re-executes its producer instead of
    deserializing garbage."""

    def __init__(self, path: str, detail: str):
        super().__init__(PAGE_TRANSPORT_ERROR,
                         f"spool corruption in {path}: {detail}")
        self.path = path


def write_frame(f, page: bytes) -> None:
    """Append one length-prefixed page frame ([u32 LE length][bytes]) —
    the shared on-disk/wire framing used by the spiller and the file
    connector (and scanned natively by native/pagefile.cpp)."""
    f.write(struct.pack("<I", len(page)))
    f.write(page)


def write_stream_header(f) -> None:
    """Start a v2 CRC-checked frame stream (call once, before any
    write_frame_crc on the same file)."""
    f.write(SPOOL_STREAM_MAGIC)


def write_frame_crc(f, page: bytes) -> None:
    """Append one v2 frame: [u32 LE length][u32 LE crc32][bytes]."""
    f.write(struct.pack("<II", len(page), zlib.crc32(page) & 0xFFFFFFFF))
    f.write(page)


def _iter_frames_crc(f, path: str):
    while True:
        hdr = f.read(8)
        if not hdr:
            return
        if len(hdr) < 8:
            raise SpoolCorruptionError(path, "truncated frame header")
        n, crc = struct.unpack("<II", hdr)
        payload = f.read(n)
        if len(payload) < n:
            raise SpoolCorruptionError(
                path, f"torn frame: expected {n} bytes, got {len(payload)}")
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise SpoolCorruptionError(path, "frame CRC32 mismatch")
        yield payload


def iter_frames(f, path: str = "<stream>"):
    """Yield every frame's bytes from a file opened at the stream start.
    Auto-detects the format: a SPOOL_STREAM_MAGIC header selects v2
    CRC-checked frames (raising :class:`SpoolCorruptionError` on mismatch
    or truncation); anything else is the original unchecked v1 framing."""
    first = f.read(4)
    if first == SPOOL_STREAM_MAGIC:
        yield from _iter_frames_crc(f, path)
        return
    while True:
        if len(first) < 4:
            return
        (n,) = struct.unpack("<I", first)
        yield f.read(n)
        first = f.read(4)

_MAGIC = b"TTP1"
CODEC_NONE = 0
CODEC_ZLIB = 1


def _pack_bytes(out: list[bytes], b: bytes) -> None:
    out.append(struct.pack("<I", len(b)))
    out.append(b)


def _pack_str(out: list[bytes], s: str) -> None:
    _pack_bytes(out, s.encode("utf-8"))


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def blob(self) -> bytes:
        return self.take(self.u32())

    def text(self) -> str:
        return self.blob().decode("utf-8")


def serialize_batch(batch: ColumnBatch, codec: int = CODEC_ZLIB) -> bytes:
    """One page: MAGIC, codec, u32 rows, u32 cols, then per column
    (name, type, dtype, data, has_valid [+bitmap], has_dict [+values])."""
    batch = batch.compact()
    parts: list[bytes] = []
    parts.append(struct.pack("<II", batch.num_rows, batch.num_columns))
    for name, col in zip(batch.names, batch.columns):
        _pack_str(parts, name)
        _pack_str(parts, str(col.type))
        data = np.ascontiguousarray(np.asarray(col.data))
        _pack_str(parts, data.dtype.str)
        _pack_bytes(parts, data.tobytes())
        if col.valid is not None:
            parts.append(b"\x01")
            _pack_bytes(parts, np.packbits(np.asarray(col.valid)).tobytes())
        else:
            parts.append(b"\x00")
        if col.dictionary is not None:
            parts.append(b"\x01")
            parts.append(struct.pack("<I", len(col.dictionary)))
            for v in col.dictionary:
                # tuples (array/row/map) and python ints (long decimals)
                # round-trip through repr; strings stay plain
                _pack_str(parts, repr(v) if isinstance(v, (tuple, int))
                          else str(v))
        else:
            parts.append(b"\x00")
    payload = b"".join(parts)
    if codec == CODEC_ZLIB:
        payload = zlib.compress(payload, level=1)
    return _MAGIC + struct.pack("<BI", codec, len(payload)) + payload


def deserialize_batch(data: bytes) -> ColumnBatch:
    assert data[:4] == _MAGIC, "bad page magic"
    codec, plen = struct.unpack("<BI", data[4:9])
    payload = data[9:9 + plen]
    if codec == CODEC_ZLIB:
        payload = zlib.decompress(payload)
    r = _Reader(payload)
    num_rows, num_cols = struct.unpack("<II", r.take(8))
    names: list[str] = []
    cols: list[Column] = []
    for _ in range(num_cols):
        names.append(r.text())
        type_ = parse_type(r.text())
        dtype = np.dtype(r.text())
        arr = np.frombuffer(r.blob(), dtype=dtype).copy()
        valid: Optional[np.ndarray] = None
        if r.take(1) == b"\x01":
            bits = np.frombuffer(r.blob(), dtype=np.uint8)
            valid = np.unpackbits(bits, count=num_rows).astype(bool)
        dictionary = None
        if r.take(1) == b"\x01":
            count = r.u32()
            texts = [r.text() for _ in range(count)]
            dictionary = np.empty(count, dtype=object)
            from ..spi.types import ArrayType, DecimalType, MapType, RowType

            if isinstance(type_, (ArrayType, RowType, MapType)):
                import ast as _ast

                for i, s in enumerate(texts):
                    dictionary[i] = _ast.literal_eval(s)
            elif isinstance(type_, DecimalType) and type_.precision > 18:
                for i, s in enumerate(texts):
                    dictionary[i] = int(s)
            else:
                for i, s in enumerate(texts):
                    dictionary[i] = s
        cols.append(Column(type_, arr, valid, dictionary))
    return ColumnBatch(names, cols)
