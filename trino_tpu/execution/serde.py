"""Page serialization + compression for real network boundaries.

The wire format role of the reference's PagesSerde stack
(core/trino-main/src/main/java/io/trino/execution/buffer/PageSerializer.
java:58, PagesSerdeUtil, CompressionCodec.java LZ4/ZSTD options): a
ColumnBatch becomes one length-prefixed binary page — schema header, then
per column dtype + data + validity + dictionary — optionally compressed
(stdlib zlib stands in for lz4; the codec byte leaves room for more).

Batches are compacted before serialization (a network boundary is a host
boundary; live masks never cross it).
"""

from __future__ import annotations

import itertools
import struct
import threading
import zlib
from collections import OrderedDict
from typing import Optional

import numpy as np

from ..spi.batch import Column, ColumnBatch
from ..spi.errors import PAGE_TRANSPORT_ERROR, TrinoError
from ..spi.types import Type, parse_type

__all__ = ["serialize_batch", "deserialize_batch", "write_frame",
           "iter_frames", "CODEC_NONE", "CODEC_ZLIB",
           "SPOOL_STREAM_MAGIC", "SpoolCorruptionError",
           "write_stream_header", "write_frame_crc", "PageStreamEncoder"]

# v2 spool-stream header: a file starting with these 4 bytes carries
# CRC-checked frames ([u32 len][u32 crc32][payload]); any other first word
# is a v1 length prefix ([u32 len][payload]) — as a length it would mean an
# ~844 MB frame, far past any page the engine writes, so the two formats
# cannot collide and old spool/spill/connector files stay readable.
SPOOL_STREAM_MAGIC = b"TTS2"


class SpoolCorruptionError(TrinoError):
    """A spool frame failed its CRC32 (bit flip) or ended mid-frame (torn
    write that slipped past the atomic-rename commit, e.g. disk-level
    corruption after commit).  EXTERNAL/retryable: the FTE loop discards
    the corrupt attempt and re-executes its producer instead of
    deserializing garbage."""

    def __init__(self, path: str, detail: str):
        super().__init__(PAGE_TRANSPORT_ERROR,
                         f"spool corruption in {path}: {detail}")
        self.path = path


def write_frame(f, page: bytes) -> None:
    """Append one length-prefixed page frame ([u32 LE length][bytes]) —
    the shared on-disk/wire framing used by the spiller and the file
    connector (and scanned natively by native/pagefile.cpp)."""
    f.write(struct.pack("<I", len(page)))
    f.write(page)


def write_stream_header(f) -> None:
    """Start a v2 CRC-checked frame stream (call once, before any
    write_frame_crc on the same file)."""
    f.write(SPOOL_STREAM_MAGIC)


def write_frame_crc(f, page: bytes) -> None:
    """Append one v2 frame: [u32 LE length][u32 LE crc32][bytes]."""
    f.write(struct.pack("<II", len(page), zlib.crc32(page) & 0xFFFFFFFF))
    f.write(page)


def _iter_frames_crc(f, path: str):
    while True:
        hdr = f.read(8)
        if not hdr:
            return
        if len(hdr) < 8:
            raise SpoolCorruptionError(path, "truncated frame header")
        n, crc = struct.unpack("<II", hdr)
        payload = f.read(n)
        if len(payload) < n:
            raise SpoolCorruptionError(
                path, f"torn frame: expected {n} bytes, got {len(payload)}")
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise SpoolCorruptionError(path, "frame CRC32 mismatch")
        yield payload


def iter_frames(f, path: str = "<stream>"):
    """Yield every frame's bytes from a file opened at the stream start.
    Auto-detects the format: a SPOOL_STREAM_MAGIC header selects v2
    CRC-checked frames (raising :class:`SpoolCorruptionError` on mismatch
    or truncation); anything else is the original unchecked v1 framing."""
    first = f.read(4)
    if first == SPOOL_STREAM_MAGIC:
        yield from _iter_frames_crc(f, path)
        return
    while True:
        if len(first) < 4:
            return
        (n,) = struct.unpack("<I", first)
        yield f.read(n)
        first = f.read(4)

_MAGIC = b"TTP1"
_MAGIC2 = b"TTP2"  # compressed-execution pages (encoding byte + dict sidecar)
CODEC_NONE = 0
CODEC_ZLIB = 1

# v2 per-column encoding byte
_ENC_FLAT = 0
_ENC_RLE = 1  # one stored value + the page row count
# v2 per-column dictionary byte
_DICT_NONE = 0
_DICT_INLINE = 1  # values inline, exactly like v1 (no stream context)
_DICT_DEF = 2     # sidecar definition: stream token + dict id + values
_DICT_REF = 3     # sidecar reference: stream token + dict id only

_STREAM_TOKENS = itertools.count(1)
_STREAM_TOKENS_LOCK = threading.Lock()


class PageStreamEncoder:
    """Producer-side context for ONE ordered page stream (a single
    (task, partition) output buffer).  The first page that carries a given
    dictionary object ships its values once as a sidecar definition; every
    later page on the same stream sends a fixed-size reference, so a
    repartition exchange moves int32 codes instead of re-shipping the
    dictionary with every page.  Correctness rides on the exchange plane's
    per-buffer in-order delivery (sequential page tokens + acks): a REF can
    never overtake its DEF."""

    def __init__(self):
        with _STREAM_TOKENS_LOCK:
            self.token = next(_STREAM_TOKENS)
        self._ids: dict[int, int] = {}  # id(dictionary) -> dict id
        self._pins: list = []  # keep dicts alive so id() stays unique

    def dict_id(self, dictionary) -> tuple[int, bool]:
        """(dict_id, is_new) for a dictionary object on this stream."""
        key = id(dictionary)
        did = self._ids.get(key)
        if did is not None:
            return did, False
        did = len(self._pins)
        self._ids[key] = did
        self._pins.append(dictionary)
        return did, True


# Consumer-side sidecar registry: stream token -> dict id -> values.  The
# token is globally unique per producer stream, so pages from interleaved
# producers (a GATHER consumer pulling many upstream tasks) can never
# collide.  Bounded LRU by stream: dictionaries live as long as their
# stream stays among the most recent _DICT_REGISTRY_MAX streams.
_DICT_REGISTRY: "OrderedDict[int, dict[int, np.ndarray]]" = OrderedDict()
_DICT_REGISTRY_LOCK = threading.Lock()
_DICT_REGISTRY_MAX = 256


def _register_dict(token: int, did: int, values: np.ndarray) -> None:
    with _DICT_REGISTRY_LOCK:
        stream = _DICT_REGISTRY.get(token)
        if stream is None:
            stream = _DICT_REGISTRY[token] = {}
            while len(_DICT_REGISTRY) > _DICT_REGISTRY_MAX:
                _DICT_REGISTRY.popitem(last=False)
        else:
            _DICT_REGISTRY.move_to_end(token)
        stream[did] = values


def _lookup_dict(token: int, did: int) -> np.ndarray:
    with _DICT_REGISTRY_LOCK:
        stream = _DICT_REGISTRY.get(token)
        if stream is not None:
            _DICT_REGISTRY.move_to_end(token)
            values = stream.get(did)
            if values is not None:
                return values
    raise TrinoError(
        PAGE_TRANSPORT_ERROR,
        f"dictionary sidecar miss: stream {token} dict {did} "
        "(reference arrived before / outlived its definition)")


def _pack_bytes(out: list[bytes], b: bytes) -> None:
    out.append(struct.pack("<I", len(b)))
    out.append(b)


def _pack_str(out: list[bytes], s: str) -> None:
    _pack_bytes(out, s.encode("utf-8"))


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def blob(self) -> bytes:
        return self.take(self.u32())

    def text(self) -> str:
        return self.blob().decode("utf-8")


def _pack_dict_values(parts: list[bytes], dictionary) -> None:
    parts.append(struct.pack("<I", len(dictionary)))
    for v in dictionary:
        # tuples (array/row/map) and python ints (long decimals)
        # round-trip through repr; strings stay plain
        _pack_str(parts, repr(v) if isinstance(v, (tuple, int))
                  else str(v))


def _unpack_dict_values(r: "_Reader", type_: Type) -> np.ndarray:
    count = r.u32()
    texts = [r.text() for _ in range(count)]
    dictionary = np.empty(count, dtype=object)
    from ..spi.types import ArrayType, DecimalType, MapType, RowType

    if isinstance(type_, (ArrayType, RowType, MapType)):
        import ast as _ast

        for i, s in enumerate(texts):
            dictionary[i] = _ast.literal_eval(s)
    elif isinstance(type_, DecimalType) and type_.precision > 18:
        for i, s in enumerate(texts):
            dictionary[i] = int(s)
    else:
        for i, s in enumerate(texts):
            dictionary[i] = s
    return dictionary


def serialize_batch(batch: ColumnBatch, codec: int = CODEC_ZLIB,
                    ctx: Optional[PageStreamEncoder] = None) -> bytes:
    """One page: MAGIC, codec, u32 rows, u32 cols, then per column
    (name, type, dtype, data, has_valid [+bitmap], has_dict [+values]).

    With a :class:`PageStreamEncoder` ``ctx`` the page uses the v2 encoded
    format instead: RLE columns ship one value, dictionary columns ship
    their values once per stream (sidecar def/ref).  ``ctx=None`` stays
    bit-for-bit identical to the legacy v1 page."""
    if ctx is not None:
        return _serialize_batch_v2(batch, codec, ctx)
    batch = batch.compact()
    parts: list[bytes] = []
    parts.append(struct.pack("<II", batch.num_rows, batch.num_columns))
    for name, col in zip(batch.names, batch.columns):
        _pack_str(parts, name)
        _pack_str(parts, str(col.type))
        data = np.ascontiguousarray(np.asarray(col.data))
        _pack_str(parts, data.dtype.str)
        _pack_bytes(parts, data.tobytes())
        if col.valid is not None:
            parts.append(b"\x01")
            _pack_bytes(parts, np.packbits(np.asarray(col.valid)).tobytes())
        else:
            parts.append(b"\x00")
        if col.dictionary is not None:
            parts.append(b"\x01")
            _pack_dict_values(parts, col.dictionary)
        else:
            parts.append(b"\x00")
    payload = b"".join(parts)
    if codec == CODEC_ZLIB:
        payload = zlib.compress(payload, level=1)
    return _MAGIC + struct.pack("<BI", codec, len(payload)) + payload


def _serialize_batch_v2(batch: ColumnBatch, codec: int,
                        ctx: PageStreamEncoder) -> bytes:
    from ..telemetry import metrics as tm

    batch = batch.compact()
    parts: list[bytes] = []
    parts.append(struct.pack("<II", batch.num_rows, batch.num_columns))
    code_page = False
    for name, col in zip(batch.names, batch.columns):
        _pack_str(parts, name)
        _pack_str(parts, str(col.type))
        if col.encoding == "RLE":
            # ONE stored value; the consumer re-expands (or keeps the run)
            parts.append(struct.pack("<B", _ENC_RLE))
            value = np.ascontiguousarray(
                np.asarray(col.rle_value).reshape(1))
            _pack_str(parts, value.dtype.str)
            _pack_bytes(parts, value.tobytes())
        else:
            parts.append(struct.pack("<B", _ENC_FLAT))
            data = np.ascontiguousarray(np.asarray(col.data))
            _pack_str(parts, data.dtype.str)
            _pack_bytes(parts, data.tobytes())
        if col.valid is not None:
            parts.append(b"\x01")
            _pack_bytes(parts, np.packbits(np.asarray(col.valid)).tobytes())
        else:
            parts.append(b"\x00")
        if col.dictionary is None:
            parts.append(struct.pack("<B", _DICT_NONE))
        else:
            did, is_new = ctx.dict_id(col.dictionary)
            if is_new:
                parts.append(struct.pack("<BQI", _DICT_DEF, ctx.token, did))
                _pack_dict_values(parts, col.dictionary)
                tm.ENCODING_DICT_SIDECAR_SENT.inc()
            else:
                parts.append(struct.pack("<BQI", _DICT_REF, ctx.token, did))
                tm.ENCODING_DICT_SIDECAR_REUSED.inc()
            code_page = True
    if code_page:
        tm.ENCODING_EXCHANGE_CODE_PAGES.inc()
    payload = b"".join(parts)
    if codec == CODEC_ZLIB:
        payload = zlib.compress(payload, level=1)
    return _MAGIC2 + struct.pack("<BI", codec, len(payload)) + payload


def deserialize_batch(data: bytes) -> ColumnBatch:
    magic = data[:4]
    assert magic in (_MAGIC, _MAGIC2), "bad page magic"
    codec, plen = struct.unpack("<BI", data[4:9])
    payload = data[9:9 + plen]
    if codec == CODEC_ZLIB:
        payload = zlib.decompress(payload)
    r = _Reader(payload)
    num_rows, num_cols = struct.unpack("<II", r.take(8))
    if magic == _MAGIC2:
        return _deserialize_v2(r, num_rows, num_cols)
    names: list[str] = []
    cols: list[Column] = []
    for _ in range(num_cols):
        names.append(r.text())
        type_ = parse_type(r.text())
        dtype = np.dtype(r.text())
        arr = np.frombuffer(r.blob(), dtype=dtype).copy()
        valid: Optional[np.ndarray] = None
        if r.take(1) == b"\x01":
            bits = np.frombuffer(r.blob(), dtype=np.uint8)
            valid = np.unpackbits(bits, count=num_rows).astype(bool)
        dictionary = None
        if r.take(1) == b"\x01":
            dictionary = _unpack_dict_values(r, type_)
        cols.append(Column(type_, arr, valid, dictionary))
    return ColumnBatch(names, cols)


def _deserialize_v2(r: "_Reader", num_rows: int,
                    num_cols: int) -> ColumnBatch:
    names: list[str] = []
    cols: list[Column] = []
    for _ in range(num_cols):
        names.append(r.text())
        type_ = parse_type(r.text())
        enc = struct.unpack("<B", r.take(1))[0]
        dtype = np.dtype(r.text())
        arr = np.frombuffer(r.blob(), dtype=dtype).copy()
        valid: Optional[np.ndarray] = None
        if r.take(1) == b"\x01":
            bits = np.frombuffer(r.blob(), dtype=np.uint8)
            valid = np.unpackbits(bits, count=num_rows).astype(bool)
        dmode = struct.unpack("<B", r.take(1))[0]
        dictionary = None
        if dmode == _DICT_INLINE:
            dictionary = _unpack_dict_values(r, type_)
        elif dmode == _DICT_DEF:
            token, did = struct.unpack("<QI", r.take(12))
            dictionary = _unpack_dict_values(r, type_)
            _register_dict(token, did, dictionary)
        elif dmode == _DICT_REF:
            token, did = struct.unpack("<QI", r.take(12))
            dictionary = _lookup_dict(token, did)
        if enc == _ENC_RLE:
            cols.append(Column.rle(type_, arr[0], num_rows, valid,
                                   dictionary))
        else:
            cols.append(Column(type_, arr, valid, dictionary))
    return ColumnBatch(names, cols)
