"""Device-resident REPARTITION edges: the engine's ICI collective data plane.

When a REPARTITION edge connects two device-resident stages with equal task
counts (the PARTIAL->FINAL aggregation split being the canonical case), the
host exchange (PartitionedOutputSink hashing rows on host + pull-token
buffers) is replaced by ONE jitted ``shard_map`` program over a
``jax.sharding.Mesh``: every producer task deposits its padded device batch,
the last depositor launches the program — local hash routing +
``jax.lax.all_to_all`` per column — and each consumer task reads its
device shard.  Row data never touches the host; XLA lowers the all_to_all
onto ICI on a real TPU slice.

This is the engine-integrated form of ``parallel/distributed.py`` (which
demonstrates the same shuffle fused with static aggregation), standing in
for the reference's PagePartitioner + HTTP exchange
(operator/output/PagePartitioner.java:134, AddExchanges.java:138 choosing
FIXED_HASH_DISTRIBUTION) per SURVEY §2.4's collective mapping.
"""

from __future__ import annotations

import threading
from ..caching.executable_cache import jit_memo
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..parallel.compat import shard_map
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..exec import kernels as K
from ..exec import syncguard as SG
from ..exec.operators import Operator, _concat_device
from ..spi.batch import Column, ColumnBatch, unify_dictionaries
from ..spi.errors import (GENERIC_INTERNAL_ERROR, PAGE_TRANSPORT_TIMEOUT,
                          TrinoError)

__all__ = ["CollectiveRepartitionExchange", "CollectiveOutputSink",
           "CollectiveSourceOperator", "collectives_available"]

_AXIS = "x"

# deposits at or below this row bucket use the broadcast lane layout (one
# program, no extra host sync — right for slot-capped partial-agg states);
# larger deposits take the tiled sorted-bucket path (local sort by owner,
# per-destination tiles, ~1x data volume instead of n_dev x).  Tests force
# the tiled path by setting this to 0.
TILED_THRESHOLD_ROWS = 8192


def collectives_available(n_tasks: int) -> bool:
    try:
        return len(jax.devices()) >= n_tasks and n_tasks > 1
    except Exception:
        return False


@jit_memo("collective._shuffle_program")
def _shuffle_program(n_dev: int, n_cols: int, dtypes: tuple,
                     valid_flags: tuple, key_idx: tuple, cap: int):
    """One jitted shard_map: route rows of the local [cap] block to owner
    devices by key hash; outputs hold [n_dev*cap] lanes per device.

    Capacity contract (same as parallel/distributed.py): the lane layout
    sends a [n_dev, cap] block per column — each consumer receives
    ``n_dev*cap`` live-masked lanes.  Sized for the partial-state batches
    this edge carries (group slots, not raw rows); a tiled sorted-bucket
    all_to_all is the follow-up for raw-row repartitions.

    Routing hashes the trailing ``route key`` inputs, which the caller
    builds as VALUE hashes for dictionary columns — matching the host
    exchange's _dict_value_hashes routing so mixed collective/host edges of
    one join agree on row ownership."""
    mesh = Mesh(jax.devices()[:n_dev], (_AXIS,))
    n_keys = len(key_idx)

    def local(*flat):
        datas = list(flat[:n_cols])
        n_valid = sum(valid_flags)
        valids_in = list(flat[n_cols:n_cols + n_valid])
        route_keys = list(flat[n_cols + n_valid:n_cols + n_valid + n_keys])
        live = flat[-1]
        valids: list = []
        vi = 0
        for i in range(n_cols):
            if valid_flags[i]:
                valids.append(valids_in[vi])
                vi += 1
            else:
                valids.append(None)
        # ---- destination by key hash (NULL keys -> device 0) -------------
        h = K.hash_combine(route_keys)
        dest = (h % jnp.uint64(n_dev)).astype(jnp.int32)
        null_key = None
        for i in key_idx:
            if valids[i] is not None:
                nk = ~valids[i]
                null_key = nk if null_key is None else (null_key | nk)
        if null_key is not None:
            dest = jnp.where(null_key, 0, dest)
        # ---- lane layout [n_dev, cap]: lane (d, s) live iff row s -> d ----
        lane_live = live[None, :] & (
            dest[None, :] == jnp.arange(n_dev, dtype=jnp.int32)[:, None])

        def shuffle(x):
            lanes = jnp.broadcast_to(x[None, :], (n_dev, cap))
            out = jax.lax.all_to_all(lanes, _AXIS, 0, 0, tiled=False)
            return out.reshape(n_dev * cap)

        out_datas = [shuffle(d) for d in datas]
        out_valids = [None if v is None else shuffle(v) for v in valids]
        out_live = jax.lax.all_to_all(lane_live, _AXIS, 0, 0,
                                      tiled=False).reshape(n_dev * cap)
        flat_out = out_datas + [v for v in out_valids if v is not None]
        return (*flat_out, out_live)

    n_in = n_cols + sum(valid_flags) + n_keys + 1
    n_out = n_cols + sum(valid_flags) + 1
    return mesh, jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=tuple([P(_AXIS)] * n_in),
        out_specs=tuple([P(_AXIS)] * n_out),
        check_vma=False,
    ))


@jit_memo("collective._sort_by_dest_program")
def _sort_by_dest_program(n_dev: int, n_cols: int, valid_flags: tuple,
                          key_idx: tuple, cap: int):
    """Tiled path, stage 1: per device, route rows to owners by key hash and
    locally sort them by destination (stable argsort — all dense vector
    work); returns the dest-sorted columns plus per-destination counts.
    The [n_dev, n_dev] counts matrix is the only host-visible output — one
    small pull picks the global tile size (the single data-dependent shape
    of the shuffle, same contract as the join's candidate-total sync)."""
    mesh = Mesh(jax.devices()[:n_dev], (_AXIS,))
    n_keys = len(key_idx)

    def local(*flat):
        datas = list(flat[:n_cols])
        n_valid = sum(valid_flags)
        valids_in = list(flat[n_cols:n_cols + n_valid])
        route_keys = list(flat[n_cols + n_valid:n_cols + n_valid + n_keys])
        live = flat[-1]
        valids: list = []
        vi = 0
        for i in range(n_cols):
            if valid_flags[i]:
                valids.append(valids_in[vi])
                vi += 1
            else:
                valids.append(None)
        h = K.hash_combine(route_keys)
        dest = (h % jnp.uint64(n_dev)).astype(jnp.int32)
        # NULL keys -> consumer 0 (same contract as _shuffle_program and
        # the host exchange's partition_assignments)
        null_key = None
        for i in key_idx:
            if valids[i] is not None:
                nk = ~valids[i]
                null_key = nk if null_key is None else (null_key | nk)
        if null_key is not None:
            dest = jnp.where(null_key, 0, dest)
        dest = jnp.where(live, dest, n_dev)  # dead rows sort last
        order = jnp.argsort(dest, stable=True)
        dest_sorted = dest[order]
        r = jnp.arange(n_dev, dtype=dest_sorted.dtype)
        counts = (K.searchsorted(dest_sorted, r, side="right")
                  - K.searchsorted(dest_sorted, r)).astype(jnp.int32)
        out = [d[order] for d in datas]
        out += [v[order] for v in valids if v is not None]
        return (*out, counts)

    n_in = n_cols + sum(valid_flags) + n_keys + 1
    n_out = n_cols + sum(valid_flags) + 1
    return mesh, jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=tuple([P(_AXIS)] * n_in),
        out_specs=tuple([P(_AXIS)] * n_out),
        check_vma=False,
    ))


@jit_memo("collective._tiled_all_to_all_program")
def _tiled_all_to_all_program(n_dev: int, n_cols: int, valid_flags: tuple,
                              cap: int, tile: int):
    """Tiled path, stage 2: pack each destination's dest-sorted run into a
    [n_dev, tile] lane block and all_to_all it over ICI; consumers flatten
    to n_dev*tile live-masked lanes.  Data volume per device is ~its own
    row count padded to tiles — the raw-row repartition the round-3
    exchange deferred (PagePartitioner.partitionPage equivalent)."""
    mesh = Mesh(jax.devices()[:n_dev], (_AXIS,))

    def local(*flat):
        datas = list(flat[:n_cols])
        n_valid = sum(valid_flags)
        valids_in = list(flat[n_cols:n_cols + n_valid])
        counts = flat[-1]
        ends = jnp.cumsum(counts)
        starts = ends - counts
        d_idx = jnp.arange(n_dev, dtype=jnp.int32)[:, None]
        s_idx = jnp.arange(tile, dtype=jnp.int32)[None, :]
        row = jnp.clip(starts[:, None] + s_idx, 0, cap - 1)
        lane_live = s_idx < counts[:, None]

        def shuffle(x):
            lanes = jnp.where(lane_live, x[row], jnp.zeros((), x.dtype)) \
                if x.dtype != jnp.bool_ else (x[row] & lane_live)
            out = jax.lax.all_to_all(lanes, _AXIS, 0, 0, tiled=False)
            return out.reshape(n_dev * tile)

        out = [shuffle(d) for d in datas]
        vi = 0
        for i in range(n_cols):
            if valid_flags[i]:
                out.append(shuffle(valids_in[vi]))
                vi += 1
        out_live = jax.lax.all_to_all(
            lane_live, _AXIS, 0, 0, tiled=False).reshape(n_dev * tile)
        return (*out, out_live)

    n_in = n_cols + sum(valid_flags) + 1
    n_out = n_cols + sum(valid_flags) + 1
    return mesh, jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=tuple([P(_AXIS)] * n_in),
        out_specs=tuple([P(_AXIS)] * n_out),
        check_vma=False,
    ))


class CollectiveRepartitionExchange:
    """Rendezvous for one REPARTITION edge: ``n_tasks`` producers deposit,
    consumers take their device shard after the collective runs."""

    def __init__(self, n_tasks: int, key_channels: Sequence[int],
                 names: Sequence[str], types: Sequence):
        self.n = n_tasks
        self.key_channels = tuple(key_channels)
        self.names = list(names)
        self.types = list(types)
        self._deposits: list[Optional[ColumnBatch]] = [None] * n_tasks
        self._count = 0
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._results: list[Optional[ColumnBatch]] = [None] * n_tasks
        self._error: Optional[BaseException] = None

    # ---------------------------------------------------------- producers
    def deposit(self, task_index: int, batches: list[ColumnBatch]) -> None:
        if batches:
            batch = _concat_device(batches)
        else:
            batch = ColumnBatch(self.names, [
                Column(t, np.empty(0, t.storage_dtype)) for t in self.types])
        run_it = False
        with self._lock:
            self._deposits[task_index] = batch
            self._count += 1
            run_it = self._count == self.n
        if run_it:
            try:
                self._run_collective()
            except BaseException as e:  # surfaced to every waiting consumer
                self._error = e
            self._done.set()

    def abort(self) -> None:
        self._error = RuntimeError("collective exchange aborted")
        self._done.set()

    # ----------------------------------------------------------- the program
    def _run_collective(self) -> None:
        deposits = list(self._deposits)
        n = self.n
        cap = K.bucket(max(max(b.num_rows for b in deposits), 1))

        # unify dictionary columns across deposits (host work over the tiny
        # dictionaries only; codes are remapped with a device gather)
        unified_dicts: list = []
        for ci, t in enumerate(self.types):
            if t.is_dictionary_encoded:
                cols = [b.columns[ci] for b in deposits]
                cols = unify_dictionaries(cols)
                for b, c in zip(deposits, cols):
                    b.columns[ci] = c
                unified_dicts.append(cols[0].dictionary)
            else:
                unified_dicts.append(None)

        valid_flags = tuple(
            any(b.columns[ci].valid is not None for b in deposits)
            for ci in range(len(self.types)))
        tiled = cap > TILED_THRESHOLD_ROWS

        if tiled:
            mesh, prog = _sort_by_dest_program(
                n, len(self.types), valid_flags, self.key_channels, cap)
        else:
            mesh, prog = _shuffle_program(
                n, len(self.types),
                tuple(np.dtype(t.storage_dtype).str for t in self.types),
                valid_flags, self.key_channels, cap)

        def pad(x, dtype, fill=0):
            x = jnp.asarray(x)
            if x.shape[0] < cap:
                x = jnp.concatenate(
                    [x, jnp.full((cap - x.shape[0],), fill, x.dtype)])
            return x

        def dev_col(c, dtype):
            # compressed execution: an RLE deposit expands device-side from
            # ONE scalar (rows past the deposit are dead lanes anyway), so
            # the run never crosses the host/device boundary expanded
            if c.encoding == "RLE":
                return K.rle_fill(c.rle_value, cap)
            return pad(c.data, dtype)

        # global [n*cap] arrays: shard i lives on mesh device i
        def make_global(per_task, dtype):
            sharding = NamedSharding(mesh, P(_AXIS))
            shards = [
                jax.device_put(per_task[i], mesh.devices[i])
                for i in range(n)
            ]
            return jax.make_array_from_single_device_arrays(
                (n * cap,), sharding, shards)

        flat = []
        for ci, t in enumerate(self.types):
            flat.append(make_global(
                [dev_col(deposits[i].columns[ci], t.storage_dtype)
                 for i in range(n)], t.storage_dtype))
        for ci in range(len(self.types)):
            if valid_flags[ci]:
                flat.append(make_global(
                    [pad(deposits[i].columns[ci].valid
                         if deposits[i].columns[ci].valid is not None
                         else jnp.ones(deposits[i].num_rows, jnp.bool_),
                         np.bool_) for i in range(n)], np.bool_))
        # route keys: dictionary columns hash by VALUE (the host exchange's
        # _dict_value_hashes scheme) so every edge of a join routes equal
        # values to the same consumer regardless of per-edge code spaces
        from .task import _dict_value_hashes

        for ki in self.key_channels:
            t = self.types[ki]
            per_task = []
            for i in range(n):
                c = deposits[i].columns[ki]
                if t.is_dictionary_encoded:
                    d = unified_dicts[ki]
                    vh = _dict_value_hashes(d) if d is not None else None
                    codes = jnp.asarray(c.data)
                    rk = (jnp.asarray(vh)[codes] if vh is not None and len(vh)
                          else jnp.zeros(c.data.shape[0], jnp.int64))
                else:
                    rk = c.data
                per_task.append(pad(rk, None))
            flat.append(make_global(per_task, None))
        lives = []
        for i in range(n):
            b = deposits[i]
            lv = (jnp.asarray(b.live) if b.live is not None
                  else jnp.ones(b.num_rows, jnp.bool_))
            lives.append(pad(lv, np.bool_, fill=False))
        flat.append(make_global(lives, np.bool_))

        outs = prog(*flat)
        if tiled:
            # stage 1 out: dest-sorted columns + per-destination counts;
            # ONE small pull picks the tile, then stage 2 moves the rows
            counts = np.asarray(
                SG.fetch(outs[-1], "exchange.tile-counts")).reshape(n, n)
            tile = K.bucket(max(int(counts.max()), 1))
            _, prog2 = _tiled_all_to_all_program(
                n, len(self.types), valid_flags, cap, tile)
            outs = prog2(*outs)
        out_live = outs[-1]
        out_datas = outs[:len(self.types)]
        out_valids_flat = list(outs[len(self.types):-1])
        out_valids: list = []
        for ci in range(len(self.types)):
            out_valids.append(out_valids_flat.pop(0) if valid_flags[ci] else None)

        # per-consumer shards: addressable single-device arrays
        def shards_of(garr):
            by_dev = {s.device: s.data for s in garr.addressable_shards}
            return [by_dev[mesh.devices[i]] for i in range(n)]

        data_shards = [shards_of(d) for d in out_datas]
        valid_shards = [None if v is None else shards_of(v) for v in out_valids]
        live_shards = shards_of(out_live)
        if any(d is not None for d in unified_dicts):
            # dictionary codes crossed the shuffle as resident int32 lanes —
            # each consumer shard is one code page that never decoded
            from ..telemetry import metrics as tm

            tm.ENCODING_EXCHANGE_CODE_PAGES.inc(n)
        for i in range(n):
            cols = []
            for ci, t in enumerate(self.types):
                cols.append(Column(
                    t, data_shards[ci][i],
                    None if valid_shards[ci] is None else valid_shards[ci][i],
                    unified_dicts[ci]))
            self._results[i] = ColumnBatch(list(self.names), cols,
                                           live_shards[i])

    # ----------------------------------------------------------- consumers
    def take(self, task_index: int,
             timeout: Optional[float] = None) -> ColumnBatch:
        """Blocking take under the PR-5 timeout policy: the default comes
        from TRINO_TPU_EXCHANGE_STALL_S (execution/task.py) instead of a
        hard-coded constant, and a stall raises a *retryable*
        PAGE_TRANSPORT_TIMEOUT — the same contract the HTTP exchange client
        carries, so retry_policy=QUERY treats a wedged collective exactly
        like a wedged page transport."""
        if timeout is None:
            from .task import STALL_TIMEOUT_S

            timeout = STALL_TIMEOUT_S
        from ..telemetry import profiler

        t0 = profiler.now() if profiler.enabled() else 0.0
        ok = self._done.wait(timeout)
        if t0:
            profiler.event(profiler.EXCHANGE, "collective.take", t0,
                           stalled=not ok)
        if not ok:
            raise TrinoError(
                PAGE_TRANSPORT_TIMEOUT,
                f"collective exchange stalled after {timeout:.0f}s")
        if self._error is not None:
            if isinstance(self._error, TrinoError):
                raise self._error      # keep the original classification
            raise TrinoError(
                GENERIC_INTERNAL_ERROR,
                f"collective exchange failed: {self._error}") from self._error
        return self._results[task_index]


class CollectiveOutputSink(Operator):
    """Producer-side terminal: buffers device batches, deposits at finish."""

    def __init__(self, exchange: CollectiveRepartitionExchange, task_index: int):
        self.exchange = exchange
        self.task_index = task_index
        self._batches: list[ColumnBatch] = []

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_rows:
            self._batches.append(batch)

    def finish_input(self) -> None:
        super().finish_input()
        self.exchange.deposit(self.task_index, self._batches)

    def is_finished(self) -> bool:
        return self.input_done


class CollectiveSourceOperator(Operator):
    """Consumer-side source: emits this task's device shard once."""

    blocking = True  # see RemoteExchangeSourceOperator

    def __init__(self, exchange: CollectiveRepartitionExchange, task_index: int):
        self.exchange = exchange
        self.task_index = task_index
        self.input_done = True
        self._emitted = False

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[ColumnBatch]:
        if self._emitted or self._closed:
            return None
        if not self.blocking and not self.exchange._done.is_set():
            return None  # park; the executor reschedules us
        self._emitted = True
        batch = self.exchange.take(self.task_index)
        return batch if batch.num_rows else None

    def is_finished(self) -> bool:
        return self._emitted or self._closed
