"""Adaptive query execution: the coordinator-side control plane.

Three capabilities, all gated by ``TRINO_TPU_ADAPTIVE`` (session property
``adaptive``): ``0`` is bit-for-bit legacy (this module is never touched),
``auto`` (default) engages only when the plan has decision edges, ``1``
forces the phased scheduler even without any.

1. **Phased stage activation.**  Fragments are grouped (union-find over
   collective/fused edges, whose all_to_all rendezvous requires
   co-activation) and activated bottom-up as their input groups activate.
   Plain-edge groups cascade immediately — streaming overlap is preserved —
   but a group containing an unresolved join decision site stays inactive:
   its fragments hold no task threads and its plan remains rewritable.

2. **Runtime join-distribution switching.**  The build (and, for
   partitioned joins, probe) edges of an eligible topmost join are
   *deferred*: their producers write into single-partition staging buffers
   whose cumulative ``bytes_enqueued`` counters and heavy-hitter sketches
   are the observed runtime statistics.  At the activation barrier the
   coordinator compares observed build bytes against the broadcast
   threshold and rewrites PARTITIONED<->BROADCAST before the consumer (and
   for B->P flips, a freshly split probe stage) is activated.  Rewrites
   mutate only per-execution fragments; Tier A plan-cache entries are
   plan-node-immutable and never see them.  Decisions are memoized in a
   bounded, runtime-stat-keyed side cache (never published to Tier A).

3. **Skew-aware repartitioning.**  The probe sink's per-task
   HeavyHitterSketch (top-k over the join-key hashes, device-computed,
   folded here) identifies keys above ``skew_factor`` x the mean partition
   weight; a kept partitioned join then splits each heavy key across
   several probe tasks (round-robin scatter) while the build router
   replicates that key's build rows to exactly those tasks.  Restricted to
   INNER/LEFT joins, where duplicated build rows cannot duplicate output.

Barrier rule: a site resolves when its build staging is complete OR any
deferred edge has buffered >= half its byte budget (the early trigger that
keeps producers from parking on a full staging buffer before the router
exists).  Routing is fixed at the barrier and streams thereafter, so
correctness needs only consistency between the two routers, not complete
statistics.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict, defaultdict
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from ..exec import kernels as K
from ..exec.stats import AdaptiveStats
from ..planner.plan import Join, RemoteSource, plan_text
from ..spi.batch import ColumnBatch
from .exchange import ExchangeClient, OutputBuffer
from .fragmenter import _walk, split_probe_fragment
from .task import _partition_key_tuple, maybe_deserialize

__all__ = ["AdaptiveExec", "HeavyHitterSketch", "adaptive_mode",
           "broadcast_threshold_bytes", "skew_factor"]


# --------------------------------------------------------------------- knobs
def adaptive_mode(session) -> str:
    """``0`` | ``1`` | ``auto`` — session property wins over the env."""
    v = getattr(session, "adaptive", None)
    if v is None:
        v = os.environ.get("TRINO_TPU_ADAPTIVE", "auto")
    v = str(v).strip().lower()
    if v in ("0", "false", "off", "no"):
        return "0"
    if v in ("1", "true", "on", "yes"):
        return "1"
    return "auto"


def broadcast_threshold_bytes(session) -> int:
    """Observed build side at or under this flips to broadcast; over it,
    a static broadcast flips back to partitioned (32 MiB default, the
    miniature of join-max-broadcast-table-size)."""
    v = int(getattr(session, "broadcast_threshold_bytes", 0) or 0)
    if v > 0:
        return v
    return int(os.environ.get("TRINO_TPU_BROADCAST_THRESHOLD_BYTES",
                              str(32 << 20)) or (32 << 20))


def skew_factor(session) -> float:
    """A probe key heavier than this multiple of the mean partition weight
    is split across multiple probe tasks."""
    v = float(getattr(session, "skew_factor", 0.0) or 0.0)
    if v > 0:
        return v
    return float(os.environ.get("TRINO_TPU_SKEW_FACTOR", "2.0") or 2.0)


# -------------------------------------------------------------------- sketch
class HeavyHitterSketch:
    """Bounded top-k frequency sketch over uint64 key hashes.

    ``update`` takes the device-computed hash lanes (exec/kernels.py
    ``partition_key_hashes``) already landed host-side; the dict is pruned
    to the heaviest entries whenever it outgrows ``4 * k``.  ``total`` is
    exact, per-key counts are lower bounds after pruning — fine for a
    "which keys dominate" verdict.  One sketch per producer task (single
    writer); the coordinator folds them with ``merge`` at the barrier.
    """

    __slots__ = ("k", "counts", "total")

    def __init__(self, k: int = 64):
        self.k = k
        self.counts: dict[int, int] = {}
        self.total = 0

    def update(self, h: np.ndarray) -> None:
        if len(h) == 0:
            return
        vals, cnts = np.unique(np.asarray(h, dtype=np.uint64),
                               return_counts=True)
        self.total += int(len(h))
        c = self.counts
        for v, n in zip(vals.tolist(), cnts.tolist()):
            c[v] = c.get(v, 0) + n
        if len(c) > 4 * self.k:
            keep = sorted(c.items(), key=lambda kv: -kv[1])[:2 * self.k]
            self.counts = dict(keep)

    def merge(self, other: "HeavyHitterSketch") -> None:
        self.total += other.total
        c = self.counts
        for v, n in other.counts.items():
            c[v] = c.get(v, 0) + n
        if len(c) > 4 * self.k:
            keep = sorted(c.items(), key=lambda kv: -kv[1])[:2 * self.k]
            self.counts = dict(keep)

    def heavy(self, factor: float, num_partitions: int) -> dict[int, int]:
        """hash -> count for keys above ``factor`` x mean partition weight."""
        if self.total == 0 or num_partitions < 2:
            return {}
        mean = self.total / num_partitions
        return {v: n for v, n in self.counts.items() if n > factor * mean}


def _imbalance_ratio(sketch: "HeavyHitterSketch", split: dict,
                     n: int) -> float:
    """Sketch-estimated max partition weight under plain hash routing
    divided by the max under ``split``.  Total probe work is unchanged by
    a split, so this ratio — not the split itself — is what a parallel
    host converts into wall-clock."""
    rest = max(sketch.total - sum(sketch.counts.values()), 0) / n
    before = np.full(n, rest)
    after = np.full(n, rest)
    for hv, cnt in sketch.counts.items():
        p = int(hv % np.uint64(n))
        before[p] += cnt
        if hv in split:
            after[split[hv]] += cnt / len(split[hv])
        else:
            after[p] += cnt
    return float(before.max() / max(after.max(), 1e-9))


# --------------------------------------------------------- decision plumbing
@dataclass
class DecisionEdge:
    """One deferred producer->consumer edge: producer tasks land pages in
    single-partition staging buffers; after the barrier a router thread
    re-routes them into ``routed`` under the decided distribution."""

    producer_fid: int
    consumer_fid: int
    role: str                  # "build" | "probe"
    keys: tuple                # hash keys, producer output coordinates
    staging: list = field(default_factory=list)
    sketches: list = field(default_factory=list)
    routed: Optional[OutputBuffer] = None
    router: Optional[threading.Thread] = None

    def bytes_observed(self) -> int:
        return sum(b.bytes_enqueued for b in self.staging)

    def complete(self) -> bool:
        return bool(self.staging) and all(b.finished for b in self.staging)

    def fold_sketch(self) -> Optional[HeavyHitterSketch]:
        if not self.sketches:
            return None
        out = HeavyHitterSketch(self.sketches[0].k)
        for s in self.sketches:
            out.merge(s)
        return out


@dataclass
class JoinSite:
    """One adaptive decision point: the topmost INNER/LEFT join of a
    multi-task consumer fragment whose build (and probe, when partitioned)
    inputs are plain remote edges."""

    consumer_fid: int
    join: Join
    static: str                # the planner's choice: PARTITIONED|BROADCAST
    n: int                     # consumer task count
    build: DecisionEdge
    probe: Optional[DecisionEdge]
    can_refragment: bool = False
    resolved: bool = False

    def edges(self):
        return (self.build,) if self.probe is None else (self.build,
                                                         self.probe)


_COALESCE_ROWS = 32768


class _Router(threading.Thread):
    """Drains one deferred edge's staging buffers into its routed buffer
    under the decided distribution.  Modes: broadcast, round_robin, hash
    (with an optional heavy-key split map: probe rows scatter round-robin
    across the key's target tasks, build rows replicate to all of them).

    Hash routing slices every staging page into up-to-``n`` slivers; fed
    straight to the consumer those slivers mean one join-probe dispatch
    (and one expansion estimate) per sliver.  Slivers are therefore
    coalesced per target and released in ~``_COALESCE_ROWS``-row pages."""

    def __init__(self, name: str, staging: list, out: OutputBuffer, n: int,
                 mode: str, keys=(), split=None, replicate=False,
                 errors=None):
        super().__init__(name=name, daemon=True)
        self.staging = staging
        self.out = out
        self.n = n
        self.mode = mode
        self.keys = list(keys)
        self.split = dict(split or {})       # hash -> np.ndarray of targets
        self.replicate = replicate
        self.errors = errors
        self._rr = 0
        self._offsets: dict[int, int] = {}   # per-heavy-key scatter cursor
        self._heavy = (np.array(sorted(self.split), dtype=np.uint64)
                       if self.split else None)
        self._pend: dict[int, list] = {}     # target -> [rows, [slivers]]

    def run(self):
        try:
            client = ExchangeClient(self.staging, 0)
            while not client.is_finished():
                page = client.poll(timeout=0.05)
                if page is None:
                    continue
                self._route(maybe_deserialize(page))
            for p in list(self._pend):
                self._flush(p)
            self.out.set_finished()
        except BaseException as e:  # noqa: BLE001 — surfaced to coordinator
            if self.errors is not None:
                self.errors.append(e)
            self.out.abort()
            for b in self.staging:
                b.abort()

    def _emit(self, p: int, batch) -> None:
        ent = self._pend.get(p)
        if ent is None:
            ent = self._pend[p] = [0, []]
        ent[0] += batch.num_rows
        ent[1].append(batch)
        if ent[0] >= _COALESCE_ROWS:
            self._flush(p)

    def _flush(self, p: int) -> None:
        ent = self._pend.pop(p, None)
        if ent is not None and ent[1]:
            self.out.enqueue(p, ColumnBatch.concat(ent[1]))

    def _route(self, batch) -> None:
        n = self.n
        if batch.num_rows == 0:
            return
        if self.mode == "broadcast":
            for p in range(n):
                self.out.enqueue(p, batch)
            return
        if self.mode == "round_robin":
            self.out.enqueue(self._rr % n, batch)
            self._rr += 1
            return
        # hash: identical lanes to the legacy sink (kernels.py), so a kept
        # decision reproduces the static routing bit-for-bit per producer
        h = K.partition_key_hashes(
            [_partition_key_tuple(batch.columns[k]) for k in self.keys])
        parts = (h % np.uint64(n)).astype(np.int32)
        heavy_mask = (np.isin(h, self._heavy) if self._heavy is not None
                      else None)
        for p in range(n):
            m = parts == p
            if heavy_mask is not None:
                m = m & ~heavy_mask
            sub = batch.filter(m)
            if sub.num_rows:
                self._emit(p, sub)
        if heavy_mask is None or not heavy_mask.any():
            return
        for hv, targets in self.split.items():
            m = h == np.uint64(hv)
            if not m.any():
                continue
            if self.replicate:
                sub = batch.filter(m)
                for t in targets:
                    self._emit(int(t), sub)
                continue
            idx = np.nonzero(m)[0]
            off = self._offsets.get(hv, 0)
            slot = (np.arange(len(idx)) + off) % len(targets)
            self._offsets[hv] = off + len(idx)
            for j, t in enumerate(targets):
                mm = np.zeros(len(h), dtype=bool)
                mm[idx[slot == j]] = True
                sub = batch.filter(mm)
                if sub.num_rows:
                    self._emit(int(t), sub)


# -------------------------------------------------- runtime-stat-keyed memo
# Decision memo: (plan shape, log2-bucketed runtime stats, knobs) -> kind.
# Deliberately separate from the Tier A plan cache — rewritten plans are
# per-execution and must never be published there.  Bounded LRU.
_MEMO: OrderedDict = OrderedDict()
_MEMO_CAP = 256
_MEMO_LOCK = threading.Lock()


def _memo_get(key):
    with _MEMO_LOCK:
        kind = _MEMO.get(key)
        if kind is not None:
            _MEMO.move_to_end(key)
        return kind


def _memo_put(key, kind) -> None:
    with _MEMO_LOCK:
        _MEMO[key] = kind
        _MEMO.move_to_end(key)
        while len(_MEMO) > _MEMO_CAP:
            _MEMO.popitem(last=False)


def reset_memo_for_test() -> None:
    with _MEMO_LOCK:
        _MEMO.clear()


# ----------------------------------------------------------------- the plane
class AdaptiveExec:
    """Per-query adaptive controller, driven by the coordinator's polled
    join loop: ``start`` activates every group not gated by a decision,
    ``advance`` resolves barriers and cascades newly unblocked groups."""

    def __init__(self, stages: dict, fragments: list, edges: dict,
                 sink_cap: int, session, errors: list):
        self.stages = stages
        self.sink_cap = sink_cap
        self.session = session
        self.errors = errors
        self.stats = AdaptiveStats()
        self.threshold = broadcast_threshold_bytes(session)
        self.skew = skew_factor(session)
        self.sites: list[JoinSite] = []
        self._aborted = False
        self._next_fid = max(stages) + 1 if stages else 0
        self._order = [f.id for f in fragments]
        self._plan_sites(fragments, edges)
        self._edge_by_producer = {
            e.producer_fid: e for s in self.sites for e in s.edges()}
        self._wire_staging()
        self._build_groups(fragments, edges)
        self._unspawned = set(self._order)

    # ------------------------------------------------------------- planning
    def _plan_sites(self, fragments, edges) -> None:
        def plain(fid: int, kind: str) -> bool:
            st = self.stages.get(fid)
            return (st is not None and fid not in edges
                    and st.fragment.output_kind == kind)

        for f in fragments:
            st = self.stages[f.id]
            if st.task_count < 2:
                continue
            join = next((x for x in _walk(f.root) if isinstance(x, Join)),
                        None)
            if join is None or join.join_type not in ("INNER", "LEFT"):
                continue
            br = join.right
            if not isinstance(br, RemoteSource):
                continue
            if join.distribution == "PARTITIONED":
                if br.kind != "REPARTITION" or not plain(br.fragment_id,
                                                         "REPARTITION"):
                    continue
                bl = join.left
                if (not isinstance(bl, RemoteSource)
                        or bl.kind != "REPARTITION"
                        or not plain(bl.fragment_id, "REPARTITION")):
                    continue
                build = DecisionEdge(
                    br.fragment_id, f.id, "build",
                    tuple(self.stages[br.fragment_id].fragment.output_keys))
                probe = DecisionEdge(
                    bl.fragment_id, f.id, "probe",
                    tuple(self.stages[bl.fragment_id].fragment.output_keys))
                self.sites.append(JoinSite(
                    f.id, join, "PARTITIONED", st.task_count, build, probe))
            elif join.distribution == "BROADCAST":
                if br.kind != "BROADCAST" or not plain(br.fragment_id,
                                                       "BROADCAST"):
                    continue
                if not join.left_keys:
                    continue
                # re-fragmenting cuts join.left into a new stage: every
                # remote edge inside it must be a plain buffer edge (no
                # collective/fused rendezvous, no order-sensitive MERGE) —
                # and the consumer itself must not be a fused/collective
                # producer: a fused seam plans a SNAPSHOT of the feed
                # subtree, so a runtime root rewrite would be invisible to
                # the task while the build-side client swap still happened
                ok = f.id not in edges
                for rs in _walk(join.left):
                    if not isinstance(rs, RemoteSource):
                        continue
                    p = self.stages.get(rs.fragment_id)
                    if (p is None or rs.fragment_id in edges
                            or p.fragment.output_kind == "MERGE"):
                        ok = False
                        break
                if not ok:
                    continue
                build = DecisionEdge(br.fragment_id, f.id, "build",
                                     tuple(join.right_keys))
                self.sites.append(JoinSite(
                    f.id, join, "BROADCAST", st.task_count, build, None,
                    can_refragment=True))

    def _wire_staging(self) -> None:
        """Swap each deferred producer's stage buffers for single-partition
        staging buffers: its tasks, abort paths and backpressure all keep
        working through the normal ``stage.buffers`` plumbing."""
        for site in self.sites:
            for e in site.edges():
                pstage = self.stages[e.producer_fid]
                e.staging = [OutputBuffer(1, max_bytes=self.sink_cap)
                             for _ in range(pstage.task_count)]
                pstage.buffers = e.staging
                e.routed = OutputBuffer(site.n, max_bytes=self.sink_cap)
                if e.role == "probe":
                    e.sketches = [HeavyHitterSketch()
                                  for _ in range(pstage.task_count)]

    def _build_groups(self, fragments, edges) -> None:
        parent = {f.id: f.id for f in fragments}

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        consumer_of = {}
        for f in fragments:
            for src in f.source_fragments:
                consumer_of[src] = f.id
        # collective/fused edges rendezvous producer and consumer tasks:
        # both sides must activate together
        for src in edges:
            if src in consumer_of and src in parent:
                parent[find(src)] = find(consumer_of[src])
        self._group_of = {fid: find(fid) for fid in parent}
        members = defaultdict(list)
        for fid in self._order:
            members[self._group_of[fid]].append(fid)
        self._group_members = dict(members)
        self._group_deps = {
            g: {self._group_of[src]
                for fid in m
                for src in self.stages[fid].fragment.source_fragments
                if self._group_of.get(src, g) != g}
            for g, m in self._group_members.items()}
        self._sites_of_group = defaultdict(list)
        for s in self.sites:
            self._sites_of_group[self._group_of[s.consumer_fid]].append(s)
        self._activated: set = set()

    # ----------------------------------------------------------- accessors
    def routed_buffer(self, src: int) -> Optional[OutputBuffer]:
        """The consumer-facing buffer of a deferred edge (None otherwise)."""
        e = self._edge_by_producer.get(src)
        return e.routed if e is not None else None

    def sink_override(self, fid: int, task_index: int):
        """(sketch, sketch_keys) for a deferred producer's sink — its kind
        is forced to GATHER into staging; None for ordinary fragments."""
        e = self._edge_by_producer.get(fid)
        if e is None:
            return None
        if e.sketches:
            return e.sketches[task_index], tuple(e.keys)
        return None, ()

    def is_deferred_producer(self, fid: int) -> bool:
        return fid in self._edge_by_producer

    def observed_stats(self) -> dict:
        """Per deferred-producer fragment: exact rows/bytes observed at the
        activation barrier (staging is single-partition, so the counters
        are not inflated by broadcast fan-out) plus the folded sketch's
        heavy-hitter share — the feed for history-based optimization."""
        out: dict[int, dict] = {}
        for fid, e in self._edge_by_producer.items():
            entry = {
                "rows": sum(b.rows_enqueued for b in e.staging),
                "bytes": e.bytes_observed(),
            }
            sk = e.fold_sketch()
            if sk is not None and sk.total:
                entry["skew"] = max(sk.counts.values(), default=0) / sk.total
            out[fid] = entry
        return out

    def done(self) -> bool:
        return self._aborted or (
            all(s.resolved for s in self.sites)
            and len(self._activated) == len(self._group_members))

    def unactivated(self) -> list[str]:
        if self._aborted:
            return []
        return [f"stage-{fid}" for fid in sorted(self._unspawned)]

    def abort(self) -> None:
        self._aborted = True
        for site in self.sites:
            for e in site.edges():
                for b in e.staging:
                    b.abort()
                if e.routed is not None:
                    e.routed.abort()

    # ----------------------------------------------------------- scheduling
    def start(self, spawn: Callable[[int], list]) -> list:
        return self._cascade(spawn)

    def advance(self, spawn: Callable[[int], list]) -> list:
        if self._aborted:
            return []
        out = []
        for site in self.sites:
            if site.resolved:
                continue
            # every deferred edge drained to completion (full statistics)
            # OR any edge nearing its staging budget (partial statistics
            # beat a parked producer; routing is fixed here either way)
            if (all(e.complete() for e in site.edges())
                    or any(self._early(e) for e in site.edges())):
                out.extend(self._decide(site, spawn))
                site.resolved = True
        out.extend(self._cascade(spawn))
        return out

    def _early(self, e: DecisionEdge) -> bool:
        # resolve before any producer parks on a full staging buffer; the
        # routers started at the barrier keep draining from then on
        return any(b.bytes_enqueued >= self.sink_cap // 2
                   for b in e.staging)

    def _cascade(self, spawn) -> list:
        out = []
        progress = True
        while progress and not self._aborted:
            progress = False
            for g, members in self._group_members.items():
                if g in self._activated:
                    continue
                if any(d not in self._activated
                       for d in self._group_deps[g]):
                    continue
                if any(not s.resolved for s in self._sites_of_group.get(
                        g, ())):
                    continue
                self._activated.add(g)
                progress = True
                for fid in members:
                    out.extend(spawn(fid))
                    self._unspawned.discard(fid)
                    self.stats.activations += 1
        return out

    # ------------------------------------------------------------ decisions
    def _decide(self, site: JoinSite, spawn) -> list:
        from ..planner.add_exchanges import rewrite_join_distribution
        from ..telemetry import metrics as tm
        from ..telemetry import profiler
        from ..telemetry import runtime as rt

        b_bytes = site.build.bytes_observed()
        b_complete = site.build.complete()
        sketch = site.probe.fold_sketch() if site.probe is not None else None
        p_rows = sketch.total if sketch is not None else 0
        key = (hashlib.sha1(plan_text(
                   self.stages[site.consumer_fid].fragment.root
               ).encode()).hexdigest()[:12],
               site.static, int(b_bytes).bit_length(),
               int(p_rows).bit_length(), self.threshold,
               round(self.skew, 3), site.n)
        kind = _memo_get(key)
        if kind is not None and self._valid(site, kind, b_complete):
            self.stats.memo_hits += 1
            tm.ADAPTIVE_MEMO_HITS.inc()
        else:
            if site.static == "PARTITIONED":
                kind = ("flip_to_broadcast"
                        if b_complete and b_bytes <= self.threshold
                        else "keep")
            else:
                kind = ("flip_to_partitioned"
                        if b_bytes > self.threshold and site.can_refragment
                        else "keep")
            _memo_put(key, kind)

        out: list = []
        consumer = self.stages[site.consumer_fid].fragment
        tag = f"{kind}[f{site.consumer_fid}]"
        if site.static == "PARTITIONED":
            if kind == "flip_to_broadcast":
                consumer.root = rewrite_join_distribution(
                    consumer.root, site.join, "BROADCAST")
                self._start_router(site.build, site, "broadcast")
                self._start_router(site.probe, site, "round_robin")
                self.stats.broadcast_flips += 1
                tm.ADAPTIVE_BROADCAST_FLIPS.inc()
            else:
                # split map computed fresh from this run's sketch (never
                # memoized: targets depend on live counts)
                split = self._split_map(sketch, site.n)
                self._start_router(site.build, site, "hash",
                                   keys=site.build.keys, split=split,
                                   replicate=True)
                self._start_router(site.probe, site, "hash",
                                   keys=site.probe.keys, split=split,
                                   replicate=False)
                if split:
                    kind = "skew_split"
                    tag = f"skew_split[f{site.consumer_fid}:{len(split)}k]"
                    self.stats.skew_splits += 1
                    tm.ADAPTIVE_SKEW_SPLITS.inc()
                    tm.ADAPTIVE_SKEW_IMBALANCE.set(
                        _imbalance_ratio(sketch, split, site.n))
        else:
            if kind == "flip_to_partitioned":
                from .distributed_runner import _Stage

                new_fid = self._next_fid
                self._next_fid += 1
                new_frag = split_probe_fragment(consumer, site.join, new_fid)
                new_frag.sink_coalesce_rows = _COALESCE_ROWS
                self.stages[new_fid] = _Stage(new_frag, site.n, [
                    OutputBuffer(site.n, max_bytes=self.sink_cap)
                    for _ in range(site.n)])
                self._start_router(site.build, site, "hash",
                                   keys=site.build.keys)
                out.extend(spawn(new_fid))
                self.stats.partition_flips += 1
                tm.ADAPTIVE_PARTITION_FLIPS.inc()
            else:
                self._start_router(site.build, site, "broadcast")
        for e in site.edges():
            if e.router is not None:
                out.append(e.router)

        self.stats.decision_points += 1
        self.stats.decisions.append(tag)
        tm.ADAPTIVE_DECISIONS.inc()
        if profiler.enabled():
            profiler.instant(
                profiler.ADAPTIVE, f"adaptive.{kind}",
                fragment=site.consumer_fid, static=site.static,
                build_bytes=b_bytes, build_complete=b_complete,
                probe_rows=p_rows, threshold=self.threshold)
        rt.add_adaptive(rt.current_record(), tag)
        return out

    @staticmethod
    def _valid(site: JoinSite, kind: str, b_complete: bool) -> bool:
        """Memoized kinds apply only when their preconditions still hold."""
        if kind == "flip_to_broadcast":
            return b_complete and site.probe is not None
        if kind == "flip_to_partitioned":
            return site.can_refragment
        return True

    def _split_map(self, sketch: Optional[HeavyHitterSketch],
                   n: int) -> dict:
        if sketch is None or sketch.total == 0:
            return {}
        mean = sketch.total / n
        split = {}
        for hv, cnt in sketch.heavy(self.skew, n).items():
            d = min(n, max(2, int(np.ceil(cnt / mean))))
            base = int(hv % np.uint64(n))
            split[hv] = np.array([(base + i) % n for i in range(d)],
                                 dtype=np.int32)
        return split

    def _start_router(self, e: Optional[DecisionEdge], site: JoinSite,
                      mode: str, keys=(), split=None,
                      replicate=False) -> None:
        if e is None:
            return
        e.router = _Router(
            f"adaptive-route-f{e.producer_fid}", e.staging, e.routed,
            site.n, mode, keys=keys, split=split, replicate=replicate,
            errors=self.errors)
        e.router.start()
