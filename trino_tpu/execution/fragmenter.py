"""PlanFragmenter: cut the distributed plan at REMOTE exchanges.

Mirrors sql/planner/PlanFragmenter.java:94 (``createSubPlans:124``): every
``Exchange(scope=REMOTE)`` boundary becomes a fragment edge; the consumer
side sees a ``RemoteSource`` leaf naming the producer fragment.  Fragment
partitioning (how many tasks execute it) follows SystemPartitioningHandle:
SOURCE (split-driven leaf), HASH (repartition consumer), SINGLE (gather
consumer / coordinator stage).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

import numpy as np

from ..planner.plan import (
    Aggregate,
    Exchange,
    Filter,
    Join,
    MatchRecognize,
    PlanNode,
    Project,
    RemoteSource,
    TableFunctionScan,
    TableScan,
    TableWriter,
    Union,
    plan_text,
)
from ..sql.ir import InputRef, referenced_inputs

__all__ = ["PlanFragment", "SubPlan", "FusedSeam", "ResidentEdge",
           "ResidentJoin", "ResidentPlan", "fragment_plan",
           "mark_device_residency", "split_probe_fragment"]

# Aggregate functions whose PARTIAL state merges with plain
# sum/min/max combines inside one jitted program (avg rides as its
# sum+count expansion from add_exchanges.partial_agg_layout).  distinct
# and STAT_AGGS never reach a PARTIAL/FINAL split with these fns.
_FUSABLE_AGGS = frozenset({"count", "sum", "min", "max", "avg"})

# Plan nodes whose operators keep batches host-side (Python row loops or
# connector writes); any fragment containing one is not device-resident.
_HOST_NODES = (MatchRecognize, TableFunctionScan, TableWriter)


@dataclass(frozen=True)
class FusedSeam:
    """A REPARTITION edge eligible for whole-stage compilation: the
    producer's PARTIAL aggregation, the all_to_all shuffle and the
    consumer's FINAL aggregation compile into ONE jitted program
    (execution/stage_compiler.py).  ``in_spec``/``out_spec`` record the
    seam PartitionSpec contract: both sides shard dim 0 over the mesh
    axis, so the fused program needs no resharding at the boundary."""

    producer_fid: int
    consumer_fid: int
    nk: int                    # number of group-key columns
    axis: str = "x"            # mesh axis name (matches collective_exchange)
    in_spec: tuple = ("x",)    # producer deposit sharding, dim 0
    out_spec: tuple = ("x",)   # consumer take sharding, dim 0


@dataclass(frozen=True)
class ResidentEdge:
    """One interior exchange edge of a ResidentPlan with its PartitionSpec
    contract.  BROADCAST edges gather build tables replicated (out_spec
    ``()``); the terminal REPARTITION seam keeps dim 0 sharded on both
    sides (``("x",) -> ("x",)``) so the compiled program inserts exactly
    one in-program ``all_to_all`` and no resharding."""

    producer_fid: int
    consumer_fid: int
    kind: str                  # BROADCAST | REPARTITION
    axis: str = "x"
    in_spec: tuple = ("x",)
    out_spec: tuple = ("x",)


@dataclass(frozen=True)
class ResidentJoin:
    """One broadcast hash join inlined into a resident-plan program.
    ``probe_key`` indexes the probe-side schema at this join's depth
    (feed columns ++ payloads of already-applied joins, bottom-up);
    ``build_key`` indexes the build fragment's output schema."""

    build_fid: int
    join_type: str             # INNER | LEFT
    probe_key: int
    build_key: int
    n_build_cols: int


@dataclass(frozen=True)
class ResidentPlan:
    """A maximal connected subtree of device-resident fragments — a
    multi-join broadcast tree feeding one PARTIAL->FINAL agg seam —
    compiled by execution/plan_compiler.py as ONE jitted program over a
    named mesh.  ``core_fid`` is the probe/agg fragment carrying the
    terminal FusedSeam; ``joins`` are bottom-up along the probe spine."""

    core_fid: int
    consumer_fid: int
    nk: int
    joins: tuple[ResidentJoin, ...]
    edges: tuple[ResidentEdge, ...]
    fragment_ids: tuple[int, ...]


@dataclass
class PlanFragment:
    id: int
    root: PlanNode
    partitioning: str          # SOURCE | HASH | SINGLE
    output_kind: str           # GATHER | REPARTITION | BROADCAST | OUTPUT
    output_keys: tuple[int, ...]
    source_fragments: list[int]
    device_resident: bool = False   # every operator keeps batches on device
    fused_seam: Optional[FusedSeam] = None  # set when this fragment's
    #                                 REPARTITION edge is whole-stage fusable
    resident_plan: Optional[ResidentPlan] = None  # set on the core fragment
    #                                 of a coalesced whole-plan program
    sink_coalesce_rows: int = 0     # >0: the output sink buffers each
    #                                 partition's slivers into pages of
    #                                 about this many rows (adaptive
    #                                 re-fragmented stages set this; one
    #                                 join-probe dispatch per sliver is
    #                                 what it avoids)


@dataclass
class SubPlan:
    fragment: PlanFragment
    children: list["SubPlan"]

    def all_fragments(self) -> list[PlanFragment]:
        out = []
        for c in self.children:
            out.extend(c.all_fragments())
        out.append(self.fragment)
        return out

    def text(self) -> str:
        lines = []
        for f in self.all_fragments():
            lines.append(
                f"Fragment {f.id} [{f.partitioning} -> {f.output_kind}"
                + (f" keys={list(f.output_keys)}" if f.output_keys else "")
                + f" sources={f.source_fragments}"
                + (" device-resident" if f.device_resident else "")
                + (f" fused-seam->f{f.fused_seam.consumer_fid}"
                   if f.fused_seam is not None else "")
                + (f" resident-plan[{len(rp.fragment_ids)}f/"
                   f"{len(rp.edges)}e]"
                   if (rp := getattr(f, "resident_plan", None)) is not None
                   else "")
                + "]")
            lines.append(plan_text(f.root, 1))
        return "\n".join(lines)


class _Fragmenter:
    def __init__(self):
        self.next_id = 0
        self.subplans: dict[int, SubPlan] = {}

    def fragment(self, node: PlanNode, output_kind: str,
                 output_keys: tuple[int, ...]) -> SubPlan:
        fid = self.next_id
        self.next_id += 1
        sources: list[int] = []
        children: list[SubPlan] = []
        root = self._rewrite(node, sources, children)
        partitioning = self._partitioning(root)
        frag = PlanFragment(fid, root, partitioning, output_kind,
                            output_keys, sources)
        sp = SubPlan(frag, children)
        self.subplans[fid] = sp
        return sp

    def _rewrite(self, node: PlanNode, sources: list[int],
                 children: list[SubPlan]) -> PlanNode:
        if isinstance(node, Exchange) and node.scope == "REMOTE":
            child = self.fragment(node.source, node.kind, node.partition_keys)
            sources.append(child.fragment.id)
            children.append(child)
            return RemoteSource(node.output_names, node.output_types,
                               child.fragment.id, node.kind, node.sort_keys)
        kids = node.children
        if not kids:
            return node
        new_kids = [self._rewrite(c, sources, children) for c in kids]
        if all(a is b for a, b in zip(kids, new_kids)):
            return node
        if isinstance(node, Union):
            return replace(node, sources=tuple(new_kids))
        if len(kids) == 1:
            return replace(node, source=new_kids[0])
        return replace(node, left=new_kids[0], right=new_kids[1]) \
            if hasattr(node, "left") else \
            replace(node, source=new_kids[0], filter_source=new_kids[1])

    @staticmethod
    def _partitioning(root: PlanNode) -> str:
        has_scan = False
        kinds = []

        def walk(n: PlanNode):
            nonlocal has_scan
            if isinstance(n, TableScan):
                has_scan = True
            if isinstance(n, RemoteSource):
                kinds.append(n.kind)
            for c in n.children:
                walk(c)

        walk(root)
        if has_scan:
            return "SOURCE"
        if "REPARTITION" in kinds:
            return "HASH"
        if "ROUND_ROBIN" in kinds:
            return "ARBITRARY"  # FIXED_ARBITRARY_DISTRIBUTION: multi-task
        return "SINGLE"


def _walk(node: PlanNode):
    yield node
    for c in node.children:
        yield from _walk(c)


def _dict_free(expr, in_types) -> bool:
    """True when ``expr`` reads no dictionary-encoded channel (the fused
    accumulate program evaluates expressions on raw lanes; dictionary
    columns may only pass through as bare InputRefs)."""
    return not any(in_types[i].is_dictionary_encoded
                   for i in referenced_inputs(expr))


def _match_fused_seam(producer: PlanFragment,
                      consumer: PlanFragment) -> Optional[FusedSeam]:
    """Structural eligibility of one REPARTITION edge for whole-stage
    compilation: producer root is ``Aggregate(PARTIAL)`` over a
    Filter/Project chain, consumer FINAL-aggregates exactly this edge,
    aggregate states merge with plain sum/min/max combines, and every
    fused expression reads only non-dictionary channels."""
    root = producer.root
    if producer.output_kind != "REPARTITION":
        return None
    if not isinstance(root, Aggregate) or root.step != "PARTIAL":
        return None
    nk = len(root.group_keys)
    if nk == 0 or producer.output_keys != tuple(range(nk)):
        return None
    if any(a.distinct or a.fn not in _FUSABLE_AGGS for a in root.aggregates):
        return None
    src_types = root.source.output_types
    for a in root.aggregates:
        # agg args must be plain numeric lanes (covers long decimals:
        # precision > 18 is dictionary/limb-encoded)
        if a.arg >= 0 and src_types[a.arg].is_dictionary_encoded:
            return None
    node = root.source
    while isinstance(node, (Filter, Project)):
        in_types = node.source.output_types
        if isinstance(node, Filter):
            if not _dict_free(node.predicate, in_types):
                return None
        else:
            for e in node.expressions:
                if not isinstance(e, InputRef) and not _dict_free(e, in_types):
                    return None
        node = node.source
    # the consumer must FINAL-aggregate this edge, and reference it only there
    finals = [n for n in _walk(consumer.root)
              if isinstance(n, Aggregate) and n.step == "FINAL"
              and isinstance(n.source, RemoteSource)
              and n.source.fragment_id == producer.id]
    remotes = [n for n in _walk(consumer.root)
               if isinstance(n, RemoteSource)
               and n.fragment_id == producer.id]
    if len(finals) != 1 or len(remotes) != 1:
        return None
    fin = finals[0]
    if fin.group_keys != tuple(range(nk)) or len(fin.aggregates) != len(root.aggregates):
        return None
    if any(fa.fn != pa.fn for fa, pa in zip(fin.aggregates, root.aggregates)):
        return None
    return FusedSeam(producer.id, consumer.id, nk)


def _resident_key_ok(t) -> bool:
    """Join keys the in-program sorted-probe handles: plain integer lanes.
    Dictionary codes on the PROBE side drift per batch (remapped host-side
    before the launch); value-space decimals/doubles never reach broadcast
    join keys in TPC-H shapes we inline."""
    from ..spi.types import DecimalType
    if t.is_dictionary_encoded or isinstance(t, DecimalType):
        return False
    return np.dtype(t.storage_dtype).kind in "iu"


def _match_resident_plan(producer: PlanFragment,
                         frags: dict[int, PlanFragment],
                         rs_counts: dict[int, int],
                         ) -> Optional[ResidentPlan]:
    """Coalesce a maximal broadcast-join tree under an already-matched
    FusedSeam into one ResidentPlan: the producer's probe spine must be
    single-key BROADCAST INNER/LEFT joins whose build sides are
    device-resident single-consumer SOURCE fragments, bottoming out in a
    pure scan chain.  Every interior edge gets a PartitionSpec contract;
    plan_compiler.py lowers the whole record to a single jitted program."""
    seam = producer.fused_seam
    if seam is None or rs_counts.get(producer.id, 0) != 1:
        return None
    node = producer.root.source            # Aggregate(PARTIAL).source
    while isinstance(node, (Filter, Project)):
        node = node.source
    joins: list[ResidentJoin] = []
    build_fids: list[int] = []
    while isinstance(node, Join):
        if (node.distribution != "BROADCAST"
                or node.join_type not in ("INNER", "LEFT")
                or node.residual is not None
                or len(node.left_keys) != 1 or len(node.right_keys) != 1):
            return None
        rs = node.right
        if not isinstance(rs, RemoteSource) or rs.kind != "BROADCAST":
            return None
        b = frags.get(rs.fragment_id)
        if (b is None or not b.device_resident
                or b.output_kind != "BROADCAST"
                or b.partitioning != "SOURCE"
                or b.source_fragments
                or rs_counts.get(b.id, 0) != 1):
            return None
        pk_t = node.left.output_types[node.left_keys[0]]
        bk_t = rs.output_types[node.right_keys[0]]
        if not (_resident_key_ok(pk_t) and _resident_key_ok(bk_t)):
            return None
        joins.append(ResidentJoin(b.id, node.join_type, node.left_keys[0],
                                  node.right_keys[0], len(rs.output_types)))
        build_fids.append(b.id)
        node = node.left
    if not joins:
        return None
    if any(isinstance(n, RemoteSource) for n in _walk(node)):
        return None                        # feed must be a pure scan chain
    if set(producer.source_fragments) != set(build_fids):
        return None
    joins.reverse()                        # bottom-up along the probe spine
    edges = tuple(
        ResidentEdge(fid, producer.id, "BROADCAST", out_spec=())
        for fid in build_fids
    ) + (ResidentEdge(producer.id, seam.consumer_fid, "REPARTITION"),)
    return ResidentPlan(
        core_fid=producer.id, consumer_fid=seam.consumer_fid, nk=seam.nk,
        joins=tuple(joins), edges=edges,
        fragment_ids=tuple(sorted({producer.id, seam.consumer_fid,
                                   *build_fids})))


def mark_device_residency(subplan: SubPlan) -> SubPlan:
    """Bottom-up TPU-residency propagation + fused-seam recording.

    A fragment is device-resident when none of its own nodes run host-side
    loops and all of its source fragments are device-resident; on every
    device-resident REPARTITION producer whose consumer FINAL-aggregates
    it, record the FusedSeam that stage_compiler.py compiles into one
    jitted program."""
    frags = {f.id: f for f in subplan.all_fragments()}
    for f in subplan.all_fragments():  # children first
        own = not any(isinstance(n, _HOST_NODES) for n in _walk(f.root))
        f.device_resident = own and all(
            frags[s].device_resident for s in f.source_fragments)
    for consumer in frags.values():
        for src in consumer.source_fragments:
            producer = frags[src]
            if not producer.device_resident:
                continue
            seam = _match_fused_seam(producer, consumer)
            if seam is not None:
                producer.fused_seam = seam
    # RemoteSource reference counts gate whole-plan coalescing: a build
    # or core fragment consumed from more than one site can't fold into
    # one program without duplicating work.
    rs_counts: dict[int, int] = {}
    for f in frags.values():
        for n in _walk(f.root):
            if isinstance(n, RemoteSource):
                rs_counts[n.fragment_id] = rs_counts.get(n.fragment_id, 0) + 1
    for f in frags.values():
        if f.fused_seam is not None:
            f.resident_plan = _match_resident_plan(f, frags, rs_counts)
    return subplan


def split_probe_fragment(consumer: PlanFragment, join,
                         new_fid: int) -> PlanFragment:
    """Runtime broadcast->partitioned re-fragmentation (adaptive plane):
    cut ``join.left`` (the probe subtree) out of the not-yet-activated
    ``consumer`` fragment into a new REPARTITION fragment hashing on the
    join's probe keys, and re-enter it as a RemoteSource.  RemoteSources
    inside the subtree move with it: their producer fragments now feed the
    new fragment.  ``consumer`` is mutated in place (runtime fragments are
    per-execution copies, never plan-cache residents)."""
    from ..planner.add_exchanges import rewrite_join_distribution

    subtree = join.left
    moved = [n.fragment_id for n in _walk(subtree)
             if isinstance(n, RemoteSource)]
    new_frag = PlanFragment(
        new_fid, subtree, _Fragmenter._partitioning(subtree),
        "REPARTITION", tuple(join.left_keys), moved)
    rs = RemoteSource(subtree.output_names, subtree.output_types,
                      new_fid, "REPARTITION", ())
    consumer.root = rewrite_join_distribution(
        consumer.root, join, "PARTITIONED", new_left=rs)
    consumer.source_fragments = [
        s for s in consumer.source_fragments if s not in moved] + [new_fid]
    consumer.partitioning = _Fragmenter._partitioning(consumer.root)
    return new_frag


def fragment_plan(root: PlanNode) -> SubPlan:
    """Root fragment is the coordinator (OUTPUT) stage."""
    return mark_device_residency(_Fragmenter().fragment(root, "OUTPUT", ()))
