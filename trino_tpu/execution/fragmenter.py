"""PlanFragmenter: cut the distributed plan at REMOTE exchanges.

Mirrors sql/planner/PlanFragmenter.java:94 (``createSubPlans:124``): every
``Exchange(scope=REMOTE)`` boundary becomes a fragment edge; the consumer
side sees a ``RemoteSource`` leaf naming the producer fragment.  Fragment
partitioning (how many tasks execute it) follows SystemPartitioningHandle:
SOURCE (split-driven leaf), HASH (repartition consumer), SINGLE (gather
consumer / coordinator stage).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..planner.plan import (
    Exchange,
    PlanNode,
    RemoteSource,
    TableScan,
    Union,
    plan_text,
)

__all__ = ["PlanFragment", "SubPlan", "fragment_plan"]


@dataclass
class PlanFragment:
    id: int
    root: PlanNode
    partitioning: str          # SOURCE | HASH | SINGLE
    output_kind: str           # GATHER | REPARTITION | BROADCAST | OUTPUT
    output_keys: tuple[int, ...]
    source_fragments: list[int]


@dataclass
class SubPlan:
    fragment: PlanFragment
    children: list["SubPlan"]

    def all_fragments(self) -> list[PlanFragment]:
        out = []
        for c in self.children:
            out.extend(c.all_fragments())
        out.append(self.fragment)
        return out

    def text(self) -> str:
        lines = []
        for f in self.all_fragments():
            lines.append(
                f"Fragment {f.id} [{f.partitioning} -> {f.output_kind}"
                + (f" keys={list(f.output_keys)}" if f.output_keys else "")
                + f" sources={f.source_fragments}]")
            lines.append(plan_text(f.root, 1))
        return "\n".join(lines)


class _Fragmenter:
    def __init__(self):
        self.next_id = 0
        self.subplans: dict[int, SubPlan] = {}

    def fragment(self, node: PlanNode, output_kind: str,
                 output_keys: tuple[int, ...]) -> SubPlan:
        fid = self.next_id
        self.next_id += 1
        sources: list[int] = []
        children: list[SubPlan] = []
        root = self._rewrite(node, sources, children)
        partitioning = self._partitioning(root)
        frag = PlanFragment(fid, root, partitioning, output_kind,
                            output_keys, sources)
        sp = SubPlan(frag, children)
        self.subplans[fid] = sp
        return sp

    def _rewrite(self, node: PlanNode, sources: list[int],
                 children: list[SubPlan]) -> PlanNode:
        if isinstance(node, Exchange) and node.scope == "REMOTE":
            child = self.fragment(node.source, node.kind, node.partition_keys)
            sources.append(child.fragment.id)
            children.append(child)
            return RemoteSource(node.output_names, node.output_types,
                               child.fragment.id, node.kind, node.sort_keys)
        kids = node.children
        if not kids:
            return node
        new_kids = [self._rewrite(c, sources, children) for c in kids]
        if all(a is b for a, b in zip(kids, new_kids)):
            return node
        if isinstance(node, Union):
            return replace(node, sources=tuple(new_kids))
        if len(kids) == 1:
            return replace(node, source=new_kids[0])
        return replace(node, left=new_kids[0], right=new_kids[1]) \
            if hasattr(node, "left") else \
            replace(node, source=new_kids[0], filter_source=new_kids[1])

    @staticmethod
    def _partitioning(root: PlanNode) -> str:
        has_scan = False
        kinds = []

        def walk(n: PlanNode):
            nonlocal has_scan
            if isinstance(n, TableScan):
                has_scan = True
            if isinstance(n, RemoteSource):
                kinds.append(n.kind)
            for c in n.children:
                walk(c)

        walk(root)
        if has_scan:
            return "SOURCE"
        if "REPARTITION" in kinds:
            return "HASH"
        if "ROUND_ROBIN" in kinds:
            return "ARBITRARY"  # FIXED_ARBITRARY_DISTRIBUTION: multi-task
        return "SINGLE"


def fragment_plan(root: PlanNode) -> SubPlan:
    """Root fragment is the coordinator (OUTPUT) stage."""
    return _Fragmenter().fragment(root, "OUTPUT", ())
