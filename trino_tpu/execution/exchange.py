"""In-memory exchange data plane: pull-token output buffers.

Implements the reference's page-streaming protocol in-process (reference:
execution/buffer/ClientBuffer.java:318-376 — a read at token T implicitly
acknowledges and frees every page before T; execution/buffer/
PartitionedOutputBuffer.java:42 / BroadcastOutputBuffer.java:56).  The
network hop is a method call here; the protocol (token sequencing, ack-on-
advance, done marker) is kept so a real DCN/HTTP transport can slot in
without changing operators.

Backpressure: per-buffer byte budget; producers block in ``enqueue`` until
consumers drain (OutputBufferMemoryManager.java's blocking future).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..spi.batch import ColumnBatch

__all__ = ["OutputBuffer", "ExchangeClient"]


class OutputBuffer:
    """Per-task output: ``num_partitions`` independent page streams."""

    def __init__(self, num_partitions: int, max_bytes: int = 256 << 20):
        self.num_partitions = num_partitions
        self.max_bytes = max_bytes
        self._pages: list[list[Optional[ColumnBatch]]] = [
            [] for _ in range(num_partitions)]
        self._acked: list[int] = [0] * num_partitions
        self._finished = False
        self._aborted = False
        self._bytes = 0
        self._cv = threading.Condition()
        self.pages_enqueued = 0
        self.rows_enqueued = 0
        # cumulative (never decremented on ack) — the adaptive scheduler's
        # observed-output counter for activation barriers and join-
        # distribution decisions
        self.bytes_enqueued = 0

    def enqueue(self, partition: int, batch: ColumnBatch,
                block: bool = True) -> None:
        """``block=False`` skips the backpressure wait (time-sharing mode:
        the sink's driver parks via ``needs_input`` instead of pinning its
        executor worker here; at most one batch's partitions overshoot the
        byte budget between capacity checks)."""
        with self._cv:
            while (block and self._bytes > self.max_bytes
                   and not self._aborted):
                self._cv.wait(timeout=0.5)
            if self._aborted:
                return
            self._pages[partition].append(batch)
            self._bytes += batch.nbytes
            self.pages_enqueued += 1
            # wire relays enqueue SerializedPage, which carries no row count
            self.rows_enqueued += getattr(batch, "num_rows", 0)
            self.bytes_enqueued += batch.nbytes
            self._cv.notify_all()

    def has_capacity(self) -> bool:
        """True while the byte budget admits another page (the non-blocking
        sink's park predicate; only consumer acks can turn this back on)."""
        with self._cv:
            return self._aborted or self._bytes <= self.max_bytes

    def set_finished(self) -> None:
        with self._cv:
            self._finished = True
            self._cv.notify_all()

    @property
    def finished(self) -> bool:
        return self._finished

    def abort(self) -> None:
        with self._cv:
            self._aborted = True
            self._pages = [[] for _ in range(self.num_partitions)]
            self._bytes = 0
            self._cv.notify_all()

    @property
    def aborted(self) -> bool:
        return self._aborted

    @property
    def drained(self) -> bool:
        """True once the producer finished AND every page has been acked
        away — the point at which a draining worker may drop the task
        without losing unfetched output."""
        with self._cv:
            if self._aborted:
                return True
            return self._finished and not any(self._pages)

    def get(self, partition: int, token: int, timeout: float = 10.0
            ) -> tuple[list[ColumnBatch], int, bool]:
        """Read pages from sequence id ``token``; implicitly acks (frees)
        everything before it.  Returns (pages, next_token, done)."""
        with self._cv:
            # ack: free pages below token
            acked = self._acked[partition]
            if token > acked:
                stream = self._pages[partition]
                for i in range(acked, min(token, acked + len(stream))):
                    b = stream[i - acked]
                    if b is not None:
                        self._bytes -= b.nbytes
                        stream[i - acked] = None
                # drop freed prefix
                drop = token - acked
                self._pages[partition] = stream[drop:]
                self._acked[partition] = token
                self._cv.notify_all()
            acked = self._acked[partition]
            deadline = threading.TIMEOUT_MAX if timeout is None else timeout
            stream = self._pages[partition]
            if not stream and not self._finished and not self._aborted:
                self._cv.wait(timeout=deadline)
                stream = self._pages[partition]
            pages = [b for b in stream if b is not None]
            next_token = acked + len(stream)
            # an aborted buffer reports done so consumers unwind instead of
            # polling a dead producer forever
            done = (self._finished and not stream) or self._aborted
            return pages, next_token, done


class ExchangeClient:
    """Consumer side: pulls one partition from many upstream task buffers
    (operator/DirectExchangeClient.java:56)."""

    def __init__(self, buffers: list[OutputBuffer], partition: int):
        self._sources = [[b, 0, False] for b in buffers]
        self.partition = partition

    def poll(self, timeout: float = 0.05) -> Optional[ColumnBatch]:
        """One batch if available anywhere; None if drained-for-now.
        Consuming a page advances the token by one; the NEXT get() at that
        token acks (frees) it — exactly the reference's ack-on-advance."""
        from ..telemetry import profiler

        t0 = profiler.now() if profiler.enabled() else 0.0
        for s in self._sources:
            buf, token, done = s
            if done:
                continue
            pages, _next_token, fin = buf.get(self.partition, token,
                                              timeout=timeout)
            if pages:
                s[1] = token + 1
                if t0:
                    # serde-wired buffers hand back SerializedPage (no
                    # num_rows until deserialization downstream)
                    rows = getattr(pages[0], "num_rows", None)
                    profiler.event(profiler.EXCHANGE, "exchange.poll", t0,
                                   rows=rows)
                return pages[0]
            s[2] = fin
        # only dry polls that actually blocked are worth a timeline slice —
        # the 50ms poll loop would otherwise flood the ring with no-ops
        if t0 and profiler.now() - t0 > 0.010:
            profiler.event(profiler.EXCHANGE, "exchange.poll", t0, empty=True)
        return None

    def is_finished(self) -> bool:
        return all(done for _, _, done in self._sources)
