"""Multi-tenant serving plane: weighted-fair resource groups + cluster
memory manager with a low-memory killer.

The L1 layer below everything else — what one tenant may do to the cluster:

- :class:`ResourceGroup` — hierarchical admission with per-group
  ``soft/hard_concurrency_limit``, ``max_queued``, ``weight``,
  ``scheduling_policy in {fair, weighted_fair, query_priority}``,
  ``soft_memory_limit_bytes`` and CPU quotas with periodic regeneration
  (reference: execution/resourcegroups/InternalResourceGroup.java:75 —
  canRunMore/canQueueMore, internalRefreshStats, the scheduling policy
  queues).  This class REPLACES the flat group previously defined in
  control.py behind the same acquire/release surface; control.py re-exports
  it so every existing import keeps working.
- :class:`ClusterMemoryManager` — the coordinator-side aggregation of every
  query memory pool (in-process :class:`~..spi.memory.MemoryPool` refs plus
  worker reservations shipped in the /v1/status JSON), per-query
  ``max_memory`` enforcement and a pluggable low-memory killer
  (``largest_query`` / ``lowest_priority`` / ``youngest``) that fails the
  victim with CLUSTER_OUT_OF_MEMORY through the spi/errors.py taxonomy
  (reference: memory/ClusterMemoryManager.java:90 + LowMemoryKiller).
- :func:`estimate_peak_memory` — memory-aware admission input: the peak of
  recent finished runs of the same plan fingerprint
  (telemetry/runtime.py query records), falling back to a configured
  default.

Config: ``TRINO_TPU_RESOURCE_GROUPS`` holds a JSON group tree + selector
rules (see :func:`build_group_tree`); ``TRINO_TPU_CLUSTER_MEMORY_BYTES``
caps the coordinator's cluster memory view (unset = uncapped);
``TRINO_TPU_OOM_POLICY`` picks the victim policy;
``TRINO_TPU_QUERY_MAX_MEMORY`` bounds any single query's reservation.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import weakref
from typing import Callable, Optional

from ..spi.errors import (
    CLUSTER_OUT_OF_MEMORY,
    EXCEEDED_GLOBAL_MEMORY_LIMIT,
    QUERY_QUEUE_FULL,
    QUERY_QUEUED_TIMEOUT,
    TrinoError,
)

__all__ = [
    "ResourceGroup", "ClusterMemoryManager", "QueryMemoryHandle",
    "build_group_tree", "build_dispatch_manager", "find_group",
    "estimate_peak_memory", "OOM_POLICIES",
]

SCHEDULING_POLICIES = ("fair", "weighted_fair", "query_priority")
OOM_POLICIES = ("largest_query", "lowest_priority", "youngest")


class _Ticket:
    __slots__ = ("seq", "priority", "group", "event")

    def __init__(self, seq: int, priority: int, group: "ResourceGroup"):
        self.seq = seq
        self.priority = priority
        self.group = group
        self.event = threading.Event()


class ResourceGroup:
    """Hierarchical admission: a query runs when every ancestor has a free
    concurrency slot; otherwise it queues up to ``max_queued``.

    Scheduling policy decides which queued query a freed slot goes to —
    ``fair`` is global FIFO (the pre-existing behavior), ``weighted_fair``
    admits from the eligible child subtree with the lowest running/weight
    ratio, ``query_priority`` admits the highest-priority ticket.  A group
    above its ``soft_concurrency_limit`` only wins a slot when no sibling
    below its own soft limit wants it.  ``soft_memory_limit_bytes`` blocks
    NEW admissions while the group's aggregated reservation (pushed by the
    ClusterMemoryManager) sits above the limit; running queries are never
    interrupted here — that is the OOM killer's job.  CPU quotas regenerate
    at ``cpu_quota_generation_s_per_s``: usage above ``soft_cpu_limit_s``
    scales the concurrency limit down linearly, usage at
    ``hard_cpu_limit_s`` stops admissions entirely
    (reference: InternalResourceGroup updateGroupsAndProcessQueuedQueries +
    internalGenerateCpuQuota)."""

    def __init__(self, name: str, hard_concurrency_limit: int = 100,
                 max_queued: int = 1000,
                 parent: Optional["ResourceGroup"] = None,
                 soft_concurrency_limit: Optional[int] = None,
                 weight: int = 1,
                 scheduling_policy: str = "fair",
                 soft_memory_limit_bytes: Optional[int] = None,
                 soft_cpu_limit_s: Optional[float] = None,
                 hard_cpu_limit_s: Optional[float] = None,
                 cpu_quota_generation_s_per_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None):
        if scheduling_policy not in SCHEDULING_POLICIES:
            raise ValueError(
                f"scheduling_policy {scheduling_policy!r} not in "
                f"{SCHEDULING_POLICIES}")
        self.name = name
        self.hard_concurrency_limit = hard_concurrency_limit
        self.soft_concurrency_limit = soft_concurrency_limit
        self.max_queued = max_queued
        self.weight = max(1, int(weight))
        self.scheduling_policy = scheduling_policy
        self.soft_memory_limit_bytes = soft_memory_limit_bytes
        self.soft_cpu_limit_s = soft_cpu_limit_s
        self.hard_cpu_limit_s = hard_cpu_limit_s
        self.cpu_quota_generation_s_per_s = cpu_quota_generation_s_per_s
        self.parent = parent
        self.children: dict[str, ResourceGroup] = {}
        self._running = 0          # subtree total (every ancestor counts)
        self._running_direct = 0   # queries admitted AT this group
        self._queue: list[_Ticket] = []
        self._memory_usage_bytes = 0
        self._cpu_usage_s = 0.0
        self._lock = parent._lock if parent is not None else threading.Lock()
        self._clock = clock or (parent._clock if parent is not None
                                else time.monotonic)
        self._last_regen = self._clock()
        if parent is None:
            self._seq = itertools.count()
        self._gauges = None  # lazy (running, queued) gauge pair

    # ------------------------------------------------------------- structure
    def subgroup(self, name: str, **kwargs) -> "ResourceGroup":
        with self._lock:  # admission walks children under the lock
            if name not in self.children:
                self.children[name] = ResourceGroup(
                    f"{self.name}.{name}", parent=self, **kwargs)
            return self.children[name]

    @property
    def root(self) -> "ResourceGroup":
        g = self
        while g.parent is not None:
            g = g.parent
        return g

    def walk(self) -> list["ResourceGroup"]:
        out = [self]
        for c in self.children.values():
            out.extend(c.walk())
        return out

    # ------------------------------------------------------------- admission
    def _regen_cpu(self) -> None:
        rate = self.cpu_quota_generation_s_per_s
        now = self._clock()
        if rate:
            dt = now - self._last_regen
            if dt > 0:
                self._cpu_usage_s = max(0.0, self._cpu_usage_s - dt * rate)
        self._last_regen = now

    def _effective_concurrency_limit(self) -> int:
        """Hard limit, scaled down linearly while CPU usage sits between the
        soft and hard CPU quotas (the reference's penalty curve)."""
        limit = self.hard_concurrency_limit
        soft, hard = self.soft_cpu_limit_s, self.hard_cpu_limit_s
        if (soft is not None and hard is not None and hard > soft
                and self._cpu_usage_s > soft):
            over = (self._cpu_usage_s - soft) / (hard - soft)
            limit = int(limit * max(0.0, 1.0 - over))
        return limit

    def _can_admit(self) -> bool:
        """One NEW admission allowed at THIS group right now (lock held)."""
        self._regen_cpu()
        if (self.hard_cpu_limit_s is not None
                and self._cpu_usage_s >= self.hard_cpu_limit_s):
            return False
        if (self.soft_memory_limit_bytes is not None
                and self._memory_usage_bytes >= self.soft_memory_limit_bytes):
            return False
        return self._running < self._effective_concurrency_limit()

    def _can_run(self) -> bool:
        g: Optional[ResourceGroup] = self
        while g is not None:
            if not g._can_admit():
                return False
            g = g.parent
        return True

    def _acquire_now(self) -> None:
        self._running_direct += 1
        g: Optional[ResourceGroup] = self
        while g is not None:
            g._running += 1
            g = g.parent
        self._update_gauges()

    def acquire(self, timeout: float = 300.0, priority: int = 0) -> None:
        """Block until admitted.  Raises a classified USER TrinoError when
        the queue is full (QUERY_QUEUE_FULL) or the wait expires
        (QUERY_QUEUED_TIMEOUT) — admission rejections re-fail identically,
        so the retry machinery must never re-run them."""
        with self._lock:
            if self._can_run() and not self._queue:
                self._acquire_now()
                return
            if len(self._queue) >= self.max_queued:
                raise TrinoError(
                    QUERY_QUEUE_FULL,
                    f"resource group {self.name}: queue full "
                    f"({self.max_queued})")
            ticket = _Ticket(next(self.root._seq), priority, self)
            self._queue.append(ticket)
            self._update_gauges()
        if not ticket.event.wait(timeout):
            with self._lock:
                if not ticket.event.is_set():  # lost the admit race: timeout
                    if ticket in self._queue:
                        self._queue.remove(ticket)
                    self._update_gauges()
                    raise TrinoError(
                        QUERY_QUEUED_TIMEOUT,
                        f"resource group {self.name}: queued for {timeout}s")
        # admitted by release()/refresh()

    def release(self, cpu_s: float = 0.0) -> None:
        with self._lock:
            self._running_direct -= 1
            g: Optional[ResourceGroup] = self
            while g is not None:
                g._running -= 1
                if cpu_s:
                    g._cpu_usage_s += cpu_s
                g = g.parent
            self._update_gauges()
            self._dispatch_queued()

    def refresh(self) -> None:
        """Re-run queued dispatch: wakes queries a regenerated CPU quota or
        a dropped memory reservation has unblocked (release() is the usual
        trigger, but quota/memory headroom can appear without one)."""
        with self._lock:
            self._dispatch_queued()

    def set_memory_usage(self, nbytes: int) -> None:
        """Aggregated reservation of this group's member queries, pushed by
        the ClusterMemoryManager; dropping below the soft limit re-opens
        admission."""
        with self._lock:
            before = self._memory_usage_bytes
            self._memory_usage_bytes = int(nbytes)
            if nbytes < before:
                self._dispatch_queued()

    # ------------------------------------------------------------ scheduling
    def _queue_head(self) -> Optional[_Ticket]:
        if not self._queue:
            return None
        if self.scheduling_policy == "query_priority":
            return min(self._queue, key=lambda t: (-t.priority, t.seq))
        return self._queue[0]  # FIFO (fair/weighted_fair own-queue order)

    def _has_demand(self) -> bool:
        if self._queue:
            return True
        return any(c._has_demand() for c in self.children.values())

    def _next_ticket(self) -> Optional[_Ticket]:
        """The next admissible ticket in this subtree under this group's
        policy, or None (lock held).  Each level picks among its own queue
        head and its children's winners; the recursion already verified the
        winner's whole ancestor chain below this level."""
        if not self._can_admit():
            return None
        cands: list[tuple[ResourceGroup, _Ticket]] = []
        head = self._queue_head()
        if head is not None:
            cands.append((self, head))
        for c in self.children.values():
            if not c._has_demand():
                continue
            t = c._next_ticket()
            if t is not None:
                cands.append((c, t))
        if not cands:
            return None
        # soft concurrency: a group at/over its soft limit only wins when no
        # candidate below its soft limit is waiting
        def above_soft(g: ResourceGroup) -> bool:
            return (g.soft_concurrency_limit is not None
                    and g._running >= g.soft_concurrency_limit)

        soft_ok = [c for c in cands if not above_soft(c[0])]
        pool = soft_ok or cands
        if self.scheduling_policy == "weighted_fair":
            # least served relative to weight; for own-queue tickets the
            # "subtree" is the queries admitted directly at this group
            def key(c):
                g, t = c
                running = (g._running_direct if g is self else g._running)
                return (running / g.weight, t.seq)
        elif self.scheduling_policy == "query_priority":
            def key(c):
                return (-c[1].priority, c[1].seq)
        else:  # fair: global FIFO across the subtree
            def key(c):
                return c[1].seq
        return min(pool, key=key)[1]

    def _dispatch_queued(self) -> None:
        root = self.root
        while True:
            t = root._next_ticket()
            if t is None:
                return
            g = t.group
            g._queue.remove(t)
            g._acquire_now()
            t.event.set()

    # ---------------------------------------------------------- observability
    @property
    def running(self) -> int:
        with self._lock:
            return self._running

    @property
    def queued(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def queued_total(self) -> int:
        with self._lock:
            return sum(len(g._queue) for g in self.walk())

    @property
    def memory_usage_bytes(self) -> int:
        with self._lock:
            return self._memory_usage_bytes

    @property
    def cpu_usage_s(self) -> float:
        with self._lock:
            self._regen_cpu()
            return self._cpu_usage_s

    def _update_gauges(self) -> None:
        # per-group gauges (dynamic names; registered on first touch)
        if self._gauges is None:
            from ..telemetry.metrics import resource_group_gauges

            self._gauges = resource_group_gauges(self.name)
        run_g, que_g = self._gauges
        run_g.set(self._running)
        que_g.set(len(self._queue))


# ---------------------------------------------------------------------------
# config-driven group trees + dispatch manager construction

_GROUP_KWARGS = (
    "hard_concurrency_limit", "soft_concurrency_limit", "max_queued",
    "weight", "scheduling_policy", "soft_memory_limit_bytes",
    "soft_cpu_limit_s", "hard_cpu_limit_s", "cpu_quota_generation_s_per_s",
)


def _build_group(spec: dict, parent: Optional[ResourceGroup],
                 clock) -> ResourceGroup:
    kwargs = {k: spec[k] for k in _GROUP_KWARGS if k in spec}
    name = spec.get("name", "global")
    if parent is None:
        g = ResourceGroup(name, clock=clock, **kwargs)
    else:
        g = parent.subgroup(name, **kwargs)
    for sub in spec.get("subgroups", ()):
        _build_group(sub, g, clock)
    return g


def build_group_tree(spec, clock=None):
    """``spec`` is the TRINO_TPU_RESOURCE_GROUPS payload: either a bare
    group dict (the root) or ``{"root": {...}, "selectors": [...]}`` where
    selectors are spi/session.py rule dicts mapping (user, source, sql) to a
    dotted group path.  Returns (root_group, selector_callable_or_None)."""
    if isinstance(spec, str):
        spec = json.loads(spec)
    root_spec = spec.get("root", spec)
    root = _build_group(root_spec, None, clock)
    selector = None
    rules = spec.get("selectors")
    if rules:
        from ..spi.session import GroupSelector

        selector = GroupSelector.from_spec(rules).select
    return root, selector


def build_dispatch_manager(session):
    """The runner's admission plane: the TRINO_TPU_RESOURCE_GROUPS tree when
    configured, else the flat global group sized from the session knobs
    (exactly the pre-existing behavior)."""
    from .control import DispatchManager

    spec = os.environ.get("TRINO_TPU_RESOURCE_GROUPS")
    if spec:
        root, selector = build_group_tree(spec)
        return DispatchManager(root, selector)
    return DispatchManager(ResourceGroup(
        "global",
        hard_concurrency_limit=session.query_concurrency,
        max_queued=session.query_max_queued))


def find_group(root: Optional[ResourceGroup],
               path: str) -> Optional[ResourceGroup]:
    """Resolve a full dotted group name (``global.etl``) in a tree."""
    if root is None or not path:
        return None
    for g in root.walk():
        if g.name == path:
            return g
    return None


# ---------------------------------------------------------------------------
# memory-aware admission: history-based peak estimation


def estimate_peak_memory(fingerprint: str, default_bytes: int,
                         history: int = 5) -> int:
    """Estimated peak for a plan fingerprint: the max peak of its most
    recent finished runs (telemetry/runtime.py records), else the default.
    The max (not mean) keeps admission conservative — letting one query in
    on an optimistic estimate is how clusters OOM."""
    from ..telemetry import runtime as rt

    peaks = [q.peak_memory_bytes for q in rt.queries()
             if q.fingerprint == fingerprint and q.state == "FINISHED"
             and q.peak_memory_bytes > 0]
    if peaks:
        return max(peaks[-history:])
    # no in-memory history (fresh coordinator): the durable query journal
    # seeds the estimate across restarts (telemetry/journal.py)
    try:
        from ..telemetry import journal as tj

        seeded = tj.seeded_peak(fingerprint, history)
        if seeded > 0:
            return seeded
    # tpulint: disable=error-taxonomy -- journal trouble never blocks admission
    except Exception:  # noqa: BLE001
        pass
    return default_bytes


# ---------------------------------------------------------------------------
# cluster memory manager + low-memory killer


class QueryMemoryHandle:
    """One registered query's view of the killer: ``poll()`` runs a
    rate-limited enforcement pass and returns the kill error once this query
    was chosen as a victim (the coordinator drain loops raise it)."""

    def __init__(self, manager: "ClusterMemoryManager", query_id: str,
                 priority: int, create_seq: int,
                 group: Optional[ResourceGroup] = None,
                 max_memory: Optional[int] = None):
        self._manager = manager
        self.query_id = query_id
        self.priority = priority
        self.create_seq = create_seq
        self.group = group
        self.max_memory = max_memory
        self._error: Optional[TrinoError] = None
        self._killed = threading.Event()

    @property
    def killed(self) -> bool:
        return self._killed.is_set()

    def kill(self, error: TrinoError) -> None:
        self._error = error
        self._killed.set()

    def killed_error(self) -> Optional[TrinoError]:
        return self._error if self._killed.is_set() else None

    def poll(self) -> Optional[TrinoError]:
        self._manager.maybe_enforce()
        return self.killed_error()

    def check(self) -> None:
        err = self.poll()
        if err is not None:
            raise err


class ClusterMemoryManager:
    """Coordinator-side cluster memory view + low-memory killer.

    Reservations come from two planes: in-process MemoryPool refs registered
    per query (held weakly — a pool dropping with its finished task leaves
    the books automatically) and per-worker snapshots parsed out of the
    /v1/status JSON the failure detector already sweeps.  ``enforce()``
    refreshes the cluster gauges, pushes per-group usage into the resource
    group tree, kills any query over its ``max_memory``, and — when total
    reservation exceeds ``capacity_bytes`` — kills victims under
    ``oom_policy`` until the projection fits
    (reference: ClusterMemoryManager.process:~200 + LowMemoryKiller
    implementations TotalReservationLowMemoryKiller et al.)."""

    def __init__(self, capacity_bytes: Optional[int] = None,
                 oom_policy: Optional[str] = None,
                 enforce_interval_s: float = 0.05,
                 clock: Callable[[], float] = time.monotonic):
        if capacity_bytes is None:
            env = os.environ.get("TRINO_TPU_CLUSTER_MEMORY_BYTES")
            capacity_bytes = int(env) if env else None
        self.capacity_bytes = capacity_bytes
        policy = oom_policy or os.environ.get(
            "TRINO_TPU_OOM_POLICY", "largest_query")
        if policy not in OOM_POLICIES:
            raise ValueError(f"oom_policy {policy!r} not in {OOM_POLICIES}")
        self.oom_policy = policy
        self.enforce_interval_s = enforce_interval_s
        self._clock = clock
        self._lock = threading.Lock()
        self._pools: dict[str, list] = {}       # qid -> [weakref to pool]
        self._workers: dict[str, dict[str, int]] = {}  # node -> qid -> bytes
        self._handles: dict[str, QueryMemoryHandle] = {}
        self._groups_seen: set = set()
        self._seq = itertools.count()
        self._last_enforce = 0.0
        self.oom_kills = 0

    # ---------------------------------------------------------- registration
    def register_query(self, query_id: str, priority: int = 0,
                       group: Optional[ResourceGroup] = None,
                       max_memory: Optional[int] = None) -> QueryMemoryHandle:
        h = QueryMemoryHandle(self, query_id, priority, next(self._seq),
                              group, max_memory)
        with self._lock:
            self._handles[query_id] = h
        return h

    def unregister_query(self, query_id: str) -> None:
        with self._lock:
            self._handles.pop(query_id, None)
            self._pools.pop(query_id, None)
            for per_node in self._workers.values():
                per_node.pop(query_id, None)

    def register_pool(self, query_id: str, pool) -> None:
        """Track an in-process MemoryPool under a query (weakly: the pool
        leaves the accounting when its task drops it)."""
        ref = weakref.ref(pool)
        with self._lock:
            self._pools.setdefault(query_id, []).append(ref)

    def update_worker(self, node_id: str, status_json: dict) -> None:
        """Fold one /v1/status payload: per-task ``query_id`` +
        ``memory_reserved_bytes`` (worker.py ships both).  The snapshot
        replaces the node's previous view wholesale, so finished tasks age
        out with the next sweep."""
        per_query: dict[str, int] = {}
        for st in (status_json or {}).get("tasks", {}).values():
            qid = st.get("query_id")
            nbytes = int(st.get("memory_reserved_bytes", 0) or 0)
            if qid and nbytes:
                per_query[qid] = per_query.get(qid, 0) + nbytes
        with self._lock:
            self._workers[node_id] = per_query

    def forget_worker(self, node_id: str) -> None:
        with self._lock:
            self._workers.pop(node_id, None)

    # ------------------------------------------------------------ accounting
    def reserved_by_query(self) -> dict[str, int]:
        with self._lock:
            out: dict[str, int] = {}
            for qid, refs in self._pools.items():
                live = [r for r in refs if r() is not None]
                self._pools[qid] = live
                total = 0
                for r in live:
                    p = r()
                    if p is not None:
                        total += int(p.reserved + p.reserved_revocable)
                if total:
                    out[qid] = out.get(qid, 0) + total
            for per_node in self._workers.values():
                for qid, nbytes in per_node.items():
                    out[qid] = out.get(qid, 0) + nbytes
            return out

    def cluster_reserved_bytes(self) -> int:
        return sum(self.reserved_by_query().values())

    def cluster_free_bytes(self) -> float:
        if self.capacity_bytes is None:
            return float("inf")
        return self.capacity_bytes - self.cluster_reserved_bytes()

    def can_admit(self, estimated_bytes: int) -> bool:
        """Memory-aware admission: room for the estimate on top of current
        reservations?  Uncapped clusters always admit."""
        if self.capacity_bytes is None:
            return True
        return self.cluster_free_bytes() >= estimated_bytes

    # ------------------------------------------------------------- the killer
    def maybe_enforce(self) -> list[str]:
        now = self._clock()
        if now - self._last_enforce < self.enforce_interval_s:
            return []
        self._last_enforce = now
        return self.enforce()

    def _victim_order(self, handles: list[QueryMemoryHandle],
                      usage: dict[str, int]) -> list[QueryMemoryHandle]:
        if self.oom_policy == "lowest_priority":
            return sorted(handles, key=lambda h: (
                h.priority, -usage.get(h.query_id, 0)))
        if self.oom_policy == "youngest":
            return sorted(handles, key=lambda h: -h.create_seq)
        return sorted(handles, key=lambda h: -usage.get(h.query_id, 0))

    def enforce(self) -> list[str]:
        """One enforcement pass; returns the query ids killed this round."""
        from ..telemetry import metrics as tm

        usage = self.reserved_by_query()
        total = sum(usage.values())
        tm.CLUSTER_MEMORY_RESERVED.set(total)
        if self.capacity_bytes is not None:
            tm.CLUSTER_MEMORY_FREE.set(max(0, self.capacity_bytes - total))
        with self._lock:
            handles = list(self._handles.values())
        # per-group roll-up into the admission tree (soft_memory_limit)
        group_usage: dict[ResourceGroup, int] = {}
        for h in handles:
            nbytes = usage.get(h.query_id, 0)
            g = h.group
            while g is not None:
                group_usage[g] = group_usage.get(g, 0) + nbytes
                g = g.parent
        for g in self._groups_seen - set(group_usage):
            g.set_memory_usage(0)
        for g, nbytes in group_usage.items():
            g.set_memory_usage(nbytes)
        self._groups_seen = set(group_usage)

        killed: list[str] = []
        # per-query max_memory (reference: query.max-memory enforcement)
        for h in handles:
            if (h.max_memory and not h.killed
                    and usage.get(h.query_id, 0) > h.max_memory):
                h.kill(TrinoError(
                    EXCEEDED_GLOBAL_MEMORY_LIMIT,
                    f"query {h.query_id} reserved "
                    f"{usage.get(h.query_id, 0)} bytes, max_memory "
                    f"{h.max_memory}"))
                killed.append(h.query_id)
        # cluster low-memory killer
        if self.capacity_bytes is not None and total > self.capacity_bytes:
            victims = self._victim_order(
                [h for h in handles if not h.killed], usage)
            for h in victims:
                if total <= self.capacity_bytes:
                    break
                nbytes = usage.get(h.query_id, 0)
                if nbytes <= 0:
                    continue  # killing a zero-reservation query frees nothing
                h.kill(TrinoError(
                    CLUSTER_OUT_OF_MEMORY,
                    f"cluster reserved {total} of {self.capacity_bytes} "
                    f"bytes; killed {h.query_id} ({nbytes} bytes, policy "
                    f"{self.oom_policy})"))
                total -= nbytes
                self.oom_kills += 1
                tm.OOM_KILLS.inc()
                killed.append(h.query_id)
        return killed
