"""Spool retention + GC: replaces FTE's unconditional end-of-query rmtree.

The old contract — ``shutil.rmtree(spool_root)`` in a finally — was both
too eager and too weak: too eager because committed stage outputs are the
engine's recovery currency (coordinator crash recovery *and* non-leaf
straggler speculation both re-read them), too weak because a coordinator
killed before the finally leaked the root forever.  This module makes
retention explicit (reference: FileSystemExchangeManager's exchange
lifecycle + its cleanup of abandoned exchange directories):

- every live spool root carries a **lease** (``.lease.json``: owner query
  id, pid, timestamp, TTL) written at query start;
- ``release()`` is the happy-path GC — the query is done, its root is
  reclaimed immediately (byte-accounted through ``trino_fte_spool_*``);
- ``sweep()`` is the boot-time / periodic pass over the spool base dir:
  roots whose owner pid is dead (a crashed coordinator) or whose lease
  expired are reclaimed — EXCEPT roots named in ``keep``, which recovery
  (server/protocol.py) passes for queries it is about to resume;
- ``TRINO_TPU_SPOOL_TTL_S`` bounds how long an unleased/abandoned root may
  linger; ``TRINO_TPU_SPOOL_MAX_BYTES`` is the retention budget — once
  retained roots exceed it the sweep reclaims reclaimable roots
  oldest-first (never a root owned by a live pid or under recovery).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Iterable, Optional

__all__ = ["acquire", "release", "sweep", "dir_bytes", "spool_base",
           "LEASE_FILE", "SPOOL_PREFIX"]

LEASE_FILE = ".lease.json"
SPOOL_PREFIX = "trino-tpu-spool-"


def spool_base() -> str:
    from ..spi.knobs import get_str

    return get_str("TRINO_TPU_SPOOL_DIR") or tempfile.gettempdir()


def _ttl_s() -> float:
    from ..spi.knobs import get_float

    v = get_float("TRINO_TPU_SPOOL_TTL_S")
    return 3600.0 if v is None else v


def _max_bytes() -> int:
    from ..spi.knobs import get_int

    v = get_int("TRINO_TPU_SPOOL_MAX_BYTES")
    return (1 << 30) if v is None else v


def dir_bytes(root: str) -> int:
    total = 0
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, fn))
            except OSError:
                pass
    return total


def acquire(root: str, query_id: str,
            ttl_s: Optional[float] = None) -> None:
    """Write the root's lease (atomic tmp+rename so a reader never sees a
    torn lease; an existing lease is superseded — recovery re-leases a
    crashed query's root under the new coordinator pid)."""
    lease = {"query_id": query_id, "pid": os.getpid(), "ts": time.time(),
             "ttl_s": _ttl_s() if ttl_s is None else float(ttl_s)}
    tmp = os.path.join(root, LEASE_FILE + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(lease, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(root, LEASE_FILE))


def _read_lease(root: str) -> Optional[dict]:
    try:
        with open(os.path.join(root, LEASE_FILE), encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _pid_alive(pid) -> bool:
    if not isinstance(pid, int) or pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    return True


def _reclaim(root: str, reason: str) -> int:
    """rmtree + byte accounting; returns bytes reclaimed."""
    n = dir_bytes(root)
    shutil.rmtree(root, ignore_errors=True)
    try:
        from ..telemetry import metrics as tm
        from ..telemetry import profiler

        tm.FTE_SPOOL_BYTES_RECLAIMED.inc(n)
        profiler.instant(profiler.RECOVERY, "spool-reclaim",
                         root=os.path.basename(root), reason=reason,
                         bytes=n)
    # tpulint: disable=error-taxonomy -- byte accounting is best-effort; the rmtree above already happened
    except Exception:
        pass
    return n


def release(root: str) -> int:
    """Happy-path GC at query end: reclaim the root now (idempotent)."""
    if not root or not os.path.isdir(root):
        return 0
    return _reclaim(root, "release")


def sweep(base: Optional[str] = None, keep: Iterable[str] = (),
          now: Optional[float] = None) -> dict:
    """One retention pass over every ``trino-tpu-spool-*`` root under
    ``base``.  Returns ``{"kept": [...], "reclaimed": [...],
    "live_bytes": n}`` and refreshes the live-bytes gauge."""
    base = base or spool_base()
    now = time.time() if now is None else now
    keep = {os.path.abspath(k) for k in keep}
    kept: list[tuple[float, str, int, bool]] = []  # (age_ts, root, bytes, pinned)
    reclaimed: list[str] = []
    try:
        names = sorted(os.listdir(base))
    except OSError:
        names = []
    for name in names:
        if not name.startswith(SPOOL_PREFIX):
            continue
        root = os.path.join(base, name)
        if not os.path.isdir(root):
            continue
        if os.path.abspath(root) in keep:
            kept.append((now, root, dir_bytes(root), True))
            continue
        lease = _read_lease(root)
        if lease is not None:
            ttl = float(lease.get("ttl_s") or _ttl_s())
            expired = now - float(lease.get("ts") or 0) > ttl
            if _pid_alive(lease.get("pid")) and not expired:
                kept.append((float(lease.get("ts") or now), root,
                             dir_bytes(root), True))
                continue
            # owner died (crashed coordinator, not under recovery) or the
            # lease ran out: the root is a leak
            reclaimed.append(root)
            _reclaim(root, "dead-owner" if expired is False else "expired")
            continue
        # no lease: a foreign/interrupted mkdtemp — only age can judge it
        try:
            age_ts = os.path.getmtime(root)
        except OSError:
            age_ts = 0.0
        if now - age_ts > _ttl_s():
            reclaimed.append(root)
            _reclaim(root, "ttl")
        else:
            kept.append((age_ts, root, dir_bytes(root), False))
    # retention budget: reclaim unpinned keepers oldest-first
    budget = _max_bytes()
    total = sum(b for _ts, _r, b, _p in kept)
    if total > budget:
        for ts, root, nbytes, pinned in sorted(kept):
            if total <= budget or pinned:
                continue
            reclaimed.append(root)
            _reclaim(root, "budget")
            total -= nbytes
        kept = [k for k in kept if k[1] not in set(reclaimed)]
    live = sum(b for _ts, _r, b, _p in kept)
    try:
        from ..telemetry import metrics as tm

        tm.FTE_SPOOL_BYTES_LIVE.set(live)
    # tpulint: disable=error-taxonomy -- gauge refresh is best-effort; sweep results stand either way
    except Exception:
        pass
    return {"kept": [r for _ts, r, _b, _p in kept],
            "reclaimed": reclaimed, "live_bytes": live}
