"""Distributed query execution: fragments, stages, tasks, exchanges.

The L3-L5 layers of SURVEY §1 (reference: execution/SqlTaskExecution.java:85,
execution/scheduler/PipelinedQueryScheduler.java:157, execution/buffer/*):
a fragmented plan runs as a tree of stages, each stage as N concurrent
tasks, wired by pull-token output buffers.
"""
