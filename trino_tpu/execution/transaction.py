"""Transactions: in-memory manager coordinating per-connector handles.

Mirrors ``transaction/InMemoryTransactionManager.java:72`` /
``TransactionManager.java:30``: the coordinator tracks a transaction as a
set of per-connector handles created lazily on first touch; COMMIT/ROLLBACK
fan out to every enlisted connector.  Like the reference, there is no
cross-connector two-phase commit — each connector commits independently
(single-connector writes are the supported atomic unit).

Connector contract (spi/connector.py): ``begin_transaction() -> handle``,
``commit_transaction(handle)``, ``rollback_transaction(handle)``; the
memory connector implements snapshot-based rollback (undoes INSERT/CTAS/
CREATE TABLE since BEGIN)."""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Optional

from ..sql import ast
from ..runner import count_result

__all__ = ["TransactionHandle", "TransactionManager", "handle_transaction_stmt"]


@dataclass
class TransactionHandle:
    id: str
    # catalog name -> connector-private handle
    connector_handles: dict = field(default_factory=dict)


class TransactionManager:
    _ids = itertools.count(1)
    _lock = threading.Lock()

    def __init__(self, catalog):
        self.catalog = catalog

    def begin(self) -> TransactionHandle:
        with self._lock:
            return TransactionHandle(f"txn_{next(self._ids)}")

    def enlist(self, txn: TransactionHandle, catalog_name: str) -> None:
        """Lazily open the connector's transaction on first touch (mirrors
        InMemoryTransactionManager.getTransactionMetadata enlisting)."""
        if catalog_name in txn.connector_handles:
            return
        conn = self.catalog.connector(catalog_name)
        txn.connector_handles[catalog_name] = conn.begin_transaction()

    def commit(self, txn: TransactionHandle) -> None:
        for cat, handle in txn.connector_handles.items():
            self.catalog.connector(cat).commit_transaction(handle)
        txn.connector_handles.clear()

    def rollback(self, txn: TransactionHandle) -> None:
        for cat, handle in txn.connector_handles.items():
            self.catalog.connector(cat).rollback_transaction(handle)
        txn.connector_handles.clear()
        # rollback can undo CREATE TABLE/CTAS: plans cached since BEGIN may
        # reference tables that no longer exist — force a replan
        self.catalog.bump_generation()


def handle_transaction_stmt(stmt, session, catalog) -> Optional[object]:
    """START TRANSACTION / COMMIT / ROLLBACK statement dispatch (the
    TransactionControl DataDefinitionTasks).  Returns a QueryResult or None
    when ``stmt`` is not transaction control."""
    if isinstance(stmt, ast.StartTransaction):
        if getattr(session, "transaction", None) is not None:
            raise ValueError("transaction already in progress")
        tm = TransactionManager(catalog)
        txn = tm.begin()
        # every known catalog enlists up front: writes through any connector
        # are then covered without per-statement bookkeeping
        for cat_name in catalog.names():
            tm.enlist(txn, cat_name)
        session.transaction = txn
        session._transaction_manager = tm
        return count_result("rows", 0)
    if isinstance(stmt, ast.Commit):
        txn = getattr(session, "transaction", None)
        if txn is None:
            raise ValueError("no transaction in progress")
        session._transaction_manager.commit(txn)
        session.transaction = None
        return count_result("rows", 0)
    if isinstance(stmt, ast.Rollback):
        txn = getattr(session, "transaction", None)
        if txn is None:
            raise ValueError("no transaction in progress")
        session._transaction_manager.rollback(txn)
        session.transaction = None
        return count_result("rows", 0)
    return None
