"""Coordinator heartbeat sweep over worker /v1/status with a node state
machine (reference: failuredetector/HeartbeatFailureDetector.java:76 — the
coordinator probes every node on an interval and gates placement on the
result; server/GracefulShutdownHandler.java:42 for the drain state).

Node states:

- ``ACTIVE``         responding; eligible for new task placement
- ``SHUTTING_DOWN``  responding but draining; keeps running tasks, gets none
- ``UNRESPONSIVE``   probes failing, below the failure threshold; placement
                     skips it, but its tasks are not yet declared lost
- ``GONE``           threshold consecutive probe failures, or authoritative
                     process death (:class:`NodeGoneError`); terminal for
                     this worker incarnation — the runner replaces it

Unlike the in-process pinger in execution/control.py (boolean callbacks over
announced names), this detector drives real HTTP ``/v1/status`` probes,
caches each worker's full status payload (node state + per-task states), and
exposes it so the coordinator's query sweep costs ONE poll per worker instead
of one per task.  Probes are injectable callables so every transition is
deterministically testable without sockets.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = ["ACTIVE", "SHUTTING_DOWN", "UNRESPONSIVE", "GONE",
           "NodeGoneError", "WorkerFailureDetector"]

ACTIVE = "ACTIVE"
SHUTTING_DOWN = "SHUTTING_DOWN"
UNRESPONSIVE = "UNRESPONSIVE"
GONE = "GONE"


class NodeGoneError(RuntimeError):
    """Raised by a probe that KNOWS the node is dead (e.g. the worker
    process handle reports an exit code) — skips the miss-counting path and
    transitions the node straight to GONE."""


@dataclass
class _Node:
    node_id: str
    probe: Callable[[], dict]
    state: str = ACTIVE
    consecutive_failures: int = 0
    last_status: Optional[dict] = None
    last_error: Optional[str] = None
    last_seen: float = field(default_factory=time.monotonic)


class WorkerFailureDetector:
    """Heartbeat sweep + state machine over monitored workers.

    ``sweep_once()`` probes every node (deterministic, used by tests and by
    the coordinator's status loop); ``maybe_sweep()`` rate-limits to the
    heartbeat interval; ``start()``/``stop()`` run the sweep on a background
    thread for long-lived deployments.  State transitions append
    ``("heartbeat", node_id, old, new)`` to ``events``."""

    def __init__(self, heartbeat_interval_s: float = 0.5,
                 failure_threshold: int = 3,
                 events: Optional[list] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.failure_threshold = max(1, int(failure_threshold))
        self.events = events if events is not None else []
        self.transitions = 0
        self._clock = clock
        self._nodes: dict[str, _Node] = {}
        self._lock = threading.Lock()
        self._last_sweep = float("-inf")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ membership
    def monitor(self, node_id: str, probe: Callable[[], dict]) -> None:
        with self._lock:
            self._nodes[node_id] = _Node(node_id, probe)

    def unmonitor(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    # --------------------------------------------------------------- probing
    def sweep_once(self) -> None:
        """One heartbeat round: probe every monitored node and apply the
        state machine.  Probes run outside the lock (they do network I/O)."""
        with self._lock:
            self._last_sweep = self._clock()
            nodes = list(self._nodes.values())
        for node in nodes:
            if node.state == GONE:
                continue  # terminal for this incarnation
            try:
                status = node.probe()
                self._observe(node, ok=True, status=status)
            except NodeGoneError as e:
                self._observe(node, ok=False, error=str(e), authoritative=True)
            except BaseException as e:  # noqa: BLE001 — any probe trouble
                self._observe(node, ok=False, error=f"{type(e).__name__}: {e}")

    def maybe_sweep(self) -> None:
        """sweep_once, rate-limited to the heartbeat interval (callers can
        invoke it opportunistically from hot loops)."""
        if self._clock() - self._last_sweep >= self.heartbeat_interval_s:
            self.sweep_once()

    def _observe(self, node: _Node, ok: bool, status: Optional[dict] = None,
                 error: Optional[str] = None,
                 authoritative: bool = False) -> None:
        with self._lock:
            old = node.state
            if old == GONE:
                return
            if ok:
                node.consecutive_failures = 0
                node.last_status = status
                node.last_error = None
                node.last_seen = self._clock()
                new = (SHUTTING_DOWN
                       if (status or {}).get("state") == "SHUTTING_DOWN"
                       else ACTIVE)
            else:
                node.consecutive_failures += 1
                node.last_error = error
                new = (GONE if authoritative
                       or node.consecutive_failures >= self.failure_threshold
                       else UNRESPONSIVE)
            node.state = new
            if new == old:
                return
            self.transitions += 1
            self.events.append(("heartbeat", node.node_id, old, new))

    # ------------------------------------------------------------- accessors
    def state_of(self, node_id: str) -> Optional[str]:
        with self._lock:
            node = self._nodes.get(node_id)
            return node.state if node is not None else None

    def last_status(self, node_id: str) -> Optional[dict]:
        """The most recent successful /v1/status payload (node state plus
        per-task states) — the coordinator's task sweep reads THIS instead
        of re-polling each task."""
        with self._lock:
            node = self._nodes.get(node_id)
            return node.last_status if node is not None else None

    def last_error(self, node_id: str) -> Optional[str]:
        with self._lock:
            node = self._nodes.get(node_id)
            return node.last_error if node is not None else None

    def active(self) -> list[str]:
        with self._lock:
            return sorted(n.node_id for n in self._nodes.values()
                          if n.state == ACTIVE)

    def gone(self) -> list[str]:
        with self._lock:
            return sorted(n.node_id for n in self._nodes.values()
                          if n.state == GONE)

    def states(self) -> dict[str, str]:
        with self._lock:
            return {n.node_id: n.state for n in self._nodes.values()}

    def worker_rows(self) -> list[dict]:
        """Per-worker operational snapshot for ``system.runtime.workers``:
        detector state, task counts from the cached /v1/status payload, and
        heartbeat age.  Blacklist scores are joined in by the caller (they
        live on the coordinator's ClusterBlacklist, not here)."""
        now = self._clock()
        with self._lock:
            out = []
            for n in self._nodes.values():
                tasks = ((n.last_status or {}).get("tasks") or {})
                running = sum(1 for s in tasks.values()
                              if s.get("state") == "RUNNING"
                              and s.get("ready", True))
                queued = sum(1 for s in tasks.values()
                             if s.get("state") == "RUNNING"
                             and not s.get("ready", True))
                out.append({
                    "worker": n.node_id,
                    "state": n.state,
                    "running_tasks": running,
                    "queued_tasks": queued,
                    "last_heartbeat_age_ms": (now - n.last_seen) * 1000.0,
                })
            return out

    # ------------------------------------------------- background monitoring
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="worker-failure-detector", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            self.sweep_once()
