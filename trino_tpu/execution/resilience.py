"""Fleet-shared durable resilience state: the cluster-blacklist store.

PR 15 made the :class:`~trino_tpu.execution.speculation.ClusterBlacklist`
durable by journaling strikes into the per-coordinator query journal —
correct for one coordinator, wrong for a fleet: two coordinators each
re-seed only their OWN journal, so a worker that fails under coordinator A
gets a clean slate from coordinator B, and a naive shared snapshot file
would be last-writer-wins (B's flush clobbers A's strikes).

:class:`SharedBlacklistStore` fixes both with the engine's usual durable
idiom (telemetry/journal.py, query_state.py): one append-only JSONL file
at ``TRINO_TPU_BLACKLIST_PATH`` shared by every coordinator.  Writes are
single ``O_APPEND`` writes (atomic for these line sizes on POSIX), so two
writers interleave whole records instead of clobbering each other; readers
merge-on-load — each coordinator incrementally tails the file and folds
every unexpired entry (its own AND its peers') into its in-memory table,
back-dated so TTL decay lands at the same wall moment on every member.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

__all__ = ["SharedBlacklistStore", "blacklist_path"]


def blacklist_path() -> str:
    from ..spi.knobs import get_str

    return get_str("TRINO_TPU_BLACKLIST_PATH")


class SharedBlacklistStore:
    """Append-only shared strike log + incremental merge-on-load reader.

    ``append`` records one strike with a WALL-clock timestamp (monotonic
    clocks do not compare across processes).  ``poll`` returns every
    record appended since the previous poll — by any writer, this process
    included — so a blacklist that feeds its own appends straight back
    through ``poll`` needs no separate local insert path (single source of
    truth, no double counting).  Truncation or replacement of the file
    (operator reset) is detected by shrinkage and re-read from the start.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._offset = 0
        self._buf = b""

    def append(self, worker: str, weight: float, reason: str,
               query_id: str = "", ts: Optional[float] = None) -> None:
        rec = {
            "ts": time.time() if ts is None else float(ts),
            "worker": worker,
            "weight": float(weight),
            "reason": reason,
            "query_id": query_id,
        }
        data = (json.dumps(rec, separators=(",", ":")) + "\n").encode("utf-8")
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                     0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)

    def poll(self) -> list[dict]:
        """New records since the last poll, oldest first.  A torn tail
        (a writer mid-append) stays buffered until its newline lands."""
        with self._lock:
            try:
                size = os.path.getsize(self.path)
            except OSError:
                return []
            if size < self._offset:  # truncated/replaced: start over
                self._offset = 0
                self._buf = b""
            if size == self._offset:
                return []
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
            self._offset += len(chunk)
            data = self._buf + chunk
            lines = data.split(b"\n")
            self._buf = lines.pop()  # b"" when the tail ended in newline
            out = []
            for line in lines:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "worker" in rec:
                    out.append(rec)
            return out
