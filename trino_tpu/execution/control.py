"""Query control plane: state machines, dispatcher, resource groups,
discovery + heartbeat failure detection.

Mirrors the coordinator-side orchestration stack:

- :class:`StateMachine` — listener-based FSM
  (reference: execution/StateMachine.java:43)
- :class:`QueryStateMachine` — QUEUED → WAITING_FOR_RESOURCES → DISPATCHING
  → PLANNING → STARTING → RUNNING → FINISHING → FINISHED | FAILED
  (reference: execution/QueryState.java:26-58, QueryStateMachine.java)
- :class:`ResourceGroup` — hierarchical admission control with concurrency +
  queue quotas, weighted-fair scheduling, memory/CPU quotas; defined in
  execution/resource_manager.py and re-exported here so the historical
  import path keeps working
  (reference: execution/resourcegroups/InternalResourceGroup.java:75)
- :class:`DispatchManager` — accepts queries, runs them through group
  admission, tracks them (reference: dispatcher/DispatchManager.java:72,
  execution/QueryTracker.java:51)
- :class:`NodeManager` + :class:`HeartbeatFailureDetector` — worker
  announcements and liveness gating task placement (reference:
  metadata/DiscoveryNodeManager.java:68,
  failuredetector/HeartbeatFailureDetector.java:76)

The data plane stays exactly as before — this layer decides WHEN a query
runs and WHERE tasks may be placed, not how batches move."""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = [
    "StateMachine", "QueryStateMachine", "QUERY_STATES",
    "ResourceGroup", "QueryInfo", "DispatchManager",
    "NodeManager", "HeartbeatFailureDetector",
]


class StateMachine:
    """Thread-safe listener FSM.  Terminal states absorb; when ``order`` is
    given, backward transitions are rejected (monotonic lifecycle)."""

    def __init__(self, name: str, initial: str, terminal: set[str],
                 order: Optional[list[str]] = None):
        self.name = name
        self._state = initial
        self._terminal = set(terminal)
        self._rank = {s: i for i, s in enumerate(order or [])}
        self._listeners: list[Callable[[str], None]] = []
        self._cond = threading.Condition()

    @property
    def state(self) -> str:
        with self._cond:
            return self._state

    def is_terminal(self) -> bool:
        return self.state in self._terminal

    def add_listener(self, cb: Callable[[str], None]) -> None:
        with self._cond:
            self._listeners.append(cb)
            state = self._state
        cb(state)  # fire with current state (reference: addStateChangeListener)

    def set(self, new_state: str) -> bool:
        """Transition; returns False if already terminal (absorbed) or the
        move would go backward along ``order``."""
        with self._cond:
            if self._state in self._terminal:
                return False
            if self._state == new_state:
                return True
            if (self._rank and new_state in self._rank
                    and self._state in self._rank
                    and self._rank[new_state] < self._rank[self._state]):
                return False
            self._state = new_state
            listeners = list(self._listeners)
            self._cond.notify_all()
        for cb in listeners:
            cb(new_state)
        return True

    def wait_for(self, predicate: Callable[[str], bool],
                 timeout: float = 30.0) -> str:
        deadline = time.monotonic() + timeout
        with self._cond:
            while not predicate(self._state):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"{self.name}: still {self._state} after {timeout}s")
                self._cond.wait(remaining)
            return self._state


QUERY_STATES = [
    "QUEUED", "WAITING_FOR_RESOURCES", "DISPATCHING", "PLANNING",
    "STARTING", "RUNNING", "FINISHING", "FINISHED", "FAILED",
]


class QueryStateMachine(StateMachine):
    def __init__(self, query_id: str):
        super().__init__(f"query {query_id}", "QUEUED",
                         {"FINISHED", "FAILED"}, QUERY_STATES)
        self.query_id = query_id
        self.error: Optional[BaseException] = None
        self.create_time = time.time()
        self.end_time: Optional[float] = None

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.end_time = time.time()
        self.set("FAILED")

    def finish(self) -> None:
        self.end_time = time.time()
        self.set("FINISHED")


@dataclass
class QueryInfo:
    query_id: str
    sql: str
    resource_group: str
    state_machine: QueryStateMachine

    @property
    def state(self) -> str:
        return self.state_machine.state


# the full weighted-fair/memory/CPU-quota group lives with the serving
# plane; this module keeps the name so `from .control import ResourceGroup`
# (tests, runners) stays the import path
from .resource_manager import ResourceGroup  # noqa: E402


class DispatchManager:
    """Accepts queries, applies resource-group admission, tracks lifecycle
    (reference: dispatcher/DispatchManager.java:173 createQuery →
    createQueryInternal:205; QueryTracker keeps recent history)."""

    def __init__(self, root_group: Optional[ResourceGroup] = None,
                 selector: Optional[Callable[[str, object], str]] = None,
                 max_history: int = 100):
        self.root = root_group or ResourceGroup("global")
        # selector(sql, session) -> subgroup name ('' = root)
        self._selector = selector
        self._tracker: dict[str, QueryInfo] = {}
        # deque: history eviction is O(1) popleft under the lock (list.pop(0)
        # shifted the whole buffer on every submit past max_history)
        self._history: deque[str] = deque()
        self._max_history = max_history
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def _group_for(self, sql: str, session) -> ResourceGroup:
        """Selector output is a dotted path under the root (``etl.heavy``);
        path segments resolve against configured subgroups, creating
        default-knob groups for unconfigured names."""
        if self._selector is None:
            return self.root
        path = self._selector(sql, session)
        g = self.root
        for part in (path or "").split("."):
            if part:
                g = g.subgroup(part)
        return g

    def submit(self, sql: str, session, run: Callable[[QueryStateMachine], object]):
        """Admission + lifecycle around ``run`` (the planning/execution
        callback drives PLANNING..FINISHING itself via the FSM).  Queue
        wait is recorded into the admission distribution + the query record
        (system.runtime.queries queued_time_ms); the query's process-CPU
        window is charged to the group at release so CPU quotas regenerate
        against real usage."""
        from ..telemetry import metrics as tm
        from ..telemetry import runtime as rt

        with self._lock:
            qid = f"q_{next(self._ids)}"
        fsm = QueryStateMachine(qid)
        group = self._group_for(sql, session)
        info = QueryInfo(qid, sql, group.name, fsm)
        with self._lock:
            self._tracker[qid] = info
            self._history.append(qid)
            while len(self._history) > self._max_history:
                self._tracker.pop(self._history.popleft(), None)
        fsm.set("WAITING_FOR_RESOURCES")
        t0 = time.monotonic()
        try:
            group.acquire(
                timeout=getattr(session, "query_queued_timeout_s", 300.0),
                priority=getattr(session, "query_priority", 0))
        except BaseException as e:
            fsm.fail(e)
            raise
        queued_s = time.monotonic() - t0
        tm.ADMISSION_QUEUED_SECONDS.record(queued_s)
        rec = rt.current_record()
        if rec is not None:
            rec.queued_ms = queued_s * 1e3
            rec.resource_group = group.name
        fsm.set("DISPATCHING")
        cpu0 = time.process_time()
        try:
            result = run(fsm)
            fsm.finish()
            return result
        except BaseException as e:
            fsm.fail(e)
            raise
        finally:
            group.release(cpu_s=time.process_time() - cpu0)

    def groups(self) -> list[ResourceGroup]:
        """The full group tree, preorder (system.runtime.resource_groups)."""
        return self.root.walk()

    def query_info(self, query_id: str) -> Optional[QueryInfo]:
        with self._lock:
            return self._tracker.get(query_id)

    def queries(self) -> list[QueryInfo]:
        with self._lock:
            return [self._tracker[q] for q in self._history
                    if q in self._tracker]


# ---------------------------------------------------------------------------
# discovery + failure detection


@dataclass
class NodeInfo:
    node_id: str
    last_heartbeat: float = field(default_factory=time.monotonic)
    coordinator: bool = False
    draining: bool = False


class NodeManager:
    """Worker membership via announcements (reference:
    metadata/DiscoveryNodeManager.java:68 — workers announce; the
    coordinator's view is heartbeat-gated by the failure detector)."""

    def __init__(self, heartbeat_timeout: float = 10.0):
        self.heartbeat_timeout = heartbeat_timeout
        self._nodes: dict[str, NodeInfo] = {}
        self._lock = threading.Lock()

    def announce(self, node_id: str, coordinator: bool = False) -> None:
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None:
                self._nodes[node_id] = NodeInfo(
                    node_id, time.monotonic(), coordinator)
            else:
                info.last_heartbeat = time.monotonic()

    def heartbeat(self, node_id: str) -> None:
        """Refresh liveness of an EXISTING node only — a heartbeat must not
        resurrect a node removed by the operator (remove() is deliberate)."""
        with self._lock:
            info = self._nodes.get(node_id)
            if info is not None:
                info.last_heartbeat = time.monotonic()

    def drain(self, node_id: str) -> None:
        """Graceful shutdown: stop placing new tasks on the node
        (reference: server/GracefulShutdownHandler.java:42)."""
        with self._lock:
            if node_id in self._nodes:
                self._nodes[node_id].draining = True

    def restore(self, node_id: str) -> None:
        """Undo drain: the node takes new task placements again (the
        in-process rolling-restart drill drains and restores each worker
        in turn — there is no process to replace)."""
        with self._lock:
            info = self._nodes.get(node_id)
            if info is not None:
                info.draining = False
                info.last_heartbeat = time.monotonic()

    def remove(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def active_workers(self) -> list[str]:
        now = time.monotonic()
        with self._lock:
            return sorted(
                n.node_id for n in self._nodes.values()
                if not n.coordinator and not n.draining
                and now - n.last_heartbeat <= self.heartbeat_timeout)

    def all_nodes(self) -> list[NodeInfo]:
        with self._lock:
            return list(self._nodes.values())


class HeartbeatFailureDetector:
    """Background pinger marking nodes failed after missed heartbeats
    (reference: failuredetector/HeartbeatFailureDetector.java:76 ping:344).
    ``ping`` callbacks stand in for HTTP /v1/status probes: they return True
    while the node is alive — in-process workers are functions; over DCN
    they would be HTTP checks."""

    def __init__(self, nodes: NodeManager, interval: float = 0.5):
        self.nodes = nodes
        self.interval = interval
        self._pingers: dict[str, Callable[[], bool]] = {}
        self._failed: set[str] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def monitor(self, node_id: str, ping: Callable[[], bool]) -> None:
        with self._lock:
            self._pingers[node_id] = ping

    def unmonitor(self, node_id: str) -> None:
        with self._lock:
            self._pingers.pop(node_id, None)
            self._failed.discard(node_id)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="failure-detector", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.ping_once()

    def ping_once(self) -> None:
        with self._lock:
            pingers = dict(self._pingers)
        for node_id, ping in pingers.items():
            ok = False
            try:
                ok = bool(ping())
            except BaseException:
                ok = False
            if ok:
                # heartbeat (not announce): a ping must never resurrect a
                # node the operator removed from membership
                self.nodes.heartbeat(node_id)
                with self._lock:
                    self._failed.discard(node_id)
            else:
                with self._lock:
                    self._failed.add(node_id)

    def failed_nodes(self) -> set[str]:
        with self._lock:
            return set(self._failed)
