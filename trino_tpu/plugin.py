"""Plugin loading: external connectors registered at runtime.

Mirrors ``spi/Plugin.java`` + ``server/PluginManager.java`` in python
terms: a plugin is a module (import path or .py file) exposing a
``plugin()`` callable that returns a :class:`Plugin`; its connector
factories are registered into a :class:`PluginManager`, and catalogs are
then created from factory name + config (``CatalogFactory`` role).  The
per-plugin classloader isolation of the JVM maps to python module
namespaces — good enough for in-process engines; process isolation is a
deployment concern."""

from __future__ import annotations

import importlib
import importlib.util
import os
from typing import Callable, Optional

from .connectors.catalog import Catalog
from .spi.connector import Connector

__all__ = ["Plugin", "PluginManager"]


class Plugin:
    """Base plugin: name -> connector factory (callable(config) -> Connector)."""

    def get_connector_factories(self) -> dict[str, Callable[[dict], Connector]]:
        return {}

    def get_event_listener_factories(self) -> dict[str, Callable[[dict], object]]:
        return {}


class PluginManager:
    def __init__(self, catalog: Optional[Catalog] = None):
        self.catalog = catalog
        self._factories: dict[str, Callable[[dict], Connector]] = {}
        self._listener_factories: dict[str, Callable[[dict], object]] = {}
        self.loaded: list[str] = []

    def install(self, plugin: Plugin, name: str = "") -> None:
        self._factories.update(plugin.get_connector_factories())
        self._listener_factories.update(plugin.get_event_listener_factories())
        self.loaded.append(name or type(plugin).__name__)

    def load(self, module_or_path: str) -> None:
        """Load a plugin from an import path ('my_pkg.my_plugin') or a
        filesystem path ('/plugins/foo.py'); the module must expose
        ``plugin()`` returning a Plugin."""
        if os.path.sep in module_or_path or module_or_path.endswith(".py"):
            modname = "_trino_tpu_plugin_" + os.path.splitext(
                os.path.basename(module_or_path))[0]
            spec = importlib.util.spec_from_file_location(
                modname, module_or_path)
            if spec is None or spec.loader is None:
                raise ImportError(f"cannot load plugin: {module_or_path}")
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        else:
            mod = importlib.import_module(module_or_path)
        factory = getattr(mod, "plugin", None)
        if factory is None:
            raise ImportError(
                f"plugin module {module_or_path!r} exposes no plugin()")
        self.install(factory(), module_or_path)

    def connector_factories(self) -> dict:
        return dict(self._factories)

    def create_event_listener(self, name: str, config: Optional[dict] = None):
        """Instantiate a plugin-provided event listener (register it on a
        runner via ``runner.event_listeners.add``; reference: PluginManager
        wiring EventListenerFactory into the EventListenerManager)."""
        if name not in self._listener_factories:
            raise KeyError(f"no such event listener: {name!r} "
                           f"(loaded: {sorted(self._listener_factories)})")
        return self._listener_factories[name](config or {})

    def create_catalog(self, catalog_name: str, connector_name: str,
                       config: Optional[dict] = None) -> Connector:
        """CREATE CATALOG equivalent (reference:
        connector/CoordinatorDynamicCatalogManager + CatalogFactory)."""
        if connector_name not in self._factories:
            raise KeyError(f"no such connector: {connector_name!r} "
                           f"(loaded: {sorted(self._factories)})")
        conn = self._factories[connector_name](config or {})
        if self.catalog is not None:
            self.catalog.register(catalog_name, conn)
        return conn
