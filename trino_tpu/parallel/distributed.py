"""Mesh-parallel relational programs: repartition = all_to_all over ICI.

The PartitionedOutput → Exchange data path (reference: operator/output/
PagePartitioner.java:134 hash partition + HTTP page streaming) compiled into
a single SPMD program: every device holds a row-shard (data parallelism over
splits), aggregates locally (PARTIAL step), hash-routes group slots to owner
devices with ``jax.lax.all_to_all`` (the FIXED_HASH_DISTRIBUTION analog),
and reduces again (FINAL step).  Broadcast joins use ``all_gather`` of the
build side (FIXED_BROADCAST_DISTRIBUTION — SystemPartitioningHandle.java:52).

Capacity contract: each device sends at most ``cap`` group slots to each
destination (send buffer [n_dev, cap]); unused lanes carry a dead mask.  For
relational workloads cap is sized from NDV stats, so the buffers stay tiny
compared to the row data they summarize.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from .compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .static_agg import AggSpec, combine_partials, static_grouped_agg

__all__ = [
    "make_mesh",
    "distributed_grouped_agg",
    "broadcast_gather",
]


def make_mesh(n_devices: Optional[int] = None, axis: str = "x") -> Mesh:
    devs = jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    return Mesh(devs[:n], (axis,))


def _route_hash(keys: Sequence[jnp.ndarray], n_dev: int) -> jnp.ndarray:
    h = jnp.zeros(keys[0].shape, dtype=jnp.uint32)
    for k in keys:
        x = k.astype(jnp.int64).astype(jnp.uint32) if k.dtype != jnp.bool_ else k.astype(jnp.uint32)
        h = (h ^ x) * jnp.uint32(0x9E3779B1)
        h = h ^ (h >> 15)
    return (h % jnp.uint32(n_dev)).astype(jnp.int32)


def distributed_grouped_agg(
    mesh: Mesh,
    axis: str,
    key_dtypes: Sequence,
    agg_specs: Sequence[AggSpec],
    cap: int,
):
    """Build a jitted SPMD function: (sharded key cols, sharded agg inputs,
    sharded row mask) -> per-device final group slots.

    Returned callable signature:
        fn(*keys, *agg_datas, row_mask) -> (out_keys, out_values, slot_used)
    with every input sharded on axis 0 over ``axis`` and outputs likewise
    (each device owns the groups that hash to it).
    """
    n_dev = mesh.shape[axis]
    nk = len(key_dtypes)

    def local_program(*args):
        keys = list(args[:nk])
        datas = list(args[nk : nk + len(agg_specs)])
        row_mask = args[-1]

        # ---- PARTIAL: local grouped reduction ------------------------------
        agg_inputs = []
        for spec, d in zip(agg_specs, datas):
            agg_inputs.append((spec, d, None))
        part = static_grouped_agg(keys, [None] * nk, agg_inputs, cap, row_mask)

        # ---- route: slot -> owner device -----------------------------------
        dest = _route_hash(part.keys, n_dev)
        # send buffer [n_dev, cap]: lane (d, s) = slot s if it routes to d
        lane_live = part.slot_used[None, :] & (
            dest[None, :] == jnp.arange(n_dev, dtype=jnp.int32)[:, None]
        )

        def to_lanes(x):
            return jnp.broadcast_to(x[None, :], (n_dev, cap))

        sent_keys = [
            jax.lax.all_to_all(to_lanes(k), axis, 0, 0, tiled=False)
            for k in part.keys
        ]
        sent_vals = [
            jax.lax.all_to_all(to_lanes(v), axis, 0, 0, tiled=False)
            for v in part.values
        ]
        sent_vvalids = [
            None
            if v is None
            else jax.lax.all_to_all(to_lanes(v), axis, 0, 0, tiled=False)
            for v in part.value_valids
        ]
        sent_live = jax.lax.all_to_all(lane_live, axis, 0, 0, tiled=False)

        # ---- FINAL: merge partial states from all sources ------------------
        rk = [k.reshape(n_dev * cap) for k in sent_keys]
        rlive = sent_live.reshape(n_dev * cap)
        partial_inputs = []
        for spec, v, vv in zip(agg_specs, sent_vals, sent_vvalids):
            partial_inputs.append(
                (spec, v.reshape(n_dev * cap),
                 None if vv is None else vv.reshape(n_dev * cap))
            )
        fin = combine_partials(rk, [None] * nk, partial_inputs, rlive, cap)
        # overflow signal (static-agg contract): callers must check
        # max(overflow) <= cap, else re-run with a bigger cap
        overflow = jnp.maximum(part.num_groups, fin.num_groups).reshape(1)
        return tuple(fin.keys), tuple(fin.values), fin.slot_used, overflow

    sharded = shard_map(
        local_program,
        mesh=mesh,
        in_specs=tuple([P(axis)] * (nk + len(agg_specs) + 1)),
        out_specs=(
            tuple([P(axis)] * nk),
            tuple([P(axis)] * len(agg_specs)),
            P(axis),
            P(axis),
        ),
        check_vma=False,
    )
    return jax.jit(sharded)


def broadcast_gather(mesh: Mesh, axis: str):
    """all_gather of a sharded build side — the broadcast-join distribution
    (BroadcastOutputBuffer.java:56 → one collective)."""

    def program(x):
        return jax.lax.all_gather(x, axis, axis=0, tiled=True)

    return jax.jit(
        shard_map(
            program, mesh=mesh, in_specs=P(axis), out_specs=P(), check_vma=False
        )
    )
