"""Distributed execution over a jax.sharding.Mesh.

The TPU-native replacement for Trino's exchange data plane (reference:
operator/output/PartitionedOutputOperator.java:47 hash-shuffle +
operator/ExchangeOperator.java:44 consumer + execution/buffer/*OutputBuffer):
when a stage's producing and consuming tasks are all TPU-resident, the
repartition/broadcast/gather edges compile into XLA collectives
(``all_to_all`` / ``all_gather`` / ``psum``) under ``shard_map`` riding ICI —
there is no serialize → HTTP → deserialize hop at all.
"""
