"""Fully-static grouped aggregation — jittable with NO host syncs.

The dynamic-shape kernel in exec/kernels.py syncs the group count to the host
to pick a bucket; that is fine between operators but illegal inside
``shard_map``/``pjit`` programs.  This variant promises a static group-slot
capacity ``cap`` up front (TPC-H Q1 has 4 groups; planners pick ``cap`` from
table stats / NDV estimates, mirroring how Trino sizes hash tables from
``EstimatedRowCount``), so the whole pipeline — filter, project, group, reduce
— is one XLA program and can fuse with the collectives around it.

Overflow contract: if the true group count exceeds ``cap``, ``num_groups``
in the result exceeds ``cap`` — the caller must check and re-run with a
bigger cap (the recompile-bucket strategy of SURVEY §7).

When ``TRINO_TPU_HASH_IMPL`` selects the Pallas open-addressing path, group
ids come straight from the hash-insert kernel: no lexsort, and the row count
stays a device scalar.  Slot ORDER then differs from the sort route (first
occurrence vs key order) — callers already must not rely on slot order, and
``combine_partials`` re-groups anyway.  One semantic divergence: the sort
route's raw ``!=`` comparison makes every NaN its own group, while the hash
route canonicalizes NaNs into one group (SQL semantics).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

from .. import ops as _ops  # noqa: F401  (enables jax x64)
from ..exec import kernels as _K

__all__ = ["AggSpec", "StaticAggResult", "static_grouped_agg", "combine_partials"]


class AggSpec(NamedTuple):
    """One aggregate column in kernel form.

    fn: sum | count | count_star | min | max | any_value
    (avg is decomposed into sum+count by the caller).
    """

    fn: str
    dtype: jnp.dtype


class StaticAggResult(NamedTuple):
    keys: list  # [cap] per key column (representative values)
    key_valids: list  # [cap] bool or None per key column
    values: list  # [cap] per agg
    value_valids: list  # [cap] bool or None per agg
    slot_used: jnp.ndarray  # [cap] bool — slot holds a real group
    num_groups: jnp.ndarray  # scalar int32 (may exceed cap: overflow signal)


def _sentinel(fn: str, dtype):
    kind = jnp.dtype(dtype).kind
    if fn == "min":
        return jnp.inf if kind == "f" else (True if kind == "b" else jnp.iinfo(dtype).max)
    return -jnp.inf if kind == "f" else (False if kind == "b" else jnp.iinfo(dtype).min)


def _sort_gids(keys, key_valids, cap, row_mask):
    """lexsort route: (perm, live, gid, num_groups) with rows sorted so
    equal keys are adjacent and boundary flags derive dense group ids."""
    n = keys[0].shape[0]
    norm = []
    for k, v in zip(keys, key_valids):
        kk = k
        if v is not None:
            kk = jnp.where(v, kk, jnp.zeros((), kk.dtype))
        norm.append(kk)

    sort_keys = []
    for i in reversed(range(len(norm))):
        sort_keys.append(norm[i])
        if key_valids[i] is not None:
            sort_keys.append(key_valids[i])
    if row_mask is not None:
        # dead rows sort to the back so live groups get the low slot ids
        sort_keys.append(~row_mask)
    perm = jnp.lexsort(tuple(sort_keys)) if sort_keys else jnp.arange(n)

    live = row_mask[perm] if row_mask is not None else jnp.ones(n, jnp.bool_)
    new_group = jnp.zeros(n, jnp.bool_)
    for i, k in enumerate(norm):
        d = k[perm]
        diff = jnp.concatenate([jnp.ones((1,), jnp.bool_), d[1:] != d[:-1]])
        if key_valids[i] is not None:
            v = key_valids[i][perm]
            diff = diff | jnp.concatenate([jnp.ones((1,), jnp.bool_), v[1:] != v[:-1]])
        new_group = new_group | diff
    new_group = new_group & live
    # first live row starts group 0 even if its boundary flag got masked
    first_live = jnp.argmax(live) if n else jnp.zeros((), jnp.int64)
    new_group = jnp.where(live.any(), new_group.at[first_live].set(True), new_group)
    gid_all = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    num_groups = jnp.where(live.any(), gid_all[-1] + 1, 0) if n else jnp.zeros((), jnp.int32)
    # dead rows scatter into the overflow slot
    gid = jnp.where(live, jnp.clip(gid_all, 0, cap - 1), cap)
    return perm, live, gid, num_groups


def static_grouped_agg(
    keys: Sequence[jnp.ndarray],
    key_valids: Sequence[Optional[jnp.ndarray]],
    agg_inputs: Sequence[tuple],  # (AggSpec, data|None, valid|None)
    cap: int,
    row_mask: Optional[jnp.ndarray] = None,
) -> StaticAggResult:
    """Group rows by ``keys`` and reduce; everything static-shaped.

    ``row_mask`` folds an upstream filter into the kernel (selection-vector
    style — SURVEY §7 shift 2): masked-out rows join group slot ``cap`` + are
    dropped by reduction identity values.
    """
    n = keys[0].shape[0]
    pairs = list(zip(keys, key_valids))
    if n and _K._use_hash_impl(n, _K._plane_count(pairs)):
        # hash route: the insert kernel hands every ORIGINAL row its dense
        # group id, so perm stays identity and the segment scatters below
        # work unsorted; the count stays a device scalar (still zero syncs)
        row_gid, num_groups = _K.hash_row_gids(pairs, live=row_mask)
        S = _K.bucket(2 * max(n, 1))
        perm = jnp.arange(n)
        live = row_mask if row_mask is not None else jnp.ones(n, jnp.bool_)
        gid = jnp.where(row_gid < S, jnp.minimum(row_gid, cap - 1), cap)
    else:
        perm, live, gid, num_groups = _sort_gids(keys, key_valids, cap,
                                                 row_mask)

    out_keys, out_kvalids = [], []
    for k, v in zip(keys, key_valids):
        rep = jnp.zeros((cap + 1,), k.dtype).at[gid].set(k[perm])
        out_keys.append(rep[:cap])
        if v is not None:
            rv = jnp.zeros((cap + 1,), jnp.bool_).at[gid].max(v[perm])
            out_kvalids.append(rv[:cap])
        else:
            out_kvalids.append(None)

    values, vvalids = [], []
    for spec, data, valid in agg_inputs:
        if spec.fn == "count_star":
            ones = live.astype(jnp.int64)
            values.append(jax.ops.segment_sum(ones, gid, cap + 1)[:cap])
            vvalids.append(None)
            continue
        d = data[perm]
        v = valid[perm] if valid is not None else None
        eff_valid = v if v is not None else None
        if spec.fn == "count":
            c = live if eff_valid is None else (live & eff_valid)
            values.append(jax.ops.segment_sum(c.astype(jnp.int64), gid, cap + 1)[:cap])
            vvalids.append(None)
        elif spec.fn == "sum":
            keep = live if eff_valid is None else (live & eff_valid)
            x = jnp.where(keep, d, jnp.zeros((), d.dtype)).astype(spec.dtype)
            values.append(jax.ops.segment_sum(x, gid, cap + 1)[:cap])
            vvalids.append(jax.ops.segment_max(keep, gid, cap + 1)[:cap])
        elif spec.fn in ("min", "max"):
            keep = live if eff_valid is None else (live & eff_valid)
            sent = _sentinel(spec.fn, d.dtype)
            x = jnp.where(keep, d, sent)
            red = jax.ops.segment_min if spec.fn == "min" else jax.ops.segment_max
            values.append(red(x, gid, cap + 1)[:cap])
            vvalids.append(jax.ops.segment_max(keep, gid, cap + 1)[:cap])
        elif spec.fn == "any_value":
            keep = live if eff_valid is None else (live & eff_valid)
            rep = jnp.zeros((cap + 1,), d.dtype).at[jnp.where(keep, gid, cap)].set(d)
            values.append(rep[:cap])
            vvalids.append(jax.ops.segment_max(keep, gid, cap + 1)[:cap])
        else:
            raise NotImplementedError(spec.fn)

    slot_used = jnp.arange(cap) < num_groups
    return StaticAggResult(out_keys, out_kvalids, values, vvalids, slot_used, num_groups)


_COMBINE = {"sum": "sum", "count": "sum", "count_star": "sum",
            "min": "min", "max": "max", "any_value": "any_value"}


def combine_partials(
    keys: Sequence[jnp.ndarray],
    key_valids: Sequence[Optional[jnp.ndarray]],
    partial_inputs: Sequence[tuple],  # (AggSpec, values, valid|None)
    slot_used: jnp.ndarray,
    cap: int,
) -> StaticAggResult:
    """FINAL step: re-group partial state rows by key, merge states
    (sum→sum, count→sum, min→min …) — Trino's partial/final split
    (AggregationNode.Step PARTIAL/FINAL)."""
    merged = []
    for spec, vals, valid in partial_inputs:
        merged.append((AggSpec(_COMBINE[spec.fn], spec.dtype), vals, valid))
    return static_grouped_agg(keys, key_valids, merged, cap, row_mask=slot_used)
