"""jax API compatibility shims for the collective data plane.

``shard_map`` moved from ``jax.experimental.shard_map`` to ``jax.shard_map``
and renamed its replication-check kwarg (``check_rep`` -> ``check_vma``)
along the way.  The engine's collective programs always disable the check
(row routing is intentionally non-replicated), so the shim only has to map
that one flag onto whichever API the installed jax exposes.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map"]

if hasattr(jax, "shard_map"):
    def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
else:  # pre-0.6 jax: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)
