"""StandaloneQueryRunner: SQL string → result batch, in process.

The single-node equivalent of the reference's StandaloneQueryRunner
(core/trino-main/src/main/java/io/trino/testing/StandaloneQueryRunner.java):
parse → plan → optimize → local-plan → drive.  The distributed runner
(coordinator + workers + exchanges) layers on top of the same pieces.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from .connectors.catalog import Catalog, default_catalog
from .exec.driver import run_pipelines
from .exec.local_planner import LocalPlanner
from .planner.logical import LogicalPlanner
from .planner.optimizer import optimize
from .planner.plan import PlanNode, plan_text
from .spi.batch import ColumnBatch
from .sql.parser import parse_statement

__all__ = ["QueryResult", "StandaloneQueryRunner"]


@dataclass
class QueryResult:
    names: list[str]
    batch: ColumnBatch

    def rows(self) -> list[tuple]:
        return self.batch.to_pylist()


@dataclass
class Session:
    """Per-query knobs (the SystemSessionProperties miniature)."""

    default_catalog: str = "tpch"
    splits_per_node: int = 4
    node_count: int = 1


class StandaloneQueryRunner:
    def __init__(self, catalog: Optional[Catalog] = None,
                 session: Optional[Session] = None):
        self.catalog = catalog if catalog is not None else default_catalog()
        self.session = session if session is not None else Session()

    def create_plan(self, sql: str) -> PlanNode:
        stmt = parse_statement(sql)
        planner = LogicalPlanner(self.catalog, self.session.default_catalog)
        plan = planner.plan(stmt)
        return optimize(plan, self.catalog)

    def explain(self, sql: str) -> str:
        return plan_text(self.create_plan(sql))

    def execute(self, sql: str) -> QueryResult:
        plan = self.create_plan(sql)
        local = LocalPlanner(
            self.catalog,
            splits_per_node=self.session.splits_per_node,
            node_count=self.session.node_count,
        ).plan(plan)
        run_pipelines(local.pipelines)
        batches = local.collector.batches
        if batches:
            batch = ColumnBatch.concat(batches)
        else:
            from .spi.batch import Column

            batch = ColumnBatch(
                local.output_names,
                [Column(t, np.empty(0, t.storage_dtype))
                 for t in local.output_types],
            )
        return QueryResult(local.output_names, batch)
