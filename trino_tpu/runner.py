"""StandaloneQueryRunner: SQL string → result batch, in process.

The single-node equivalent of the reference's StandaloneQueryRunner
(core/trino-main/src/main/java/io/trino/testing/StandaloneQueryRunner.java):
parse → plan → optimize → local-plan → drive.  The distributed runner
(coordinator + workers + exchanges) layers on top of the same pieces.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from .connectors.catalog import Catalog, default_catalog
from .exec.driver import (collect_encoding_stats, collect_scan_stats,
                          run_pipelines)
from .exec.local_planner import LocalPlanner
from .exec.stats import QueryStats
from .execution.tracing import annotate_scan_span, annotate_sync_span
from .planner.logical import LogicalPlanner
from .planner.optimizer import optimize
from .planner.plan import PlanNode, plan_text
from .spi.batch import Column, ColumnBatch
from .spi.types import VARCHAR
from .sql import ast
from .sql.parser import parse_statement

__all__ = ["QueryResult", "StandaloneQueryRunner"]


def text_result(name: str, lines: list[str]) -> "QueryResult":
    return QueryResult([name], ColumnBatch(
        [name], [Column.from_values(VARCHAR, lines)]))


def count_result(name: str, n: int) -> "QueryResult":
    from .spi.types import BIGINT

    return QueryResult([name], ColumnBatch(
        [name], [Column(BIGINT, np.array([n], np.int64))]))


def _refresh_materialized_view(name: str, catalog, run_select,
                               default_catalog_name: str = "memory") -> int:
    """(Re)materialize a view into its backing table in the 'memory'
    catalog; returns the row count (reference:
    operator/RefreshMaterializedViewOperator.java:27)."""
    from .connectors.catalog import ViewDefinition  # noqa: F401
    from .spi.connector import ColumnSchema, TableSchema

    view = catalog.views[name]
    conn = catalog.connector("memory")
    # capture the base tables' data_version vector BEFORE reading them:
    # Catalog.mv_is_stale compares these against current tokens, and a base
    # mutation racing the refresh must leave the MV looking stale, not fresh
    try:
        from .caching import plan_cache
        from .planner.logical import LogicalPlanner

        base_plan = LogicalPlanner(catalog, default_catalog_name).plan(
            ast.QueryStatement(view.query))
        base_versions = catalog.table_versions(
            plan_cache.scan_tables(base_plan))
    except Exception:  # noqa: BLE001 — staleness stays conservative (None)
        base_versions = None
    result = run_select(ast.QueryStatement(view.query))
    batch = result.batch.compact()
    backing = f"__mv_{name}"
    conn.drop_table(backing)
    conn.create_table(TableSchema(backing, tuple(
        ColumnSchema(n, c.type)
        for n, c in zip(result.names, batch.columns))))
    sink = conn.create_page_sink(backing)
    sink.append(batch.rename(list(result.names)))
    conn.finish_insert(backing, sink.finish())
    view.backing = ("memory", backing)
    view.base_versions = base_versions
    return batch.num_rows


def _literal_value(e):
    """Constant AST node -> python value (SET SESSION / CALL arguments)."""
    if isinstance(e, (ast.IntLiteral, ast.DoubleLiteral, ast.BooleanLiteral,
                      ast.StringLiteral)):
        return e.value
    if isinstance(e, ast.DecimalLiteral):
        return float(e.text)
    if isinstance(e, ast.NullLiteral):
        return None
    raise ValueError("expected a constant")


# knobs SET SESSION may touch; identity/transaction/injection state is NOT
# settable through SQL (a restricted user must not setattr session.user)
SETTABLE_SESSION_PROPERTIES = {
    "default_catalog", "splits_per_node", "node_count", "dynamic_filtering",
    "hbm_limit_bytes", "spill_to_disk_bytes", "use_collectives",
    "exchange_serde", "retry_policy", "task_retry_attempts",
    "task_scheduler", "executor_workers", "query_concurrency",
    "query_max_queued", "scale_writers", "writer_task_limit",
    "task_concurrency", "fte_speculative", "fte_speculative_delay_s",
    "fte_memory_growth",
    "query_retry_attempts", "retry_initial_delay_s", "retry_max_delay_s",
    "heartbeat_interval_s", "heartbeat_failure_threshold",
    "max_worker_replacements", "exchange_backoff_min_s",
    "exchange_backoff_max_s", "exchange_max_failure_duration_s",
    "speculation", "speculation_lag_multiplier", "speculation_min_delay_s",
    "speculation_nonleaf",
    "blacklist_ttl_s", "blacklist_threshold", "drain_timeout_s",
    "adaptive", "broadcast_threshold_bytes", "skew_factor",
}


def execute_session_stmt(stmt, session) -> Optional["QueryResult"]:
    """SET SESSION (reference: execution/SetSessionTask.java): mutate a
    public Session knob with loose literal typing."""
    if not isinstance(stmt, ast.SetSession):
        return None
    name = stmt.name.lower()
    if name not in SETTABLE_SESSION_PROPERTIES:
        raise KeyError(f"unknown or protected session property: {name}")
    value = _literal_value(stmt.value)
    current = getattr(session, name)
    if isinstance(current, bool) and not isinstance(value, bool):
        value = str(value).lower() in ("true", "1")
    elif isinstance(current, int) and not isinstance(value, bool) \
            and value is not None:
        value = int(value)
    setattr(session, name, value)
    return text_result("result", [f"{name} = {value}"])


def execute_ddl(stmt, catalog, default_catalog_name: str,
                run_select) -> Optional["QueryResult"]:
    """Metadata statements shared by both runners (CREATE TABLE with
    columns, DROP TABLE, DELETE).  Returns None for non-DDL statements.
    Reference: metadata/MetadataManager create/drop, and DELETE planned as
    scan+filter+rewrite (the simple connectors have no row-id deletes)."""
    out = _execute_ddl(stmt, catalog, default_catalog_name, run_select)
    if out is not None:
        # any metadata statement (DDL, views, functions, ANALYZE stats,
        # procedures) may change how future statements plan: cached
        # logical plans against the old catalog state must miss
        catalog.bump_generation()
    return out


def _execute_ddl(stmt, catalog, default_catalog_name: str,
                 run_select) -> Optional["QueryResult"]:
    from .spi.connector import ColumnSchema, TableSchema
    from .spi.types import parse_type

    if isinstance(stmt, ast.CreateFunction):
        from .sql.analyzer import is_builtin_function

        if is_builtin_function(stmt.name):
            raise ValueError(
                f"cannot create function {stmt.name!r}: shadows a builtin")
        catalog.sql_functions[stmt.name.lower()] = (
            stmt.params, stmt.return_type, stmt.body)
        return count_result("rows", 0)
    if isinstance(stmt, ast.DropFunction):
        if catalog.sql_functions.pop(stmt.name.lower(), None) is None:
            raise KeyError(f"no such function: {stmt.name}")
        return count_result("rows", 0)
    if isinstance(stmt, ast.CreateView):
        from .connectors.catalog import ViewDefinition

        name = stmt.name.split(".")[-1]
        if name in catalog.views and not stmt.replace:
            raise ValueError(f"view already exists: {name}")
        catalog.views[name] = ViewDefinition(stmt.query, stmt.materialized)
        if stmt.materialized:
            _refresh_materialized_view(name, catalog, run_select,
                                       default_catalog_name)
        return count_result("rows", 0)
    if isinstance(stmt, ast.DropView):
        name = stmt.name.split(".")[-1]
        view = catalog.views.pop(name, None)
        if view is None:
            if stmt.if_exists:
                return count_result("rows", 0)
            raise KeyError(f"no such view: {name}")
        if view.backing is not None:
            catalog.connector(view.backing[0]).drop_table(view.backing[1])
        return count_result("rows", 0)
    if isinstance(stmt, ast.RefreshMaterializedView):
        name = stmt.name.split(".")[-1]
        if name not in catalog.views or not catalog.views[name].materialized:
            raise KeyError(f"no such materialized view: {name}")
        rows = _refresh_materialized_view(name, catalog, run_select,
                                          default_catalog_name)
        return count_result("rows", rows)
    if isinstance(stmt, ast.CallProcedure):
        cat, proc = _split_name(stmt.name, default_catalog_name)
        procs = catalog.connector(cat).get_procedures()
        if proc not in procs:
            raise KeyError(f"no such procedure: {cat}.{proc}")
        out = procs[proc](*[_literal_value(a) for a in stmt.args])
        return text_result("result", [str(out)])
    if isinstance(stmt, ast.Analyze):
        cat, table, schema = catalog.resolve_table(
            stmt.table, default_catalog_name)
        conn = catalog.connector(cat)
        from .spi.connector import TableStatistics

        rows = 0
        ndv: dict[str, set] = {c.name: set() for c in schema.columns}
        cols = [c.name for c in schema.columns]
        for split in conn.get_splits(table, 1, 1):
            src = conn.create_page_source(split, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is None:
                    continue
                b = b.compact()
                rows += b.num_rows
                for name_, col in zip(b.names, b.columns):
                    data = np.asarray(col.data)
                    if col.valid is not None:
                        data = data[np.asarray(col.valid)]
                    if col.dictionary is not None:
                        # codes are per-batch namespaces: count VALUES
                        ndv[name_].update(col.dictionary[np.unique(data)])
                    else:
                        ndv[name_].update(np.unique(data).tolist())
        conn.set_analyzed_statistics(table, TableStatistics(
            row_count=float(rows),
            ndv={k: float(len(v)) for k, v in ndv.items()}))
        return count_result("rows", rows)
    if isinstance(stmt, ast.CreateTable):
        cat, table = _split_name(stmt.table, default_catalog_name)
        conn = catalog.connector(cat)
        conn.create_table(TableSchema(table, tuple(
            ColumnSchema(n, parse_type(t)) for n, t in stmt.columns)))
        return count_result("rows", 0)
    if isinstance(stmt, ast.DropTable):
        cat, table = _split_name(stmt.table, default_catalog_name)
        conn = catalog.connector(cat)
        try:
            conn.get_table_schema(table)
        except KeyError:
            if stmt.if_exists:
                return count_result("rows", 0)
            raise
        conn.drop_table(table)
        return count_result("rows", 0)
    if isinstance(stmt, ast.Delete):
        cat, table, schema = catalog.resolve_table(
            stmt.table, default_catalog_name)
        conn = catalog.connector(cat)
        from .spi.connector import Connector as _BaseConnector

        impl = getattr(type(conn), "create_page_sink", None)
        if impl is None or impl is _BaseConnector.create_page_sink:
            raise ValueError(f"connector {cat} does not support DELETE")
        stats = conn.get_table_statistics(table)
        before = int(stats.row_count) if stats.row_count == stats.row_count else None
        if before is None:  # no stats: count the table first
            cq = ast.Query(ast.QuerySpec(
                (ast.SelectItem(ast.FunctionCall("count", (), is_star=True)),),
                False, ast.Table(f"{cat}.{table}"), None, (), None))
            before = int(run_select(ast.QueryStatement(cq)).rows()[0][0])
        # rows to KEEP: NOT coalesce(pred, false) — NULL predicates keep
        if stmt.where is None:
            keep_where = ast.BooleanLiteral(False)
        else:
            keep_where = ast.Not(ast.FunctionCall(
                "coalesce", (stmt.where, ast.BooleanLiteral(False))))
        q = ast.Query(ast.QuerySpec(
            (ast.SelectItem(None),), False,
            ast.Table(f"{cat}.{table}"), keep_where, (), None))
        kept = run_select(ast.QueryStatement(q))
        # stage the kept rows FIRST: every risky step (serde, disk) happens
        # before the original table is touched, so a failed rewrite cannot
        # destroy data
        staging = f"__rewrite_{table}"
        conn.drop_table(staging)
        conn.create_table(TableSchema(staging, schema.columns))
        try:
            sink = conn.create_page_sink(staging)
            sink.append(kept.batch)
            conn.finish_insert(staging, sink.finish())
        except BaseException:
            conn.drop_table(staging)
            raise
        conn.drop_table(table)
        conn.create_table(TableSchema(table, schema.columns))
        sink = conn.create_page_sink(table)
        for split in conn.get_splits(staging, 1, 1):
            src = conn.create_page_source(
                split, [c.name for c in schema.columns])
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    sink.append(b)
        conn.finish_insert(table, sink.finish())
        conn.drop_table(staging)
        kept_rows = kept.batch.compact().num_rows
        return count_result("rows", before - kept_rows)
    return None


def run_with_query_events(qid: str, sql: str, user: str, listeners, tracer,
                          thunk):
    """Shared query lifecycle wrapper: created/completed events, the root
    tracing span, the process query registry entry
    (telemetry/runtime.py -> system.runtime.queries) and the query-level
    metrics (telemetry/metrics.py) around ``thunk`` (both runners use this;
    reference: QueryMonitor emitting eventlistener events around the
    dispatch).  ``cpu_ms`` is process CPU over the query window —
    concurrent queries overlap in it, like the reference's per-node
    cumulative totals."""
    import time as _time

    from .spi.eventlistener import QueryCompletedEvent, QueryCreatedEvent
    from .telemetry import metrics as tm
    from .telemetry import profiler
    from .telemetry import runtime as rt

    listeners.query_created(QueryCreatedEvent(qid, sql, user))
    rec = rt.query_started(qid, sql, user)
    tm.QUERIES_STARTED.inc()
    prof_ctx = profiler.set_context(qid)
    t0 = _time.perf_counter()
    cpu0 = _time.process_time()

    def _finish(state: str, rows: int, error, error_code=None):
        wall = (_time.perf_counter() - t0) * 1e3
        cpu = (_time.process_time() - cpu0) * 1e3
        tm.QUERY_WALL_SECONDS.record(wall / 1e3)
        (tm.QUERIES_FINISHED if state == "FINISHED"
         else tm.QUERIES_FAILED).inc()
        peak = tm.update_device_memory_watermark() or 0
        rt.query_finished(rec, state, wall, cpu, rows, error,
                          peak_memory_bytes=peak)
        # this process's ring events move into the bounded per-query
        # profile store before the rings can wrap (worker-process events
        # arrive separately, via task status JSON)
        profiler.harvest(qid)
        # Tier B warm journal: persist any memo keys this query minted so
        # the next process can pre-instantiate them at boot (no-op when
        # nothing changed — one flag check per query)
        try:
            from .caching import executable_cache

            executable_cache.flush_warm_keys()
        except Exception:  # noqa: BLE001 — persistence is best-effort
            pass
        profiler.apply_context(prof_ctx)
        listeners.query_completed(QueryCompletedEvent(
            qid, sql, state, user, wall, rows, error,
            cpu_ms=cpu, peak_memory_bytes=peak,
            input_rows=rec.input_rows, input_bytes=rec.input_bytes,
            retry_count=rec.retry_count,
            queued_time_ms=rec.queued_ms,
            resource_group=rec.resource_group,
            speculative_wins=rec.speculative_wins,
            error_code=error_code))

    try:
        with tracer.span("trino.query", query_id=qid):
            result = thunk()
    except BaseException as e:
        from .spi.errors import classify

        _finish("FAILED", -1, str(e), error_code=classify(e).code.name)
        raise
    rows = result.batch.live_count if result.batch.columns else 0
    _finish("FINISHED", rows, None)
    return result


def check_select_access(plan, access_control, user: str) -> None:
    """Every table the plan scans needs SELECT on its projected columns
    (reference: AccessControlManager.checkCanSelectFromColumns called from
    StatementAnalyzer)."""
    from .planner.plan import TableScan

    def walk(node):
        if isinstance(node, TableScan):
            access_control.check_can_select(
                user, node.catalog, node.table, node.columns)
        for c in node.children:
            walk(c)

    walk(plan)


def check_ddl_access(stmt, access_control, user: str,
                     default_catalog_name: str) -> None:
    """Pre-execution privilege checks for metadata/write statements."""
    if isinstance(stmt, (ast.CreateTable, ast.CreateTableAsSelect)):
        cat, table = _split_name(stmt.table, default_catalog_name)
        access_control.check_can_create_table(user, cat, table)
    elif isinstance(stmt, ast.DropTable):
        cat, table = _split_name(stmt.table, default_catalog_name)
        access_control.check_can_drop_table(user, cat, table)
    elif isinstance(stmt, ast.InsertInto):
        cat, table = _split_name(stmt.table, default_catalog_name)
        access_control.check_can_insert(user, cat, table)
    elif isinstance(stmt, ast.Delete):
        cat, table = _split_name(stmt.table, default_catalog_name)
        access_control.check_can_delete(user, cat, table)


def _split_name(name: str, default: str) -> tuple[str, str]:
    parts = name.split(".")
    if len(parts) == 1:
        return default, parts[0]
    return parts[0], parts[-1]


@dataclass
class QueryResult:
    names: list[str]
    batch: ColumnBatch

    def rows(self) -> list[tuple]:
        return self.batch.to_pylist()


@dataclass
class Session:
    """Per-query knobs (the SystemSessionProperties miniature)."""

    default_catalog: str = "tpch"
    user: str = "user"
    splits_per_node: int = 4
    node_count: int = 1
    dynamic_filtering: bool = True
    # per-task HBM pool limit for blocking operators' buffered device bytes
    hbm_limit_bytes: int = 16 << 30
    # per-operator host-buffer bytes before the disk spill tier engages
    # (0 = disabled)
    spill_to_disk_bytes: int = 0
    # REPARTITION edges run as device collectives (all_to_all) when the
    # mesh has enough devices; host exchange is the fallback
    use_collectives: bool = True
    # serialize exchange pages to compressed wire bytes (network mode)
    exchange_serde: bool = False
    # NONE = streaming pipelined scheduler; TASK = fault-tolerant execution
    # (stage-by-stage spooled exchange + per-task retry); QUERY = streaming
    # scheduler with coordinator query-level retry — on a retryable
    # (non-USER) failure the whole subplan re-runs with the implicated
    # worker blacklisted (reference: coordinator query retries keep the
    # pipelined overlap; recovery unit is the query)
    retry_policy: str = "NONE"
    task_retry_attempts: int = 2
    # retry_policy=QUERY knobs: attempt budget and the deterministic
    # exponential backoff between re-runs (spi/errors.py Backoff)
    query_retry_attempts: int = 2
    retry_initial_delay_s: float = 0.1
    retry_max_delay_s: float = 2.0
    # heartbeat failure detection over worker /v1/status
    # (execution/failure_detector.py): sweep cadence and how many
    # consecutive probe misses declare a worker GONE
    heartbeat_interval_s: float = 0.5
    heartbeat_failure_threshold: int = 3
    # how many GONE workers the runner may respawn over its lifetime
    # (0 = never replace; capacity shrinks instead)
    max_worker_replacements: int = 2
    # per-source exchange backoff (HttpExchangeClient): delay bounds and the
    # failure-duration budget after which an unreachable producer surfaces
    # as a classified EXTERNAL failure instead of a silent stall
    exchange_backoff_min_s: float = 0.05
    exchange_backoff_max_s: float = 2.0
    exchange_max_failure_duration_s: float = 120.0
    # intra-task parallelism: concurrent source drivers per pipeline over a
    # local gather exchange (reference: LocalExchange.java:67 +
    # AddLocalExchanges.java:111; task_concurrency session property)
    task_concurrency: int = 1
    # THREADS = a thread per task; TIME_SHARING = bounded worker pool with
    # MLFQ quanta (TimeSharingTaskExecutor)
    task_scheduler: str = "THREADS"
    executor_workers: int = 4
    # dispatcher admission: concurrent queries per runner (resource groups;
    # reference: execution/resourcegroups/InternalResourceGroup.java:75)
    query_concurrency: int = 16
    query_max_queued: int = 200
    # multi-tenant serving (execution/resource_manager.py): the selector
    # workload tag (maps to a resource group via TRINO_TPU_RESOURCE_GROUPS
    # selectors), the ticket priority under scheduling_policy=query_priority
    # and the OOM-killer victim ordering, the admission-queue wait budget,
    # and the per-query reservation cap (0 = TRINO_TPU_QUERY_MAX_MEMORY env
    # or unlimited)
    source: str = ""
    query_priority: int = 0
    query_queued_timeout_s: float = 300.0
    query_max_memory_bytes: int = 0
    # active transaction (execution/transaction.py); None = autocommit
    transaction: object = None
    _transaction_manager: object = None
    # engine-level failure injection (execution/failure_injector.py;
    # reference: execution/FailureInjector.java:35)
    failure_injector: object = None
    # base directory for the durable FTE spool (None = system temp)
    fte_spool_dir: object = None
    # FTE tier 2 (reference: TaskExecutionClass.java:19 STANDARD/SPECULATIVE,
    # ExponentialGrowthPartitionMemoryEstimator.java:55): stragglers get a
    # speculative attempt once half the stage committed and the task exceeds
    # max(2x median stage duration, fte_speculative_delay_s); a memory
    # failure multiplies the next attempt's HBM budget by fte_memory_growth
    fte_speculative: bool = True
    fte_speculative_delay_s: float = 0.25
    fte_memory_growth: float = 2.0
    # streaming-path straggler speculation (execution/speculation.py): the
    # tri-state None defers to TRINO_TPU_SPECULATION; a leaf task whose wall
    # time exceeds max(lag_multiplier x stage-median, min_delay) without a
    # committed page gets a racing twin under first-commit-wins
    speculation: object = None
    speculation_lag_multiplier: float = 2.0
    speculation_min_delay_s: float = 0.25
    # non-leaf streaming speculation (tri-state None defers to
    # TRINO_TPU_SPECULATION_NONLEAF): producers feeding an eligible
    # non-leaf stage tee their pages into a durable spool so a straggling
    # consumer's twin can re-read committed upstream output — the retention
    # FTE's spool provides, now available to retry_policy=QUERY
    speculation_nonleaf: object = None
    # cross-query cluster blacklist (coordinator-held, TTL decay): None
    # defers to TRINO_TPU_BLACKLIST_TTL_S / TRINO_TPU_BLACKLIST_THRESHOLD
    blacklist_ttl_s: object = None
    blacklist_threshold: object = None
    # coordinator-driven graceful drain budget (None = the
    # TRINO_TPU_DRAIN_TIMEOUT_S env knob, default 30s coordinator-side)
    drain_timeout_s: object = None
    # adaptive execution (execution/adaptive.py): tri-state None defers to
    # TRINO_TPU_ADAPTIVE ("auto" default; "0" is bit-for-bit legacy, "1"
    # forces the phased scheduler); 0 thresholds defer to
    # TRINO_TPU_BROADCAST_THRESHOLD_BYTES / TRINO_TPU_SKEW_FACTOR
    adaptive: object = None
    broadcast_threshold_bytes: int = 0
    skew_factor: float = 0.0
    # INSERT/CTAS fan out over round-robin writer tasks when the source is
    # large (SCALED_WRITER_* partitionings in miniature; planned by estimate)
    scale_writers: bool = False
    writer_task_limit: int = 4


class StandaloneQueryRunner:
    def __init__(self, catalog: Optional[Catalog] = None,
                 session: Optional[Session] = None):
        import itertools

        from .execution.tracing import Tracer
        from .spi.eventlistener import EventListenerManager
        from .spi.security import AccessControlManager

        self.catalog = catalog if catalog is not None else default_catalog()
        self.session = session if session is not None else Session()
        self.tracer = Tracer()
        self.event_listeners = EventListenerManager()
        self.access_control = AccessControlManager()
        self._qids = itertools.count(1)
        sysconn = self.catalog._connectors.get("system")
        if sysconn is not None and hasattr(sysconn, "attach"):
            sysconn.attach(self)
        from .telemetry import journal as _journal

        j = _journal.get_journal()
        if j is not None:
            self.event_listeners.add(j)
        from .caching import executable_cache

        executable_cache.init_compile_cache()

    def create_plan(self, sql: str) -> PlanNode:
        return self._plan_stmt(parse_statement(sql))

    def _plan_stmt(self, stmt: ast.Statement) -> PlanNode:
        with self.tracer.span("trino.planner"):
            planner = LogicalPlanner(self.catalog, self.session.default_catalog)
            plan = planner.plan(stmt)
            plan = optimize(plan, self.catalog)
        check_select_access(plan, self.access_control, self.session.user)
        return plan

    def explain(self, sql: str) -> str:
        return plan_text(self.create_plan(sql))

    def execute(self, sql: str,
                query_id: Optional[str] = None) -> QueryResult:
        # an explicit query_id (the HTTP dispatcher passes its own) keeps
        # one identity across the protocol, the registries and the profile
        return run_with_query_events(
            query_id or f"sq_{next(self._qids)}", sql, self.session.user,
            self.event_listeners, self.tracer, lambda: self._execute(sql))

    def profile(self, query_id: str) -> Optional[dict]:
        """Chrome trace_event JSON of a profiled query (telemetry/
        profiler.py timeline), or None when unknown."""
        from .telemetry import profiler

        return profiler.chrome_trace(query_id)

    def _execute(self, sql: str) -> QueryResult:
        from .caching import plan_cache, result_cache

        # Tier A fast path: a cached plan skips parse → analyze → plan →
        # optimize entirely (only statements that reached _plan_stmt are
        # ever stored, so DDL/session/transaction texts always miss here)
        entry = plan_cache.lookup(sql, self.session, self.catalog)
        if entry is not None:
            return self._execute_cached_plan(entry)
        stmt = parse_statement(sql)
        from .execution.transaction import handle_transaction_stmt

        txn = handle_transaction_stmt(stmt, self.session, self.catalog)
        if txn is not None:
            return txn
        check_ddl_access(stmt, self.access_control, self.session.user,
                         self.session.default_catalog)
        sess = execute_session_stmt(stmt, self.session)
        if sess is not None:
            return sess
        if isinstance(stmt, ast.Explain):
            return self._execute_explain(stmt)
        if isinstance(stmt, ast.ShowTables):
            conn = self.catalog.connector(self.session.default_catalog)
            return text_result("Table", sorted(
                list(conn.list_tables()) + list(self.catalog.views)))
        if isinstance(stmt, ast.ShowColumns):
            cat, table, schema = self.catalog.resolve_table(
                stmt.table, self.session.default_catalog)
            return text_result(
                "Column", [f"{c.name} {c.type}" for c in schema.columns])
        ddl = execute_ddl(stmt, self.catalog, self.session.default_catalog,
                          lambda st: self._execute_stmt(st, False)[0])
        if ddl is not None:
            return ddl
        plan = self._plan_stmt(stmt)
        entry = plan_cache.store(sql, self.session, self.catalog, plan)
        # Tier C: capture the table-version vector BEFORE executing — a
        # mutation racing the read then strands the entry under a stale
        # key (never served) instead of publishing stale data as fresh
        versions = result_cache.version_vector(entry.tables, self.catalog)
        key = result_cache.result_key(entry, versions)
        result, _ = self._execute_stmt(stmt, collect_stats=False, plan=plan)
        result_cache.store(key, result, entry.tables)
        return result

    def _execute_cached_plan(self, entry) -> QueryResult:
        """Run from a Tier-A hit: re-check access (the cache is keyed on
        session knobs, not identity), try Tier C, else execute a private
        clone of the cached tree and publish the result."""
        from .caching import plan_cache, result_cache

        check_select_access(entry.plan, self.access_control,
                            self.session.user)
        versions = result_cache.version_vector(entry.tables, self.catalog)
        key = result_cache.result_key(entry, versions)
        cached = result_cache.lookup(key)
        if cached is not None:
            return cached
        result, _ = self._execute_stmt(
            None, collect_stats=False, plan=plan_cache.clone(entry.plan))
        result_cache.store(key, result, entry.tables)
        return result

    def _execute_stmt(self, stmt: ast.Statement, collect_stats: bool,
                      plan: Optional[PlanNode] = None,
                      ) -> tuple[QueryResult, Optional[QueryStats]]:
        if plan is None:
            plan = self._plan_stmt(stmt)
        local = LocalPlanner(
            self.catalog,
            splits_per_node=self.session.splits_per_node,
            node_count=self.session.node_count,
            dynamic_filtering=self.session.dynamic_filtering,
            hbm_limit_bytes=self.session.hbm_limit_bytes,
            spill_to_disk_bytes=self.session.spill_to_disk_bytes,
            task_concurrency=self.session.task_concurrency,
        ).plan(plan)
        stats = QueryStats() if collect_stats else None
        from .exec import syncguard

        sync_before = syncguard.snapshot()
        with self.tracer.span("trino.execution") as sp:
            run_pipelines(local.pipelines, stats)
            ingest = collect_scan_stats(local.pipelines)
            sync_delta = syncguard.take_delta(sync_before)
            annotate_scan_span(sp, ingest)
            annotate_sync_span(sp, sync_delta)
        from .telemetry import metrics as tm
        from .telemetry import runtime as rt

        tm.observe_scan(ingest)
        tm.observe_sync(sync_delta)
        tm.observe_encoding(collect_encoding_stats(local.pipelines))
        if ingest is not None:
            rt.add_input(rt.current_record(), ingest.scan_rows,
                         ingest.scan_bytes)
        batches = local.collector.batches
        if batches:
            batch = ColumnBatch.concat(batches)
        else:
            batch = ColumnBatch(
                local.output_names,
                [Column(t, np.empty(0, t.storage_dtype))
                 for t in local.output_types],
            )
        return QueryResult(local.output_names, batch), stats

    def _execute_explain(self, stmt: ast.Explain) -> QueryResult:
        """EXPLAIN -> plan text; EXPLAIN ANALYZE -> run it, then render the
        plan with per-operator wall/row/batch stats (the
        ExplainAnalyzeOperator.java:36 equivalent)."""
        inner = stmt.statement
        plan = self._plan_stmt(inner)
        lines = plan_text(plan).splitlines()
        # rule-firing trace of the iterative optimizer run that shaped this
        # plan (planner/iterative/driver.py publishes it per-thread)
        from .planner.optimizer import optimizer_mode

        if optimizer_mode() == "iterative":
            from .planner.iterative import last_report

            trace = last_report()
            if trace is not None:
                lines.extend(trace.lines(timings=stmt.analyze))
        if stmt.analyze:
            import time as _time

            t0 = _time.perf_counter()
            _, stats = self._execute_stmt(inner, collect_stats=True, plan=plan)
            wall = _time.perf_counter() - t0
            lines.append(f"total: {wall * 1e3:.1f} ms")
            lines.extend(stats.text().splitlines())
        return text_result("Query Plan", lines)
