"""JSON functions as dictionary transforms.

Mirrors the SQL/JSON path engine role (reference: json/JsonPathEvaluator
.java, operator/scalar/JsonFunctions — JSON_EXTRACT/json_extract_scalar
with the jayway-style simple paths Trino supports).  Same TPU stance as
every string function: JSON text lives in the host-side dictionary; the
function evaluates once per distinct value and the device gathers the
precomputed result by code (the chip never parses bytes).

Supported path subset: ``$``, ``$.key``, ``$.a.b``, ``$[0]``,
``$.a[2].b`` — member access and array subscripts (the overwhelmingly
common forms; filters/wildcards are a later round)."""

from __future__ import annotations

import json
from typing import Optional

__all__ = ["parse_json_path", "eval_json_path", "json_scalar_text"]


def parse_json_path(path: str) -> list:
    """'$.a[0].b' -> ['a', 0, 'b'].  Raises ValueError on malformed paths."""
    if not path or path[0] != "$":
        raise ValueError(f"JSON path must start with '$': {path!r}")
    steps: list = []
    i = 1
    n = len(path)
    while i < n:
        c = path[i]
        if c == ".":
            j = i + 1
            while j < n and path[j] not in ".[":
                j += 1
            key = path[i + 1:j]
            if not key:
                raise ValueError(f"empty member in JSON path: {path!r}")
            steps.append(key)
            i = j
        elif c == "[":
            j = path.index("]", i)
            body = path[i + 1:j].strip()
            if body.startswith('"') and body.endswith('"'):
                steps.append(body[1:-1])
            else:
                steps.append(int(body))
            i = j + 1
        else:
            raise ValueError(f"bad JSON path at {i}: {path!r}")
    return steps


def eval_json_path(text: str, steps: list):
    """Evaluate a parsed path against a JSON document; None on any miss or
    parse error (SQL NULL-on-error semantics of json_extract*)."""
    try:
        v = json.loads(text)
    except (ValueError, TypeError):
        return None
    for s in steps:
        if isinstance(s, int):
            if not isinstance(v, list) or not -len(v) <= s < len(v):
                return None
            v = v[s]
        else:
            if not isinstance(v, dict) or s not in v:
                return None
            v = v[s]
    return v


def json_scalar_text(v) -> Optional[str]:
    """json_extract_scalar result: scalars as text, NULL for objects/arrays
    (reference: JsonFunctions.varcharJsonExtractScalar)."""
    if v is None or isinstance(v, (dict, list)):
        return None
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)
