"""Device kernels (JAX/XLA + Pallas) — the engine's "native" layer.

Plays the role of Trino's runtime bytecode generation (``io.trino.sql.gen``,
reference: sql/gen/PageFunctionCompiler.java:104) and hand-specialized
flat-memory kernels (operator/FlatHash.java:42, operator/join/PagesHash.java):
row expressions lower to jaxprs, hot group-by/join/repartition kernels are
XLA programs (Pallas where XLA's codegen isn't enough).

Importing this package configures JAX for the engine (x64 lanes for
bigint/decimal); the pure-numpy SPI layer stays jax-free.
"""

import jax

# Decimal/bigint paths require 64-bit lanes; on TPU int64 is emulated with
# int32 pairs by XLA, fine for the bandwidth-bound relational ops.
jax.config.update("jax_enable_x64", True)
