"""RowExpression -> JAX lowering (the bytecode-generation replacement).

Trino compiles row expressions to JVM classes at runtime
(sql/gen/PageFunctionCompiler.java:104 ``compileProjection:167`` /
``compileFilter:374``, ExpressionCompiler.java:63).  Here the same IR lowers
to closures over ``jax.numpy`` ops; wrapping them in ``jax.jit`` hands XLA a
whole operator pipeline to fuse (filter+project collapse into one kernel, the
ScanFilterAndProjectOperator analogue).

Evaluation model:
- every expression evaluates to ``(data, valid)`` — fixed-shape value array +
  optional validity (None == all valid), SQL three-valued logic throughout;
- scalars broadcast: literals stay 0-d until the caller broadcasts;
- **strings never reach the device as bytes**: a varchar expression carries a
  compile-time host-side sorted dictionary; string functions (LIKE, substring,
  upper, ...) are evaluated host-side over the dictionary and become device
  gathers of the precomputed result (`mask[codes]` / `remap[codes]`).  This is
  the TPU-native replacement for Trino's per-row UTF-8 kernels
  (likematcher/DenseDfaMatcher.java, operator/scalar/StringFunctions.java).

Division/modulo by zero currently yields NULL rather than raising
(Trino raises DIVISION_BY_ZERO; a lane-error side channel is a later round).
"""

from __future__ import annotations

import re
import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

import numpy as np

from . import datetime as dt  # noqa: F401  (registers jax config via package)
import jax.numpy as jnp

from ..spi.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    TIMESTAMP,
    UNKNOWN,
    VARCHAR,
    ArrayType,
    DecimalType,
    Type,
    is_string,
)
from ..spi.batch import rescale_scaled_int
from ..sql.ir import Call, InputRef, Literal, RowExpression

__all__ = ["CompiledExpression", "compile_expression", "compile_projection"]

Cols = Sequence[tuple[Any, Optional[Any]]]  # per-channel (data, valid)


def _and_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _all_valids(vs):
    out = None
    for v in vs:
        out = _and_valid(out, v)
    return out


# ---------------------------------------------------------------------------
# masked-lane error channel (reference: StandardErrorCode DIVISION_BY_ZERO /
# NUMERIC_VALUE_OUT_OF_RANGE / INVALID_CAST_ARGUMENT).  Vectorized evaluation
# computes every lane of every branch, so errors cannot raise eagerly: an
# erroring op RECORDS a lane mask into the active scope instead, conditionals
# ($if / $and / $or / coalesce) mask the lanes their branch doesn't select,
# and the compiled program reduces the surviving lanes to one error-code
# scalar that the runner checks (batched with the result fetch — a query
# without error-capable ops pays nothing).

DIVISION_BY_ZERO = 1
NUMERIC_OUT_OF_RANGE = 2
INVALID_CAST = 3
SUBQUERY_MULTIPLE_ROWS = 4
ERROR_NAMES = {
    DIVISION_BY_ZERO: "DIVISION_BY_ZERO: division by zero",
    NUMERIC_OUT_OF_RANGE: "NUMERIC_VALUE_OUT_OF_RANGE: value out of range",
    INVALID_CAST: "INVALID_CAST_ARGUMENT: invalid cast",
    SUBQUERY_MULTIPLE_ROWS:
        "SUBQUERY_MULTIPLE_ROWS: scalar subquery returned multiple rows",
}


class QueryError(RuntimeError):
    def __init__(self, code: int):
        super().__init__(ERROR_NAMES.get(code, f"error code {code}"))
        self.code = code


class _ErrState(threading.local):
    acc = None  # list[(code, lane_mask)] while a scope is active
    mask = None  # current conditional lane mask (None = all lanes)


_ERRS = _ErrState()


@contextmanager
def expr_error_scope():
    """Collect (code, lanes) pairs recorded while tracing expression fns.
    Active only inside a compiled program body — evaluation outside any
    scope keeps the legacy NULL-on-error semantics."""
    prev_acc, prev_mask = _ERRS.acc, _ERRS.mask
    _ERRS.acc = acc = []
    _ERRS.mask = None
    try:
        yield acc
    finally:
        _ERRS.acc, _ERRS.mask = prev_acc, prev_mask


@contextmanager
def expr_condition_mask(mask):
    """Lanes where ``mask`` is False cannot raise (unselected branch /
    filtered-out row)."""
    prev = _ERRS.mask
    if mask is not None:
        _ERRS.mask = mask if prev is None else (prev & mask)
    try:
        yield
    finally:
        _ERRS.mask = prev


def _record_error(code: int, lanes) -> None:
    if _ERRS.acc is None:
        return
    if _ERRS.mask is not None:
        lanes = lanes & _ERRS.mask
    _ERRS.acc.append((code, lanes))


def reduce_error_lanes(acc, shape):
    """Combine a scope's recordings into ONE int32 lane array (0 = ok), or
    None when nothing error-capable was traced.  A zero-row shape (empty
    partition / fully-pruned batch) has no lanes that can raise — return
    None so callers never reduce over a zero-size array."""
    if shape and int(shape[0]) == 0:
        return None
    err = None
    for code, lanes in acc:
        lanes = jnp.broadcast_to(lanes, shape)
        e = jnp.where(lanes, jnp.int32(code), jnp.int32(0))
        err = e if err is None else jnp.maximum(err, e)
    return err


def check_error_scalars(scalars) -> None:
    """One batched device fetch; raises QueryError on the worst code."""
    if not scalars:
        return
    from ..exec import syncguard as SG

    codes = [int(c) for c in SG.fetch(list(scalars), "exec.error-scalars")]
    worst = max(codes)
    if worst:
        raise QueryError(worst)


@dataclass
class Lowered:
    type: Type
    dictionary: Optional[np.ndarray]
    fn: Callable[[Cols], tuple[Any, Optional[Any]]]


@dataclass
class CompiledExpression:
    """Public handle: callable on per-channel (data, valid) pairs."""

    type: Type
    dictionary: Optional[np.ndarray]
    _fn: Callable[[Cols], tuple[Any, Optional[Any]]]

    def __call__(self, cols: Cols) -> tuple[Any, Optional[Any]]:
        return self._fn(cols)


# ---------------------------------------------------------------------------
# elementwise numeric helpers


def _trunc_div(a, b):
    """SQL integer division truncates toward zero (jnp // floors)."""
    q = jnp.abs(a) // jnp.abs(b)
    return jnp.where((a < 0) ^ (b < 0), -q, q)


def _round_half_up_div(a, b):
    """Rounded division for decimal rescale: round(a/b) half away from zero."""
    q = (2 * jnp.abs(a) + jnp.abs(b)) // (2 * jnp.abs(b))
    return jnp.where((a < 0) ^ (b < 0), -q, q)


def _decimal_rescale(data, from_scale: int, to_scale: int):
    if to_scale == from_scale:
        return data
    if to_scale > from_scale:
        return data * (10 ** (to_scale - from_scale))
    return _round_half_up_div(data, 10 ** (from_scale - to_scale))


def _scale_of(t: Type) -> int:
    return t.scale if isinstance(t, DecimalType) else 0


def _is_long_dec(t: Type) -> bool:
    return isinstance(t, DecimalType) and t.precision > 18


def _long_dec_transform(col: Lowered, pyfn, out_type: Type) -> Lowered:
    """Exact host transform over a long-decimal dictionary (python ints);
    ``pyfn`` returns a scaled int at out_type.scale or None (NULL, e.g.
    division by zero).  Mirrors the string _dict_transform idiom —
    spi/type/Int128Math.java's role is played by python bignums over the
    (small) dictionary, never per row."""
    vals = [pyfn(int(v)) for v in col.dictionary]
    uniq = sorted({v for v in vals if v is not None} or {0})
    pos = {v: i for i, v in enumerate(uniq)}
    remap = np.array([pos.get(v, 0) for v in vals], dtype=np.int32)
    entry_ok = np.array([v is not None for v in vals])
    newdict = np.empty(len(uniq), dtype=object)
    for i, v in enumerate(uniq):
        newdict[i] = v
    all_ok = bool(entry_ok.all())

    def fn(cols: Cols):
        codes, valid = col.fn(cols)
        data = jnp.asarray(remap)[codes]
        if not all_ok:
            ok = jnp.asarray(entry_ok)[codes]
            valid = ok if valid is None else (jnp.asarray(valid) & ok)
        return data, valid

    return Lowered(out_type, newdict, fn)


def _long_dec_literal_value(x: Lowered):
    """Scaled-int value of a long-decimal literal Lowered (or None)."""
    if x.dictionary is not None and len(x.dictionary) == 1 and \
            hasattr(x.fn, "_literal_value"):
        return int(x.fn._literal_value)
    return None


def _long_arith_value(name: str, va, sa, vb, sb, os: int):
    """Exact scaled-int arithmetic (python bignums), HALF_UP rounding.
    Runs under an 80-digit context: the default 28-digit context would
    silently round wide decimals."""
    import decimal as _d

    with _d.localcontext() as ctx:
        ctx.prec = 80
        return _long_arith_ctx(name, va, sa, vb, sb, os)


def _long_arith_ctx(name: str, va, sa, vb, sb, os: int):
    import decimal as _d

    A = _d.Decimal(va).scaleb(-sa)
    B = _d.Decimal(vb).scaleb(-sb)
    if name == "add":
        r = A + B
    elif name == "subtract":
        r = A - B
    elif name == "multiply":
        r = A * B
    elif name == "divide":
        if B == 0:
            return None
        r = A / B
    else:  # modulus
        if B == 0:
            return None
        r = A % B
    return int(r.scaleb(os).quantize(0, rounding=_d.ROUND_HALF_UP))


def _arith_handler(name: str):
    def handler(out_type: Type, args: list[Lowered]) -> Lowered:
        a, b = args
        if _is_long_dec(a.type) or _is_long_dec(b.type) or _is_long_dec(out_type):
            if getattr(a.fn, "_literal_null", False) or getattr(
                    b.fn, "_literal_null", False):
                # NULL operand: the whole expression is NULL (Trino
                # three-valued arithmetic), no transform needed
                d0 = None
                if _is_long_dec(out_type):
                    d0 = np.empty(1, dtype=object)
                    d0[0] = 0

                def fn_null(cols: Cols):
                    return (jnp.zeros((), out_type.storage_dtype),
                            jnp.zeros((), bool))

                return Lowered(out_type, d0, fn_null)
            os = _scale_of(out_type)
            sa, sb = _scale_of(a.type), _scale_of(b.type)
            la, lb = _long_dec_literal_value(a), _long_dec_literal_value(b)
            # literal sides that are short decimals/integers also qualify
            if la is None and hasattr(a.fn, "_literal_value") and not _is_long_dec(a.type):
                la = int(a.fn._literal_value)
            if lb is None and hasattr(b.fn, "_literal_value") and not _is_long_dec(b.type):
                lb = int(b.fn._literal_value)
            if _is_long_dec(a.type) and a.dictionary is not None and lb is not None:
                out = _long_dec_transform(
                    a, lambda v: _long_arith_value(name, v, sa, lb, sb, os),
                    out_type)
                return _and_extra_valid(out, [b])
            if _is_long_dec(b.type) and b.dictionary is not None and la is not None:
                out = _long_dec_transform(
                    b, lambda v: _long_arith_value(name, la, sa, v, sb, os),
                    out_type)
                return _and_extra_valid(out, [a])
            raise NotImplementedError(
                "long-decimal arithmetic between two columns is not "
                "supported (dictionary-encoded int128 path; rewrite with a "
                "literal operand or cast to double)")

        def fn(cols: Cols):
            (av, avalid), (bv, bvalid) = a.fn(cols), b.fn(cols)
            valid = _and_valid(avalid, bvalid)
            if isinstance(out_type, DecimalType):
                os = out_type.scale
                if name in ("add", "subtract"):
                    av2 = _decimal_rescale(av, _scale_of(a.type), os)
                    bv2 = _decimal_rescale(bv, _scale_of(b.type), os)
                    data = av2 + bv2 if name == "add" else av2 - bv2
                elif name == "multiply":
                    data = _decimal_rescale(
                        av * bv, _scale_of(a.type) + _scale_of(b.type), os
                    )
                elif name == "divide":
                    # value = a/b at scale os:  round(a * 10^(os - sa + sb) / b)
                    shift = os - _scale_of(a.type) + _scale_of(b.type)
                    num = av * (10**shift) if shift >= 0 else _round_half_up_div(av, 10**-shift)
                    safe_b = jnp.where(bv == 0, 1, bv)
                    data = _round_half_up_div(num, safe_b)
                    _record_error(DIVISION_BY_ZERO, (bv == 0) if valid is None
                                  else ((bv == 0) & valid))
                    valid = _and_valid(valid, bv != 0)
                else:  # modulus
                    s = max(_scale_of(a.type), _scale_of(b.type))
                    av2 = _decimal_rescale(av, _scale_of(a.type), s)
                    bv2 = _decimal_rescale(bv, _scale_of(b.type), s)
                    safe_b = jnp.where(bv2 == 0, 1, bv2)
                    data = av2 - _trunc_div(av2, safe_b) * bv2
                    _record_error(DIVISION_BY_ZERO, (bv2 == 0) if valid is None
                                  else ((bv2 == 0) & valid))
                    valid = _and_valid(valid, bv2 != 0)
                return data, valid
            dtype = out_type.storage_dtype
            av = av.astype(dtype)
            bv = bv.astype(dtype)
            is_int = bool(np.issubdtype(np.dtype(dtype), np.integer))

            def ovf_err(ovf):
                _record_error(NUMERIC_OUT_OF_RANGE,
                              ovf if valid is None else (ovf & valid))

            signed = bool(np.issubdtype(np.dtype(dtype), np.signedinteger))
            if name == "add":
                data = av + bv
                if signed:  # wraparound flips the sign against both operands
                    ovf_err(((av ^ data) & (bv ^ data)) < 0)
            elif name == "subtract":
                data = av - bv
                if signed:
                    ovf_err(((av ^ bv) & (av ^ data)) < 0)
            elif name == "multiply":
                data = av * bv
                if signed:  # wrapped product no longer divides back
                    safe_a = jnp.where(av == 0, 1, av)
                    ovf_err((av != 0) & (_trunc_div(data, safe_a) != bv))
            elif name == "divide":
                if is_int:
                    safe_b = jnp.where(bv == 0, 1, bv)
                    data = _trunc_div(av, safe_b)
                    _record_error(DIVISION_BY_ZERO, (bv == 0) if valid is None
                                  else ((bv == 0) & valid))
                    valid = _and_valid(valid, bv != 0)
                else:
                    safe_b = jnp.where(bv == 0, 1.0, bv)
                    data = av / safe_b
                    if (isinstance(a.type, DecimalType)
                            or isinstance(b.type, DecimalType)
                            or getattr(a.fn, "_from_decimal", False)
                            or getattr(b.fn, "_from_decimal", False)):
                        # decimal division folded to double still carries
                        # exact-arithmetic semantics: /0 raises (Trino
                        # DIVISION_BY_ZERO); pure double /0 stays NULL
                        _record_error(
                            DIVISION_BY_ZERO, (bv == 0) if valid is None
                            else ((bv == 0) & valid))
                    valid = _and_valid(valid, bv != 0)
            else:  # modulus
                safe_b = jnp.where(bv == 0, 1, bv)
                if is_int:
                    data = av - _trunc_div(av, safe_b) * bv
                    _record_error(DIVISION_BY_ZERO, (bv == 0) if valid is None
                                  else ((bv == 0) & valid))
                else:
                    data = av - jnp.trunc(av / safe_b) * bv
                valid = _and_valid(valid, bv != 0)
            return data, valid

        return Lowered(out_type, None, fn)

    return handler


# ---------------------------------------------------------------------------
# comparisons (dictionary-aware)

_CMP = {
    "eq": jnp.equal,
    "ne": jnp.not_equal,
    "lt": jnp.less,
    "le": jnp.less_equal,
    "gt": jnp.greater,
    "ge": jnp.greater_equal,
}


def _dicts_equal(a: Optional[np.ndarray], b: Optional[np.ndarray]) -> bool:
    if a is None or b is None:
        return False
    return a is b or (a.shape == b.shape and (a == b).all())


def _cmp_dict_literal(name: str, col: Lowered, lit_value: str):
    """Compare dictionary codes against a string literal using only the
    host-side sorted dictionary (order-correct by construction)."""
    d = col.dictionary
    if isinstance(lit_value, tuple):
        # array dictionary: numpy would treat a tuple needle as an array of
        # elements, and entries sort by _canon_key not raw order — linear
        # scan (only eq/ne reach here for arrays; dictionaries are small)
        hits = [i for i, v in enumerate(d) if v == lit_value]
        lo = hits[0] if hits else 0
        hi = lo + 1 if hits else 0
    else:
        lo = int(np.searchsorted(d, lit_value, side="left"))
        hi = int(np.searchsorted(d, lit_value, side="right"))
    present = lo < hi

    def fn(cols: Cols):
        codes, valid = col.fn(cols)
        if name == "eq":
            data = (codes == lo) if present else jnp.zeros_like(codes, dtype=bool)
        elif name == "ne":
            data = (codes != lo) if present else jnp.ones_like(codes, dtype=bool)
        elif name == "lt":
            data = codes < lo
        elif name == "le":
            data = codes < hi
        elif name == "gt":
            data = codes >= hi
        else:  # ge
            data = codes >= lo
        return data, valid

    return Lowered(BOOLEAN, None, fn)


def _cmp_handler(name: str):
    def handler(out_type: Type, args: list[Lowered]) -> Lowered:
        a, b = args
        from ..spi.types import MapType, RowType

        is_arr = any(isinstance(t, (ArrayType, RowType, MapType))
                     for t in (a.type, b.type))
        is_ldec = _is_long_dec(a.type) or _is_long_dec(b.type)
        if is_arr and name not in ("eq", "ne"):
            raise NotImplementedError("array/row/map ordering comparison")
        if is_string(a.type) or is_string(b.type) or is_arr or is_ldec:
            # array/row/map dictionaries hold python tuples, long-decimal
            # dictionaries hold python ints — comparable/sortable like
            # strings, but never coerced through str()
            def lit(d):
                if is_arr:
                    return d[0]
                if is_ldec:
                    return int(d[0])
                return str(d[0])

            # literal vs column: route through the sorted dictionary
            if b.dictionary is not None and len(b.dictionary) == 1 and a.dictionary is not None and len(a.dictionary) != 1:
                return _cmp_dict_literal(name, a, lit(b.dictionary))
            if a.dictionary is not None and len(a.dictionary) == 1 and b.dictionary is not None and len(b.dictionary) != 1:
                flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}
                return _cmp_dict_literal(flip[name], b, lit(a.dictionary))
            if _dicts_equal(a.dictionary, b.dictionary):
                pass  # codes comparable directly (sorted dictionary)
            elif name in ("eq", "ne") and a.dictionary is not None and b.dictionary is not None:
                # translate b's code space into a's
                trans = np.searchsorted(a.dictionary, b.dictionary).clip(0, len(a.dictionary) - 1).astype(np.int32)
                hit = (a.dictionary[trans] == b.dictionary)

                def fn_ne(cols: Cols):
                    (ac, avalid), (bc, bvalid) = a.fn(cols), b.fn(cols)
                    eq = (ac == jnp.asarray(trans)[bc]) & jnp.asarray(hit)[bc]
                    return (eq if name == "eq" else ~eq), _and_valid(avalid, bvalid)

                return Lowered(BOOLEAN, None, fn_ne)
            else:
                raise NotImplementedError(
                    f"ordering comparison across distinct dictionaries ({name})"
                )

        def fn(cols: Cols):
            (av, avalid), (bv, bvalid) = a.fn(cols), b.fn(cols)
            return _CMP[name](av, bv), _and_valid(avalid, bvalid)

        return Lowered(BOOLEAN, None, fn)

    return handler


# ---------------------------------------------------------------------------
# boolean logic (three-valued)


def _and_handler(out_type, args):
    # 3VL: FALSE if any definite FALSE; else NULL if any NULL.  NULL lanes
    # normalize to TRUE so garbage values can't force a definite FALSE.
    def fn(cols: Cols):
        data, valid = None, None
        for a in args:
            # short-circuit masking: once an earlier term is definite FALSE
            # the remaining terms cannot raise on that lane
            with expr_condition_mask(data):
                v, vv = a.fn(cols)
            eff = v if vv is None else (v | ~vv)
            data = eff if data is None else (data & eff)
            valid = _and_valid(valid, vv)
        if valid is not None:
            valid = valid | ~data  # definite false wins over null
        return data, valid

    return Lowered(BOOLEAN, None, fn)


def _or_handler(out_type, args):
    # 3VL dual: TRUE if any definite TRUE; NULL lanes normalize to FALSE.
    def fn(cols: Cols):
        data, valid = None, None
        for a in args:
            with expr_condition_mask(None if data is None else ~data):
                v, vv = a.fn(cols)
            eff = v if vv is None else (v & vv)
            data = eff if data is None else (data | eff)
            valid = _and_valid(valid, vv)
        if valid is not None:
            valid = valid | data  # definite true wins over null
        return data, valid

    return Lowered(BOOLEAN, None, fn)


def _not_handler(out_type, args):
    (a,) = args

    def fn(cols: Cols):
        v, vv = a.fn(cols)
        return ~v, vv

    return Lowered(BOOLEAN, None, fn)


def _is_null_handler(out_type, args):
    (a,) = args

    def fn(cols: Cols):
        v, vv = a.fn(cols)
        if vv is None:
            return jnp.zeros(jnp.shape(v), dtype=bool), None
        return ~vv, None

    return Lowered(BOOLEAN, None, fn)


# ---------------------------------------------------------------------------
# conditionals


def _unify_pair(a: Lowered, b: Lowered) -> tuple[Lowered, Lowered, Optional[np.ndarray]]:
    """Remap two dictionary-typed lowerings onto one merged dictionary."""
    if a.dictionary is None and b.dictionary is None:
        return a, b, None
    da = a.dictionary if a.dictionary is not None else np.array([], dtype=object)
    db = b.dictionary if b.dictionary is not None else np.array([], dtype=object)
    if _dicts_equal(da, db):
        return a, b, da
    merged = np.unique(np.concatenate([da, db]))

    def remapped(x: Lowered, d: np.ndarray) -> Lowered:
        remap = np.searchsorted(merged, d).astype(np.int32) if len(d) else None

        def fn(cols: Cols):
            v, vv = x.fn(cols)
            return (jnp.asarray(remap)[v] if remap is not None else v), vv

        return Lowered(x.type, merged, fn)

    return remapped(a, da), remapped(b, db), merged


def _if_handler(out_type, args):
    cond, t, f = args
    t2, f2, merged = _unify_pair(t, f)

    def fn(cols: Cols):
        cv, cvalid = cond.fn(cols)
        take_true = cv if cvalid is None else (cv & cvalid)
        # a branch's errors only count on the lanes that select it (CASE
        # WHEN x = 0 THEN 0 ELSE 1/x END must not raise on x = 0 lanes)
        with expr_condition_mask(take_true):
            tv, tvalid = t2.fn(cols)
        with expr_condition_mask(~take_true):
            fv, fvalid = f2.fn(cols)
        data = jnp.where(take_true, tv, fv)
        if tvalid is None and fvalid is None:
            valid = None
        else:
            tvv = tvalid if tvalid is not None else jnp.ones(jnp.shape(tv), bool)
            fvv = fvalid if fvalid is not None else jnp.ones(jnp.shape(fv), bool)
            valid = jnp.where(take_true, tvv, fvv)
        return data, valid

    return Lowered(out_type, merged, fn)


def _coalesce_handler(out_type, args):
    out = args[-1]
    for a in reversed(args[:-1]):
        a2, out2, merged = _unify_pair(a, out)
        prev = out2

        def make_fn(a2=a2, prev=prev):
            def fn(cols: Cols):
                av, avalid = a2.fn(cols)
                if avalid is None:
                    return av, None
                with expr_condition_mask(~avalid):
                    pv, pvalid = prev.fn(cols)
                data = jnp.where(avalid, av, pv)
                if pvalid is None:
                    return data, None  # fallback is never null
                return data, jnp.where(avalid, True, pvalid)

            return fn

        out = Lowered(out_type, merged, make_fn())
    return out


# ---------------------------------------------------------------------------
# IN / LIKE / string functions via dictionary transforms


def _in_handler(out_type, args):
    col, *items = args
    if col.dictionary is not None:
        vals = []
        for it in items:
            if it.dictionary is None or len(it.dictionary) != 1:
                raise NotImplementedError("IN over non-literal strings")
            vals.append(str(it.dictionary[0]))
        mask = np.isin(col.dictionary, np.array(vals, dtype=object))

        def fn(cols: Cols):
            codes, valid = col.fn(cols)
            return jnp.asarray(mask)[codes], valid

        return Lowered(BOOLEAN, None, fn)

    def fn(cols: Cols):
        cv, cvalid = col.fn(cols)
        data = None
        for it in items:
            iv, _ = it.fn(cols)
            hit = cv == iv
            data = hit if data is None else (data | hit)
        return data, cvalid

    return Lowered(BOOLEAN, None, fn)


def like_to_regex(pattern: str, escape: Optional[str] = None) -> str:
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


def _like_handler(out_type, args):
    col = args[0]
    pat = args[1]
    esc = args[2] if len(args) > 2 else None
    if col.dictionary is None or pat.dictionary is None or len(pat.dictionary) != 1:
        raise NotImplementedError("LIKE requires a dictionary column and literal pattern")
    escape = str(esc.dictionary[0]) if esc is not None and esc.dictionary is not None else None
    # bit-parallel NFA over the whole dictionary (ops/like_dfa.py — the
    # DenseDfaMatcher.java:23 role); small dictionaries keep the re loop
    from .like_dfa import like_mask

    mask = like_mask(col.dictionary, str(pat.dictionary[0]), escape)

    def fn(cols: Cols):
        codes, valid = col.fn(cols)
        return jnp.asarray(mask)[codes], valid

    return Lowered(BOOLEAN, None, fn)


def _dict_transform(col: Lowered, pyfn, out_type: Type) -> Lowered:
    """str->str function as a host dictionary transform + device remap."""
    vals = np.array([pyfn(str(v)) for v in col.dictionary], dtype=object)
    newdict, remap = np.unique(vals, return_inverse=True)
    remap = remap.astype(np.int32)

    def fn(cols: Cols):
        codes, valid = col.fn(cols)
        return jnp.asarray(remap)[codes], valid

    return Lowered(out_type, newdict, fn)


def _dict_scalar(col: Lowered, pyfn, out_type: Type) -> Lowered:
    """str->number function as host precompute + device gather."""
    arr = np.array([pyfn(str(v)) for v in col.dictionary], dtype=out_type.storage_dtype)

    def fn(cols: Cols):
        codes, valid = col.fn(cols)
        return jnp.asarray(arr)[codes], valid

    return Lowered(out_type, None, fn)


def _literal_int(x: Lowered) -> int:
    if not isinstance(x, Lowered) or not hasattr(x.fn, "_literal_value"):
        raise NotImplementedError("expected integer literal argument")
    return int(x.fn._literal_value)


def _substring_handler(out_type, args):
    col = args[0]
    start = _literal_int(args[1])
    length = _literal_int(args[2]) if len(args) > 2 else None
    if col.dictionary is None:
        raise NotImplementedError("substring on non-dictionary column")

    def sub(s: str) -> str:
        i = start - 1 if start > 0 else len(s) + start
        return s[i : i + length] if length is not None else s[i:]

    return _dict_transform(col, sub, VARCHAR)


def _strfn_handler(pyfn, result="str"):
    def handler(out_type, args):
        col = args[0]
        if col.dictionary is None:
            raise NotImplementedError("string function on non-dictionary column")
        if result == "str":
            return _dict_transform(col, pyfn, VARCHAR)
        return _dict_scalar(col, pyfn, out_type)

    return handler


def _literal_str(x: Lowered) -> str:
    if x.dictionary is None or len(x.dictionary) != 1:
        raise NotImplementedError("expected string literal argument")
    return str(x.dictionary[0])


def _and_extra_valid(base: Lowered, extras: Sequence[Lowered]) -> Lowered:
    """AND additional operands' validity into a result whose value was
    computed from their dictionaries alone (a NULL literal lowers to
    dictionary [""] + valid=False — the value shortcut must not drop it)."""
    extras = [x for x in extras if x is not None]
    if not extras:
        return base

    def fn(cols: Cols):
        d, v = base.fn(cols)
        for x in extras:
            _, xv = x.fn(cols)
            v = _and_valid(v, xv)
        return d, v

    return Lowered(base.type, base.dictionary, fn)


_CONCAT_DICT_LIMIT = 1 << 20  # max product-dictionary size for col || col


def _concat_pair(a: Lowered, b: Lowered) -> Lowered:
    """String concatenation in dictionary space: literal sides transform the
    other side's dictionary; column||column builds the (small) product
    dictionary and remaps the combined code on device."""
    if a.dictionary is None or b.dictionary is None:
        raise NotImplementedError("concat on non-dictionary operands")
    if len(b.dictionary) == 1:
        lit = str(b.dictionary[0])
        return _and_extra_valid(
            _dict_transform(a, lambda s: s + lit, VARCHAR), [b])
    if len(a.dictionary) == 1:
        lit = str(a.dictionary[0])
        return _and_extra_valid(
            _dict_transform(b, lambda s: lit + s, VARCHAR), [a])
    na, nb = len(a.dictionary), len(b.dictionary)
    if na * nb > _CONCAT_DICT_LIMIT:
        raise NotImplementedError(
            f"concat product dictionary too large ({na}x{nb})")
    prod = np.array([str(x) + str(y) for x in a.dictionary
                     for y in b.dictionary], dtype=object)
    newdict, remap = np.unique(prod, return_inverse=True)
    remap = remap.astype(np.int32)

    def fn(cols: Cols):
        (ac, av), (bc, bv) = a.fn(cols), b.fn(cols)
        code = ac.astype(jnp.int64) * nb + bc.astype(jnp.int64)
        return jnp.asarray(remap)[code], _and_valid(av, bv)

    return Lowered(VARCHAR, newdict, fn)


def _concat_handler(out_type, args):
    out = args[0]
    for nxt in args[1:]:
        out = _concat_pair(out, nxt)
    return out


def _replace_handler(out_type, args):
    col = args[0]
    search = _literal_str(args[1])
    rep = _literal_str(args[2]) if len(args) > 2 else ""
    if col.dictionary is None:
        raise NotImplementedError("replace on non-dictionary column")
    return _and_extra_valid(
        _dict_transform(col, lambda s: s.replace(search, rep), VARCHAR),
        args[1:])


def _strpos_handler(out_type, args):
    col = args[0]
    sub = _literal_str(args[1])
    if col.dictionary is None:
        raise NotImplementedError("strpos on non-dictionary column")
    return _and_extra_valid(
        _dict_scalar(col, lambda s: s.find(sub) + 1, BIGINT), args[1:])


def _starts_with_handler(out_type, args):
    col = args[0]
    prefix = _literal_str(args[1])
    if col.dictionary is None:
        raise NotImplementedError("starts_with on non-dictionary column")
    arr = np.array([str(v).startswith(prefix) for v in col.dictionary])

    def fn(cols: Cols):
        codes, valid = col.fn(cols)
        return jnp.asarray(arr)[codes], valid

    return _and_extra_valid(Lowered(BOOLEAN, None, fn), args[1:])


def _split_part_handler(out_type, args):
    col = args[0]
    delim = _literal_str(args[1])
    idx = _literal_int(args[2])
    if col.dictionary is None:
        raise NotImplementedError("split_part on non-dictionary column")
    if not delim:
        raise ValueError("split_part: delimiter must not be empty")
    if idx < 1:
        raise ValueError("split_part: index must be >= 1")

    def fn(s: str):
        parts = str(s).split(delim)
        # Trino: NULL when the index exceeds the number of fields
        return parts[idx - 1] if idx <= len(parts) else None

    return _and_extra_valid(
        _array_table_lookup(col, [fn(v) for v in col.dictionary], VARCHAR),
        args[1:])


def _pad_handler(left: bool):
    def handler(out_type, args):
        col = args[0]
        size = _literal_int(args[1])
        fill = _literal_str(args[2]) if len(args) > 2 else " "
        if col.dictionary is None:
            raise NotImplementedError("pad on non-dictionary column")
        if size < 0:
            raise ValueError("pad: target size must not be negative")
        if not fill:
            raise ValueError("pad: padding string must not be empty")

        def fn(s: str) -> str:
            if len(s) >= size:
                return s[:size]
            pad = (fill * size)[: size - len(s)]
            return pad + s if left else s + pad

        return _and_extra_valid(_dict_transform(col, fn, VARCHAR), args[1:])

    return handler


def _repeat_handler(out_type, args):
    """repeat(element, count) -> array(T) (reference:
    operator/scalar/RepeatFunction.java).  Element dictionaries (varchar /
    array) transform entry-wise; literal scalars build a one-entry constant
    array dictionary."""
    col = args[0]
    n = max(_literal_int(args[1]), 0)
    if col.dictionary is not None:
        vals = np.empty(len(col.dictionary), dtype=object)
        for i, v in enumerate(col.dictionary):
            elem = v if isinstance(v, tuple) else str(v)
            vals[i] = (elem,) * n
        newdict, remap = np.unique(vals, return_inverse=True)
        remap = remap.astype(np.int32)

        def fn(cols: Cols):
            codes, valid = col.fn(cols)
            return jnp.asarray(remap)[codes], valid

        return _and_extra_valid(Lowered(out_type, newdict, fn), args[1:])
    if hasattr(col.fn, "_literal_value"):
        newdict = np.empty(1, dtype=object)
        newdict[0] = (col.fn._literal_value,) * n

        def fn(cols: Cols):
            _, valid = col.fn(cols)
            return jnp.zeros((), dtype=jnp.int32), valid

        return _and_extra_valid(Lowered(out_type, newdict, fn), args[1:])
    raise NotImplementedError("repeat element must be a dictionary column "
                              "or literal")


def _translate_handler(out_type, args):
    col = args[0]
    src = _literal_str(args[1])
    dst = _literal_str(args[2])
    if col.dictionary is None:
        raise NotImplementedError("translate on non-dictionary column")
    table: dict = {}
    for i, a in enumerate(src):  # first duplicate wins (Trino semantics)
        table.setdefault(ord(a), dst[i] if i < len(dst) else None)
    return _and_extra_valid(
        _dict_transform(col, lambda s: s.translate(table), VARCHAR),
        args[1:])


def _codepoint_handler(out_type, args):
    col = args[0]
    if col.dictionary is None:
        raise NotImplementedError("codepoint on non-dictionary column")
    # Trino errors unless the input is exactly one character; dictionary
    # entries are evaluated eagerly (rows may never select a bad entry), so
    # the faithful per-row error degrades to NULL here
    return _array_table_lookup(
        col,
        [ord(str(v)) if len(str(v)) == 1 else None for v in col.dictionary],
        BIGINT)


def _variadic_minmax(jfn):
    """greatest/least: NULL if any argument is NULL (Trino semantics)."""

    def handler(out_type, args):
        def fn(cols: Cols):
            vals, valids = zip(*[a.fn(cols) for a in args])
            data = vals[0]
            for v in vals[1:]:
                data = jfn(data, v)
            return data.astype(out_type.storage_dtype), _all_valids(valids)

        return Lowered(out_type, None, fn)

    return handler


def _date_trunc_handler(truncfn):
    """date_trunc on DATE (days) or TIMESTAMP (micros since epoch)."""

    def handler(out_type, args):
        (a,) = args

        def fn(cols: Cols):
            v, vv = a.fn(cols)
            if a.type == TIMESTAMP:
                days = jnp.floor_divide(v, dt.MICROS_PER_DAY)
                return truncfn(days) * dt.MICROS_PER_DAY, vv
            return truncfn(v).astype(out_type.storage_dtype), vv

        return Lowered(out_type, None, fn)

    return handler


def _days_field_handler(field_fn):
    """Calendar field extraction over DATE (days) or TIMESTAMP (micros)."""

    def handler(out_type, args):
        (a,) = args

        def fn(cols: Cols):
            v, vv = a.fn(cols)
            if a.type == TIMESTAMP:
                v = jnp.floor_divide(v, dt.MICROS_PER_DAY)
            return field_fn(v).astype(out_type.storage_dtype), vv

        return Lowered(out_type, None, fn)

    return handler


def _const_handler(value):
    def handler(out_type, args):
        def fn(cols: Cols):
            return jnp.asarray(value, dtype=out_type.storage_dtype), None

        return Lowered(out_type, None, fn)

    return handler


def _truncate_handler(out_type, args):
    (a,) = args

    def fn(cols: Cols):
        v, vv = a.fn(cols)
        if isinstance(a.type, DecimalType):
            f = 10 ** a.type.scale
            return _trunc_div(v, f) * f, vv
        if np.issubdtype(v.dtype, np.integer):
            return v, vv
        return jnp.trunc(v), vv

    return Lowered(out_type, None, fn)


# ---------------------------------------------------------------------------
# CAST


def _nested_repr_compatible(a: Type, b: Type) -> bool:
    """True when two nested types share an identical python-value
    representation in dictionaries (so codes can pass through a cast)."""
    from ..spi.types import MapType, RowType

    def kind(t: Type):
        if isinstance(t, ArrayType):
            return ("array", kind(t.element))
        if isinstance(t, RowType):
            return ("row", tuple(kind(ft) for _, ft in t.fields))
        if isinstance(t, MapType):
            return ("map", kind(t.key), kind(t.value))
        if is_string(t):
            return "str"
        if isinstance(t, DecimalType):
            return ("dec", t.scale)
        k = np.dtype(t.storage_dtype).kind
        return {"i": "int", "u": "int", "f": "float", "b": "bool"}.get(k, t.name)

    return kind(a) == kind(b)


def _cast_handler(out_type, args):
    (a,) = args
    src = a.type
    if src == out_type:
        return a
    if is_string(src) and is_string(out_type):
        return a
    from ..spi.types import MapType, RowType

    if isinstance(src, (ArrayType, RowType, MapType)) and isinstance(
            out_type, (ArrayType, RowType, MapType)):
        # nested casts pass codes through ONLY when the python-value
        # representation is identical (same kind, matching element repr:
        # named vs anonymous row fields, int-width changes); anything else
        # (string->number elements, array->map) must not silently mistype
        if _nested_repr_compatible(src, out_type):
            return Lowered(out_type, a.dictionary, a.fn)
        raise NotImplementedError(
            f"cast {src} -> {out_type}: nested element conversion is not "
            "supported")
    ss, ds = _scale_of(src), _scale_of(out_type)
    if _is_long_dec(out_type):
        if _is_long_dec(src) and a.dictionary is not None:
            return _long_dec_transform(
                a, lambda v: rescale_scaled_int(v, ss, ds), out_type)
        if is_string(src) and a.dictionary is not None:
            # varchar -> decimal(38): exact parse over the dictionary
            from ..spi.batch import _to_scaled_int

            vals = [_to_scaled_int(str(v), ds) for v in a.dictionary]
            uniq = sorted(set(vals))
            pos = {v: i for i, v in enumerate(uniq)}
            remap = np.array([pos[v] for v in vals], dtype=np.int32)
            newdict = np.empty(len(uniq), dtype=object)
            for i, v in enumerate(uniq):
                newdict[i] = v

            def fn_vd(cols: Cols):
                codes, valid = a.fn(cols)
                return jnp.asarray(remap)[codes], valid

            return Lowered(out_type, newdict, fn_vd)
        if hasattr(a.fn, "_literal_value"):
            raw = rescale_scaled_int(int(a.fn._literal_value), ss, ds)
            d = np.empty(1, dtype=object)
            d[0] = raw

            def fn_lit(cols: Cols):
                _, valid = a.fn(cols)
                return jnp.zeros((), dtype=np.int32), valid

            fn_lit._literal_value = raw
            return Lowered(out_type, d, fn_lit)
        raise NotImplementedError(
            "cast of a device-resident column to decimal(>18) "
            "(dictionary-encoded int128 path) — cast to decimal(18,s) or "
            "double instead")
    if _is_long_dec(src):
        if a.dictionary is None:
            raise NotImplementedError("long-decimal column without dictionary")
        if np.issubdtype(out_type.storage_dtype, np.floating):
            return _dict_scalar(a, lambda s: int(s) / (10.0 ** ss), out_type)
        if is_string(out_type):
            import decimal as _d

            def fmt(s: str) -> str:
                with _d.localcontext() as ctx:
                    ctx.prec = 80
                    return str(_d.Decimal(int(s)).scaleb(-ss))

            return _dict_transform(a, fmt, VARCHAR)
        if isinstance(out_type, DecimalType) or np.issubdtype(
                out_type.storage_dtype, np.integer):
            shift = ds if isinstance(out_type, DecimalType) else 0
            return _dict_scalar(
                a, lambda s: rescale_scaled_int(int(s), ss, shift), out_type)
        raise NotImplementedError(f"cast decimal(38) -> {out_type}")

    def fn(cols: Cols):
        v, vv = a.fn(cols)
        ss, ds = _scale_of(src), _scale_of(out_type)
        if isinstance(out_type, DecimalType):
            if isinstance(src, DecimalType) or np.issubdtype(v.dtype, np.integer):
                data = _decimal_rescale(v.astype(np.int64), ss, ds)
            else:  # float -> decimal
                scaled = v * (10.0**ds)
                data = jnp.round(scaled).astype(np.int64)
        elif isinstance(src, DecimalType):
            if np.issubdtype(out_type.storage_dtype, np.floating):
                data = (v / (10.0**ss)).astype(out_type.storage_dtype)
            else:
                data = _decimal_rescale(v, ss, 0).astype(out_type.storage_dtype)
        elif src == DATE and out_type == TIMESTAMP:
            data = v.astype(np.int64) * dt.MICROS_PER_DAY
        elif src == TIMESTAMP and out_type == DATE:
            data = jnp.floor_divide(v, dt.MICROS_PER_DAY).astype(np.int32)
        elif out_type == BOOLEAN:
            data = v != 0
        else:
            data = v.astype(out_type.storage_dtype)
        return data, vv

    # exact-literal propagation: handlers that need a static operand (long-
    # decimal arithmetic, LIMIT-style ints) see through scalar casts
    if hasattr(a.fn, "_literal_value") and isinstance(
            a.fn._literal_value, (int, np.integer)) and (
            isinstance(out_type, DecimalType)
            or np.issubdtype(out_type.storage_dtype, np.integer)):
        fn._literal_value = rescale_scaled_int(int(a.fn._literal_value), ss, ds)
    if isinstance(src, DecimalType) or getattr(a.fn, "_from_decimal", False):
        # provenance marker: decimal operands folded to double keep exact-
        # arithmetic error semantics (the analyzer casts decimal -> double
        # before divide, which would otherwise hide DIVISION_BY_ZERO)
        fn._from_decimal = True
    return Lowered(out_type, None, fn)


# ---------------------------------------------------------------------------
# elementwise math / date registry


def _elementwise(jfn, null_on=None):
    def handler(out_type, args):
        def fn(cols: Cols):
            vals, valids = zip(*[a.fn(cols) for a in args])
            valid = _all_valids(valids)
            if null_on is not None:
                valid = _and_valid(valid, ~null_on(*vals))
            return jfn(*vals).astype(out_type.storage_dtype), valid

        return Lowered(out_type, None, fn)

    return handler


def _round_handler(out_type, args):
    x = args[0]
    nd = _literal_int(args[1]) if len(args) > 1 else 0

    def fn(cols: Cols):
        v, vv = x.fn(cols)
        if isinstance(x.type, DecimalType):
            s = x.type.scale
            if nd >= s:
                return v, vv
            f = 10 ** (s - nd)
            return _round_half_up_div(v, f) * f, vv
        if np.issubdtype(v.dtype, np.integer):
            return v, vv
        f = 10.0**nd
        return jnp.round(v * f) / f, vv

    return Lowered(out_type, None, fn)


# ---------------------------------------------------------------------------
# array functions: host dictionary transforms + device gathers (same stance
# as strings; reference operator/scalar/ArrayFunctions / ArraySubscript)


def _array_table_lookup(col, values, out_type: Type):
    """Per-dictionary-code precomputed result table -> device gather.
    ``values`` holds one python value (or None) per array-dictionary entry;
    Column.from_values performs type-correct storage conversion (decimal
    scaling, date days, string re-dictionarying) for the output."""
    from ..spi.batch import Column

    tab = Column.from_values(out_type, list(values))
    data_tab = np.asarray(tab.data)
    valid_tab = tab.valid_mask()
    all_valid = bool(valid_tab.all())

    def fn(cols: Cols):
        codes, valid = col.fn(cols)
        data = jnp.asarray(data_tab)[codes]
        v = valid if all_valid else _and_valid(
            valid, jnp.asarray(valid_tab)[codes])
        return data, v

    return Lowered(out_type, tab.dictionary, fn)


def _require_array_dict(col, what: str):
    if col.dictionary is None:
        raise NotImplementedError(f"{what} on non-dictionary array column")


def _row_field_handler(out_type, args):
    """ROW field access (sql/tree/DereferenceExpression): host table of the
    selected field per row-dictionary entry + device gather."""
    col = args[0]
    _require_array_dict(col, "row field access")
    fi = _literal_int(args[1])
    vals = [v[fi] if fi < len(v) else None for v in col.dictionary]
    return _array_table_lookup(col, vals, out_type)


def _map_element_at_handler(out_type, args):
    """element_at(map, key): per-dictionary-entry lookup (entries are
    key-sorted pair tuples) + device gather."""
    col, key = args[0], args[1]
    _require_array_dict(col, "element_at(map)")
    if hasattr(key.fn, "_literal_value"):
        needle = key.fn._literal_value
    elif key.dictionary is not None and len(key.dictionary) == 1:
        needle = str(key.dictionary[0])
    else:
        raise NotImplementedError("map key must be a literal")
    vals = [dict(v).get(needle) for v in col.dictionary]
    return _and_extra_valid(
        _array_table_lookup(col, vals, out_type), args[1:])


def _map_parts_handler(which: int):
    def handler(out_type, args):
        col = args[0]
        _require_array_dict(col, "map_keys/map_values")
        vals = [tuple(p[which] for p in v) for v in col.dictionary]
        return _array_table_lookup(col, vals, out_type)

    return handler


def _cardinality_handler(out_type, args):
    col = args[0]
    _require_array_dict(col, "cardinality")
    return _array_table_lookup(col, [len(v) for v in col.dictionary], BIGINT)


def _element_at_dispatch(out_type, args):
    from ..spi.types import MapType

    if isinstance(args[0].type, MapType):
        return _map_element_at_handler(out_type, args)
    return _element_at_handler(out_type, args)


def _element_at_handler(out_type, args):
    col = args[0]
    _require_array_dict(col, "element_at")
    i = _literal_int(args[1])
    if i == 0:
        raise NotImplementedError("SQL array indexes are 1-based")

    def pick(v):
        j = i - 1 if i > 0 else len(v) + i
        return v[j] if 0 <= j < len(v) else None

    return _and_extra_valid(
        _array_table_lookup(col, [pick(v) for v in col.dictionary], out_type),
        args[1:])


def _array_needle(x) -> object:
    if hasattr(x.fn, "_literal_value") and not isinstance(x.type, DecimalType):
        return x.fn._literal_value
    if x.dictionary is not None and len(x.dictionary) == 1:
        return str(x.dictionary[0])
    raise NotImplementedError("contains/array_position needle must be a "
                              "non-decimal literal")


def _contains_handler(out_type, args):
    col = args[0]
    _require_array_dict(col, "contains")
    needle = _array_needle(args[1])
    return _and_extra_valid(
        _array_table_lookup(
            col, [needle in v for v in col.dictionary], BOOLEAN),
        args[1:])


def _array_position_handler(out_type, args):
    col = args[0]
    _require_array_dict(col, "array_position")
    needle = _array_needle(args[1])

    def pos(v):
        try:
            return v.index(needle) + 1
        except ValueError:
            return 0

    return _and_extra_valid(
        _array_table_lookup(col, [pos(v) for v in col.dictionary], BIGINT),
        args[1:])


def _json_extract_handler(scalar: bool):
    """json_extract[_scalar](json, '$.path'): host path evaluation over the
    dictionary + device gather (ops/json_fns.py; reference:
    json/JsonPathEvaluator.java, operator/scalar/JsonFunctions)."""

    def handler(out_type, args):
        import json as _json

        from .json_fns import eval_json_path, json_scalar_text, parse_json_path

        col = args[0]
        if col.dictionary is None:
            raise NotImplementedError("json function on non-dictionary column")
        steps = parse_json_path(_literal_str(args[1]))
        vals = []
        for v in col.dictionary:
            r = eval_json_path(str(v), steps)
            if scalar:
                vals.append(json_scalar_text(r))
            else:
                vals.append(None if r is None else _json.dumps(r))
        return _and_extra_valid(
            _array_table_lookup(col, vals, VARCHAR), args[1:])

    return handler


def _json_array_length_handler(out_type, args):
    import json as _json

    col = args[0]
    if col.dictionary is None:
        raise NotImplementedError("json function on non-dictionary column")

    def length(v):
        try:
            doc = _json.loads(str(v))
        except (ValueError, TypeError):
            return None
        return len(doc) if isinstance(doc, list) else None

    return _array_table_lookup(
        col, [length(v) for v in col.dictionary], BIGINT)


def _grouping_mask_handler(out_type, args):
    """grouping() lowering: constant-table gather by the $groupid channel
    (args = [groupid column, one mask literal per grouping set])."""
    gid = args[0]
    masks = np.asarray([_literal_int(a) for a in args[1:]], dtype=np.int64)

    def fn(cols: Cols):
        v, vv = gid.fn(cols)
        return jnp.asarray(masks)[v], vv

    return Lowered(out_type, None, fn)


HANDLERS: dict[str, Callable] = {
    "$grouping_mask": _grouping_mask_handler,
    "cardinality": _cardinality_handler,
    "json_extract": _json_extract_handler(scalar=False),
    "json_extract_scalar": _json_extract_handler(scalar=True),
    "json_array_length": _json_array_length_handler,
    "element_at": _element_at_dispatch,
    "$row_field": _row_field_handler,
    "map_keys": _map_parts_handler(0),
    "map_values": _map_parts_handler(1),
    "contains": _contains_handler,
    "array_position": _array_position_handler,
    "add": _arith_handler("add"),
    "subtract": _arith_handler("subtract"),
    "multiply": _arith_handler("multiply"),
    "divide": _arith_handler("divide"),
    "modulus": _arith_handler("modulus"),
    "eq": _cmp_handler("eq"),
    "ne": _cmp_handler("ne"),
    "lt": _cmp_handler("lt"),
    "le": _cmp_handler("le"),
    "gt": _cmp_handler("gt"),
    "ge": _cmp_handler("ge"),
    "$and": _and_handler,
    "$or": _or_handler,
    "$not": _not_handler,
    "$is_null": _is_null_handler,
    "$if": _if_handler,
    "$coalesce": _coalesce_handler,
    "$in": _in_handler,
    "$like": _like_handler,
    "$cast": _cast_handler,
    "negate": _elementwise(lambda a: -a),
    "abs": _elementwise(jnp.abs),
    "sqrt": _elementwise(jnp.sqrt),
    "floor": _elementwise(jnp.floor),
    "ceiling": _elementwise(jnp.ceil),
    "ceil": _elementwise(jnp.ceil),
    "exp": _elementwise(jnp.exp),
    "ln": _elementwise(jnp.log),
    "log10": _elementwise(jnp.log10),
    "power": _elementwise(jnp.power),
    "pow": _elementwise(jnp.power),
    "round": _round_handler,
    "year": _days_field_handler(dt.year_of),
    "month": _days_field_handler(dt.month_of),
    "day": _days_field_handler(dt.day_of),
    "quarter": _days_field_handler(dt.quarter_of),
    "add_months": _elementwise(dt.add_months),
    "substring": _substring_handler,
    "substr": _substring_handler,
    "upper": _strfn_handler(str.upper),
    "lower": _strfn_handler(str.lower),
    "trim": _strfn_handler(str.strip),
    "ltrim": _strfn_handler(str.lstrip),
    "rtrim": _strfn_handler(str.rstrip),
    "length": _strfn_handler(len, result="scalar"),
    "reverse": _strfn_handler(lambda s: s[::-1]),
    "concat": _concat_handler,
    "replace": _replace_handler,
    "strpos": _strpos_handler,
    "starts_with": _starts_with_handler,
    "split_part": _split_part_handler,
    "lpad": _pad_handler(left=True),
    "rpad": _pad_handler(left=False),
    "repeat": _repeat_handler,
    "translate": _translate_handler,
    "codepoint": _codepoint_handler,
    "greatest": _variadic_minmax(jnp.maximum),
    "least": _variadic_minmax(jnp.minimum),
    "sign": _elementwise(jnp.sign),
    "truncate": _truncate_handler,
    "cbrt": _elementwise(jnp.cbrt),
    "degrees": _elementwise(jnp.degrees),
    "radians": _elementwise(jnp.radians),
    "sin": _elementwise(jnp.sin),
    "cos": _elementwise(jnp.cos),
    "tan": _elementwise(jnp.tan),
    "asin": _elementwise(jnp.arcsin),
    "acos": _elementwise(jnp.arccos),
    "atan": _elementwise(jnp.arctan),
    "atan2": _elementwise(jnp.arctan2),
    "log2": _elementwise(jnp.log2),
    "pi": _const_handler(np.pi),
    "e": _const_handler(np.e),
    "is_nan": _elementwise(jnp.isnan),
    "day_of_week": _days_field_handler(dt.day_of_week),
    "dow": _days_field_handler(dt.day_of_week),
    "day_of_year": _days_field_handler(dt.day_of_year),
    "doy": _days_field_handler(dt.day_of_year),
    "date_trunc_year": _date_trunc_handler(dt.trunc_year),
    "date_trunc_quarter": _date_trunc_handler(dt.trunc_quarter),
    "date_trunc_month": _date_trunc_handler(dt.trunc_month),
    "date_trunc_week": _date_trunc_handler(dt.trunc_week),
    "date_trunc_day": _date_trunc_handler(lambda d: d),
}


# ---------------------------------------------------------------------------
# compiler entry points


def _lower(
    expr: RowExpression,
    input_types: Sequence[Type],
    input_dicts: Sequence[Optional[np.ndarray]],
) -> Lowered:
    if isinstance(expr, InputRef):
        idx = expr.index

        def fn(cols: Cols):
            return cols[idx]

        return Lowered(expr.type, input_dicts[idx] if input_dicts else None, fn)

    if isinstance(expr, Literal):
        from ..spi.types import MapType, RowType

        t = expr.type
        v = expr.value
        if v is None:

            def fn_null(cols: Cols):
                return jnp.zeros((), dtype=t.storage_dtype), jnp.zeros((), dtype=bool)

            fn_null._literal_null = True
            if isinstance(t, (ArrayType, RowType, MapType)):
                d0 = np.empty(1, dtype=object)
                d0[0] = ()
                return Lowered(t, d0, fn_null)
            if _is_long_dec(t):
                d0 = np.empty(1, dtype=object)
                d0[0] = 0
                return Lowered(t, d0, fn_null)
            return Lowered(t, np.array([""], dtype=object) if is_string(t) else None, fn_null)
        if _is_long_dec(t):
            from ..spi.batch import _to_scaled_int

            raw = _to_scaled_int(v, t.scale)
            d = np.empty(1, dtype=object)
            d[0] = raw

            def fn_ldec(cols: Cols):
                return jnp.zeros((), dtype=np.int32), None

            fn_ldec._literal_value = raw
            return Lowered(t, d, fn_ldec)
        if isinstance(t, (RowType, MapType)):
            d = np.empty(1, dtype=object)
            d[0] = (tuple(sorted(v.items())) if isinstance(v, dict)
                    else tuple(v))

            def fn_rowmap(cols: Cols):
                return jnp.zeros((), dtype=np.int32), None

            return Lowered(t, d, fn_rowmap)
        if isinstance(t, ArrayType):
            d = np.empty(1, dtype=object)
            d[0] = tuple(v)

            def fn_arr(cols: Cols):
                return jnp.zeros((), dtype=np.int32), None

            return Lowered(t, d, fn_arr)
        if is_string(t):
            d = np.array([v], dtype=object)

            def fn_str(cols: Cols):
                return jnp.zeros((), dtype=np.int32), None

            return Lowered(t, d, fn_str)
        if isinstance(t, DecimalType):
            from ..spi.batch import _to_scaled_int

            raw = _to_scaled_int(v, t.scale)
        elif t == DATE:
            from ..spi.batch import _to_days

            raw = _to_days(v)
        elif t == TIMESTAMP:
            from ..spi.batch import _to_micros

            raw = _to_micros(v)
        else:
            raw = v

        def fn_lit(cols: Cols):
            return jnp.asarray(raw, dtype=t.storage_dtype), None

        fn_lit._literal_value = raw  # for handlers needing static args
        return Lowered(t, None, fn_lit)

    assert isinstance(expr, Call), expr
    handler = HANDLERS.get(expr.name)
    if handler is None:
        raise NotImplementedError(f"scalar function not implemented: {expr.name}")
    args = [_lower(a, input_types, input_dicts) for a in expr.args]
    return handler(expr.type, args)


def compile_expression(
    expr: RowExpression,
    input_types: Sequence[Type],
    input_dicts: Optional[Sequence[Optional[np.ndarray]]] = None,
) -> CompiledExpression:
    dicts = list(input_dicts) if input_dicts is not None else [None] * len(input_types)
    low = _lower(expr, list(input_types), dicts)
    return CompiledExpression(low.type, low.dictionary, low.fn)


def compile_projection(
    exprs: Sequence[RowExpression],
    input_types: Sequence[Type],
    input_dicts: Optional[Sequence[Optional[np.ndarray]]] = None,
):
    """Compile a list of projections into one traceable function
    ``cols -> [(data, valid), ...]`` (fused by jit at the operator level)."""
    compiled = [compile_expression(e, input_types, input_dicts) for e in exprs]

    def fn(cols: Cols):
        return [c(cols) for c in compiled]

    return compiled, fn
