"""Date/time device kernels.

Calendar math on int32 "days since 1970-01-01" arrays (the DATE storage) using
Howard Hinnant's civil-calendar algorithms — branch-free integer ops that XLA
vectorizes onto the VPU.  Mirrors the roles of io.trino.operator.scalar.
DateTimeFunctions (reference: operator/scalar/DateTimeFunctions.java) without
the JodaTime machinery: no timezones in v1 (DATE and naive TIMESTAMP only).
"""

from __future__ import annotations

import jax.numpy as jnp

MICROS_PER_DAY = 86_400_000_000

__all__ = [
    "civil_from_days",
    "days_from_civil",
    "year_of",
    "month_of",
    "day_of",
    "quarter_of",
    "add_months",
    "day_of_week",
    "day_of_year",
    "trunc_year",
    "trunc_quarter",
    "trunc_month",
    "trunc_week",
    "MICROS_PER_DAY",
]


def civil_from_days(z):
    """days-since-epoch -> (year, month, day); exact for +/- millions of years."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.floor_divide(jnp.where(z >= 0, z, z - 146096), 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def days_from_civil(y, m, d):
    y = jnp.where(m <= 2, y - 1, y)
    era = jnp.floor_divide(jnp.where(y >= 0, y, y - 399), 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def year_of(days):
    return civil_from_days(days)[0]


def month_of(days):
    return civil_from_days(days)[1]


def day_of(days):
    return civil_from_days(days)[2]


def quarter_of(days):
    return (civil_from_days(days)[1] + 2) // 3


def day_of_week(days):
    """ISO day of week: 1 = Monday .. 7 = Sunday (Trino day_of_week/dow).
    1970-01-01 was a Thursday, so day index (days + 3) mod 7 is Monday-based."""
    return jnp.remainder(days.astype(jnp.int64) + 3, 7) + 1


def day_of_year(days):
    return days.astype(jnp.int64) - trunc_year(days) + 1


def trunc_year(days):
    y, _, _ = civil_from_days(days)
    return days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))


def trunc_quarter(days):
    y, m, _ = civil_from_days(days)
    qm = ((m - 1) // 3) * 3 + 1
    return days_from_civil(y, qm, jnp.ones_like(y))


def trunc_month(days):
    y, m, _ = civil_from_days(days)
    return days_from_civil(y, m, jnp.ones_like(y))


def trunc_week(days):
    """Truncate to the Monday of the week."""
    return days.astype(jnp.int64) - (day_of_week(days) - 1)


_DAYS_IN_MONTH = jnp.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31])


def add_months(days, n):
    """DATE + INTERVAL n MONTH with end-of-month clamping (SQL semantics)."""
    y, m, d = civil_from_days(days)
    total = y * 12 + (m - 1) + n
    ny = jnp.floor_divide(total, 12)
    nm = jnp.remainder(total, 12) + 1
    leap = ((ny % 4 == 0) & (ny % 100 != 0)) | (ny % 400 == 0)
    dmax = _DAYS_IN_MONTH[nm - 1] + ((nm == 2) & leap)
    return days_from_civil(ny, nm, jnp.minimum(d, dmax))
