"""Pallas TPU kernels for the hottest reduction shapes.

Hand-written kernels where the access pattern benefits from explicit VMEM
accumulation rather than XLA's scatter-based ``segment_sum`` lowering:
relational aggregations reduce millions of rows into a handful of group
slots (TPC-H Q1: 4 groups), so a block-resident accumulator that revisits
one [G, 128] VMEM tile per input block avoids the scatter entirely — the
Pallas analogue of the hand-specialized accumulators the reference
generates per aggregation (operator/aggregation/*, sql/gen).

Kernels are f32/int32 (the TPU-native lanes); the engine routes REAL
aggregations here (exec/kernels.grouped_reduce fast path) while
f64/decimal reductions stay on the XLA sort+segment path.  ``interpret=
True`` runs the same kernels on CPU for tests.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["masked_segment_sum_f32", "pallas_available"]

_BLOCK = 1024  # rows per grid step (8 sublanes x 128 lanes)
_LANES = 128


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401

        return True
    except Exception:
        return False


def _segment_sum_kernel(G: int, vals_ref, gid_ref, live_ref, out_ref):
    """One grid step: accumulate this [BLOCK] slice into the [G, LANES]
    output tile (same tile every step — the accumulator stays in VMEM)."""
    from jax.experimental import pallas as pl

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    vals = vals_ref[:, :]  # [BLOCK//LANES, LANES] f32
    gid = gid_ref[:, :]  # [BLOCK//LANES, LANES] int32
    live = live_ref[:, :]  # [BLOCK//LANES, LANES] bool
    contrib = jnp.where(live, vals, 0.0)
    # G is tiny (<=64): accumulate each group's lane-sums with a vector
    # select — no scatter, pure VPU work
    for g in range(G):
        sel = jnp.where(gid == g, contrib, 0.0)
        out_ref[g, :] = out_ref[g, :] + jnp.sum(sel, axis=0)


@lru_cache(maxsize=None)
def _build(G: int, n_blocks: int, interpret: bool):
    from jax.experimental import pallas as pl

    rows = _BLOCK // _LANES

    def run(vals, gid, live):
        return pl.pallas_call(
            partial(_segment_sum_kernel, G),
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
                pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
                pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((G, _LANES), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((G, _LANES), jnp.float32),
            interpret=interpret,
        )(vals, gid, live)

    return jax.jit(run)


def masked_segment_sum_f32(values, gid, live, num_groups: int,
                           interpret: bool = False):
    """Per-group sums of an f32 column: [N] values, [N] int32 group ids in
    [0, num_groups), [N] bool live mask -> [num_groups] f32.

    N is padded to the block size internally; lanes reduce at the end.
    """
    values = jnp.asarray(values, jnp.float32)
    gid = jnp.asarray(gid, jnp.int32)
    live = (jnp.ones(values.shape, jnp.bool_) if live is None
            else jnp.asarray(live))
    n = values.shape[0]
    pad = (-n) % _BLOCK
    if pad:
        values = jnp.concatenate([values, jnp.zeros(pad, jnp.float32)])
        gid = jnp.concatenate([gid, jnp.zeros(pad, jnp.int32)])
        live = jnp.concatenate([live, jnp.zeros(pad, jnp.bool_)])
    total = n + pad
    shape2d = (total // _LANES, _LANES)
    run = _build(int(num_groups), total // _BLOCK, interpret)
    # the engine runs with jax_enable_x64 on (BIGINT/decimal lanes), but
    # Mosaic rejects the stray i64 weak types x64 mode gives Python ints —
    # the kernel itself is pure f32/i32, so trace it in 32-bit mode
    with jax.enable_x64(False):
        tile = run(values.reshape(shape2d), gid.reshape(shape2d),
                   live.reshape(shape2d))
    return jnp.sum(tile, axis=1)
