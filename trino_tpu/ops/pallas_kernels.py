"""Pallas TPU kernels for the hottest reduction shapes.

Hand-written kernels where the access pattern benefits from explicit VMEM
accumulation rather than XLA's scatter-based ``segment_sum`` lowering:
relational aggregations reduce millions of rows into a handful of group
slots (TPC-H Q1: 4 groups), so a block-resident accumulator that revisits
one [G, 128] VMEM tile per input block avoids the scatter entirely — the
Pallas analogue of the hand-specialized accumulators the reference
generates per aggregation (operator/aggregation/*, sql/gen).

Kernels are f32/int32 (the TPU-native lanes); the engine routes REAL
aggregations here (exec/kernels.grouped_reduce fast path) while
f64/decimal reductions stay on the XLA sort+segment path.  ``interpret=
True`` runs the same kernels on CPU for tests.
"""

from __future__ import annotations

from functools import partial

from ..caching.executable_cache import jit_memo

import jax
import jax.numpy as jnp
import numpy as np

# jax.enable_x64 was removed in jax 0.4.x; the experimental spelling is the
# one that exists here (the engine traces these kernels in 32-bit mode
# because Mosaic rejects the stray i64 weak types x64 mode produces)
from jax.experimental import enable_x64 as _enable_x64

__all__ = ["masked_segment_sum_f32", "pallas_available",
           "hash_insert", "hash_probe"]

_BLOCK = 1024  # rows per grid step (8 sublanes x 128 lanes)
_LANES = 128
_HBLOCK = 1024  # rows per grid step for the open-addressing kernels


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401

        return True
    except Exception:
        return False


def _segment_sum_kernel(G: int, vals_ref, gid_ref, live_ref, out_ref):
    """One grid step: accumulate this [BLOCK] slice into the [G, LANES]
    output tile (same tile every step — the accumulator stays in VMEM)."""
    from jax.experimental import pallas as pl

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    vals = vals_ref[:, :]  # [BLOCK//LANES, LANES] f32
    gid = gid_ref[:, :]  # [BLOCK//LANES, LANES] int32
    live = live_ref[:, :]  # [BLOCK//LANES, LANES] bool
    contrib = jnp.where(live, vals, 0.0)
    # G is tiny (<=64): accumulate each group's lane-sums with a vector
    # select — no scatter, pure VPU work
    for g in range(G):
        sel = jnp.where(gid == g, contrib, 0.0)
        out_ref[g, :] = out_ref[g, :] + jnp.sum(sel, axis=0)


@jit_memo("pallas._build")
def _build(G: int, n_blocks: int, interpret: bool):
    from jax.experimental import pallas as pl

    rows = _BLOCK // _LANES

    def run(vals, gid, live):
        return pl.pallas_call(
            partial(_segment_sum_kernel, G),
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
                pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
                pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((G, _LANES), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((G, _LANES), jnp.float32),
            interpret=interpret,
        )(vals, gid, live)

    return jax.jit(run)


def masked_segment_sum_f32(values, gid, live, num_groups: int,
                           interpret: bool = False):
    """Per-group sums of an f32 column: [N] values, [N] int32 group ids in
    [0, num_groups), [N] bool live mask -> [num_groups] f32.

    N is padded to the block size internally; lanes reduce at the end.
    """
    values = jnp.asarray(values, jnp.float32)
    gid = jnp.asarray(gid, jnp.int32)
    live = (jnp.ones(values.shape, jnp.bool_) if live is None
            else jnp.asarray(live))
    n = values.shape[0]
    pad = (-n) % _BLOCK
    if pad:
        values = jnp.concatenate([values, jnp.zeros(pad, jnp.float32)])
        gid = jnp.concatenate([gid, jnp.zeros(pad, jnp.int32)])
        live = jnp.concatenate([live, jnp.zeros(pad, jnp.bool_)])
    total = n + pad
    shape2d = (total // _LANES, _LANES)
    run = _build(int(num_groups), total // _BLOCK, interpret)
    # the engine runs with jax_enable_x64 on (BIGINT/decimal lanes), but
    # Mosaic rejects the stray i64 weak types x64 mode gives Python ints —
    # the kernel itself is pure f32/i32, so trace it in 32-bit mode
    with _enable_x64(False):
        tile = run(values.reshape(shape2d), gid.reshape(shape2d),
                   live.reshape(shape2d))
    return jnp.sum(tile, axis=1)


# ---------------------------------------------------------------------------
# open-addressing hash table: linear-probing insert + probe
#
# The device-resident alternative to the sort + searchsorted grouping path
# (exec/kernels.group_ids, exec/join_exec probe ranges): a power-of-two slot
# array holds one uint32 plane row per distinct key plus an int32 group id
# per slot, all VMEM-resident across the sequential grid steps.  Collision
# resolution happens in-kernel by comparing EVERY key plane (not just the
# hash), so two keys sharing a slot chain can never merge; callers encode
# NULL keys either as a dead row (sentinel hash -> ``live``=False) or as an
# extra validity plane so NULL forms its own group.  Rows are walked
# serially inside each grid step — the TPU grid is sequential, which is
# exactly what makes the shared table state sound.


def _hash_insert_kernel(P: int, S: int, block: int, planes_ref, hash_ref,
                        live_ref, gid_ref, table_ref, sgid_ref, count_ref):
    """One grid step: insert ``block`` rows into the slot table.  The table
    refs use constant index maps, so they persist across steps (same VMEM
    tiles every step — the accumulator pattern of _segment_sum_kernel)."""
    from jax.experimental import pallas as pl

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        table_ref[:, :] = jnp.zeros_like(table_ref)
        sgid_ref[:, :] = jnp.full_like(sgid_ref, -1)
        count_ref[0, 0] = jnp.int32(0)

    # every literal is explicitly i32/u32: these kernels trace INSIDE
    # x64-mode jitted programs (static_agg, the join index builder), where a
    # weak-typed Python int would promote to i64 and break the while carry
    mask = jnp.uint32(S - 1)
    one = jnp.int32(1)
    smask = jnp.int32(S - 1)

    def insert_row(i, carry):
        lv = live_ref[0, i]
        slot0 = (hash_ref[0, i] & mask).astype(jnp.int32)

        def probe_body(st):
            slot, _done, _empty = st
            cur = sgid_ref[0, slot]
            empty = cur < jnp.int32(0)
            eq = jnp.bool_(True)
            for p in range(P):
                eq = jnp.logical_and(eq,
                                     table_ref[p, slot] == planes_ref[p, i])
            done = empty | ((~empty) & eq)
            nxt = jnp.where(done, slot, (slot + one) & smask)
            return nxt, done, empty

        # dead rows start done: they never touch the table and take gid S
        # (>= any real group id, matching the group_ids dead-row contract).
        # Live rows always terminate: count <= n <= S/2 leaves empty slots.
        slot, _done, empty = jax.lax.while_loop(
            lambda st: ~st[1], probe_body,
            (slot0, ~lv, jnp.bool_(False)))

        @pl.when(lv & empty)
        def _claim():
            c = count_ref[0, 0]
            sgid_ref[0, slot] = c
            for p in range(P):
                table_ref[p, slot] = planes_ref[p, i]
            count_ref[0, 0] = c + one

        gid_ref[0, i] = jnp.where(lv, sgid_ref[0, slot], jnp.int32(S))
        return carry

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(block), insert_row,
                      jnp.int32(0))


def _hash_probe_kernel(P: int, S: int, block: int, table_ref, sgid_ref,
                       planes_ref, hash_ref, live_ref, gid_ref):
    """One grid step: look up ``block`` rows in a built slot table.  Pure
    reads — the table is an input here, shared across steps."""
    mask = jnp.uint32(S - 1)
    one = jnp.int32(1)
    smask = jnp.int32(S - 1)

    def probe_row(i, carry):
        lv = live_ref[0, i]
        slot0 = (hash_ref[0, i] & mask).astype(jnp.int32)

        def probe_body(st):
            slot, _done, _gid = st
            cur = sgid_ref[0, slot]
            empty = cur < jnp.int32(0)
            eq = jnp.bool_(True)
            for p in range(P):
                eq = jnp.logical_and(eq,
                                     table_ref[p, slot] == planes_ref[p, i])
            hit = (~empty) & eq
            done = empty | hit
            g = jnp.where(hit, cur, jnp.int32(-1))
            nxt = jnp.where(done, slot, (slot + one) & smask)
            return nxt, done, g

        _slot, _done, g = jax.lax.while_loop(
            lambda st: ~st[1], probe_body,
            (slot0, ~lv, jnp.int32(-1)))
        gid_ref[0, i] = g  # dead rows keep the initial -1 (miss)
        return carry

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(block), probe_row,
                      jnp.int32(0))


@jit_memo("pallas._build_insert")
def _build_insert(P: int, S: int, n_blocks: int, interpret: bool):
    from jax.experimental import pallas as pl

    def run(planes, hash32, live):
        return pl.pallas_call(
            partial(_hash_insert_kernel, P, S, _HBLOCK),
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec((P, _HBLOCK), lambda i: (0, i)),
                pl.BlockSpec((1, _HBLOCK), lambda i: (0, i)),
                pl.BlockSpec((1, _HBLOCK), lambda i: (0, i)),
            ],
            out_specs=[
                pl.BlockSpec((1, _HBLOCK), lambda i: (0, i)),
                pl.BlockSpec((P, S), lambda i: (0, 0)),
                pl.BlockSpec((1, S), lambda i: (0, 0)),
                pl.BlockSpec((1, 1), lambda i: (0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((1, n_blocks * _HBLOCK), jnp.int32),
                jax.ShapeDtypeStruct((P, S), jnp.uint32),
                jax.ShapeDtypeStruct((1, S), jnp.int32),
                jax.ShapeDtypeStruct((1, 1), jnp.int32),
            ],
            interpret=interpret,
        )(planes, hash32, live)

    return jax.jit(run)


@jit_memo("pallas._build_probe")
def _build_probe(P: int, S: int, n_blocks: int, interpret: bool):
    from jax.experimental import pallas as pl

    def run(table, sgid, planes, hash32, live):
        return pl.pallas_call(
            partial(_hash_probe_kernel, P, S, _HBLOCK),
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec((P, S), lambda i: (0, 0)),
                pl.BlockSpec((1, S), lambda i: (0, 0)),
                pl.BlockSpec((P, _HBLOCK), lambda i: (0, i)),
                pl.BlockSpec((1, _HBLOCK), lambda i: (0, i)),
                pl.BlockSpec((1, _HBLOCK), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((1, _HBLOCK), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((1, n_blocks * _HBLOCK),
                                           jnp.int32),
            interpret=interpret,
        )(table, sgid, planes, hash32, live)

    return jax.jit(run)


def _pad_rows(planes, hash32, live, n: int):
    """Pad the row axis to the block size; padded rows are dead."""
    pad = (-n) % _HBLOCK
    if pad:
        planes = jnp.concatenate(
            [planes, jnp.zeros((planes.shape[0], pad), jnp.uint32)], axis=1)
        hash32 = jnp.concatenate([hash32, jnp.zeros(pad, jnp.uint32)])
        live = jnp.concatenate([live, jnp.zeros(pad, jnp.bool_)])
    return planes, hash32, live, n + pad


def hash_insert(planes, hash32, live, num_slots: int,
                interpret: bool = False):
    """Build an open-addressing table over ``planes`` [P, N] uint32 key
    planes (elementwise plane equality == key equality), ``hash32`` [N]
    uint32 slot hashes, ``live`` [N] bool (or None).  ``num_slots`` must be
    a power of two >= 2 * live rows.

    Returns (row_gid, count, table_planes, slot_gid): ``row_gid`` [N] int32
    assigns dense group ids in first-occurrence order (dead rows get
    ``num_slots``, >= any real id); ``count`` is the scalar group count
    (device-resident); the last two are the table state for hash_probe."""
    planes = jnp.asarray(planes, jnp.uint32)
    hash32 = jnp.asarray(hash32, jnp.uint32)
    P, n = int(planes.shape[0]), int(planes.shape[1])
    S = int(num_slots)
    if S & (S - 1) or S <= 0:
        raise ValueError(f"num_slots must be a power of two, got {S}")
    live = (jnp.ones(n, jnp.bool_) if live is None
            else jnp.asarray(live, jnp.bool_))
    planes, hash32, live, total = _pad_rows(planes, hash32, live, n)
    run = _build_insert(P, S, total // _HBLOCK, interpret)
    # engine mode is x64 (BIGINT lanes) but Mosaic rejects stray i64 weak
    # types; the kernel is pure u32/i32, so trace it in 32-bit mode
    with _enable_x64(False):
        gid, table, sgid, count = run(
            planes, hash32.reshape(1, total), live.reshape(1, total))
    return gid[0, :n], count[0, 0], table, sgid[0]


def hash_probe(table_planes, slot_gid, planes, hash32, live=None,
               interpret: bool = False):
    """Look up [P, N] ``planes`` rows in a table built by hash_insert.
    Returns [N] int32 group ids; -1 = miss (or dead probe row)."""
    table_planes = jnp.asarray(table_planes, jnp.uint32)
    slot_gid = jnp.asarray(slot_gid, jnp.int32)
    planes = jnp.asarray(planes, jnp.uint32)
    hash32 = jnp.asarray(hash32, jnp.uint32)
    P, n = int(planes.shape[0]), int(planes.shape[1])
    S = int(slot_gid.shape[0])
    live = (jnp.ones(n, jnp.bool_) if live is None
            else jnp.asarray(live, jnp.bool_))
    planes, hash32, live, total = _pad_rows(planes, hash32, live, n)
    run = _build_probe(P, S, total // _HBLOCK, interpret)
    with _enable_x64(False):
        gid = run(table_planes, slot_gid.reshape(1, S), planes,
                  hash32.reshape(1, total), live.reshape(1, total))
    return gid[0, :n]
