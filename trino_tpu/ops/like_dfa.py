"""Vectorized LIKE matching: bit-parallel NFA over the whole dictionary.

The reference compiles LIKE patterns to a dense DFA over bytes and runs it
per row (likematcher/DenseDfaMatcher.java:23, makeNfa:141).  Here strings
are dictionary-encoded, so matching runs once per DICTIONARY ENTRY — but a
high-NDV column (l_comment-class) has millions of entries, and the round-3
``re.fullmatch`` python loop crawled (VERDICT weak #5).  This matcher is
the numpy counterpart of the dense DFA:

- pattern -> NFA with states 0..m (state s = "matched s tokens"); literal
  tokens consume one matching char, ``_`` consumes any char, ``%`` self-
  loops on any char with an epsilon edge to the next state;
- the active-state set is a uint64 BITSET per dictionary entry (pattern
  tokens capped at 63 — longer patterns fall back to ``re``);
- the dictionary becomes a padded codepoint matrix via a zero-copy numpy
  view, and each character position advances ALL entries' bitsets with a
  table gather + shift + mask — O(maxlen) vectorized passes, no python
  per-entry loop.

~1M-entry dictionaries match in tens of milliseconds vs seconds for the
``re`` loop; small dictionaries (< 1024) keep ``re`` (loop overhead is
negligible and it handles every corner).
"""

from __future__ import annotations

import re
from typing import Optional

import numpy as np

__all__ = ["like_mask", "like_tokens"]

VECTOR_THRESHOLD = 1024  # below this, the re loop is cheap enough


def like_tokens(pattern: str, escape: Optional[str] = None):
    """Pattern -> token list: ('%',), ('_',) or ('c', char).  None on an
    invalid escape (caller decides how to error)."""
    toks = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if escape and ch == escape:
            if i + 1 >= len(pattern):
                return None
            toks.append(("c", pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            if not toks or toks[-1] != ("%",):  # collapse %% runs
                toks.append(("%",))
        elif ch == "_":
            toks.append(("_",))
        else:
            toks.append(("c", ch))
        i += 1
    return toks


def _re_fallback(dictionary, pattern: str, escape: Optional[str]):
    from .expr import like_to_regex

    rx = re.compile(like_to_regex(pattern, escape), re.DOTALL)
    return np.array([rx.fullmatch(str(v)) is not None for v in dictionary])


def like_mask(dictionary, pattern: str, escape: Optional[str] = None
              ) -> np.ndarray:
    """Boolean match mask over every dictionary entry."""
    toks = like_tokens(pattern, escape)
    if toks is None:
        raise ValueError(f"invalid LIKE escape in pattern {pattern!r}")
    n = len(dictionary)
    m = len(toks)
    if (n < VECTOR_THRESHOLD or m > 63
            or any(t[0] == "c" and ord(t[1]) >= 255 for t in toks)):
        return _re_fallback(dictionary, pattern, escape)

    # padded codepoint matrix: numpy's fixed-width unicode layout IS a
    # codepoint matrix (zero-copy view); padding slots read 0
    arr = np.asarray(dictionary, dtype=np.str_)
    width = arr.dtype.itemsize // 4
    if width == 0:  # every entry is the empty string
        cp = np.zeros((n, 1), np.uint32)
        width = 1
    else:
        cp = arr.view(np.uint32).reshape(n, width)
    lengths = (cp != 0).sum(axis=1)
    # '\x00' detection: numpy's fixed-width storage both pads with and
    # strips trailing NULs, so lengths[i] <= true length always — total
    # equality implies elementwise equality, one scalar vs a per-entry
    # python-length array on this hot path
    if sum(len(str(v)) for v in dictionary) != int(lengths.sum()):
        return _re_fallback(dictionary, pattern, escape)

    pct_bits = np.uint64(0)
    any_bits = np.uint64(0)  # tokens consuming any char: '_' and '%'
    table = np.zeros(256, np.uint64)  # codepoint (clipped) -> matching tokens
    for s, t in enumerate(toks):
        bit = np.uint64(1) << np.uint64(s)
        if t[0] == "%":
            pct_bits |= bit
            any_bits |= bit
        elif t[0] == "_":
            any_bits |= bit
        else:
            table[ord(t[1])] |= bit
    # rows 0..254: literal matches + any-char tokens; row 255 = "other
    # codepoint": only any-char tokens (literals >= 255 were excluded)
    table[1:255] |= any_bits
    table[255] = any_bits
    table[0] = np.uint64(0)  # padding matches nothing

    max_pct_run = 1
    run = 0
    for t in toks:
        run = run + 1 if t[0] == "%" else 0
        max_pct_run = max(max_pct_run, run or 1)

    def eclose(a: np.ndarray) -> np.ndarray:
        # epsilon edges: state s -(e)-> s+1 when token s is '%'
        if not pct_bits:
            return a
        for _ in range(max_pct_run):
            a = a | ((a & pct_bits) << np.uint64(1))
        return a

    one = np.uint64(1)
    active = eclose(np.full(n, one))  # state 0 active (+ epsilon)
    accept_bit = np.uint64(1) << np.uint64(m)
    final = np.where(lengths == 0, active, np.uint64(0))
    for j in range(width):
        c = np.minimum(cp[:, j], 255)
        match = table[c]
        moved = ((active & match) << one) | (active & match & pct_bits)
        active = eclose(moved)
        final = np.where(lengths == j + 1, active, final)
    return (final & accept_bit) != 0
