"""Test support: sqlite correctness oracle, runners, assertion helpers.

Mirrors the reference's ``testing/trino-testing`` module family (H2QueryRunner,
QueryAssertions, DistributedQueryRunner) — shipped in the package, not tests/,
so downstream users get the same harness (SURVEY §4).
"""
