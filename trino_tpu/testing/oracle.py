"""sqlite3-backed correctness oracle.

The reference checks every ``assertQuery(sql)`` against H2 running the same
statement on the same data (testing/trino-testing/.../H2QueryRunner.java:91,
QueryAssertions.java:51).  Here the oracle is the stdlib ``sqlite3``: engine
tables are loaded into sqlite (decimals as REAL, dates as INTEGER epoch-days,
strings decoded from their dictionaries), the SQL is transpiled for the
sqlite dialect (date/interval literals and EXTRACT become integer math and
UDFs), and results are compared as multisets with float tolerance.
"""

from __future__ import annotations

import datetime
import decimal
import math
import re
import sqlite3
from typing import Iterable, Sequence

from ..spi.batch import ColumnBatch
from ..spi.types import DATE, days_to_date

__all__ = ["SqliteOracle", "normalize_rows", "assert_same_rows"]

_EPOCH = datetime.date(1970, 1, 1)


def _to_days(text: str) -> int:
    return (datetime.date.fromisoformat(text) - _EPOCH).days


def _add_months(days: int | None, n: int) -> int | None:
    if days is None:
        return None
    d = _EPOCH + datetime.timedelta(days=days)
    total = d.year * 12 + (d.month - 1) + n
    y, m = divmod(total, 12)
    m += 1
    # clamp to end of month
    if m == 12:
        last = 31
    else:
        last = (datetime.date(y, m + 1, 1) - datetime.timedelta(days=1)).day
    return (datetime.date(y, m, min(d.day, last)) - _EPOCH).days


def _year(days):
    return None if days is None else (_EPOCH + datetime.timedelta(days=days)).year


def _month(days):
    return None if days is None else (_EPOCH + datetime.timedelta(days=days)).month


def _quarter(days):
    return None if days is None else (_month(days) + 2) // 3


def transpile(sql: str) -> str:
    """Rewrite engine SQL into sqlite dialect (dates are INTEGER days)."""
    out = sql
    # date literal +- interval  =>  computed integer / add_months()
    out = re.sub(r"(?i)\bdate\s*'(\d{4}-\d\d-\d\d)'", lambda m: str(_to_days(m.group(1))), out)

    def interval_repl(m):
        lhs, op, n, unit = m.group(1), m.group(2), int(m.group(3)), m.group(4).lower()
        if op == "-":
            n = -n
        if unit == "day":
            return f"({lhs} + {n})"
        months = n * (12 if unit == "year" else 1)
        return f"add_months({lhs}, {months})"

    prev = None
    while prev != out:
        prev = out
        out = re.sub(
            r"(?is)([\w.]+|\([^()]*\)|\d+)\s*([+-])\s*interval\s*'(\d+)'\s*(day|month|year)",
            interval_repl,
            out,
        )
    # fold decimal-literal +/- decimal-literal exactly (sqlite would do it in
    # binary float: 0.06 + 0.01 != 0.07 there, so BETWEEN endpoints miss rows
    # that SQL decimal semantics include).  Folding only fires right after a
    # comparison/BETWEEN/AND token so operator precedence and left-
    # associativity can't change the value (never inside `a - b - c` chains
    # or next to * and /).
    def fold(m):
        a, op, b = decimal.Decimal(m.group(2)), m.group(3), decimal.Decimal(m.group(4))
        return m.group(1) + str(a + b if op == "+" else a - b)

    prev = None
    while prev != out:
        prev = out
        out = re.sub(
            r"(?is)(between\s+|and\s+|[=<>]=?\s*)"
            r"(\d+\.\d+)\s*([+-])\s*(\d+\.\d+)(?!\s*[*/])(?![\w.])",
            fold,
            out,
        )
    out = re.sub(r"(?is)extract\s*\(\s*year\s+from\s+", "tpch_year(", out)
    out = re.sub(r"(?is)extract\s*\(\s*month\s+from\s+", "tpch_month(", out)
    out = re.sub(r"(?is)extract\s*\(\s*quarter\s+from\s+", "tpch_quarter(", out)
    out = re.sub(r"(?i)\bsubstring\s*\(", "substr(", out)
    out = re.sub(r"(?i)\bgreatest\s*\(", "max(", out)
    out = re.sub(r"(?i)\bleast\s*\(", "min(", out)
    out = re.sub(r"(?i)\bif\s*\(", "iif(", out)
    return out


class _VarAgg:
    """Aggregate UDF for the variance/stddev family (matches Trino's
    VarianceAccumulator semantics: *_samp NULL below 2 rows, *_pop 0 for 1)."""

    kind = "var_samp"

    def __init__(self):
        self.n = 0
        self.s = 0.0
        self.q = 0.0

    def step(self, v):
        if v is None:
            return
        v = float(v)
        self.n += 1
        self.s += v
        self.q += v * v

    def finalize(self):
        if self.n == 0:
            return None
        m2 = max(self.q - self.s * self.s / self.n, 0.0)
        if self.kind in ("var_pop", "stddev_pop"):
            var = m2 / self.n
        else:
            if self.n < 2:
                return None
            var = m2 / (self.n - 1)
        return math.sqrt(var) if self.kind.startswith("stddev") else var


def _var_agg(kind_name):
    return type(f"_Agg_{kind_name}", (_VarAgg,), {"kind": kind_name})


class _BoolAgg:
    all_mode = True

    def __init__(self):
        self.acc = None

    def step(self, v):
        if v is None:
            return
        b = bool(v)
        self.acc = b if self.acc is None else (
            (self.acc and b) if self.all_mode else (self.acc or b))

    def finalize(self):
        return None if self.acc is None else int(self.acc)


def _date_trunc(unit, days):
    if days is None:
        return None
    d = _EPOCH + datetime.timedelta(days=days)
    u = unit.lower()
    if u == "year":
        t = datetime.date(d.year, 1, 1)
    elif u == "quarter":
        t = datetime.date(d.year, ((d.month - 1) // 3) * 3 + 1, 1)
    elif u == "month":
        t = datetime.date(d.year, d.month, 1)
    elif u == "week":
        t = d - datetime.timedelta(days=d.weekday())
    else:
        t = d
    return (t - _EPOCH).days


class SqliteOracle:
    def __init__(self):
        self.db = sqlite3.connect(":memory:")
        self.db.create_function("add_months", 2, _add_months, deterministic=True)
        self.db.create_function("tpch_year", 1, _year, deterministic=True)
        self.db.create_function("tpch_month", 1, _month, deterministic=True)
        self.db.create_function("tpch_quarter", 1, _quarter, deterministic=True)
        for k in ("stddev", "stddev_samp", "stddev_pop",
                  "variance", "var_samp", "var_pop"):
            self.db.create_aggregate(k, 1, _var_agg(k))
        self.db.create_aggregate(
            "bool_and", 1, type("_BA", (_BoolAgg,), {"all_mode": True}))
        self.db.create_aggregate(
            "bool_or", 1, type("_BO", (_BoolAgg,), {"all_mode": False}))
        self.db.create_function("date_trunc", 2, _date_trunc, deterministic=True)
        self.db.create_function(
            "day_of_week", 1,
            lambda d: None if d is None else
            (_EPOCH + datetime.timedelta(days=d)).isoweekday(),
            deterministic=True)
        self.db.create_function(
            "day_of_year", 1,
            lambda d: None if d is None else
            (_EPOCH + datetime.timedelta(days=d)).timetuple().tm_yday,
            deterministic=True)
        self.db.create_function(
            "strpos", 2,
            lambda s, sub: None if s is None or sub is None else s.find(sub) + 1,
            deterministic=True)
        self.db.create_function(
            "starts_with", 2,
            lambda s, p: None if s is None or p is None else int(s.startswith(p)),
            deterministic=True)
        self.db.create_function(
            "reverse", 1, lambda s: None if s is None else s[::-1],
            deterministic=True)
        self.db.create_function(
            "concat", -1,
            lambda *a: None if any(x is None for x in a) else
            "".join(str(x) for x in a),
            deterministic=True)
        self.db.create_function(
            "sign", 1,
            lambda v: None if v is None else (v > 0) - (v < 0),
            deterministic=True)
        self.db.create_function(
            "mod", 2,
            lambda a, b: None if a is None or b is None or b == 0 else
            math.fmod(a, b) if isinstance(a, float) or isinstance(b, float)
            else int(math.fmod(a, b)),
            deterministic=True)
        self.db.create_aggregate("count_if", 1, type("_CI", (), {
            "__init__": lambda s: setattr(s, "n", 0),
            "step": lambda s, v: setattr(s, "n", s.n + bool(v)),
            "finalize": lambda s: s.n,
        }))

    def load_table(self, name: str, batches: Iterable[ColumnBatch]) -> None:
        batches = list(batches)
        first = batches[0]
        cols = ", ".join(f'"{c}"' for c in first.names)
        self.db.execute(f'create table "{name}" ({cols})')
        placeholders = ", ".join("?" * first.num_columns)
        for b in batches:
            rows = []
            for row in b.to_pylist():
                rows.append(tuple(_to_sqlite(v) for v in row))
            self.db.executemany(f'insert into "{name}" values ({placeholders})', rows)
        self.db.commit()

    def query(self, sql: str) -> list[tuple]:
        return list(self.db.execute(transpile(sql)))


def _to_sqlite(v):
    if isinstance(v, decimal.Decimal):
        return float(v)
    if isinstance(v, datetime.date):
        return (v - _EPOCH).days
    return v


def normalize_rows(rows: Sequence[tuple], float_digits: int = 2) -> list[tuple]:
    """Normalize to comparable form: dates -> epoch days, Decimal/float ->
    rounded float, None kept."""
    out = []
    for row in rows:
        norm = []
        for v in row:
            if isinstance(v, datetime.date):
                norm.append((v - _EPOCH).days)
            elif isinstance(v, decimal.Decimal):
                norm.append(round(float(v), float_digits))
            elif isinstance(v, float):
                if math.isnan(v):
                    norm.append("NaN")
                else:
                    norm.append(round(v, float_digits))
            elif isinstance(v, bool):
                norm.append(int(v))
            else:
                norm.append(v)
        out.append(tuple(norm))
    return out


def assert_same_rows(actual: Sequence[tuple], expected: Sequence[tuple],
                     ordered: bool = False, float_digits: int = 2) -> None:
    a = normalize_rows(actual, float_digits)
    e = normalize_rows(expected, float_digits)
    if not ordered:
        # numbers sort together regardless of int/float representation
        # (sqlite keeps literal ints where the engine produces decimals)
        def key(r):
            out = []
            for x in r:
                if x is None:
                    out.append((1, "", 0.0, ""))
                elif isinstance(x, (int, float)):
                    out.append((0, "num", float(x), ""))
                else:
                    out.append((0, str(type(x)), 0.0, str(x)))
            return tuple(out)

        a = sorted(a, key=key)
        e = sorted(e, key=key)
    assert len(a) == len(e), f"row count {len(a)} != expected {len(e)}\nactual head: {a[:5]}\nexpected head: {e[:5]}"
    for i, (ra, re_) in enumerate(zip(a, e)):
        assert _row_eq(ra, re_), f"row {i} differs:\n  actual   {ra}\n  expected {re_}"


def _row_eq(a: tuple, b: tuple) -> bool:
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if isinstance(x, int) and isinstance(y, int):
            if x != y:
                return False
        elif isinstance(x, (int, float)) and isinstance(y, (int, float)):
            # representations may differ (sqlite int vs engine decimal/float)
            if not math.isclose(x, y, rel_tol=1e-6, abs_tol=1e-2):
                return False
        elif x != y:
            return False
    return True
