"""Randomized fault-injection soak harness ("chaos certification").

Seeded scenario generator that drives the engine-level FailureInjector
(TASK_FAILURE / TASK_STALL / TASK_OOM / GET_RESULTS_FAILURE /
PROCESS_EXIT) plus live coordinator-driven drains under a sustained
TPC-H query mix, and checks the invariant the resilience plane promises:

    every query either returns oracle-correct rows (possibly after a
    classified retry under retry_policy=QUERY), or fails fast with a
    correctly classified USER / unretryable error.  Nothing hangs.

Scenarios are a pure function of ``(base_seed, scenario_index)`` —
``random.Random(seed)`` picks the SQL, the fault kind, the target task
and the drain victims — so any failing scenario replays exactly from
its seed.  Two modes:

- ``inproc``  : DistributedQueryRunner (threads), cheap; covers the
  in-process injection sites, speculation and logical drain/restore.
- ``process`` : ProcessDistributedQueryRunner (real worker processes),
  expensive; adds PROCESS_EXIT hard-kills and real PUT /v1/shutdown
  drains with worker replacement mid-query.

Every query runs under a watchdog thread: a query that neither returns
nor raises within the budget is recorded as outcome="hang" (the soak's
acceptance gate requires zero of those).

Entry points: ``run_scenario`` (one seeded scenario) and ``run_chaos``
(the full soak; ``bench.py --chaos`` wraps it and writes BENCH_r09.json).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from ..connectors.catalog import default_catalog
from ..execution.distributed_runner import DistributedQueryRunner
from ..execution.failure_injector import (
    GET_RESULTS_FAILURE,
    PROCESS_EXIT,
    TASK_FAILURE,
    TASK_OOM,
    TASK_STALL,
    FailureInjector,
)
from ..runner import Session
from .oracle import SqliteOracle, assert_same_rows

__all__ = ["QUERY_MIX", "USER_ERROR_SQL", "build_expected",
           "run_scenario", "run_chaos"]

CATALOG_SPEC = {
    "factory": "trino_tpu.connectors.catalog:default_catalog",
    "kwargs": {"scale_factor": 0.01},
}

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}

_TABLES = ["customer", "orders", "lineitem"]

# Sustained mix: scans, multi-key aggregation, filtered join — all
# checkable against the sqlite oracle with an unordered row compare.
QUERY_MIX = [
    "select count(*) from lineitem",
    "select l_returnflag, l_linestatus, count(*), sum(l_quantity) "
    "from lineitem group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus",
    "select o_orderstatus, count(*), sum(o_totalprice) from orders "
    "group by o_orderstatus order by o_orderstatus",
    "select c_mktsegment, count(*), sum(c_acctbal) from customer "
    "group by c_mktsegment order by c_mktsegment",
    "select o_orderpriority, count(*) from orders, customer "
    "where o_custkey = c_custkey and c_mktsegment = 'BUILDING' "
    "group by o_orderpriority order by o_orderpriority",
    "select count(*), sum(o_totalprice) from orders "
    "where o_totalprice > 100000",
]

# USER-classified error: must fail fast with ZERO retries even while
# faults are being injected around it.
USER_ERROR_SQL = \
    "select o_orderkey / (o_orderkey - o_orderkey) from orders"

# Fault menu per mode.  "none" keeps a healthy baseline inside every
# scenario; "drain" is a live coordinator-driven drain mid-query.
_INPROC_FAULTS = ["none", "none", TASK_FAILURE, TASK_STALL, TASK_OOM,
                  GET_RESULTS_FAILURE, "drain"]
_PROCESS_FAULTS = _INPROC_FAULTS + [PROCESS_EXIT]


def build_expected() -> dict:
    """Oracle rows for every SQL in QUERY_MIX (computed once per soak —
    expected rows are a pure function of the sf=0.01 dataset)."""
    catalog = default_catalog(scale_factor=0.01)
    conn = catalog.connector("tpch")
    oracle = SqliteOracle()
    for t in _TABLES:
        cols = conn.get_table_schema(t).column_names()
        batches = []
        for s in conn.get_splits(t, 2, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    return {sql: oracle.query(sql) for sql in QUERY_MIX}


def _execute_watched(runner, sql: str, timeout_s: float):
    """Run ``runner.execute(sql)`` under a watchdog.  Returns
    (rows | None, exception | None, hung: bool, wall_s)."""
    holder: dict = {}

    def _work():
        try:
            holder["rows"] = runner.execute(sql).rows()
        except BaseException as e:  # noqa: BLE001 - classified by caller
            holder["exc"] = e

    t0 = time.monotonic()
    th = threading.Thread(target=_work, daemon=True, name="chaos-query")
    th.start()
    th.join(timeout_s)
    wall = time.monotonic() - t0
    if th.is_alive():
        return None, None, True, wall
    return holder.get("rows"), holder.get("exc"), False, wall


def _classify_outcome(sql, rows, exc, hung, retried, expected):
    if hung:
        return "hang", "watchdog timeout"
    if sql == USER_ERROR_SQL:
        if exc is not None and "DIVISION_BY_ZERO" in str(exc):
            return "classified_failure", None
        return "unexpected", f"user error not classified: {exc!r}"
    if exc is not None:
        return "unexpected", f"{type(exc).__name__}: {exc}"
    try:
        assert_same_rows(rows, expected[sql], ordered=False)
    except AssertionError as e:
        return "unexpected", f"oracle mismatch: {e}"
    return ("ok_after_retry" if retried else "ok"), None


def run_scenario(seed: int, mode: str = "inproc", n_queries: int = 8,
                 expected: Optional[dict] = None,
                 query_timeout_s: Optional[float] = None) -> dict:
    """One seeded chaos scenario: a fresh 2-worker runner, ``n_queries``
    queries from the mix, each with a seeded fault (or none), plus live
    drains.  Returns {"seed", "mode", "outcomes": [...], counts...}."""
    if expected is None:
        expected = build_expected()
    rng = random.Random(seed)
    timeout = query_timeout_s or (30.0 if mode == "inproc" else 90.0)
    inj = FailureInjector()
    session = Session(node_count=2, retry_policy="QUERY",
                      failure_injector=inj, retry_initial_delay_s=0.01,
                      heartbeat_interval_s=0.2, speculation=True,
                      drain_timeout_s=5.0)
    if mode == "inproc":
        runner = DistributedQueryRunner(
            default_catalog(scale_factor=0.01), worker_count=2,
            session=session)
        faults = _INPROC_FAULTS
    else:
        from ..execution.remote import ProcessDistributedQueryRunner
        runner = ProcessDistributedQueryRunner(
            CATALOG_SPEC, worker_count=2, session=session,
            env_overrides=_ENV)
        faults = _PROCESS_FAULTS

    from ..caching import result_cache

    outcomes = []
    try:
        # the soak certifies *execution* under faults — a cached result for
        # a repeated mix query would skip the fragment path and leave the
        # armed injection waiting for the wrong query
        with result_cache.disabled():
            for qi in range(n_queries):
                sql = (USER_ERROR_SQL if rng.random() < 0.12
                       else rng.choice(QUERY_MIX))
                fault = rng.choice(faults)
                task_index = rng.randrange(2)
                if fault == TASK_STALL:
                    inj.inject(TASK_STALL, fragment_id=None,
                               task_index=task_index, attempt=0, times=1,
                               stall_s=round(0.3 + rng.random() * 0.5, 2))
                elif fault not in ("none", "drain"):
                    inj.inject(fault, fragment_id=None,
                               task_index=task_index, attempt=0, times=1)

                retries_before = runner.resilience.query_retries
                if fault == "drain":
                    rows, exc, hung, wall = _run_with_drain(
                        runner, sql, mode, rng, timeout)
                else:
                    rows, exc, hung, wall = _execute_watched(
                        runner, sql, timeout)
                retried = runner.resilience.query_retries > retries_before
                outcome, detail = _classify_outcome(
                    sql, rows, exc, hung, retried, expected)
                outcomes.append({
                    "query": qi, "sql": sql, "fault": fault,
                    "outcome": outcome, "detail": detail,
                    "wall_s": round(wall, 3), "retried": retried,
                })
                if outcome == "hang":
                    break  # the runner is wedged; stop the scenario here
    finally:
        close = getattr(runner, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass

    counts: dict = {}
    for o in outcomes:
        counts[o["outcome"]] = counts.get(o["outcome"], 0) + 1
    return {"seed": seed, "mode": mode, "outcomes": outcomes,
            "counts": counts,
            "speculative_starts": getattr(runner, "speculative_starts", 0),
            "speculative_wins": getattr(runner, "speculative_wins", 0)}


def _run_with_drain(runner, sql, mode, rng, timeout_s):
    """Run a query and drain a seeded-random worker mid-flight.  In-proc
    the drain is logical (stop scheduling; restore afterwards); process
    mode issues a real PUT /v1/shutdown and replaces the worker."""
    holder: dict = {}

    def _work():
        try:
            holder["rows"] = runner.execute(sql).rows()
        except BaseException as e:  # noqa: BLE001
            holder["exc"] = e

    t0 = time.monotonic()
    th = threading.Thread(target=_work, daemon=True, name="chaos-query")
    th.start()
    time.sleep(0.02 + rng.random() * 0.15)
    if mode == "inproc":
        victim = f"worker-{rng.randrange(2)}"
        try:
            runner.drain_worker(victim)
            th.join(timeout_s)
        finally:
            runner.restore_worker(victim)
    else:
        victim = runner.workers[rng.randrange(2)]
        runner.drain_worker(victim, replace=True)
        th.join(timeout_s)
    wall = time.monotonic() - t0
    if th.is_alive():
        return None, None, True, wall
    return holder.get("rows"), holder.get("exc"), False, wall


def run_chaos(n_scenarios: int = 25, base_seed: int = 1009,
              inproc_queries: int = 8, process_queries: int = 4,
              process_stride: int = 4, verbose: bool = True) -> dict:
    """The full soak.  Every ``process_stride``-th scenario runs against
    real worker processes; the rest are in-process.  Returns a summary
    with per-scenario records and the acceptance booleans."""
    expected = build_expected()
    scenarios = []
    for i in range(n_scenarios):
        mode = ("process" if process_stride and i % process_stride
                == process_stride - 1 else "inproc")
        n_q = process_queries if mode == "process" else inproc_queries
        t0 = time.monotonic()
        rec = run_scenario(base_seed + i, mode=mode, n_queries=n_q,
                           expected=expected)
        rec["scenario"] = i
        rec["wall_s"] = round(time.monotonic() - t0, 2)
        scenarios.append(rec)
        if verbose:
            print(f"  chaos scenario {i:2d} seed={base_seed + i} "
                  f"mode={mode:7s} {rec['counts']} "
                  f"({rec['wall_s']:.1f}s)", flush=True)

    totals: dict = {}
    retry_walls = []
    for rec in scenarios:
        for k, v in rec["counts"].items():
            totals[k] = totals.get(k, 0) + v
        retry_walls += [o["wall_s"] for o in rec["outcomes"]
                        if o["retried"]]
    n_queries = sum(len(r["outcomes"]) for r in scenarios)
    return {
        "n_scenarios": n_scenarios,
        "base_seed": base_seed,
        "n_queries": n_queries,
        "totals": totals,
        "hangs": totals.get("hang", 0),
        "unexpected": totals.get("unexpected", 0),
        "max_recovery_s": round(max(retry_walls), 3) if retry_walls
        else 0.0,
        "all_accounted": (totals.get("hang", 0) == 0
                          and totals.get("unexpected", 0) == 0),
        "scenarios": scenarios,
    }
