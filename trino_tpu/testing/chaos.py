"""Randomized fault-injection soak harness ("chaos certification").

Seeded scenario generator that drives the engine-level FailureInjector
(TASK_FAILURE / TASK_STALL / TASK_OOM / GET_RESULTS_FAILURE /
PROCESS_EXIT) plus live coordinator-driven drains under a sustained
TPC-H query mix, and checks the invariant the resilience plane promises:

    every query either returns oracle-correct rows (possibly after a
    classified retry under retry_policy=QUERY), or fails fast with a
    correctly classified USER / unretryable error.  Nothing hangs.

Scenarios are a pure function of ``(base_seed, scenario_index)`` —
``random.Random(seed)`` picks the SQL, the fault kind, the target task
and the drain victims — so any failing scenario replays exactly from
its seed.  Two modes:

- ``inproc``  : DistributedQueryRunner (threads), cheap; covers the
  in-process injection sites, speculation and logical drain/restore.
- ``process`` : ProcessDistributedQueryRunner (real worker processes),
  expensive; adds PROCESS_EXIT hard-kills and real PUT /v1/shutdown
  drains with worker replacement mid-query.

Every query runs under a watchdog thread: a query that neither returns
nor raises within the budget is recorded as outcome="hang" (the soak's
acceptance gate requires zero of those).

Entry points: ``run_scenario`` (one seeded scenario) and ``run_chaos``
(the full soak; ``bench.py --chaos`` wraps it and writes BENCH_r09.json).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from ..connectors.catalog import default_catalog
from ..execution.distributed_runner import DistributedQueryRunner
from ..execution.failure_injector import (
    GET_RESULTS_FAILURE,
    PROCESS_EXIT,
    SPOOL_CORRUPTION,
    TASK_FAILURE,
    TASK_OOM,
    TASK_STALL,
    FailureInjector,
)
from ..runner import Session
from .oracle import SqliteOracle, assert_same_rows

__all__ = ["QUERY_MIX", "USER_ERROR_SQL", "build_expected",
           "run_scenario", "run_chaos", "run_fte_scenario", "run_fte_chaos",
           "run_coordinator_kill_drill", "run_ha_takeover_drill"]

CATALOG_SPEC = {
    "factory": "trino_tpu.connectors.catalog:default_catalog",
    "kwargs": {"scale_factor": 0.01},
}

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}

_TABLES = ["customer", "orders", "lineitem"]

# Sustained mix: scans, multi-key aggregation, filtered join — all
# checkable against the sqlite oracle with an unordered row compare.
QUERY_MIX = [
    "select count(*) from lineitem",
    "select l_returnflag, l_linestatus, count(*), sum(l_quantity) "
    "from lineitem group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus",
    "select o_orderstatus, count(*), sum(o_totalprice) from orders "
    "group by o_orderstatus order by o_orderstatus",
    "select c_mktsegment, count(*), sum(c_acctbal) from customer "
    "group by c_mktsegment order by c_mktsegment",
    "select o_orderpriority, count(*) from orders, customer "
    "where o_custkey = c_custkey and c_mktsegment = 'BUILDING' "
    "group by o_orderpriority order by o_orderpriority",
    "select count(*), sum(o_totalprice) from orders "
    "where o_totalprice > 100000",
]

# USER-classified error: must fail fast with ZERO retries even while
# faults are being injected around it.
USER_ERROR_SQL = \
    "select o_orderkey / (o_orderkey - o_orderkey) from orders"

# Fault menu per mode.  "none" keeps a healthy baseline inside every
# scenario; "drain" is a live coordinator-driven drain mid-query.
_INPROC_FAULTS = ["none", "none", TASK_FAILURE, TASK_STALL, TASK_OOM,
                  GET_RESULTS_FAILURE, "drain"]
_PROCESS_FAULTS = _INPROC_FAULTS + [PROCESS_EXIT]
# FTE (retry_policy=TASK) leg: the streaming menu minus drains (FTE's
# stage-by-stage loop has no placement to drain in-process) plus
# SPOOL_CORRUPTION — a byte flipped inside a committed spool part file
# right before a consumer reads it, which must surface as a CRC-classified
# SpoolCorruptionError and re-execute only the corrupted producer
_FTE_FAULTS = ["none", "none", TASK_FAILURE, TASK_STALL, TASK_OOM,
               GET_RESULTS_FAILURE, SPOOL_CORRUPTION]


def build_expected() -> dict:
    """Oracle rows for every SQL in QUERY_MIX (computed once per soak —
    expected rows are a pure function of the sf=0.01 dataset)."""
    catalog = default_catalog(scale_factor=0.01)
    conn = catalog.connector("tpch")
    oracle = SqliteOracle()
    for t in _TABLES:
        cols = conn.get_table_schema(t).column_names()
        batches = []
        for s in conn.get_splits(t, 2, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    return {sql: oracle.query(sql) for sql in QUERY_MIX}


def _execute_watched(runner, sql: str, timeout_s: float):
    """Run ``runner.execute(sql)`` under a watchdog.  Returns
    (rows | None, exception | None, hung: bool, wall_s)."""
    holder: dict = {}

    def _work():
        try:
            holder["rows"] = runner.execute(sql).rows()
        except BaseException as e:  # noqa: BLE001 - classified by caller
            holder["exc"] = e

    t0 = time.monotonic()
    th = threading.Thread(target=_work, daemon=True, name="chaos-query")
    th.start()
    th.join(timeout_s)
    wall = time.monotonic() - t0
    if th.is_alive():
        return None, None, True, wall
    return holder.get("rows"), holder.get("exc"), False, wall


def _classify_outcome(sql, rows, exc, hung, retried, expected):
    if hung:
        return "hang", "watchdog timeout"
    if sql == USER_ERROR_SQL:
        if exc is not None and "DIVISION_BY_ZERO" in str(exc):
            return "classified_failure", None
        return "unexpected", f"user error not classified: {exc!r}"
    if exc is not None:
        return "unexpected", f"{type(exc).__name__}: {exc}"
    try:
        assert_same_rows(rows, expected[sql], ordered=False)
    except AssertionError as e:
        return "unexpected", f"oracle mismatch: {e}"
    return ("ok_after_retry" if retried else "ok"), None


def run_scenario(seed: int, mode: str = "inproc", n_queries: int = 8,
                 expected: Optional[dict] = None,
                 query_timeout_s: Optional[float] = None) -> dict:
    """One seeded chaos scenario: a fresh 2-worker runner, ``n_queries``
    queries from the mix, each with a seeded fault (or none), plus live
    drains.  Returns {"seed", "mode", "outcomes": [...], counts...}."""
    if expected is None:
        expected = build_expected()
    rng = random.Random(seed)
    timeout = query_timeout_s or (30.0 if mode == "inproc" else 90.0)
    inj = FailureInjector()
    session = Session(node_count=2, retry_policy="QUERY",
                      failure_injector=inj, retry_initial_delay_s=0.01,
                      heartbeat_interval_s=0.2, speculation=True,
                      drain_timeout_s=5.0)
    if mode == "inproc":
        runner = DistributedQueryRunner(
            default_catalog(scale_factor=0.01), worker_count=2,
            session=session)
        faults = _INPROC_FAULTS
    else:
        from ..execution.remote import ProcessDistributedQueryRunner
        runner = ProcessDistributedQueryRunner(
            CATALOG_SPEC, worker_count=2, session=session,
            env_overrides=_ENV)
        faults = _PROCESS_FAULTS

    from ..caching import result_cache

    outcomes = []
    try:
        # the soak certifies *execution* under faults — a cached result for
        # a repeated mix query would skip the fragment path and leave the
        # armed injection waiting for the wrong query
        with result_cache.disabled():
            for qi in range(n_queries):
                sql = (USER_ERROR_SQL if rng.random() < 0.12
                       else rng.choice(QUERY_MIX))
                fault = rng.choice(faults)
                task_index = rng.randrange(2)
                if fault == TASK_STALL:
                    inj.inject(TASK_STALL, fragment_id=None,
                               task_index=task_index, attempt=0, times=1,
                               stall_s=round(0.3 + rng.random() * 0.5, 2))
                elif fault not in ("none", "drain"):
                    inj.inject(fault, fragment_id=None,
                               task_index=task_index, attempt=0, times=1)

                retries_before = runner.resilience.query_retries
                if fault == "drain":
                    rows, exc, hung, wall = _run_with_drain(
                        runner, sql, mode, rng, timeout)
                else:
                    rows, exc, hung, wall = _execute_watched(
                        runner, sql, timeout)
                retried = runner.resilience.query_retries > retries_before
                outcome, detail = _classify_outcome(
                    sql, rows, exc, hung, retried, expected)
                outcomes.append({
                    "query": qi, "sql": sql, "fault": fault,
                    "outcome": outcome, "detail": detail,
                    "wall_s": round(wall, 3), "retried": retried,
                })
                if outcome == "hang":
                    break  # the runner is wedged; stop the scenario here
    finally:
        close = getattr(runner, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass

    counts: dict = {}
    for o in outcomes:
        counts[o["outcome"]] = counts.get(o["outcome"], 0) + 1
    return {"seed": seed, "mode": mode, "outcomes": outcomes,
            "counts": counts,
            "speculative_starts": getattr(runner, "speculative_starts", 0),
            "speculative_wins": getattr(runner, "speculative_wins", 0)}


def _run_with_drain(runner, sql, mode, rng, timeout_s):
    """Run a query and drain a seeded-random worker mid-flight.  In-proc
    the drain is logical (stop scheduling; restore afterwards); process
    mode issues a real PUT /v1/shutdown and replaces the worker."""
    holder: dict = {}

    def _work():
        try:
            holder["rows"] = runner.execute(sql).rows()
        except BaseException as e:  # noqa: BLE001
            holder["exc"] = e

    t0 = time.monotonic()
    th = threading.Thread(target=_work, daemon=True, name="chaos-query")
    th.start()
    time.sleep(0.02 + rng.random() * 0.15)
    if mode == "inproc":
        victim = f"worker-{rng.randrange(2)}"
        try:
            runner.drain_worker(victim)
            th.join(timeout_s)
        finally:
            runner.restore_worker(victim)
    else:
        victim = runner.workers[rng.randrange(2)]
        runner.drain_worker(victim, replace=True)
        th.join(timeout_s)
    wall = time.monotonic() - t0
    if th.is_alive():
        return None, None, True, wall
    return holder.get("rows"), holder.get("exc"), False, wall


def run_fte_scenario(seed: int, n_queries: int = 6,
                     expected: Optional[dict] = None,
                     query_timeout_s: float = 45.0) -> dict:
    """One seeded FTE chaos scenario: a fresh 2-worker runner under
    ``retry_policy="TASK"``, each query with a seeded fault from the FTE
    menu (including SPOOL_CORRUPTION bit flips on committed spool files).
    The acceptance invariant is the streaming soak's: every query is
    oracle-correct, classified, or — never — hung."""
    from ..telemetry import metrics as tm

    if expected is None:
        expected = build_expected()
    rng = random.Random(seed)
    inj = FailureInjector()
    session = Session(node_count=2, retry_policy="TASK",
                      failure_injector=inj, task_retry_attempts=4,
                      fte_speculative=True, fte_speculative_delay_s=0.3)
    runner = DistributedQueryRunner(
        default_catalog(scale_factor=0.01), worker_count=2,
        session=session)

    from ..caching import result_cache

    outcomes = []
    with result_cache.disabled():
        for qi in range(n_queries):
            sql = (USER_ERROR_SQL if rng.random() < 0.12
                   else rng.choice(QUERY_MIX))
            fault = rng.choice(_FTE_FAULTS)
            task_index = rng.randrange(2)
            if fault == TASK_STALL:
                inj.inject(TASK_STALL, fragment_id=None,
                           task_index=task_index, attempt=0, times=1,
                           stall_s=round(0.5 + rng.random() * 0.8, 2))
            elif fault != "none":
                inj.inject(fault, fragment_id=None,
                           task_index=task_index, attempt=0, times=1)
            retries_before = tm.FTE_ATTEMPT_RETRIES.value()
            corrupt_before = tm.FTE_SPOOL_CORRUPTIONS.value()
            rows, exc, hung, wall = _execute_watched(
                runner, sql, query_timeout_s)
            retried = tm.FTE_ATTEMPT_RETRIES.value() > retries_before
            outcome, detail = _classify_outcome(
                sql, rows, exc, hung, retried, expected)
            outcomes.append({
                "query": qi, "sql": sql, "fault": fault,
                "outcome": outcome, "detail": detail,
                "wall_s": round(wall, 3), "retried": retried,
                "spool_corruption_repairs":
                    tm.FTE_SPOOL_CORRUPTIONS.value() - corrupt_before,
            })
            if outcome == "hang":
                break

    counts: dict = {}
    for o in outcomes:
        counts[o["outcome"]] = counts.get(o["outcome"], 0) + 1
    return {"seed": seed, "mode": "fte", "outcomes": outcomes,
            "counts": counts}


def run_fte_chaos(n_scenarios: int = 12, base_seed: int = 1515,
                  fte_queries: int = 6, verbose: bool = True) -> dict:
    """The FTE chaos leg: seeded scenarios over the FTE fault menu.
    Same acceptance booleans as ``run_chaos`` (PR-9 bar: 100%% of queries
    accounted, zero hangs)."""
    expected = build_expected()
    scenarios = []
    for i in range(n_scenarios):
        t0 = time.monotonic()
        rec = run_fte_scenario(base_seed + i, n_queries=fte_queries,
                               expected=expected)
        rec["scenario"] = i
        rec["wall_s"] = round(time.monotonic() - t0, 2)
        scenarios.append(rec)
        if verbose:
            print(f"  fte chaos scenario {i:2d} seed={base_seed + i} "
                  f"{rec['counts']} ({rec['wall_s']:.1f}s)", flush=True)
    totals: dict = {}
    for rec in scenarios:
        for k, v in rec["counts"].items():
            totals[k] = totals.get(k, 0) + v
    n_queries = sum(len(r["outcomes"]) for r in scenarios)
    return {
        "n_scenarios": n_scenarios,
        "base_seed": base_seed,
        "n_queries": n_queries,
        "totals": totals,
        "hangs": totals.get("hang", 0),
        "unexpected": totals.get("unexpected", 0),
        "all_accounted": (totals.get("hang", 0) == 0
                          and totals.get("unexpected", 0) == 0),
        "scenarios": scenarios,
    }


# ---------------------------------------------------- coordinator kill -9
_DRILL_SQL = ("select l_returnflag, l_linestatus, count(*), "
              "sum(l_quantity) from lineitem group by l_returnflag, "
              "l_linestatus order by l_returnflag, l_linestatus")


def _coordinator_child() -> None:
    """Subprocess entry for the coordinator-kill drill: boot a 2-worker
    FTE coordinator behind the HTTP statement protocol, write the bound
    port to ``CHAOS_PORT_FILE`` (atomic rename), and serve until killed.
    ``CHAOS_STALL_S`` arms a one-shot TASK_STALL on task 0 of the first
    stage scheduled — the deterministic 'mid-query' the parent kills
    into; with ``fte_speculative`` off nothing can rescue the stall, so
    the kill is guaranteed to land with the query in flight."""
    import os

    from ..connectors.catalog import default_catalog as _catalog
    from ..execution.distributed_runner import DistributedQueryRunner as _R
    from ..execution.failure_injector import FailureInjector as _Inj
    from ..execution.failure_injector import TASK_STALL as _STALL
    from ..runner import Session as _S
    from ..server.protocol import TrinoTpuServer

    inj = None
    stall_s = float(os.environ.get("CHAOS_STALL_S", "0") or 0)
    if stall_s > 0:
        inj = _Inj()
        inj.inject(_STALL, fragment_id=None, task_index=0, attempt=0,
                   times=1, stall_s=stall_s)
    session = _S(node_count=2, retry_policy="TASK", fte_speculative=False,
                 failure_injector=inj)
    runner = _R(_catalog(scale_factor=0.01), worker_count=2,
                session=session)
    srv = TrinoTpuServer(runner).start()
    port_file = os.environ["CHAOS_PORT_FILE"]
    with open(port_file + ".tmp", "w", encoding="utf-8") as f:
        f.write(str(srv.address[1]))
    os.replace(port_file + ".tmp", port_file)
    while True:
        time.sleep(1.0)


def _http_json(method: str, url: str, body: Optional[bytes] = None,
               timeout: float = 10.0) -> dict:
    import json
    from urllib.request import Request, urlopen

    req = Request(url, data=body, method=method)
    with urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def run_coordinator_kill_drill(stall_s: float = 300.0,
                               boot_timeout_s: float = 180.0,
                               finish_timeout_s: float = 180.0,
                               workdir: Optional[str] = None) -> dict:
    """The tentpole drill: kill -9 a coordinator mid-FTE-query, restart
    it, and certify durable recovery end to end.

    Epoch 1 boots a subprocess coordinator with a one-shot un-rescuable
    stall, submits ``_DRILL_SQL`` over POST /v1/statement, waits (by
    reading the query-state WAL) until at least one task attempt has
    committed, then SIGKILLs the process.  Epoch 2 boots a fresh
    coordinator against the same state/spool dirs; its dispatcher must
    rehydrate the query under the ORIGINAL id, resume from the committed-
    attempt map, and finish.  Asserts, from the WAL's attempt counters:
    committed attempts were NEVER re-executed.  Returns the full record
    (also the shape tests/test_query_state.py consumes)."""
    import os
    import shutil
    import signal
    import subprocess
    import sys
    import tempfile

    from ..execution import query_state

    work = workdir or tempfile.mkdtemp(prefix="trino-tpu-kill-drill-")
    state_dir = os.path.join(work, "query-state")
    spool_dir = os.path.join(work, "spool")
    port_file = os.path.join(work, "port")
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "TRINO_TPU_QUERY_STATE": "1",
        "TRINO_TPU_QUERY_STATE_DIR": state_dir,
        "TRINO_TPU_SPOOL_DIR": spool_dir,
        "TRINO_TPU_RESULT_CACHE": "0",
        "CHAOS_PORT_FILE": port_file,
        "PYTHONPATH": repo_root + os.pathsep + env.get("PYTHONPATH", ""),
    })
    child_cmd = [sys.executable, "-c",
                 "from trino_tpu.testing.chaos import _coordinator_child; "
                 "_coordinator_child()"]

    def _boot(extra_env: dict) -> tuple:
        try:
            os.remove(port_file)
        except OSError:
            pass
        proc = subprocess.Popen(child_cmd, env={**env, **extra_env},
                                cwd=repo_root)
        deadline = time.monotonic() + boot_timeout_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"coordinator child died at boot (rc={proc.returncode})")
            if os.path.exists(port_file):
                with open(port_file, encoding="utf-8") as f:
                    return proc, int(f.read().strip())
            time.sleep(0.1)
        proc.kill()
        raise TimeoutError("coordinator child never wrote its port")

    record: dict = {"sql": _DRILL_SQL, "workdir": work}
    proc2 = None
    proc1, port1 = _boot({"CHAOS_STALL_S": str(stall_s)})
    try:
        # epoch 1: submit, wait for >=1 committed attempt, kill -9
        sub = _http_json("POST", f"http://127.0.0.1:{port1}/v1/statement",
                         _DRILL_SQL.encode("utf-8"))
        qid = sub["id"]
        record["query_id"] = qid
        wal_path = None
        pq = None
        deadline = time.monotonic() + boot_timeout_s
        while time.monotonic() < deadline:
            walls = [os.path.join(state_dir, n)
                     for n in os.listdir(state_dir)] \
                if os.path.isdir(state_dir) else []
            walls = [w for w in walls if w.endswith(".wal")]
            if walls:
                wal_path = walls[0]
                pq = query_state.load(wal_path)
                if pq is not None and len(pq.committed) >= 1:
                    break
            time.sleep(0.1)
        if pq is None or not pq.committed:
            raise TimeoutError("no committed attempt before the kill")
        committed_at_kill = dict(pq.committed)
        starts_at_kill = dict(pq.attempt_counts)
        record["committed_at_kill"] = len(committed_at_kill)
        os.kill(proc1.pid, signal.SIGKILL)
        proc1.wait(timeout=30)

        # epoch 2: fresh coordinator, same dirs — recovery must finish the
        # query under its original id
        proc2, port2 = _boot({})
        rows: list = []
        state = None
        token = 0
        deadline = time.monotonic() + finish_timeout_s
        while time.monotonic() < deadline:
            out = _http_json(
                "GET", f"http://127.0.0.1:{port2}/v1/statement/{qid}/{token}")
            state = out.get("stats", {}).get("state")
            if state == "FAILED":
                record["error"] = out.get("error")
                break
            rows += out.get("data", [])
            nxt = out.get("nextUri")
            if state == "FINISHED":
                if not nxt:
                    break
                token += 1
                continue
            time.sleep(0.2)
        record["state"] = state
        record["rows"] = rows

        final = query_state.load(wal_path)
        re_executed = {
            f"f{fid}_t{t}": final.attempt_counts.get((fid, t), 0)
            - starts_at_kill.get((fid, t), 0)
            for (fid, t) in committed_at_kill
            if final.attempt_counts.get((fid, t), 0)
            > starts_at_kill.get((fid, t), 0)
        }
        record["committed_reexecuted"] = re_executed
        record["resumed_attempt_starts"] = {
            f"f{fid}_t{t}": n - starts_at_kill.get((fid, t), 0)
            for (fid, t), n in final.attempt_counts.items()
            if n > starts_at_kill.get((fid, t), 0)
        }
        record["wal_ended"] = final.ended

        # spool GC: the resumed query's root must be reclaimed at its end
        spool_root = pq.spool_root
        deadline = time.monotonic() + 30.0
        while os.path.isdir(spool_root) and time.monotonic() < deadline:
            time.sleep(0.2)
        record["spool_reclaimed"] = not os.path.isdir(spool_root)
        record["pass"] = (state == "FINISHED" and not re_executed
                         and record["spool_reclaimed"]
                         and final.ended == "FINISHED")
        return record
    finally:
        for p in (proc1, proc2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=15)
        if workdir is None:
            shutil.rmtree(work, ignore_errors=True)


# ------------------------------------------------- HA fleet lease takeover

def _ha_coordinator_child() -> None:
    """Subprocess entry for the HA takeover drill: one fleet member.  Boots
    the 2-worker FTE coordinator behind the statement protocol, wraps it in
    an :class:`~trino_tpu.execution.ha.HACoordinator` (lease + failover
    watcher), writes its bound port to ``CHAOS_PORT_FILE``, and serves
    until killed.  ``CHAOS_STALL_S`` arms the same one-shot unrescuable
    stall as the single-coordinator drill — only the victim node gets it."""
    import os

    from ..connectors.catalog import default_catalog as _catalog
    from ..execution.distributed_runner import DistributedQueryRunner as _R
    from ..execution.failure_injector import FailureInjector as _Inj
    from ..execution.failure_injector import TASK_STALL as _STALL
    from ..execution.ha import HACoordinator
    from ..runner import Session as _S
    from ..server.protocol import TrinoTpuServer

    inj = None
    stall_s = float(os.environ.get("CHAOS_STALL_S", "0") or 0)
    if stall_s > 0:
        inj = _Inj()
        inj.inject(_STALL, fragment_id=None, task_index=0, attempt=0,
                   times=1, stall_s=stall_s)
    session = _S(node_count=2, retry_policy="TASK", fte_speculative=False,
                 failure_injector=inj)
    runner = _R(_catalog(scale_factor=0.01), worker_count=2,
                session=session)
    srv = TrinoTpuServer(runner).start()
    HACoordinator(srv).start()
    port_file = os.environ["CHAOS_PORT_FILE"]
    with open(port_file + ".tmp", "w", encoding="utf-8") as f:
        f.write(str(srv.address[1]))
    os.replace(port_file + ".tmp", port_file)
    while True:
        time.sleep(1.0)


def run_ha_takeover_drill(stall_s: float = 300.0,
                          lease_ttl_s: float = 2.0,
                          heartbeat_s: float = 0.5,
                          boot_timeout_s: float = 180.0,
                          finish_timeout_s: float = 180.0,
                          workdir: Optional[str] = None) -> dict:
    """The HA tentpole drill: kill -9 one coordinator of a two-member
    fleet mid-FTE-query and certify a PEER (not a restart) finishes it.

    Coordinator A boots with an unrescuable one-shot stall and owns the
    drill query; B is healthy.  After >=1 fsync'd committed attempt lands
    in A's WAL, A is SIGKILLed.  B's failover watcher must claim A's
    expired lease (atomic lease-file rename), take custody of A's WAL
    directory, adopt the query under its ORIGINAL id, resume from the
    committed-attempt map and finish — the parent polls B's ordinary
    ``GET /v1/statement/{qid}/{token}`` surface throughout.  Asserts from
    the claimed WAL's attempt counters that committed attempts were never
    re-executed, and that A's lease is gone from the cluster directory."""
    import os
    import shutil
    import signal
    import subprocess
    import sys
    import tempfile

    from ..execution import query_state

    work = workdir or tempfile.mkdtemp(prefix="trino-tpu-ha-drill-")
    ha_root = os.path.join(work, "ha")
    spool_dir = os.path.join(work, "spool")
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    base_env = dict(os.environ)
    base_env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "TRINO_TPU_HA": "1",
        "TRINO_TPU_HA_DIR": ha_root,
        "TRINO_TPU_HA_LEASE_TTL_S": str(lease_ttl_s),
        "TRINO_TPU_HA_HEARTBEAT_S": str(heartbeat_s),
        "TRINO_TPU_QUERY_STATE": "1",
        "TRINO_TPU_SPOOL_DIR": spool_dir,
        "TRINO_TPU_JOURNAL_DIR": os.path.join(work, "journal"),
        "TRINO_TPU_RESULT_CACHE": "0",
        "PYTHONPATH": repo_root + os.pathsep + base_env.get("PYTHONPATH",
                                                            ""),
    })
    child_cmd = [sys.executable, "-c",
                 "from trino_tpu.testing.chaos import _ha_coordinator_child;"
                 " _ha_coordinator_child()"]

    def _boot(node: str, extra_env: dict) -> tuple:
        port_file = os.path.join(work, f"port-{node}")
        env = {**base_env,
               "TRINO_TPU_HA_NODE_ID": node,
               "TRINO_TPU_QUERY_STATE_DIR": os.path.join(
                   ha_root, "wal", node),
               "CHAOS_PORT_FILE": port_file,
               **extra_env}
        proc = subprocess.Popen(child_cmd, env=env, cwd=repo_root)
        deadline = time.monotonic() + boot_timeout_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"HA child {node} died at boot (rc={proc.returncode})")
            if os.path.exists(port_file):
                with open(port_file, encoding="utf-8") as f:
                    return proc, int(f.read().strip())
            time.sleep(0.1)
        proc.kill()
        raise TimeoutError(f"HA child {node} never wrote its port")

    record: dict = {"sql": _DRILL_SQL, "workdir": work}
    proc_a = proc_b = None
    try:
        proc_a, port_a = _boot("coordA", {"CHAOS_STALL_S": str(stall_s)})
        proc_b, port_b = _boot("coordB", {})

        # the query must land on A (the stalled victim): submit straight to
        # A's statement endpoint — ownership in the drill is by submission,
        # the front-tier hash path is exercised by bench.py --ha
        sub = _http_json("POST", f"http://127.0.0.1:{port_a}/v1/statement",
                         _DRILL_SQL.encode("utf-8"))
        qid = sub["id"]
        record["query_id"] = qid
        wal_a = os.path.join(ha_root, "wal", "coordA", qid + ".wal")
        pq = None
        deadline = time.monotonic() + boot_timeout_s
        while time.monotonic() < deadline:
            pq = query_state.load(wal_a)
            if pq is not None and len(pq.committed) >= 1:
                break
            time.sleep(0.1)
        if pq is None or not pq.committed:
            raise TimeoutError("no committed attempt before the kill")
        committed_at_kill = dict(pq.committed)
        starts_at_kill = dict(pq.attempt_counts)
        record["committed_at_kill"] = len(committed_at_kill)
        t_kill = time.monotonic()
        os.kill(proc_a.pid, signal.SIGKILL)
        proc_a.wait(timeout=30)

        # B's watcher claims the expired lease and finishes the query under
        # its original id; the client just switches which address it polls
        rows: list = []
        state = None
        token = 0
        deadline = time.monotonic() + finish_timeout_s
        while time.monotonic() < deadline:
            try:
                out = _http_json(
                    "GET",
                    f"http://127.0.0.1:{port_b}/v1/statement/{qid}/{token}")
            except Exception:  # 404 until B adopts; keep polling
                time.sleep(0.2)
                continue
            state = out.get("stats", {}).get("state")
            if state == "FAILED":
                record["error"] = out.get("error")
                break
            rows += out.get("data", [])
            nxt = out.get("nextUri")
            if state == "FINISHED":
                if not nxt:
                    break
                token += 1
                continue
            time.sleep(0.2)
        record["state"] = state
        record["rows"] = rows
        record["takeover_s"] = round(time.monotonic() - t_kill, 2)

        # A's WAL now lives under B's claimed custody
        wal_root = os.path.join(ha_root, "wal")
        claimed = [d for d in sorted(os.listdir(wal_root))
                   if d.startswith("coordA.claimed-coordB-")]
        record["claimed_dirs"] = claimed
        final = None
        if claimed:
            final = query_state.load(
                os.path.join(wal_root, claimed[0], qid + ".wal"))
        re_executed = {}
        if final is not None:
            re_executed = {
                f"f{fid}_t{t}": final.attempt_counts.get((fid, t), 0)
                - starts_at_kill.get((fid, t), 0)
                for (fid, t) in committed_at_kill
                if final.attempt_counts.get((fid, t), 0)
                > starts_at_kill.get((fid, t), 0)
            }
        record["committed_reexecuted"] = re_executed
        record["wal_ended"] = final.ended if final is not None else None
        record["lease_a_gone"] = not os.path.exists(
            os.path.join(ha_root, "coordinators", "coordA.json"))
        record["pass"] = (state == "FINISHED" and bool(claimed)
                          and final is not None and not re_executed
                          and final.ended == "FINISHED"
                          and record["lease_a_gone"])
        return record
    finally:
        for p in (proc_a, proc_b):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=15)
        if workdir is None:
            shutil.rmtree(work, ignore_errors=True)


def run_chaos(n_scenarios: int = 25, base_seed: int = 1009,
              inproc_queries: int = 8, process_queries: int = 4,
              process_stride: int = 4, verbose: bool = True) -> dict:
    """The full soak.  Every ``process_stride``-th scenario runs against
    real worker processes; the rest are in-process.  Returns a summary
    with per-scenario records and the acceptance booleans."""
    expected = build_expected()
    scenarios = []
    for i in range(n_scenarios):
        mode = ("process" if process_stride and i % process_stride
                == process_stride - 1 else "inproc")
        n_q = process_queries if mode == "process" else inproc_queries
        t0 = time.monotonic()
        rec = run_scenario(base_seed + i, mode=mode, n_queries=n_q,
                           expected=expected)
        rec["scenario"] = i
        rec["wall_s"] = round(time.monotonic() - t0, 2)
        scenarios.append(rec)
        if verbose:
            print(f"  chaos scenario {i:2d} seed={base_seed + i} "
                  f"mode={mode:7s} {rec['counts']} "
                  f"({rec['wall_s']:.1f}s)", flush=True)

    totals: dict = {}
    retry_walls = []
    for rec in scenarios:
        for k, v in rec["counts"].items():
            totals[k] = totals.get(k, 0) + v
        retry_walls += [o["wall_s"] for o in rec["outcomes"]
                        if o["retried"]]
    n_queries = sum(len(r["outcomes"]) for r in scenarios)
    return {
        "n_scenarios": n_scenarios,
        "base_seed": base_seed,
        "n_queries": n_queries,
        "totals": totals,
        "hangs": totals.get("hang", 0),
        "unexpected": totals.get("unexpected", 0),
        "max_recovery_s": round(max(retry_walls), 3) if retry_walls
        else 0.0,
        "all_accounted": (totals.get("hang", 0) == 0
                          and totals.get("unexpected", 0) == 0),
        "scenarios": scenarios,
    }
