"""Benchmark kernels: the fused TPC-H Q1 program (single-chip + SPMD).

Q1 = scan(lineitem) → filter(shipdate <= cutoff) → project(disc_price,
charge) → group by (returnflag, linestatus) → 7 sums/counts.  In the
reference this is ScanFilterAndProjectOperator + HashAggregationOperator
(BenchmarkHashAndStreamingAggregationOperators.java); here the whole query
is ONE XLA program: the filter becomes a row mask folded into the reduction
(no compaction), money columns are decimal-scaled int64 summed in f64 lanes,
and the group table is 8 static slots.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .parallel.static_agg import AggSpec, static_grouped_agg
from .parallel.distributed import distributed_grouped_agg, make_mesh

__all__ = ["Q1Batch", "make_q1_inputs", "q1_step", "q1_spmd", "q1_numpy"]

Q1_CUTOFF_DAYS = 10471  # date '1998-12-01' - interval '90' day = 1998-09-02

_SPECS = [
    AggSpec("sum", jnp.float64),   # sum_qty
    AggSpec("sum", jnp.float64),   # sum_base_price
    AggSpec("sum", jnp.float64),   # sum_disc_price
    AggSpec("sum", jnp.float64),   # sum_charge
    AggSpec("sum", jnp.float64),   # sum_discount (for avg_disc)
    AggSpec("count_star", jnp.int64),  # count_order (and avg divisors)
]


class Q1Batch(NamedTuple):
    returnflag: jnp.ndarray  # int32 codes
    linestatus: jnp.ndarray  # int32 codes
    quantity: jnp.ndarray    # int64 scale-2
    extendedprice: jnp.ndarray  # int64 scale-2
    discount: jnp.ndarray    # int64 scale-2
    tax: jnp.ndarray         # int64 scale-2
    shipdate: jnp.ndarray    # int32 days


def make_q1_inputs(sf: float, splits: int = 8):
    """Generate lineitem Q1 columns via the TPC-H connector (host, numpy)."""
    from .connectors.tpch import TpchConnector

    conn = TpchConnector(scale_factor=sf)
    cols = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
            "l_discount", "l_tax", "l_shipdate"]
    batches = []
    for s in conn.get_splits("lineitem", splits, 1):
        src = conn.create_page_source(s, cols)
        while not src.is_finished():
            b = src.get_next_batch()
            if b is not None:
                batches.append(b)
    from .spi.batch import ColumnBatch

    all_ = ColumnBatch.concat(batches)
    return Q1Batch(
        np.asarray(all_.column("l_returnflag").data, np.int32),
        np.asarray(all_.column("l_linestatus").data, np.int32),
        np.asarray(all_.column("l_quantity").data, np.int64),
        np.asarray(all_.column("l_extendedprice").data, np.int64),
        np.asarray(all_.column("l_discount").data, np.int64),
        np.asarray(all_.column("l_tax").data, np.int64),
        np.asarray(all_.column("l_shipdate").data, np.int32),
    )


def _q1_project(b: Q1Batch):
    mask = b.shipdate <= Q1_CUTOFF_DAYS
    qty = b.quantity.astype(jnp.float64)
    price = b.extendedprice.astype(jnp.float64)
    disc = b.discount.astype(jnp.float64)
    tax = b.tax.astype(jnp.float64)
    disc_price = price * (100.0 - disc) / 100.0
    charge = disc_price * (100.0 + tax) / 100.0
    keys = [b.returnflag, b.linestatus]
    datas = [qty, price, disc_price, charge, disc, qty]
    return keys, datas, mask


@jax.jit
def q1_step(b: Q1Batch):
    """Single-chip fused Q1: one jitted program, 8 group slots."""
    keys, datas, mask = _q1_project(b)
    agg_inputs = [(s, d, None) for s, d in zip(_SPECS, datas)]
    r = static_grouped_agg(keys, [None, None], agg_inputs, cap=8, row_mask=mask)
    return tuple(r.keys), tuple(r.values), r.slot_used


def q1_spmd(mesh, axis: str = "x"):
    """SPMD Q1 over a device mesh: dp row shards -> partial agg ->
    all_to_all repartition of group slots -> final agg."""
    inner = distributed_grouped_agg(
        mesh, axis, [jnp.int32, jnp.int32], _SPECS, cap=8)

    def step(b: Q1Batch):
        keys, datas, mask = _q1_project(b)
        return inner(*keys, *datas, mask)

    return step


def q1_numpy(b: Q1Batch):
    """Reference single-thread numpy implementation (the CPU baseline)."""
    mask = b.shipdate <= Q1_CUTOFF_DAYS
    rf = b.returnflag[mask]
    ls = b.linestatus[mask]
    qty = b.quantity[mask].astype(np.float64)
    price = b.extendedprice[mask].astype(np.float64)
    disc = b.discount[mask].astype(np.float64)
    tax = b.tax[mask].astype(np.float64)
    disc_price = price * (100.0 - disc) / 100.0
    charge = disc_price * (100.0 + tax) / 100.0
    key = rf.astype(np.int64) * 1000 + ls
    uniq, inv = np.unique(key, return_inverse=True)
    out = {}
    for name, col in (("qty", qty), ("price", price),
                      ("disc_price", disc_price), ("charge", charge),
                      ("disc", disc)):
        acc = np.zeros(len(uniq))
        np.add.at(acc, inv, col)
        out[name] = acc
    out["count"] = np.bincount(inv, minlength=len(uniq))
    return uniq, out
