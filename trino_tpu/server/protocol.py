"""REST statement protocol: POST /v1/statement + nextUri paging.

The stdlib-only analogue of the reference's client protocol
(core/trino-main/.../dispatcher/QueuedStatementResource.java:104 +
protocol/ExecutingStatementResource + docs/src/main/sphinx/develop/
client-protocol.md): a client POSTs SQL, receives a query id and a
``nextUri``, and follows nextUri until ``state`` is FINISHED, collecting
``columns`` + ``data`` pages along the way.  DELETE cancels.

The dispatcher runs queries on a bounded worker pool (the miniature of
dispatcher/DispatchManager + resource-group admission) against either
runner; results are paged back JSON-encoded.
"""

from __future__ import annotations

import json
import threading
import uuid
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

__all__ = ["QueryDispatcher", "TrinoTpuServer"]

_PAGE_ROWS = 4096


def _json_value(v):
    import datetime
    import decimal

    if isinstance(v, decimal.Decimal):
        return str(v)
    if isinstance(v, datetime.date):
        return v.isoformat()
    if isinstance(v, float) and v != v:  # NaN
        return "NaN"
    return v


class _Query:
    def __init__(self, qid: str, sql: str):
        self.id = qid
        self.sql = sql
        self.state = "QUEUED"
        self.error: Optional[str] = None
        self.columns: Optional[list] = None
        self.rows: list = []
        self.done = threading.Event()
        self.cancelled = False
        self.recovered = False  # rehydrated from the query-state WAL


class QueryDispatcher:
    """Admission + execution: a bounded pool of query slots (the stand-in
    for DispatchManager + resource groups).  At boot it also runs
    coordinator crash recovery: in-flight ``retry_policy="TASK"`` queries
    found in the query-state WAL are re-registered under their ORIGINAL
    query ids (so clients reattach through the unchanged
    ``GET /v1/statement/{id}/{token}`` surface) and resumed from their
    committed-attempt maps; afterwards the leaked-spool sweep reclaims
    every spool root no live query owns."""

    def __init__(self, runner, max_concurrent: int = 4,
                 recover: bool = True):
        self.runner = runner
        self.pool = ThreadPoolExecutor(max_workers=max_concurrent)
        self.queries: dict[str, _Query] = {}
        self._lock = threading.Lock()
        self.recovered_query_ids: list[str] = []
        if recover:
            self._recover_and_sweep()

    def _recover_and_sweep(self) -> None:
        from ..execution import query_state, spool_gc

        pending = []
        try:
            if hasattr(self.runner, "pending_fte_recoveries"):
                pending = self.runner.pending_fte_recoveries()
        except Exception:
            pending = []
        keep = []
        for pq in pending:
            if self.adopt(pq) and pq.spool_root:
                keep.append(pq.spool_root)
        try:
            query_state.prune_ended()
            # roots under recovery are pinned; everything else follows
            # lease/TTL/budget rules
            spool_gc.sweep(keep=keep)
        except Exception:
            pass

    def adopt(self, pq) -> bool:
        """Register one WAL-recovered query under its ORIGINAL id and
        resume it.  Shared by boot-time self-recovery and HA lease
        takeover (execution/ha.py), where the WAL dir being adopted
        belonged to a dead fleet peer.  False if the id is already live
        here (double-adoption guard)."""
        with self._lock:
            if pq.query_id in self.queries:
                return False
            q = _Query(pq.query_id, pq.sql)
            q.recovered = True
            self.queries[q.id] = q
        self.recovered_query_ids.append(pq.query_id)
        self.pool.submit(self._resume, q, pq)
        return True

    def in_flight(self) -> int:
        """Queries registered and not yet done (lease-file enrichment and
        the runtime.coordinators table)."""
        with self._lock:
            return sum(1 for q in self.queries.values()
                       if not q.done.is_set())

    MAX_RETAINED = 256

    def submit(self, sql: str, qid: Optional[str] = None) -> _Query:
        """``qid`` lets the HA front tier pre-assign the query id it hashed
        the owning coordinator from, so routing and identity agree."""
        from ..telemetry.metrics import DISPATCHER_QUERIES

        DISPATCHER_QUERIES.inc()
        q = _Query(qid or uuid.uuid4().hex[:16], sql)
        with self._lock:
            self.queries[q.id] = q
            # bound the registry: evict oldest finished queries (the
            # reference expires results once the client stops polling)
            finished = [k for k, v in self.queries.items() if v.done.is_set()]
            for k in finished[:max(0, len(self.queries) - self.MAX_RETAINED)]:
                del self.queries[k]
        self.pool.submit(self._run, q)
        return q

    def _run(self, q: _Query) -> None:
        if q.cancelled:
            q.state = "CANCELED"
            q.done.set()
            return
        try:
            self._await_memory(q)
        except Exception as e:
            q.error = f"{type(e).__name__}: {e}"
            q.state = "FAILED"
            q.done.set()
            return
        if q.cancelled:
            q.state = "CANCELED"
            q.done.set()
            return
        q.state = "RUNNING"
        try:
            # the protocol query id IS the engine query id, so the flight
            # recorder's /v1/query/{id}/profile resolves without a mapping
            result = self.runner.execute(q.sql, query_id=q.id)
            self._deliver(q, result)
        except Exception as e:  # surfaced through the protocol, not the log
            q.error = f"{type(e).__name__}: {e}"
            q.state = "FAILED"
        q.done.set()

    def _resume(self, q: _Query, pq) -> None:
        """Run one crash-recovered query to completion under its original
        id; a client that survived the coordinator restart keeps polling
        the same nextUri and sees the query finish."""
        if q.cancelled:
            q.state = "CANCELED"
            q.done.set()
            return
        q.state = "RUNNING"
        try:
            self._deliver(q, self.runner.resume_fte_query(pq))
        except Exception as e:
            q.error = f"{type(e).__name__}: {e}"
            q.state = "FAILED"
        q.done.set()

    def _deliver(self, q: _Query, result) -> None:
        if q.cancelled:
            # the engine ran to completion (no mid-kernel interruption
            # yet), but a cancelled query must not deliver results
            q.state = "CANCELED"
            return
        q.columns = [
            {"name": n, "type": str(t)}
            for n, t in zip(result.names, result.batch.types)
        ]
        q.rows = [[_json_value(v) for v in row] for row in result.rows()]
        q.state = "FINISHED"

    def _await_memory(self, q: _Query) -> None:
        """Memory-aware admission: estimate the query's peak from the
        query-record history of the same plan fingerprint (telemetry
        runtime.fingerprint) and hold it QUEUED while the cluster lacks
        headroom — admitting into certain OOM just feeds the killer.
        Raises QUERY_QUEUED_TIMEOUT (USER, never retried) when the wait
        budget runs out.  No-op when the runner has no memory manager or
        the cluster is uncapped."""
        mm = getattr(self.runner, "memory_manager", None)
        if mm is None or mm.capacity_bytes is None:
            return
        import os
        import time

        from ..execution.resource_manager import estimate_peak_memory
        from ..spi.errors import QUERY_QUEUED_TIMEOUT, TrinoError
        from ..telemetry import metrics as tm
        from ..telemetry.runtime import fingerprint

        default = int(os.environ.get("TRINO_TPU_QUERY_DEFAULT_MEMORY",
                                     str(64 << 20)))
        est = estimate_peak_memory(fingerprint(q.sql), default)
        budget = getattr(getattr(self.runner, "session", None),
                         "query_queued_timeout_s", 300.0)
        t0 = time.monotonic()
        while not mm.can_admit(est):
            if q.cancelled:
                return
            if time.monotonic() - t0 > budget:
                raise TrinoError(
                    QUERY_QUEUED_TIMEOUT,
                    f"queued {budget:.0f}s waiting for {est} bytes of "
                    f"cluster memory (free: {mm.cluster_free_bytes()})")
            mm.maybe_enforce()
            time.sleep(0.05)
        waited = time.monotonic() - t0
        if waited > 0.05:
            tm.ADMISSION_QUEUED_SECONDS.record(waited)

    def get(self, qid: str) -> Optional[_Query]:
        with self._lock:
            return self.queries.get(qid)

    def cancel(self, qid: str) -> bool:
        q = self.get(qid)
        if q is None:
            return False
        q.cancelled = True
        return True


class _Handler(BaseHTTPRequestHandler):
    dispatcher: QueryDispatcher = None  # set by TrinoTpuServer

    def log_message(self, fmt, *args):  # quiet
        pass

    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _query_payload(self, q: _Query, token: int) -> dict:
        out = {
            "id": q.id,
            "stats": {"state": q.state},
        }
        if q.state in ("QUEUED", "RUNNING"):
            out["nextUri"] = f"/v1/statement/{q.id}/{token}"
            return out
        if q.state == "FAILED":
            out["error"] = {"message": q.error}
            return out
        # FINISHED: page the rows out
        if q.columns is not None:
            out["columns"] = q.columns
        start = token * _PAGE_ROWS
        page = q.rows[start:start + _PAGE_ROWS]
        if page:
            out["data"] = page
        if start + _PAGE_ROWS < len(q.rows):
            out["nextUri"] = f"/v1/statement/{q.id}/{token + 1}"
        return out

    def do_POST(self):
        if self.path.rstrip("/") != "/v1/statement":
            self._send(404, {"error": {"message": "not found"}})
            return
        length = int(self.headers.get("Content-Length", "0"))
        sql = self.rfile.read(length).decode("utf-8")
        qid = (self.headers.get("X-Trino-Tpu-Query-Id") or "").strip() or None
        q = self.dispatcher.submit(sql, qid=qid)
        self._send(200, self._query_payload(q, 0))

    def _cluster_metrics(self) -> str:
        """One Prometheus exposition for the whole cluster: the coordinator
        registry folded with every live worker's snapshot (counters summed,
        distributions merged bucket-wise).  A dead worker is skipped — a
        scrape must never fail because one node is down."""
        from ..telemetry import metrics as tm

        snaps = []
        for w in getattr(self.dispatcher.runner, "workers", None) or []:
            url = getattr(w, "url", None)
            if not url:
                continue
            try:
                from ..execution.remote import _http

                with _http("GET", f"{url}/v1/metrics?format=json",
                           timeout=5.0) as resp:
                    snaps.append(json.loads(resp.read()))
            except Exception:  # noqa: BLE001
                continue
        return tm.render_cluster(snaps)

    def do_GET(self):
        from urllib.parse import parse_qs, urlsplit

        url = urlsplit(self.path)
        qs = parse_qs(url.query)
        parts = [p for p in url.path.split("/") if p]
        if parts == ["v1", "metrics"]:
            # Prometheus text exposition — coordinator-process registry, or
            # the merged cluster fold with ?scope=cluster (_send is
            # JSON-only, so write the text inline)
            from ..telemetry.metrics import REGISTRY

            if qs.get("scope", [""])[0] == "cluster":
                body = self._cluster_metrics().encode("utf-8")
            else:
                body = REGISTRY.render_prometheus().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if parts == ["v1", "caches"]:
            # per-tier cache-plane stats (same rows as system.runtime.caches)
            from .. import caching

            self._send(200, {"caches": caching.cache_rows(
                per_exec_cache=qs.get("detail", [""])[0] == "1")})
            return
        if len(parts) == 4 and parts[:2] == ["v1", "query"] and \
                parts[3] == "profile":
            # flight-recorder timeline as Chrome trace_event JSON
            from ..telemetry import profiler

            trace = profiler.chrome_trace(parts[2])
            if trace is None:
                self._send(404, {"error": {
                    "message": f"no profile for query {parts[2]}"}})
            else:
                self._send(200, trace)
            return
        # /v1/statement/{id}/{token}
        if len(parts) != 4 or parts[:2] != ["v1", "statement"]:
            self._send(404, {"error": {"message": "not found"}})
            return
        q = self.dispatcher.get(parts[2])
        if q is None:
            self._send(404, {"error": {"message": "unknown query"}})
            return
        # brief server-side wait cuts client poll round trips
        q.done.wait(timeout=0.5)
        self._send(200, self._query_payload(q, int(parts[3])))

    def do_DELETE(self):
        parts = self.path.strip("/").split("/")
        if len(parts) >= 3 and parts[:2] == ["v1", "statement"]:
            ok = self.dispatcher.cancel(parts[2])
            self._send(200 if ok else 404, {"cancelled": ok})
            return
        self._send(404, {"error": {"message": "not found"}})


class TrinoTpuServer:
    """In-process HTTP server hosting the statement protocol."""

    def __init__(self, runner, host: str = "127.0.0.1", port: int = 0,
                 max_concurrent: int = 4):
        self.dispatcher = QueryDispatcher(runner, max_concurrent)
        handler = type("_BoundHandler", (_Handler,),
                       {"dispatcher": self.dispatcher})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> tuple[str, int]:
        return self.httpd.server_address[:2]

    def start(self) -> "TrinoTpuServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="trino-tpu-http",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
