"""Statement-protocol client + CLI (the StatementClientV1 / trino-cli
equivalent — client/trino-client/.../StatementClientV1.java:74,
client/trino-cli).  Stdlib http.client only; follows nextUri until the
query reaches a terminal state, accumulating data pages.

CLI: ``python -m trino_tpu.server.client --server host:port "select 1"``
"""

from __future__ import annotations

import http.client
import json
import time
from typing import Optional

__all__ = ["Client", "QueryFailed", "main"]


class QueryFailed(RuntimeError):
    pass


class Client:
    def __init__(self, host: str = "127.0.0.1", port: int = 8080,
                 timeout: float = 300.0):
        self.host = host
        self.port = port
        self.timeout = timeout

    def _request(self, method: str, path: str, body: Optional[str] = None) -> dict:
        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        try:
            conn.request(method, path, body=body)
            resp = conn.getresponse()
            return json.loads(resp.read().decode("utf-8"))
        finally:
            conn.close()

    def execute(self, sql: str) -> tuple[list[dict], list[list]]:
        """-> (columns, rows); raises QueryFailed on error states."""
        payload = self._request("POST", "/v1/statement", sql)
        columns: list[dict] = []
        rows: list[list] = []
        deadline = time.monotonic() + self.timeout
        while True:
            state = payload.get("stats", {}).get("state")
            if state == "FAILED":
                raise QueryFailed(payload.get("error", {}).get("message", "?"))
            columns = payload.get("columns", columns)
            rows.extend(payload.get("data", []))
            nxt = payload.get("nextUri")
            if nxt is None:
                if state in ("FINISHED", "CANCELED"):
                    return columns, rows
                raise QueryFailed(f"query ended in state {state}")
            if time.monotonic() > deadline:
                self.cancel(payload["id"])
                raise TimeoutError("client timed out; query cancelled")
            payload = self._request("GET", nxt)

    def cancel(self, query_id: str) -> None:
        self._request("DELETE", f"/v1/statement/{query_id}")


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(description="trino_tpu SQL client")
    p.add_argument("--server", default="127.0.0.1:8080", help="host:port")
    p.add_argument("sql", help="SQL statement")
    args = p.parse_args(argv)
    host, _, port = args.server.partition(":")
    client = Client(host, int(port or 8080))
    columns, rows = client.execute(args.sql)
    if columns:
        print("\t".join(c["name"] for c in columns))
    for r in rows:
        print("\t".join("NULL" if v is None else str(v) for v in r))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
