"""Stateless HTTP front tier for the HA coordinator fleet.

The miniature of the reference's dispatcher tier split (a load balancer
in front of N dispatchers in front of N coordinators): clients speak the
ordinary statement protocol (server/protocol.py) to ONE stable address
and never learn the fleet topology.

- ``POST /v1/statement``: the tier mints the query id itself and forwards
  to the owning coordinator — rendezvous hash over the live membership
  (execution/ha.py ``owner_of``) — passing the id down via the
  ``X-Trino-Tpu-Query-Id`` header so routing and identity agree.
- ``GET /v1/statement/{id}/{token}`` / ``DELETE``: routed by the same
  hash.  When the owner is unreachable or does not know the query (it
  died; a peer claimed its lease and adopted the query), the tier probes
  every live member and pins the one that answers.  While nobody answers
  — the takeover window — polls get a synthetic ``QUEUED`` page with an
  unchanged ``nextUri`` for up to ``TRINO_TPU_HA_ROUTE_RETRY_S``, so a
  client polling through a failover sees a slow query, never an error.

The tier holds no query state: every response is recomputed from the
lease directory plus one proxied upstream call, so any number of tier
replicas can run behind one load balancer and a tier restart loses
nothing (the routing pin cache is a pure latency optimisation).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..execution import ha

__all__ = ["FrontTier"]


class _Upstream:
    """One proxied call's outcome."""

    __slots__ = ("status", "body")

    def __init__(self, status: int, body: bytes):
        self.status = status
        self.body = body


def _call(url: str, method: str, body: Optional[bytes] = None,
          headers: Optional[dict] = None,
          timeout: float = 30.0) -> Optional[_Upstream]:
    """HTTP round trip; None on transport failure (dead coordinator)."""
    req = urllib.request.Request(url, data=body, method=method)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return _Upstream(resp.status, resp.read())
    except urllib.error.HTTPError as e:
        return _Upstream(e.code, e.read())
    except (urllib.error.URLError, OSError):
        return None


class FrontTier:
    """Stateless statement-protocol router over the coordinator fleet."""

    def __init__(self, root: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 ttl: Optional[float] = None,
                 retry_s: Optional[float] = None,
                 call_timeout: float = 30.0):
        from ..spi.knobs import get_float

        self.root = root or ha.ha_dir()
        self.ttl = ha.lease_ttl_s() if ttl is None else ttl
        self.retry_s = (get_float("TRINO_TPU_HA_ROUTE_RETRY_S") or 15.0
                        ) if retry_s is None else retry_s
        self.call_timeout = call_timeout
        # qid -> coordinator url that last answered for it (latency pin,
        # safe to lose); qid -> first-miss wall ts (failover grace window)
        self._pins: dict[str, str] = {}
        self._misses: dict[str, float] = {}
        self._lock = threading.Lock()
        handler = type("_BoundFrontHandler", (_FrontHandler,),
                       {"tier": self})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- topology
    def members(self) -> list:
        return ha.live_members(self.root, self.ttl)

    def owner_url(self, qid: str) -> Optional[str]:
        members = self.members()
        owner = ha.owner_of(qid, [m.node_id for m in members])
        for m in members:
            if m.node_id == owner:
                return m.url
        return None

    # -------------------------------------------------------------- routing
    def route_post(self, sql: bytes) -> tuple[int, dict]:
        qid = uuid.uuid4().hex[:16]
        deadline = time.monotonic() + self.retry_s
        while True:
            url = self.owner_url(qid)
            if url is not None:
                up = _call(f"{url}/v1/statement", "POST", body=sql,
                           headers={"X-Trino-Tpu-Query-Id": qid},
                           timeout=self.call_timeout)
                if up is not None:
                    with self._lock:
                        self._pins[qid] = url
                    return up.status, _decode(up.body)
            # owner down and not yet claimed: wait out a slice of the
            # failover window and rehash over the new membership
            if time.monotonic() >= deadline:
                return 503, {"error": {
                    "message": "no live coordinator for query"}}
            time.sleep(0.1)

    def route_query(self, qid: str, path: str,
                    method: str = "GET") -> tuple[int, dict]:
        """Route one ``/v1/statement/{qid}/...`` poll (or DELETE)."""
        from ..telemetry import metrics as tm

        tried = []
        with self._lock:
            pin = self._pins.get(qid)
        candidates = [pin] if pin else []
        owner = self.owner_url(qid)
        if owner and owner not in candidates:
            candidates.append(owner)
        for url in candidates:
            up = _call(f"{url}{path}", method, timeout=self.call_timeout)
            tried.append(url)
            if up is not None and up.status == 200:
                self._answered(qid, url)
                return up.status, _decode(up.body)
        # the routed coordinator is dead or disowned the query: a peer may
        # have adopted it — probe the whole live fleet
        for m in self.members():
            if m.url in tried:
                continue
            up = _call(f"{m.url}{path}", method, timeout=self.call_timeout)
            if up is not None and up.status == 200:
                tm.HA_REROUTES.inc()
                self._answered(qid, m.url)
                return up.status, _decode(up.body)
        if method == "GET":
            # nobody answers: inside the takeover window clients see a
            # synthetic QUEUED page and keep polling the same nextUri
            now = time.monotonic()
            with self._lock:
                first = self._misses.setdefault(qid, now)
            if now - first <= self.retry_s:
                return 200, {"id": qid, "stats": {"state": "QUEUED"},
                             "nextUri": path}
        return 404, {"error": {"message": f"unknown query {qid}"}}

    def _answered(self, qid: str, url: str) -> None:
        with self._lock:
            self._pins[qid] = url
            self._misses.pop(qid, None)
            if len(self._pins) > 4096:  # stateless: pins are disposable
                self._pins.clear()

    # ------------------------------------------------------------ lifecycle
    @property
    def address(self) -> tuple[str, int]:
        return self.httpd.server_address[:2]

    def start(self) -> "FrontTier":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="trino-tpu-front-tier",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)


def _decode(body: bytes) -> dict:
    try:
        out = json.loads(body)
        return out if isinstance(out, dict) else {"value": out}
    except ValueError:
        return {"error": {"message": "bad upstream payload"}}


class _FrontHandler(BaseHTTPRequestHandler):
    tier: FrontTier = None  # set by FrontTier

    def log_message(self, fmt, *args):  # quiet
        pass

    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        if self.path.rstrip("/") != "/v1/statement":
            self._send(404, {"error": {"message": "not found"}})
            return
        length = int(self.headers.get("Content-Length", "0"))
        sql = self.rfile.read(length)
        code, payload = self.tier.route_post(sql)
        self._send(code, payload)

    def do_GET(self):
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        if parts == ["v1", "metrics"]:
            from ..telemetry.metrics import REGISTRY

            body = REGISTRY.render_prometheus().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if len(parts) == 4 and parts[:2] == ["v1", "statement"]:
            code, payload = self.tier.route_query(parts[2], self.path)
            self._send(code, payload)
            return
        self._send(404, {"error": {"message": "not found"}})

    def do_DELETE(self):
        parts = [p for p in self.path.strip("/").split("/") if p]
        if len(parts) >= 3 and parts[:2] == ["v1", "statement"]:
            code, payload = self.tier.route_query(parts[2], self.path,
                                                  method="DELETE")
            self._send(code, payload)
            return
        self._send(404, {"error": {"message": "not found"}})
