"""HTTP server + client protocol layer (L8/L9)."""

from .protocol import QueryDispatcher, TrinoTpuServer  # noqa: F401
from .client import Client  # noqa: F401
