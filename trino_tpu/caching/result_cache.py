"""Tier C: the versioned result cache.

Identical reads against unchanged tables re-executed end to end before
this tier.  Now every connector exposes a ``data_version(table)`` token
(spi/connector.py): the memory and file connectors bump it on every
INSERT / CTAS / DELETE / TRUNCATE / DROP (and on transaction rollback),
the TPC-H generator is immutable per scale factor, and the system
connector returns ``None`` — volatile tables are never cached.  A result
entry is keyed by the plan-cache key prefix (statement text ⊕ session ⊕
env knobs ⊕ catalog instance) ⊕ the **sorted table-version vector** of
every table the plan scans.  Any mutation of an input table changes its
token, so the old entry can never be served again — correctness does not
depend on eviction racing the write (the write additionally drops
matching entries eagerly via :func:`invalidate_table`, which is what the
``invalidations`` counter measures).

The store is size-bounded (``TRINO_TPU_RESULT_CACHE_BYTES``, default
64 MiB) with LRU eviction; a single result larger than a quarter of the
budget is not admitted (one giant scan must not wipe the dashboard
working set).  ``TRINO_TPU_RESULT_CACHE=0`` (checked per lookup) gives
bit-for-bit legacy behavior.

The materialized-view staleness contract (connectors/catalog.py
``Catalog.mv_is_stale``) is re-expressed on the same tokens: an MV is
stale exactly when some base table's current version differs from the
vector captured at refresh time.
"""

from __future__ import annotations

import contextlib
import os
import threading
from collections import OrderedDict
from typing import Optional

__all__ = [
    "enabled", "disabled", "capacity_bytes", "version_vector", "result_key",
    "lookup", "store", "invalidate_table", "result_nbytes", "stats",
    "reset_for_test",
]


def enabled() -> bool:
    return os.environ.get("TRINO_TPU_RESULT_CACHE", "1").strip().lower() \
        not in ("0", "off", "false", "no")


@contextlib.contextmanager
def disabled():
    """Scope with the result tier off — for harnesses that measure
    *execution* (fault injection, OOM drills, sync accounting, profiler
    timelines): a served cached result would skip the very machinery under
    measurement."""
    old = os.environ.get("TRINO_TPU_RESULT_CACHE")
    os.environ["TRINO_TPU_RESULT_CACHE"] = "0"
    try:
        yield
    finally:
        if old is None:
            del os.environ["TRINO_TPU_RESULT_CACHE"]
        else:
            os.environ["TRINO_TPU_RESULT_CACHE"] = old


def capacity_bytes() -> int:
    return int(os.environ.get("TRINO_TPU_RESULT_CACHE_BYTES",
                              str(64 << 20)))


_LOCK = threading.Lock()
# key -> (result, nbytes, tables)
_ENTRIES: OrderedDict = OrderedDict()
_BYTES = 0
_HITS = 0
_MISSES = 0
_EVICTIONS = 0
_INVALIDATIONS = 0


def version_vector(tables: tuple, catalog) -> Optional[tuple]:
    """Sorted (catalog, table, version-token) vector for the scanned table
    set, or None when any table is unversioned (→ uncacheable read)."""
    out = []
    for cat_name, table in tables:
        try:
            conn = catalog.connector(cat_name)
            v = conn.data_version(table)
        except Exception:  # noqa: BLE001 — a vanished table is a miss
            return None
        if v is None:
            return None
        out.append((cat_name, table, str(v)))
    return tuple(sorted(out))


def result_key(entry, versions: Optional[tuple]) -> Optional[tuple]:
    """Compose the full Tier-C key, or None when this read is uncacheable
    (unversioned input, or a plan the plan cache flagged — table
    functions / writers)."""
    if versions is None or not getattr(entry, "cacheable_result", False):
        return None
    return (entry.result_key_base, versions)


def result_nbytes(result) -> int:
    """Host/device byte footprint of a QueryResult's batch."""
    total = 0
    for col in result.batch.columns:
        for arr in (col.data, col.valid, col.dictionary):
            if arr is None:
                continue
            total += int(getattr(arr, "nbytes", 0) or 0)
    live = result.batch.live
    if live is not None:
        total += int(getattr(live, "nbytes", 0) or 0)
    return total


def lookup(key: Optional[tuple]):
    global _HITS, _MISSES
    if key is None or not enabled():
        return None
    from ..telemetry import metrics as tm

    with _LOCK:
        hit = _ENTRIES.get(key)
        if hit is not None:
            _ENTRIES.move_to_end(key)
            _HITS += 1
        else:
            _MISSES += 1
    if hit is None:
        tm.CACHE_RESULT_MISSES.inc()
        return None
    tm.CACHE_RESULT_HITS.inc()
    from ..telemetry import profiler

    if profiler.enabled():
        profiler.instant("cache", "result_hit", rows=hit[0].batch.num_rows)
    return hit[0]


def store(key: Optional[tuple], result, tables: tuple) -> bool:
    global _BYTES, _EVICTIONS
    if key is None or not enabled():
        return False
    nbytes = result_nbytes(result)
    cap = capacity_bytes()
    if nbytes > cap // 4:
        return False
    from ..telemetry import metrics as tm

    with _LOCK:
        old = _ENTRIES.pop(key, None)
        if old is not None:
            _BYTES -= old[1]
        _ENTRIES[key] = (result, nbytes, tables)
        _BYTES += nbytes
        while _BYTES > cap and _ENTRIES:
            _, (_r, nb, _t) = _ENTRIES.popitem(last=False)
            _BYTES -= nb
            _EVICTIONS += 1
            tm.CACHE_RESULT_EVICTIONS.inc()
        tm.CACHE_RESULT_ENTRIES.set(len(_ENTRIES))
        tm.CACHE_RESULT_BYTES.set(_BYTES)
    return True


def invalidate_table(catalog_name: str, table: str) -> int:
    """Eagerly drop every entry that read (catalog_name, table).  The
    version vector already guarantees such entries can never be served;
    this frees their bytes at mutation time instead of waiting for LRU
    pressure.  Called by connectors on writes; cheap — the store holds at
    most a few hundred dashboard-sized entries."""
    global _BYTES, _INVALIDATIONS
    if not enabled():
        return 0
    from ..telemetry import metrics as tm

    dropped = 0
    with _LOCK:
        doomed = [k for k, (_r, _nb, tables) in _ENTRIES.items()
                  if any(c == catalog_name and t == table
                         for c, t in tables)]
        for k in doomed:
            _r, nb, _t = _ENTRIES.pop(k)
            _BYTES -= nb
            dropped += 1
        if dropped:
            _INVALIDATIONS += dropped
            tm.CACHE_RESULT_INVALIDATIONS.inc(dropped)
            tm.CACHE_RESULT_ENTRIES.set(len(_ENTRIES))
            tm.CACHE_RESULT_BYTES.set(_BYTES)
    return dropped


def stats() -> dict:
    with _LOCK:
        return {
            "tier": "result", "name": "result", "entries": len(_ENTRIES),
            "bytes": _BYTES, "hits": _HITS, "misses": _MISSES,
            "evictions": _EVICTIONS, "invalidations": _INVALIDATIONS,
        }


def reset_for_test() -> None:
    global _BYTES, _HITS, _MISSES, _EVICTIONS, _INVALIDATIONS
    with _LOCK:
        _ENTRIES.clear()
        _BYTES = 0
        _HITS = _MISSES = _EVICTIONS = _INVALIDATIONS = 0
