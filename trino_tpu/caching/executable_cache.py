"""Tier B: the persistent compiled-executable cache registry.

Before this module, every jitted program memo in the engine was an ad-hoc
per-process ``functools.lru_cache(maxsize=None)`` — ~20 sites across
exec/kernels.py, exec/join_exec.py, exec/window_kernels.py,
ops/pallas_kernels.py and the stage compiler, each an unbounded-growth
hazard under long-lived multi-tenant serving (VERDICT §2.2 records
``trino-cache: no``; the reference ships a whole cache subsystem).  The
:func:`jit_memo` decorator replaces them with bounded, observable,
evictable entries in one process-wide registry:

- **bounded**: per-cache LRU capped at ``TRINO_TPU_EXEC_CACHE_ENTRIES``
  (default 256) keys; eviction drops the Python wrapper + its jitted
  closure (XLA's own trace cache is freed with it since the closure holds
  the only reference).
- **observable**: hits/misses/evictions per cache and in aggregate, via
  the lint-clean ``trino_cache_exec_*`` metrics and the
  ``system.runtime.caches`` table (caching/__init__.py cache_rows()).
- **persistent across restarts**, two ways.  (1) Setting
  ``TRINO_TPU_COMPILE_CACHE_DIR`` enables JAX's on-disk compilation cache
  (:func:`init_compile_cache`), so an XLA compile performed by any past
  process is a disk load, not a recompile.  (2) JSON-serializable memo
  keys are journaled to ``exec_warm.json`` next to the query journal
  (telemetry/journal.py dir) at query end; :func:`warm_at_boot` — called
  from the worker boot path — replays them so the hottest shape buckets
  have live wrappers before the first query arrives, and their first
  invocation hits the disk compile cache instead of tracing cold.

``TRINO_TPU_EXEC_CACHE=0`` restores bit-for-bit legacy behavior: every
decorated site degrades to a plain unbounded ``lru_cache`` with no
registry, no metrics, no warm file (checked once, at import/decoration
time — flipping it requires a fresh process, exactly like the legacy
per-process caches it reproduces).
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from functools import lru_cache
from typing import Any, Callable, Optional

__all__ = [
    "jit_memo", "register_external", "enabled", "default_maxsize",
    "registry_stats", "aggregate_stats", "clear_all", "warm_at_boot",
    "flush_warm_keys", "init_compile_cache", "warm_file_path",
    "reset_warm_state_for_test",
]

_WARM_FILE = "exec_warm.json"
_WARM_KEY_CAP = 256  # hottest keys journaled per process


def enabled() -> bool:
    return os.environ.get("TRINO_TPU_EXEC_CACHE", "1").strip().lower() \
        not in ("0", "off", "false", "no")


def default_maxsize() -> int:
    return int(os.environ.get("TRINO_TPU_EXEC_CACHE_ENTRIES", "256"))


def _metrics():
    # bound lazily once: telemetry.metrics is import-light, but binding at
    # decoration time would force it on every module that defines a kernel
    global _TM
    if _TM is None:
        from ..telemetry import metrics as tm

        _TM = tm
    return _TM


_TM = None


class _ExecutableCache:
    """One bounded LRU memo over a jit-wrapper factory.  Callable drop-in
    for the ``lru_cache`` it replaces; stats are plain ints under the same
    lock the OrderedDict needs anyway (these paths already pay a Python
    dispatch per batch — a dict move is noise next to the jnp work)."""

    def __init__(self, name: str, fn: Callable, maxsize: int):
        self.name = name
        self.fn = fn
        self.maxsize = maxsize
        self._entries: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.__wrapped__ = fn
        self.__doc__ = fn.__doc__

    def __call__(self, *args, **kwargs):
        key = args if not kwargs else (args, tuple(sorted(kwargs.items())))
        with self._lock:
            hit = self._entries.get(key, _MISSING)
            if hit is not _MISSING:
                self._entries.move_to_end(key)
                self.hits += 1
                _metrics().CACHE_EXEC_HITS.inc()
                return hit
        # build outside the lock: factories trace/jit and may re-enter
        value = self.fn(*args, **kwargs)
        tm = _metrics()
        tm.CACHE_EXEC_MISSES.inc()
        with self._lock:
            self.misses += 1
            if key not in self._entries:
                self._entries[key] = value
                while len(self._entries) > self.maxsize:
                    self._entries.popitem(last=False)
                    self.evictions += 1
                    tm.CACHE_EXEC_EVICTIONS.inc()
        if not kwargs:
            _record_warm_key(self.name, args)
        return value

    def warm(self, key: tuple) -> bool:
        """Re-instantiate the wrapper for a journaled key; never raises —
        a stale key (code drift across restarts) is simply skipped."""
        try:
            self(*key)
            return True
        except Exception:  # noqa: BLE001 — boot warming is best-effort
            return False

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def stats(self) -> dict:
        with self._lock:
            return {
                "tier": "exec", "name": self.name,
                "entries": len(self._entries), "bytes": 0,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "invalidations": 0,
            }


class _MISSING:  # sentinel (None is a legal cached value)
    pass


_REGISTRY: dict[str, _ExecutableCache] = {}
_EXTERNAL: dict[str, Callable[[], dict]] = {}
_REGISTRY_LOCK = threading.Lock()

# JSON-able (cache_name, key) pairs seen this process, flushed to the warm
# file at query end (flush_warm_keys) and replayed at worker boot
_WARM_LOCK = threading.Lock()
_WARM_SEEN: OrderedDict = OrderedDict()
_WARM_DIRTY = False


def jit_memo(name: str, maxsize: Optional[int] = None):
    """Decorator for jit-wrapper factories — the registry's replacement
    for ``@lru_cache(maxsize=None)``.  ``name`` must be unique (dotted
    module.func convention); ``maxsize`` defaults to the
    TRINO_TPU_EXEC_CACHE_ENTRIES knob."""

    def deco(fn: Callable):
        if not enabled():
            return lru_cache(maxsize=None)(fn)
        cache = _ExecutableCache(
            name, fn, maxsize if maxsize is not None else default_maxsize())
        with _REGISTRY_LOCK:
            if name in _REGISTRY:
                raise ValueError(f"duplicate executable cache name: {name!r}")
            _REGISTRY[name] = cache
        return cache

    return deco


def register_external(name: str, stats_fn: Callable[[], dict]) -> None:
    """Adopt a cache the registry doesn't own (e.g. the stage compiler's
    id()-keyed accumulate memo) into the observability plane: ``stats_fn``
    returns the same dict shape as _ExecutableCache.stats()."""
    with _REGISTRY_LOCK:
        _EXTERNAL[name] = stats_fn


def registry_stats() -> list[dict]:
    with _REGISTRY_LOCK:
        caches = list(_REGISTRY.values())
        external = list(_EXTERNAL.values())
    out = [c.stats() for c in caches]
    for fn in external:
        try:
            out.append(fn())
        except Exception:  # noqa: BLE001 — observability must not throw
            continue
    return sorted(out, key=lambda r: r["name"])


def aggregate_stats() -> dict:
    agg = {"tier": "exec", "name": "exec", "entries": 0, "bytes": 0,
           "hits": 0, "misses": 0, "evictions": 0, "invalidations": 0}
    for row in registry_stats():
        for k in ("entries", "bytes", "hits", "misses", "evictions",
                  "invalidations"):
            agg[k] += row[k]
    # the entries gauge is refreshed on the observability pull path (here)
    # rather than on every memo insert — summing the registry per insert
    # would put an O(#caches) walk on the batch hot path
    _metrics().CACHE_EXEC_ENTRIES.set(agg["entries"])
    return agg


def clear_all() -> None:
    with _REGISTRY_LOCK:
        caches = list(_REGISTRY.values())
    for c in caches:
        c.clear()


# ---------------------------------------------------------------------------
# persistence: the XLA disk compile cache + the warm-key journal


def init_compile_cache() -> Optional[str]:
    """Point JAX's persistent compilation cache at
    ``TRINO_TPU_COMPILE_CACHE_DIR`` (unset = leave JAX defaults alone).
    Returns the directory when enabled.  Idempotent; called from runner
    construction and worker boot so compiles survive process restarts."""
    cache_dir = os.environ.get("TRINO_TPU_COMPILE_CACHE_DIR")
    if not cache_dir:
        return None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:  # knob name varies across jax versions
            pass
    except Exception:  # noqa: BLE001 — cache trouble must not block queries
        return None
    return cache_dir


def warm_file_path() -> str:
    from ..telemetry import journal as tj

    d = os.environ.get("TRINO_TPU_JOURNAL_DIR") or tj.default_dir()
    return os.path.join(d, _WARM_FILE)


def _record_warm_key(cache_name: str, key: tuple) -> None:
    """Remember a JSON-round-trippable memo key for the warm journal.
    Keys holding dtypes/Type objects fail json.dumps and are skipped."""
    global _WARM_DIRTY
    try:
        json.dumps(key)
    except (TypeError, ValueError):
        return
    pair = (cache_name, key)
    with _WARM_LOCK:
        if pair in _WARM_SEEN:
            _WARM_SEEN.move_to_end(pair)
            return
        _WARM_SEEN[pair] = True
        while len(_WARM_SEEN) > _WARM_KEY_CAP:
            _WARM_SEEN.popitem(last=False)
        _WARM_DIRTY = True


def flush_warm_keys() -> Optional[str]:
    """Write the seen-key set to the warm file if it changed since the
    last flush (called from the query-completion path — one stat + maybe
    one small atomic write per query, never on the batch hot path)."""
    global _WARM_DIRTY
    if not enabled():
        return None
    with _WARM_LOCK:
        if not _WARM_DIRTY:
            return None
        pairs = [[name, list(key)] for (name, key) in _WARM_SEEN]
        _WARM_DIRTY = False
    path = warm_file_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "keys": pairs}, f)
        os.replace(tmp, path)
    except OSError:
        return None
    return path


def _freeze(v):
    """JSON round trip turns tuples into lists; memo keys are tuples."""
    if isinstance(v, list):
        return tuple(_freeze(x) for x in v)
    return v


def warm_at_boot(limit: int = 64) -> int:
    """Replay the warm journal: import the cache-owning modules, then
    re-instantiate up to ``limit`` recorded wrappers (most recent first —
    the file is LRU-ordered oldest-first).  With the disk compile cache
    enabled the first real invocation of each is a cache load, not a cold
    XLA compile.  Returns the number of entries warmed."""
    if not enabled() or os.environ.get(
            "TRINO_TPU_EXEC_WARM", "1").strip().lower() in (
            "0", "off", "false", "no"):
        return 0
    try:
        with open(warm_file_path(), encoding="utf-8") as f:
            doc = json.load(f)
        pairs = doc.get("keys", [])
    except (OSError, ValueError):
        return 0
    # the decorated sites only exist once their modules are imported
    for mod in ("exec.kernels", "exec.join_exec", "exec.window_kernels",
                "ops.pallas_kernels", "execution.stage_compiler",
                "execution.collective_exchange", "execution.plan_compiler"):
        try:
            __import__(f"{__package__.rsplit('.', 1)[0]}.{mod}",
                       fromlist=["_"])
        except Exception:  # noqa: BLE001
            continue
    warmed = 0
    for name, key in reversed(pairs[-limit:] if limit else pairs):
        with _REGISTRY_LOCK:
            cache = _REGISTRY.get(name)
        if cache is None:
            continue
        if cache.warm(_freeze(key)):
            warmed += 1
    return warmed


def reset_warm_state_for_test() -> None:
    global _WARM_DIRTY
    with _WARM_LOCK:
        _WARM_SEEN.clear()
        _WARM_DIRTY = False
