"""Tier A: the logical plan cache.

Dashboard traffic re-submits the same statement text thousands of times;
before this cache every submission re-paid parse → analyze → plan →
optimize.  A hit skips all four: the runner goes straight from SQL text to
a cloned optimized plan tree (the reference engine's query-plan cache
role).

Key = SQL fingerprint (telemetry.runtime.fingerprint, for observability)
⊕ the exact statement text (fingerprints normalize case, which would
merge ``'BUILDING'`` with ``'building'`` — the text disambiguates) ⊕ the
session properties that shape planning/execution ⊕ the engine env knobs
that select alternate executables (``TRINO_TPU_HASH_IMPL`` etc., so a
knob flip can never serve a plan built for the other implementation) ⊕
the catalog **generation counter** (connectors/catalog.py), which bumps
on every DDL/ANALYZE so schema or stats changes invalidate wholesale.

Hits hand out ``copy.deepcopy`` clones: plan nodes are frozen dataclasses
but carry compare-excluded mutable payloads (TupleDomain constraints), so
sharing one tree across concurrent executions would be a footgun.  A
clone is microseconds against the multi-millisecond plan pipeline it
replaces.

``TRINO_TPU_PLAN_CACHE=0`` (checked per lookup) disables the tier:
every query re-plans exactly as before, bit for bit.
"""

from __future__ import annotations

import copy
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

__all__ = [
    "PlanEntry", "lookup", "store", "clone", "scan_tables",
    "planning_env_key", "session_key", "enabled", "stats",
    "invalidate_all", "reset_for_test",
]

# env knobs that change which jitted programs execute a plan (and hence
# the bitwise result of float aggregation): a flip must miss
PLANNING_ENV_KNOBS = (
    "TRINO_TPU_HASH_IMPL", "TRINO_TPU_FUSED_STAGE", "TRINO_TPU_FUSED_CAP",
    "TRINO_TPU_SYNC_FREE", "TRINO_TPU_LEGACY_EXPAND",
    "TRINO_TPU_TPCH_VECTOR_DECODE", "TRINO_TPU_PREFETCH",
    "TRINO_TPU_OPTIMIZER", "TRINO_TPU_HBO",
    "TRINO_TPU_JOIN_REORDER_DP_LIMIT", "TRINO_TPU_BROADCAST_ROW_LIMIT",
)

# session properties that shape the logical plan or the execution layout
# (split counts change partial-agg accumulation order → float bits)
SESSION_KEY_PROPS = (
    "default_catalog", "splits_per_node", "node_count", "dynamic_filtering",
    "task_concurrency", "hbm_limit_bytes", "spill_to_disk_bytes",
    "use_collectives", "exchange_serde", "scale_writers",
    "writer_task_limit",
)


def enabled() -> bool:
    return os.environ.get("TRINO_TPU_PLAN_CACHE", "1").strip().lower() \
        not in ("0", "off", "false", "no")


def _max_entries() -> int:
    return int(os.environ.get("TRINO_TPU_PLAN_CACHE_ENTRIES", "256"))


def planning_env_key() -> tuple:
    return tuple(os.environ.get(k, "") for k in PLANNING_ENV_KNOBS)


def session_key(session) -> tuple:
    return tuple(getattr(session, p, None) for p in SESSION_KEY_PROPS)


@dataclass
class PlanEntry:
    """One cached optimized plan + everything the execution fast path
    needs without re-walking: the scanned (catalog, table) set feeding the
    result-cache version vector, and the generation-free key prefix the
    result cache keys on (a harmless catalog-generation bump must re-plan
    but may still serve a version-validated cached result)."""

    plan: object
    tables: tuple
    result_key_base: tuple
    fingerprint: str
    cacheable_result: bool


_LOCK = threading.Lock()
_ENTRIES: OrderedDict = OrderedDict()
_HITS = 0
_MISSES = 0
_EVICTIONS = 0
_INVALIDATIONS = 0


def scan_tables(plan) -> tuple:
    """Sorted unique (catalog, table) pairs the plan reads."""
    from ..planner.plan import TableScan

    out = set()

    def walk(node):
        if isinstance(node, TableScan):
            out.add((node.catalog, node.table))
        for c in node.children:
            walk(c)

    walk(plan)
    return tuple(sorted(out))


def _result_cacheable(plan) -> bool:
    """Table functions have no version token and may synthesize volatile
    data; plans containing them never enter the result tier."""
    from ..planner.plan import TableFunctionScan, TableWriter

    def walk(node):
        if isinstance(node, (TableFunctionScan, TableWriter)):
            return False
        return all(walk(c) for c in node.children)

    return walk(plan)


def _has_writer(plan) -> bool:
    """Writer plans stay out of Tier A: the hit path re-checks SELECT
    access only, so a cached INSERT/CTAS/DELETE rewrite would bypass the
    write-privilege check that guards the cold path."""
    from ..planner.plan import TableWriter

    def walk(node):
        if isinstance(node, TableWriter):
            return True
        return any(walk(c) for c in node.children)

    return walk(plan)


def _key(sql: str, session, catalog, flavor: str) -> tuple:
    from ..telemetry.runtime import fingerprint

    # flavor partitions plan shapes ("local" vs "fragmented" — the
    # distributed runner's trees carry exchange nodes); the catalog
    # instance id keeps the process-global cache partitioned per catalog:
    # two runners with fresh catalogs (and fresh memory connectors) must
    # never see each other's plans or results
    # the history epoch keys out plans shaped by observed stats: new
    # plan_stats records -> new epoch -> cached history-driven plans
    # cannot outlive (or poison) the history that shaped them
    from ..planner.history import history_epoch

    return (flavor, fingerprint(sql), sql.strip(), session_key(session),
            planning_env_key(), getattr(catalog, "instance_id", id(catalog)),
            getattr(catalog, "generation", 0), history_epoch())


def lookup(sql: str, session, catalog,
           flavor: str = "local") -> Optional[PlanEntry]:
    global _HITS, _MISSES
    if not enabled():
        return None
    key = _key(sql, session, catalog, flavor)
    from ..telemetry import metrics as tm

    with _LOCK:
        entry = _ENTRIES.get(key)
        if entry is not None:
            _ENTRIES.move_to_end(key)
            _HITS += 1
        else:
            _MISSES += 1
    if entry is None:
        tm.CACHE_PLAN_MISSES.inc()
        return None
    tm.CACHE_PLAN_HITS.inc()
    from ..telemetry import profiler

    if profiler.enabled():
        profiler.instant("cache", "plan_hit", fingerprint=entry.fingerprint)
    return entry


def store(sql: str, session, catalog, plan,
          flavor: str = "local") -> PlanEntry:
    """Build the entry for a freshly planned statement and (when the tier
    is enabled) publish it.  Always returns the entry — the execution fast
    path uses it even when caching is off."""
    global _EVICTIONS
    from ..telemetry.runtime import fingerprint

    key = _key(sql, session, catalog, flavor)
    publish = enabled() and not _has_writer(plan)
    # the caller executes ``plan`` (execution attaches mutable TupleDomain
    # constraints to scan nodes) — the cache must hold a pristine copy
    entry = PlanEntry(
        plan=clone(plan) if publish else plan,
        tables=scan_tables(plan),
        # key[:-1] drops the catalog generation — the result tier
        # re-validates freshness through per-table version tokens instead
        result_key_base=key[:-1],
        fingerprint=fingerprint(sql),
        cacheable_result=_result_cacheable(plan),
    )
    if not publish:
        return entry
    from ..telemetry import metrics as tm

    with _LOCK:
        _ENTRIES[key] = entry
        while len(_ENTRIES) > _max_entries():
            _ENTRIES.popitem(last=False)
            _EVICTIONS += 1
            tm.CACHE_PLAN_EVICTIONS.inc()
        tm.CACHE_PLAN_ENTRIES.set(len(_ENTRIES))
    return entry


def clone(plan):
    """A private copy of a cached tree for one execution."""
    return copy.deepcopy(plan)


def invalidate_all() -> None:
    global _INVALIDATIONS
    from ..telemetry import metrics as tm

    with _LOCK:
        n = len(_ENTRIES)
        _ENTRIES.clear()
        _INVALIDATIONS += n
        if n:
            tm.CACHE_PLAN_INVALIDATIONS.inc(n)
        tm.CACHE_PLAN_ENTRIES.set(0)


def stats() -> dict:
    with _LOCK:
        return {
            "tier": "plan", "name": "plan", "entries": len(_ENTRIES),
            "bytes": 0, "hits": _HITS, "misses": _MISSES,
            "evictions": _EVICTIONS, "invalidations": _INVALIDATIONS,
        }


def reset_for_test() -> None:
    global _HITS, _MISSES, _EVICTIONS, _INVALIDATIONS
    with _LOCK:
        _ENTRIES.clear()
        _HITS = _MISSES = _EVICTIONS = _INVALIDATIONS = 0
