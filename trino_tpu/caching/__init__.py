"""The three-tier cache plane for repeated dashboard-style traffic.

- **Tier A** — :mod:`.plan_cache`: fingerprinted logical plans; a hit
  skips parse → analyze → plan → optimize.
- **Tier B** — :mod:`.executable_cache`: the bounded/observable registry
  behind every jitted-program memo, plus JAX's on-disk compilation cache
  and the boot-time warm journal.
- **Tier C** — :mod:`.result_cache`: table-version-keyed query results
  with connector invalidation.

Each tier has an independent ``TRINO_TPU_{PLAN,EXEC,RESULT}_CACHE=0``
kill switch that restores bit-for-bit legacy behavior.  This module only
adds the cross-tier observability roll-up consumed by
``system.runtime.caches`` and ``GET /v1/caches``.
"""

from __future__ import annotations

__all__ = ["cache_rows", "reset_for_test"]


def cache_rows(per_exec_cache: bool = False) -> list[dict]:
    """Per-tier stats rows: plan, exec (aggregated — or one row per
    registered cache with ``per_exec_cache``), result.  Dict shape matches
    the ``system.runtime.caches`` schema."""
    from . import executable_cache, plan_cache, result_cache

    rows = [plan_cache.stats()]
    if per_exec_cache:
        rows.extend(executable_cache.registry_stats())
    else:
        rows.append(executable_cache.aggregate_stats())
    rows.append(result_cache.stats())
    return rows


def reset_for_test() -> None:
    """Clear every tier's entries and stats (exec registry keeps its
    registered caches, drops their contents)."""
    from . import executable_cache, plan_cache, result_cache

    plan_cache.reset_for_test()
    result_cache.reset_for_test()
    executable_cache.clear_all()
    executable_cache.reset_warm_state_for_test()
    from ..exec import join_exec

    join_exec.reset_estimate_seeds_for_test()
