"""Resource-group selectors: which group a session's queries land in.

The miniature of the reference's resource-group selector rules
(spi/resourcegroups/SelectionCriteria.java + db/file selector configs):
each rule optionally matches the session ``user``, the session ``source``
(client-declared workload tag, e.g. ``etl`` vs ``dashboard``) and the SQL
text by regex; the first matching rule names the dotted group path under
the root.  A rule with no match fields is a catch-all.

Rules arrive either programmatically or as the ``selectors`` list inside
the ``TRINO_TPU_RESOURCE_GROUPS`` JSON
(execution/resource_manager.py ``build_group_tree``)::

    {"selectors": [
        {"source": "etl.*",  "group": "batch"},
        {"user": "admin",    "group": "admin"},
        {"group": "adhoc"}]}
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

__all__ = ["SelectorRule", "GroupSelector"]


@dataclass(frozen=True)
class SelectorRule:
    """One selector: regexes are full-match (anchored), like the
    reference's ``userRegex``/``sourceRegex``."""

    group: str
    user: Optional[str] = None
    source: Optional[str] = None
    sql: Optional[str] = None

    def matches(self, sql: str, session) -> bool:
        if self.user is not None and not re.fullmatch(
                self.user, getattr(session, "user", "") or ""):
            return False
        if self.source is not None and not re.fullmatch(
                self.source, getattr(session, "source", "") or ""):
            return False
        if self.sql is not None and not re.search(self.sql, sql or ""):
            return False
        return True


class GroupSelector:
    """First-match-wins rule list; ``select`` returns the dotted group path
    ('' = root) and plugs straight into DispatchManager's selector hook."""

    def __init__(self, rules: list[SelectorRule]):
        self.rules = list(rules)

    @classmethod
    def from_spec(cls, spec: list[dict]) -> "GroupSelector":
        rules = []
        for d in spec:
            if "group" not in d:
                raise ValueError(f"selector rule without 'group': {d!r}")
            rules.append(SelectorRule(
                group=d["group"], user=d.get("user"),
                source=d.get("source"), sql=d.get("sql")))
        return cls(rules)

    def select(self, sql: str, session) -> str:
        for rule in self.rules:
            if rule.matches(sql, session):
                return rule.group
        return ""
