"""Event listener SPI: query lifecycle events to external sinks.

Mirrors ``spi/eventlistener/EventListener.java:16`` (QueryCreatedEvent /
QueryCompletedEvent dispatched by the coordinator; plugins ship them to
HTTP/Kafka/MySQL sinks).  Listeners here are python objects registered on a
runner; exceptions in a listener never fail the query (reference
behavior)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

__all__ = ["QueryCreatedEvent", "QueryCompletedEvent", "EventListener",
           "EventListenerManager"]


@dataclass(frozen=True)
class QueryCreatedEvent:
    query_id: str
    sql: str
    user: str = ""
    create_time: float = field(default_factory=time.time)


@dataclass(frozen=True)
class QueryCompletedEvent:
    query_id: str
    sql: str
    state: str = "FINISHED"  # FINISHED | FAILED
    user: str = ""
    wall_ms: float = 0.0
    output_rows: int = -1
    error: Optional[str] = None
    # final QueryStats roll-up (the reference ships cpu/wall/peak-memory/
    # input counts in its QueryCompletedEvent statistics block): process CPU
    # over the query window, device allocator peak, scanned input, and the
    # retry_policy=QUERY attempt count
    cpu_ms: float = 0.0
    peak_memory_bytes: int = 0
    input_rows: int = 0
    input_bytes: int = 0
    retry_count: int = 0
    # admission + speculation + failure classification (PR 8/PR 9 additions
    # the journal round-trips): queue wait, the resource group the query
    # ran under, speculative twins that won its task races, and the
    # spi/errors.py error-code name for FAILED queries
    queued_time_ms: float = 0.0
    resource_group: str = ""
    speculative_wins: int = 0
    error_code: Optional[str] = None
    end_time: float = field(default_factory=time.time)


class EventListener:
    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass


class EventListenerManager:
    def __init__(self):
        self._listeners: list[EventListener] = []

    def add(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def query_created(self, event: QueryCreatedEvent) -> None:
        for l in self._listeners:
            try:
                l.query_created(event)
            except Exception:  # noqa: BLE001 — listeners never fail queries
                pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        for l in self._listeners:
            try:
                l.query_completed(event)
            except Exception:  # noqa: BLE001
                pass
