"""Columnar batch data model (the Page/Block equivalent).

Mirrors Trino's ``io.trino.spi.Page`` / ``spi/block/Block`` (reference:
core/trino-spi/src/main/java/io/trino/spi/Page.java:95, spi/block/Block.java:23)
re-designed for XLA:

- A :class:`Column` is one fixed-shape 1-D array (``numpy`` on host, moved to
  device at kernel boundaries) + an optional validity mask (True = non-null).
  This replaces the four sealed Block shapes (ValueBlock / DictionaryBlock /
  RunLengthEncodedBlock / LazyBlock): dictionary encoding is *mandatory* for
  strings, RLE is left to XLA's fusion, and laziness lives in the connector
  (columns are only generated/loaded when the plan projects them).
- String columns store ``int32`` codes into a host-side **sorted** dictionary
  (``np.ndarray`` of python str).  Sortedness makes code-space comparisons
  order-correct, so <, >, ORDER BY, MIN/MAX run on the device on codes alone.
  String *functions* are dictionary transforms evaluated host-side over the
  (small) dictionary, then a device-side gather remaps codes — the TPU never
  touches bytes of text.
- A :class:`ColumnBatch` is an ordered set of equal-length Columns, the unit
  that flows between operators (Trino targets ~1MB Pages; we target fixed
  row-count batches so jit caches hit).
"""

from __future__ import annotations

import datetime
import decimal
from dataclasses import dataclass
from typing import Sequence

import numpy as np

from .types import (
    BOOLEAN,
    DATE,
    DOUBLE,
    TIMESTAMP,
    ArrayType,
    DecimalType,
    MapType,
    RowType,
    Type,
    days_to_date,
)

__all__ = ["Column", "ColumnBatch", "encode_strings", "unify_dictionaries",
           "round_up_pow2", "pad_to_bucket", "encoded_exec", "maybe_rle",
           "set_materialize_hook"]


def encoded_exec() -> bool:
    """Compressed execution master switch (TRINO_TPU_ENCODED_EXEC):
    ``auto``/``1`` let operators consume RLE/LAZY/dictionary encodings
    directly; ``0`` is the bit-for-bit legacy expand-at-scan path."""
    import os

    return os.environ.get("TRINO_TPU_ENCODED_EXEC", "auto") != "0"


# telemetry hook (set by telemetry/metrics.py): called with
# (encoding, nbytes) whenever an encoded column materializes its flat
# representation.  A plain module global so spi stays import-light.
_MATERIALIZE_HOOK = None


def set_materialize_hook(fn) -> None:
    global _MATERIALIZE_HOOK
    _MATERIALIZE_HOOK = fn


def round_up_pow2(n: int, minimum: int = 8) -> int:
    """Round up to a power of two — the static-shape recompile bucket.  All
    batch shapes in the jitted data plane are bucketed so XLA programs are
    compiled once per (pipeline, bucket) instead of once per row count."""
    c = minimum
    while c < n:
        c <<= 1
    return c


def encode_strings(values: Sequence[str | None]) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Encode python strings into (codes, valid, sorted_dictionary)."""
    valid = np.array([v is not None for v in values], dtype=np.bool_)
    filled = np.array([v if v is not None else "" for v in values], dtype=object)
    dictionary, codes = np.unique(filled, return_inverse=True)
    return codes.astype(np.int32), valid, dictionary


def _canon_key(v):
    """Deterministic sort key for array dictionary entries: lexicographic
    with NULL elements last (comparisons must never hit None<x)."""
    return tuple((e is None, e if e is not None else 0) for e in v)


def _object_array(values) -> np.ndarray:
    # np.array(list_of_equal_len_tuples) would build a 2-D array; fill by slot
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


def encode_arrays(values: Sequence) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Encode python sequences (arrays) into (codes, valid, dictionary of
    tuples).  Same contract as encode_strings, tuple-valued dictionary."""
    filled = [tuple(v) if v is not None else () for v in values]
    valid = np.array([v is not None for v in values], dtype=np.bool_)
    uniq = sorted(set(filled), key=_canon_key)
    pos = {v: i for i, v in enumerate(uniq)}
    codes = np.array([pos[v] for v in filled], dtype=np.int32)
    return codes, valid, _object_array(uniq)


def encode_sorted_objects(values: Sequence, null_fill
                          ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Encode naturally-orderable python objects (long-decimal scaled ints)
    into (codes, valid, sorted dictionary)."""
    valid = np.array([v is not None for v in values], dtype=np.bool_)
    filled = [v if v is not None else null_fill for v in values]
    uniq = sorted(set(filled))
    pos = {v: i for i, v in enumerate(uniq)}
    codes = np.array([pos[v] for v in filled], dtype=np.int32)
    return codes, valid, _object_array(uniq)


# dictionary byte accounting: object-dtype dictionaries (strings, tuples)
# report pointer bytes via .nbytes, so the real payload is summed once and
# memoized by (id, len) — accounting, not an exact allocator figure
_DICT_NBYTES_CACHE: dict = {}


def _dictionary_nbytes(d) -> int:
    if d is None:
        return 0
    if d.dtype != object:
        return int(d.nbytes)
    key = id(d)
    hit = _DICT_NBYTES_CACHE.get(key)
    if hit is not None and hit[0] == len(d):
        return hit[1]
    total = 0
    for v in d:
        total += len(str(v).encode("utf-8", "replace"))
    if len(_DICT_NBYTES_CACHE) > 4096:
        _DICT_NBYTES_CACHE.clear()
    _DICT_NBYTES_CACHE[key] = (len(d), total)
    return total


class Column:
    """One column of a batch: fixed-width array + validity + dictionary.

    ``data``/``valid`` may be numpy (host) OR jax arrays (device-resident):
    the engine's hot path keeps columns on device between operators and only
    materializes to host at true boundaries (exchange serialization, client
    results, oracle diffs).  Mirrors how the reference keeps Pages inside the
    JVM heap between compiled operators (operator/Driver.java:403-408).

    The reference's sealed Block shapes are carried as an ``encoding`` tag
    instead of subclasses (spi/block/Block.java:23):

    - ``FLAT``  — dense array (ValueBlock)
    - ``DICT``  — FLAT int32 codes + a host-side sorted ``dictionary``
      (DictionaryBlock; mandatory for strings)
    - ``RLE``   — ONE stored value + a run length (RunLengthEncodedBlock);
      ``valid`` may still be a full-length mask (nulls inside the run)
    - ``LAZY``  — a thunk producing ``(data, valid)`` on first touch
      (LazyBlock); until touched the column costs no HBM and no PCIe

    Touching ``.data``/``.valid`` on an encoded column materializes the
    flat view exactly once (RLE materializes as a zero-copy broadcast
    view).  Encoding-aware operators check ``.encoding`` first and never
    touch the flat view on their fast paths."""

    __slots__ = ("type", "dictionary", "_data", "_valid", "_length",
                 "_enc", "_rle_value", "_thunk", "_nbytes_hint", "_derived")

    def __init__(self, type: Type, data, valid=None, dictionary=None):
        self.type = type
        self.dictionary = dictionary
        self._enc = "FLAT"
        self._rle_value = None
        self._thunk = None
        self._nbytes_hint = 0
        self._derived = False
        self._data = data
        self._length = int(data.shape[0])
        self._valid = valid
        self.__post_init__()

    def __post_init__(self):
        # normalizing all-valid masks to None requires a host sync for device
        # arrays — only do it for numpy
        if isinstance(self._valid, np.ndarray) and self._valid.all():
            self._valid = None

    # -- encoded constructors ------------------------------------------------

    @staticmethod
    def rle(type_: Type, value, length: int, valid=None,
            dictionary=None) -> "Column":
        """Run-length column: one stored value repeated ``length`` times.
        ``value`` is the storage-dtype scalar (the int32 code for
        dictionary columns); ``valid`` may be a full-length mask so a run
        can contain NULLs without breaking the encoding."""
        c = Column.__new__(Column)
        c.type = type_
        c.dictionary = dictionary
        c._enc = "RLE"
        dtype = np.int32 if dictionary is not None else type_.storage_dtype
        c._rle_value = np.asarray(value, dtype=dtype)
        c._thunk = None
        c._nbytes_hint = 0
        c._derived = False
        c._data = None
        c._length = int(length)
        c._valid = valid
        c.__post_init__()
        return c

    @staticmethod
    def lazy(type_: Type, length: int, thunk, dictionary=None,
             nbytes_hint: int = 0, derived: bool = False) -> "Column":
        """Deferred column: ``thunk()`` returns ``(data, valid)`` and runs
        at most once, on first ``.data``/``.valid`` touch.  ``nbytes_hint``
        feeds byte accounting while unmaterialized (e.g. the host bytes the
        thunk would stage).  ``derived`` marks a wrapper over another lazy
        column (pad/slice composition) so the materialize hook fires once
        per logical column, at the innermost thunk."""
        c = Column.__new__(Column)
        c.type = type_
        c.dictionary = dictionary
        c._enc = "LAZY"
        c._rle_value = None
        c._thunk = thunk
        c._nbytes_hint = int(nbytes_hint)
        c._derived = bool(derived)
        c._data = None
        c._length = int(length)
        c._valid = None
        return c

    # -- encoding accessors --------------------------------------------------

    @property
    def encoding(self) -> str:
        """``FLAT | DICT | RLE | LAZY`` — DICT is a flat code array with a
        dictionary attached (codes ARE the flat representation here)."""
        if self._enc == "FLAT" and self.dictionary is not None:
            return "DICT"
        return self._enc

    @property
    def rle_value(self):
        """The RLE run's stored scalar (storage dtype; code if DICT)."""
        assert self._enc == "RLE"
        return self._rle_value

    @property
    def is_materialized(self) -> bool:
        return self._data is not None or self._enc == "RLE"

    def _materialize(self) -> None:
        if self._data is not None:
            return
        if self._enc == "RLE":
            # zero-copy: a readonly broadcast view over the single value
            self._data = np.broadcast_to(self._rle_value, (self._length,))
            return
        hook = _MATERIALIZE_HOOK
        thunk, self._thunk = self._thunk, None
        data, valid = thunk()
        assert int(data.shape[0]) == self._length, "lazy thunk length"
        self._data = data
        if self._valid is None:
            self._valid = valid
            self.__post_init__()
        self._enc = "FLAT"
        if hook is not None and not self._derived:
            hook("LAZY", self._nbytes_hint or int(data.nbytes))

    @property
    def data(self):
        if self._data is None:
            self._materialize()
        return self._data

    @property
    def valid(self):
        if self._data is None and self._enc == "LAZY":
            self._materialize()
        return self._valid

    def __len__(self) -> int:
        return self._length

    def __repr__(self) -> str:  # debugging aid (dataclass repr equivalent)
        return (f"Column(type={self.type}, encoding={self.encoding}, "
                f"len={self._length})")

    def __reduce__(self):
        # pickling (task descriptors) materializes: thunks don't pickle
        return (Column, (self.type, np.asarray(self.data), self._valid,
                         self.dictionary))

    @property
    def nbytes(self) -> int:
        if self._enc == "RLE":
            n = int(self._rle_value.nbytes)
        elif self._data is None:
            n = self._nbytes_hint
        else:
            n = int(self._data.nbytes)
        if self._valid is not None:
            n += int(self._valid.nbytes)
        return n + _dictionary_nbytes(self.dictionary)

    @property
    def flat_nbytes(self) -> int:
        """Bytes of the EXPANDED flat representation (what legacy execution
        would carry) — the baseline for bytes-saved accounting."""
        itemsize = np.dtype(
            np.int32 if self.dictionary is not None
            else self.type.storage_dtype).itemsize
        n = self._length * itemsize
        if self._valid is not None:
            n += self._length
        return n + _dictionary_nbytes(self.dictionary)

    def valid_mask(self) -> np.ndarray:
        if self.valid is None:
            return np.ones(len(self), dtype=np.bool_)
        return np.asarray(self.valid)

    @staticmethod
    def from_values(type_: Type, values: Sequence) -> "Column":
        """Build a column from python values (None = NULL)."""
        if isinstance(type_, ArrayType):
            codes, valid, dictionary = encode_arrays(values)
            return Column(type_, codes, valid, dictionary)
        if isinstance(type_, DecimalType) and type_.is_long:
            # long decimal: sorted dictionary of python scaled ints
            scaled = [None if v is None else _to_scaled_int(v, type_.scale)
                      for v in values]
            codes, valid, dictionary = encode_sorted_objects(scaled, 0)
            return Column(type_, codes, valid, dictionary)
        if isinstance(type_, RowType):
            canon = [None if v is None else tuple(v) for v in values]
            codes, valid, dictionary = encode_arrays(canon)
            return Column(type_, codes, valid, dictionary)
        if isinstance(type_, MapType):
            canon = [
                None if v is None else tuple(sorted(
                    v.items() if isinstance(v, dict) else v))
                for v in values
            ]
            codes, valid, dictionary = encode_arrays(canon)
            return Column(type_, codes, valid, dictionary)
        if type_.is_dictionary_encoded:
            codes, valid, dictionary = encode_strings(values)
            return Column(type_, codes, valid, dictionary)
        valid = np.array([v is not None for v in values], dtype=np.bool_)
        if isinstance(type_, DecimalType):
            filled = [_to_scaled_int(v, type_.scale) if v is not None else 0
                      for v in values]
        elif type_ == DATE:
            filled = [_to_days(v) if v is not None else 0 for v in values]
        elif type_ == TIMESTAMP:
            filled = [_to_micros(v) if v is not None else 0 for v in values]
        else:
            zero = type_.zero_value()
            filled = [v if v is not None else zero for v in values]
        data = np.asarray(filled, dtype=type_.storage_dtype)
        return Column(type_, data, valid)

    def _empty_flat(self) -> "Column":
        """Zero-row flat column — lets an empty selection over an
        unmaterialized LAZY column skip the thunk entirely."""
        dtype = (np.int32 if self.dictionary is not None
                 else self.type.storage_dtype)
        return Column(self.type, np.empty(0, dtype), None, self.dictionary)

    def take(self, indices: np.ndarray) -> "Column":
        if self._enc == "RLE":
            # a gather over a constant run is still a constant run
            valid = None if self._valid is None else self._valid[indices]
            return Column.rle(self.type, self._rle_value,
                              int(indices.shape[0]), valid, self.dictionary)
        if (self._enc == "LAZY" and self._data is None
                and int(np.asarray(indices).shape[0]) == 0):
            return self._empty_flat()
        # works for numpy and jax alike (jax arrays gather on device)
        valid = None if self.valid is None else self.valid[indices]
        return Column(self.type, self.data[indices], valid, self.dictionary)

    def filter(self, mask: np.ndarray) -> "Column":
        # boolean-mask compaction is inherently dynamic-shape: force host
        mask = np.asarray(mask)
        if self._enc == "RLE":
            valid = (None if self._valid is None
                     else np.asarray(self._valid)[mask])
            return Column.rle(self.type, self._rle_value,
                              int(mask.sum()), valid, self.dictionary)
        if (self._enc == "LAZY" and self._data is None
                and not mask.any()):
            return self._empty_flat()
        valid = None if self.valid is None else np.asarray(self.valid)[mask]
        return Column(self.type, np.asarray(self.data)[mask], valid, self.dictionary)

    def slice_rows(self, start: int, stop: int) -> "Column":
        """Row-range slice with encoding propagation (host path)."""
        if self._enc == "RLE":
            stop = min(stop, self._length)
            valid = (None if self._valid is None
                     else np.asarray(self._valid)[start:stop])
            return Column.rle(self.type, self._rle_value,
                              max(0, stop - start), valid, self.dictionary)
        return Column(self.type, np.asarray(self.data)[start:stop],
                      None if self.valid is None
                      else np.asarray(self.valid)[start:stop],
                      self.dictionary)

    def to_pylist(self) -> list:
        """Decode to python values (None for NULL) — used by clients/oracle."""
        data = np.asarray(self.data)
        valid = self.valid_mask()
        t = self.type
        out: list = []
        if isinstance(t, ArrayType):
            d = self.dictionary
            for i in range(len(self)):
                out.append(list(d[data[i]]) if valid[i] else None)
        elif isinstance(t, DecimalType) and t.is_long:
            d = self.dictionary
            with decimal.localcontext() as ctx:
                ctx.prec = 80  # default 28-digit context rounds wide values
                for i in range(len(self)):
                    out.append(
                        decimal.Decimal(int(d[data[i]])).scaleb(-t.scale)
                        if valid[i] else None)
        elif isinstance(t, RowType):
            d = self.dictionary
            for i in range(len(self)):
                out.append(tuple(d[data[i]]) if valid[i] else None)
        elif isinstance(t, MapType):
            d = self.dictionary
            for i in range(len(self)):
                out.append(dict(d[data[i]]) if valid[i] else None)
        elif t.is_dictionary_encoded:
            d = self.dictionary
            for i in range(len(self)):
                out.append(str(d[data[i]]) if valid[i] else None)
        elif isinstance(t, DecimalType):
            for i in range(len(self)):
                # exact: scaled int -> decimal.Decimal (never through float)
                out.append(
                    decimal.Decimal(int(data[i])).scaleb(-t.scale) if valid[i] else None
                )
        elif t == DATE:
            for i in range(len(self)):
                out.append(days_to_date(data[i]) if valid[i] else None)
        elif t == BOOLEAN:
            for i in range(len(self)):
                out.append(bool(data[i]) if valid[i] else None)
        elif t in (DOUBLE,) or t.name == "real":
            for i in range(len(self)):
                out.append(float(data[i]) if valid[i] else None)
        else:
            for i in range(len(self)):
                out.append(int(data[i]) if valid[i] else None)
        return out


def _to_days(v) -> int:
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, str):
        v = datetime.date.fromisoformat(v)
    return (v - datetime.date(1970, 1, 1)).days


def _to_micros(v) -> int:
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, str):
        v = datetime.datetime.fromisoformat(v)
    if isinstance(v, datetime.datetime):
        epoch = datetime.datetime(1970, 1, 1, tzinfo=v.tzinfo)
        return int((v - epoch) / datetime.timedelta(microseconds=1))
    raise TypeError(f"cannot convert {type(v).__name__} to timestamp")


def rescale_scaled_int(v: int, fs: int, ds: int) -> int:
    """Exact scaled-int rescale with HALF_UP rounding (python bignums,
    80-digit context — the shared Int128Math-style helper for casts and
    aggregate finalization)."""
    if ds >= fs:
        return v * 10 ** (ds - fs)
    with decimal.localcontext() as ctx:
        ctx.prec = 80
        return int(decimal.Decimal(v).scaleb(ds - fs).quantize(
            0, rounding=decimal.ROUND_HALF_UP))


def _to_scaled_int(v, scale: int) -> int:
    """Exact conversion to scaled int64 (never through float64 for exact
    inputs — int/str/Decimal keep full 18-digit precision)."""
    if isinstance(v, (int, np.integer)):
        return int(v) * 10**scale
    if isinstance(v, (str, decimal.Decimal)):
        # default decimal context rounds at 28 digits; wide decimals need
        # the full 38 -> compute under an explicit high-precision context
        with decimal.localcontext() as ctx:
            ctx.prec = 80
            d = decimal.Decimal(v)
            return int((d * 10**scale).to_integral_value(
                rounding=decimal.ROUND_HALF_UP))
    return int(round(float(v) * 10**scale))


def unify_dictionaries(columns: Sequence[Column]) -> list[Column]:
    """Remap a set of dictionary columns onto one shared sorted dictionary.

    Required before concatenating string columns coming from different
    sources.  Host-side; cost is O(total dictionary size)."""
    empty = np.array([], dtype=object)
    dicts = [c.dictionary if c.dictionary is not None else empty for c in columns]
    first = dicts[0]
    if all(d is first or (d.shape == first.shape and (d == first).all()) for d in dicts):
        return list(columns)
    if any(len(d) and isinstance(d[0], tuple) for d in dicts):
        return _unify_object_dictionaries(columns, dicts)
    merged = np.unique(np.concatenate(dicts))
    out = []
    for c, d in zip(columns, dicts):
        remap = np.searchsorted(merged, d).astype(np.int32)
        # no source dictionary => codes are meaningless; point at slot 0
        if not len(d):
            data = np.zeros(len(c), dtype=np.int32)
        elif isinstance(c.data, np.ndarray):
            data = remap[c.data]
        else:  # device codes: gather the (tiny) remap table on device
            import jax.numpy as jnp

            data = jnp.asarray(remap)[c.data]
        out.append(Column(c.type, data, c.valid, merged))
    return out


def _unify_object_dictionaries(columns: Sequence[Column], dicts) -> list[Column]:
    """Array-dictionary variant of unify_dictionaries: tuples with possible
    None elements are not numpy-sortable, so merge with the canonical key."""
    merged_list = sorted({x for d in dicts for x in d}, key=_canon_key)
    pos = {v: i for i, v in enumerate(merged_list)}
    merged = _object_array(merged_list)
    out = []
    for c, d in zip(columns, dicts):
        remap = np.array([pos[v] for v in d], dtype=np.int32)
        if not len(d):
            data = np.zeros(len(c), dtype=np.int32)
        elif isinstance(c.data, np.ndarray):
            data = remap[c.data]
        else:
            import jax.numpy as jnp

            data = jnp.asarray(remap)[c.data]
        out.append(Column(c.type, data, c.valid, merged))
    return out


@dataclass
class ColumnBatch:
    """An ordered, named set of equal-length columns (the Page equivalent).

    ``live`` is an optional per-row mask (True = row exists): the fused
    filter kernels mark rows dead instead of compacting, because compaction
    is a dynamic-shape operation XLA cannot fuse — batches stay at their
    padded power-of-two size through the jitted pipeline (the selection-
    vector idiom replacing Trino's Page.getPositions compaction).  Operators
    either understand ``live`` or call :meth:`compact` first."""

    names: list[str]
    columns: list[Column]
    live: np.ndarray | None = None  # None = every row live

    def __post_init__(self):
        assert len(self.names) == len(self.columns)
        if self.columns:
            n = len(self.columns[0])
            assert all(len(c) == n for c in self.columns), "ragged batch"

    @property
    def num_rows(self) -> int:
        """Physical row slots (including dead rows when ``live`` is set)."""
        return len(self.columns[0]) if self.columns else 0

    @property
    def live_count(self) -> int:
        """Number of live rows (host sync when ``live`` is a device array)."""
        if self.live is None:
            return self.num_rows
        return int(np.asarray(self.live).sum())

    def to_host(self) -> "ColumnBatch":
        """Materialize every device array with ONE jax.device_get round trip.

        Per-array np.asarray costs a full device round trip each (~100ms over
        a tunneled TPU); batching the transfer makes the host boundary one
        round trip per batch instead of one per column."""
        pending = []
        for c in self.columns:
            if c.encoding == "LAZY":
                continue  # untouched: materializing would defeat laziness
            if not isinstance(c.data, np.ndarray):
                pending.append(c.data)
            if c.valid is not None and not isinstance(c.valid, np.ndarray):
                pending.append(c.valid)
        if self.live is not None and not isinstance(self.live, np.ndarray):
            pending.append(self.live)
        if not pending:
            return self
        import jax

        fetched = iter(jax.device_get(pending))
        cols = []
        for c in self.columns:
            if c.encoding == "LAZY":
                cols.append(c)
                continue
            d = c.data if isinstance(c.data, np.ndarray) else next(fetched)
            v = c.valid
            if v is not None and not isinstance(v, np.ndarray):
                v = next(fetched)
            if c.encoding == "RLE":
                cols.append(Column.rle(c.type, c.rle_value, len(c), v,
                                       c.dictionary))
            else:
                cols.append(Column(c.type, d, v, c.dictionary))
        live = self.live
        if live is not None and not isinstance(live, np.ndarray):
            live = next(fetched)
        return ColumnBatch(self.names, cols, live)

    def compact(self) -> "ColumnBatch":
        """Densify: drop dead rows, return a host-side batch without live."""
        dense = self.to_host()
        if dense.live is None:
            return dense
        mask = np.asarray(dense.live)
        if mask.all():
            return ColumnBatch(dense.names, dense.columns)
        return ColumnBatch(dense.names, [c.filter(mask) for c in dense.columns])

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def nbytes(self) -> int:
        return sum(c.nbytes for c in self.columns)

    def column(self, name: str) -> Column:
        return self.columns[self.names.index(name)]

    @property
    def types(self) -> list[Type]:
        return [c.type for c in self.columns]

    @staticmethod
    def from_pydict(data: dict[str, tuple[Type, Sequence]]) -> "ColumnBatch":
        names = list(data.keys())
        cols = [Column.from_values(t, vals) for (t, vals) in data.values()]
        return ColumnBatch(names, cols)

    def take(self, indices: np.ndarray) -> "ColumnBatch":
        assert self.live is None, "take() on a masked batch (compact first)"
        return ColumnBatch(self.names, [c.take(indices) for c in self.columns])

    def filter(self, mask: np.ndarray) -> "ColumnBatch":
        assert self.live is None, "filter() on a masked batch (compact first)"
        return ColumnBatch(self.names, [c.filter(mask) for c in self.columns])

    def select(self, names: Sequence[str]) -> "ColumnBatch":
        return ColumnBatch(list(names), [self.column(n) for n in names], self.live)

    def slice(self, start: int, stop: int) -> "ColumnBatch":
        assert self.live is None, "slice() on a masked batch (compact first)"
        return ColumnBatch(
            self.names,
            [c.slice_rows(start, stop) for c in self.columns],
        )

    @staticmethod
    def concat(batches: Sequence["ColumnBatch"]) -> "ColumnBatch":
        if not batches:
            raise ValueError("ColumnBatch.concat of an empty batch list "
                             "(caller must supply at least the schema batch)")
        batches = [b.compact() for b in batches]
        batches = [b for b in batches if b.num_rows > 0] or list(batches[:1])
        if len(batches) == 1:
            return batches[0]
        names = batches[0].names
        out_cols = []
        for i in range(len(names)):
            cols = [b.columns[i] for b in batches]
            rle = _concat_rle(cols)
            if rle is not None:
                out_cols.append(rle)
                continue
            if cols[0].type.is_dictionary_encoded:
                cols = unify_dictionaries(cols)
            data = np.concatenate([np.asarray(c.data) for c in cols])
            if any(c.valid is not None for c in cols):
                valid = np.concatenate([c.valid_mask() for c in cols])
            else:
                valid = None
            out_cols.append(Column(cols[0].type, data, valid, cols[0].dictionary))
        return ColumnBatch(names, out_cols)

    def to_pylist(self) -> list[tuple]:
        """Rows as python tuples (client/oracle boundary)."""
        dense = self.compact()
        cols = [c.to_pylist() for c in dense.columns]
        return list(zip(*cols)) if cols else []

    def rename(self, names: Sequence[str]) -> "ColumnBatch":
        return ColumnBatch(list(names), self.columns, self.live)


def _same_dictionary(a, b) -> bool:
    if a is None or b is None:
        return a is b
    return a is b or (a.shape == b.shape and (a == b).all())


def _concat_rle(cols: Sequence[Column]):
    """One RLE column covering a concatenation of same-value runs, or None
    when the inputs aren't a single mergeable run."""
    if not all(c.encoding == "RLE" for c in cols):
        return None
    first = cols[0]
    for c in cols[1:]:
        if (c.rle_value != first.rle_value
                or not _same_dictionary(c.dictionary, first.dictionary)):
            return None
    total = sum(len(c) for c in cols)
    if all(c.valid is None for c in cols):
        valid = None
    else:
        valid = np.concatenate([c.valid_mask() for c in cols])
    return Column.rle(first.type, first.rle_value, total, valid,
                      first.dictionary)


# RLE page-build detection floor: below this a run saves nothing worth the
# check; the two-element probe keeps the reject path O(1)
RLE_DETECT_MIN_ROWS = 64


def maybe_rle(col: Column) -> Column:
    """Cheap constant-run detection at page build: a dense host column whose
    every element equals its first collapses to RLE.  O(1) reject via a
    first/last probe before the full equality scan; non-FLAT/DICT and
    device columns pass through untouched."""
    if col.encoding not in ("FLAT", "DICT") or len(col) < RLE_DETECT_MIN_ROWS:
        return col
    data = col._data
    if not isinstance(data, np.ndarray) or data.dtype == object:
        return col
    if data[0] != data[-1] or not (data == data[0]).all():
        return col
    if col.valid is not None and not isinstance(col.valid, np.ndarray):
        return col
    return Column.rle(col.type, data[0], len(col), col.valid, col.dictionary)


def pad_to_bucket(batch: ColumnBatch) -> ColumnBatch:
    """Pad a dense batch to its power-of-two row bucket, marking the padding
    dead in ``live``.  A batch that already carries a ``live`` mask is
    already bucket-shaped (device-pinned tables / jitted pipeline output):
    passed through untouched.  Device-resident columns pad with device ops
    (async, no host round trip); host columns pad in numpy."""
    if batch.live is not None:
        return batch
    n = batch.num_rows
    cap = round_up_pow2(n)
    if cap == n or n == 0:
        return batch
    pad = cap - n
    on_device = any(c.encoding not in ("RLE", "LAZY")
                    and not isinstance(c.data, np.ndarray)
                    for c in batch.columns)

    def _pad_encoded(c: Column):
        """RLE extends its run over the dead pad rows; LAZY composes a
        padding thunk — neither expands."""
        if c.encoding == "RLE":
            valid = c.valid
            if valid is not None:
                if isinstance(valid, np.ndarray):
                    valid = np.concatenate(
                        [valid, np.zeros(pad, np.bool_)])
                else:
                    import jax.numpy as jnp

                    valid = jnp.concatenate(
                        [valid, jnp.zeros(pad, jnp.bool_)])
            return Column.rle(c.type, c.rle_value, cap, valid, c.dictionary)
        if c.encoding == "LAZY":
            def thunk(c=c):
                data = np.concatenate(
                    [np.asarray(c.data),
                     np.zeros(pad, np.asarray(c.data).dtype)])
                valid = None
                if c.valid is not None:
                    valid = np.concatenate(
                        [np.asarray(c.valid), np.zeros(pad, np.bool_)])
                return data, valid

            return Column.lazy(c.type, cap, thunk, c.dictionary,
                               nbytes_hint=c.nbytes, derived=True)
        return None

    if on_device:
        import jax.numpy as jnp

        cols = []
        for c in batch.columns:
            enc = _pad_encoded(c)
            if enc is not None:
                cols.append(enc)
                continue
            data = jnp.concatenate(
                [jnp.asarray(c.data), jnp.zeros(pad, jnp.asarray(c.data).dtype)])
            valid = None
            if c.valid is not None:
                valid = jnp.concatenate(
                    [jnp.asarray(c.valid), jnp.zeros(pad, jnp.bool_)])
            cols.append(Column(c.type, data, valid, c.dictionary))
        live = jnp.concatenate(
            [jnp.ones(n, jnp.bool_), jnp.zeros(pad, jnp.bool_)])
        return ColumnBatch(batch.names, cols, live)
    cols = []
    for c in batch.columns:
        enc = _pad_encoded(c)
        if enc is not None:
            cols.append(enc)
            continue
        data = np.asarray(c.data)
        data = np.concatenate([data, np.zeros(pad, data.dtype)])
        valid = None
        if c.valid is not None:
            valid = np.concatenate([np.asarray(c.valid), np.zeros(pad, np.bool_)])
        cols.append(Column(c.type, data, valid, c.dictionary))
    live = np.concatenate([np.ones(n, np.bool_), np.zeros(pad, np.bool_)])
    return ColumnBatch(batch.names, cols, live)
