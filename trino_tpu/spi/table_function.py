"""Polymorphic table function SPI + built-ins.

Mirrors ``spi/function/table/ConnectorTableFunction.java`` (analyze
arguments -> returned-type descriptor) and the leaf execution side
(``operator/LeafTableFunctionOperator.java:41``).  A table function binds
its (constant) arguments at plan time, fixing the output schema; execution
pulls fixed-size batches from a generator — the XLA-friendly shape: each
batch is a plain columnar array the jitted pipeline consumes like any scan.

Built-ins: ``sequence(start, stop[, step])`` (reference:
operator/table/SequenceFunction.java).  Table-valued arguments
(exclude_columns, json_table) need TABLE(...) argument plumbing — a later
round."""

from __future__ import annotations

from typing import Callable, Iterator, Sequence

import numpy as np

from .batch import Column, ColumnBatch
from .types import BIGINT, Type

__all__ = ["TableFunction", "BoundTableFunction", "builtin_table_functions"]

_BATCH = 1 << 16


class BoundTableFunction:
    """A table function with arguments resolved: fixed schema + batch source."""

    def __init__(self, names: Sequence[str], types: Sequence[Type],
                 batches: Callable[[], Iterator[ColumnBatch]]):
        self.names = list(names)
        self.types = list(types)
        self.batches = batches


class TableFunction:
    name: str = ""

    def bind(self, args: Sequence) -> BoundTableFunction:
        """``args`` are python constants (plan-time literals)."""
        raise NotImplementedError


class SequenceFunction(TableFunction):
    """TABLE(sequence(start, stop[, step])) -> sequential_number BIGINT
    (reference: operator/table/SequenceFunction.java — stop is inclusive)."""

    name = "sequence"

    def bind(self, args: Sequence) -> BoundTableFunction:
        if not 1 <= len(args) <= 3:
            raise ValueError("sequence(start, stop[, step])")
        if len(args) == 1:
            start, stop, step = 0, int(args[0]), 1
        else:
            start, stop = int(args[0]), int(args[1])
            step = int(args[2]) if len(args) > 2 else (
                1 if stop >= start else -1)
        if step == 0:
            raise ValueError("sequence step must not be zero")

        def gen() -> Iterator[ColumnBatch]:
            cur = start
            while (cur <= stop) if step > 0 else (cur >= stop):
                n = min(_BATCH, (stop - cur) // step + 1)
                data = np.arange(cur, cur + n * step, step, dtype=np.int64)
                yield ColumnBatch(["sequential_number"],
                                  [Column(BIGINT, data)])
                cur += n * step

        return BoundTableFunction(["sequential_number"], [BIGINT], gen)


def builtin_table_functions() -> dict[str, TableFunction]:
    fns = [SequenceFunction()]
    return {f.name: f for f in fns}
