"""Access control SPI.

Mirrors ``spi/security`` + ``security/AccessControlManager.java:97``: a
chain of AccessControl implementations consulted before metadata and data
operations; the first denial wins.  Ships AllowAll (default), DenyAll, and
a rule-based implementation in the spirit of the file-based access control
plugin (user -> table privileges)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

__all__ = ["AccessDeniedError", "AccessControl", "AllowAllAccessControl",
           "DenyAllAccessControl", "RuleBasedAccessControl",
           "AccessControlManager"]


class AccessDeniedError(Exception):
    pass


class AccessControl:
    def check_can_select(self, user: str, catalog: str, table: str,
                         columns: Iterable[str]) -> None:
        pass

    def check_can_create_table(self, user: str, catalog: str,
                               table: str) -> None:
        pass

    def check_can_drop_table(self, user: str, catalog: str,
                             table: str) -> None:
        pass

    def check_can_insert(self, user: str, catalog: str, table: str) -> None:
        pass

    def check_can_delete(self, user: str, catalog: str, table: str) -> None:
        pass

    def check_can_execute_function(self, user: str, name: str) -> None:
        pass


class AllowAllAccessControl(AccessControl):
    pass


class DenyAllAccessControl(AccessControl):
    def _deny(self, what: str) -> None:
        raise AccessDeniedError(f"Access Denied: {what}")

    def check_can_select(self, user, catalog, table, columns):
        self._deny(f"select from {catalog}.{table}")

    def check_can_create_table(self, user, catalog, table):
        self._deny(f"create table {catalog}.{table}")

    def check_can_drop_table(self, user, catalog, table):
        self._deny(f"drop table {catalog}.{table}")

    def check_can_insert(self, user, catalog, table):
        self._deny(f"insert into {catalog}.{table}")

    def check_can_delete(self, user, catalog, table):
        self._deny(f"delete from {catalog}.{table}")

    def check_can_execute_function(self, user, name):
        self._deny(f"execute function {name}")


@dataclass
class TableRule:
    """One grant: user (or '*') may apply ``privileges`` to catalog.table
    patterns ('*' wildcard suffix supported)."""

    user: str
    catalog: str
    table: str  # exact name or '*'
    privileges: set = field(default_factory=lambda: {"SELECT"})

    def matches(self, user: str, catalog: str, table: str) -> bool:
        return ((self.user in ("*", user))
                and (self.catalog in ("*", catalog))
                and (self.table in ("*", table)))


class RuleBasedAccessControl(AccessControl):
    """First-match-wins table rules (reference:
    plugin/trino-resource-group-managers file-based access control model)."""

    def __init__(self, rules: list[TableRule]):
        self.rules = list(rules)

    def _check(self, priv: str, user: str, catalog: str, table: str) -> None:
        for r in self.rules:
            if r.matches(user, catalog, table):
                if priv in r.privileges or "ALL" in r.privileges:
                    return
                break
        raise AccessDeniedError(
            f"Access Denied: {user} cannot {priv} {catalog}.{table}")

    def check_can_select(self, user, catalog, table, columns):
        self._check("SELECT", user, catalog, table)

    def check_can_create_table(self, user, catalog, table):
        self._check("OWNERSHIP", user, catalog, table)

    def check_can_drop_table(self, user, catalog, table):
        self._check("OWNERSHIP", user, catalog, table)

    def check_can_insert(self, user, catalog, table):
        self._check("INSERT", user, catalog, table)

    def check_can_delete(self, user, catalog, table):
        self._check("DELETE", user, catalog, table)


class AccessControlManager(AccessControl):
    """Chain; every element must allow (reference:
    security/AccessControlManager checks system then connector controls)."""

    def __init__(self, controls: Optional[list] = None):
        self.controls = list(controls or [AllowAllAccessControl()])

    def add(self, control: AccessControl) -> None:
        self.controls.append(control)

    def __getattribute__(self, name):
        if name.startswith("check_can_"):
            controls = object.__getattribute__(self, "controls")

            def chain(*args, **kwargs):
                for c in controls:
                    getattr(c, name)(*args, **kwargs)

            return chain
        return object.__getattribute__(self, name)
