"""TupleDomain / Domain / ValueSet — the predicate algebra.

Mirrors ``core/trino-spi/src/main/java/io/trino/spi/predicate``
(TupleDomain.java:56, Domain.java:41, SortedRangeSet / EquatableValueSet):
the lingua franca for predicate pushdown, dynamic filters, and split/batch
pruning.  Values are host python comparables (ints, floats, strs, date
ordinals...) — domains describe *data*, they never touch the device; the
engine uses them to skip work before columns are padded and shipped to HBM.

Simplifications vs the reference: one range-set representation (points are
degenerate ranges) instead of Sorted/Equatable split; no type-specific
successor logic (ranges stay half-open/closed as written).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

__all__ = ["Range", "ValueSet", "Domain", "TupleDomain"]

_NEG_INF = object()
_POS_INF = object()


@dataclass(frozen=True)
class Range:
    """[low, high] with per-bound inclusivity; None bound = unbounded
    (reference: spi/predicate/Range.java)."""

    low: object = None  # None = -inf
    low_inclusive: bool = False
    high: object = None  # None = +inf
    high_inclusive: bool = False

    @staticmethod
    def point(v) -> "Range":
        return Range(v, True, v, True)

    @property
    def is_point(self) -> bool:
        return (self.low is not None and self.low == self.high
                and self.low_inclusive and self.high_inclusive)

    def contains_value(self, v) -> bool:
        if self.low is not None:
            if v < self.low or (v == self.low and not self.low_inclusive):
                return False
        if self.high is not None:
            if v > self.high or (v == self.high and not self.high_inclusive):
                return False
        return True

    def overlaps(self, other: "Range") -> bool:
        return not (self._strictly_before(other) or other._strictly_before(self))

    def _strictly_before(self, other: "Range") -> bool:
        if self.high is None or other.low is None:
            return False
        if self.high < other.low:
            return True
        if self.high == other.low:
            return not (self.high_inclusive and other.low_inclusive)
        return False

    def intersect(self, other: "Range") -> Optional["Range"]:
        if not self.overlaps(other):
            return None
        if self.low is None:
            low, li = other.low, other.low_inclusive
        elif other.low is None or self.low > other.low:
            low, li = self.low, self.low_inclusive
        elif self.low < other.low:
            low, li = other.low, other.low_inclusive
        else:
            low, li = self.low, self.low_inclusive and other.low_inclusive
        if self.high is None:
            high, hi = other.high, other.high_inclusive
        elif other.high is None or self.high < other.high:
            high, hi = self.high, self.high_inclusive
        elif self.high > other.high:
            high, hi = other.high, other.high_inclusive
        else:
            high, hi = self.high, self.high_inclusive and other.high_inclusive
        return Range(low, li, high, hi)


@dataclass(frozen=True)
class ValueSet:
    """Union of ranges (reference: spi/predicate/SortedRangeSet.java).
    ``ranges == ()`` means none (empty set); ``is_all`` marks the universe."""

    ranges: tuple[Range, ...] = ()
    is_all: bool = False

    @staticmethod
    def all() -> "ValueSet":
        return ValueSet((), True)

    @staticmethod
    def none() -> "ValueSet":
        return ValueSet(())

    @staticmethod
    def of(values: Iterable) -> "ValueSet":
        return ValueSet(tuple(Range.point(v) for v in sorted(set(values))))

    @property
    def is_none(self) -> bool:
        return not self.is_all and not self.ranges

    def contains_value(self, v) -> bool:
        if self.is_all:
            return True
        return any(r.contains_value(v) for r in self.ranges)

    def overlaps_range(self, low, high) -> bool:
        """Does any value in [low, high] (both inclusive) belong to the set?
        The batch/split pruning primitive: min/max stats form the probe."""
        if self.is_all:
            return True
        probe = Range(low, True, high, True)
        return any(r.overlaps(probe) for r in self.ranges)

    def intersect(self, other: "ValueSet") -> "ValueSet":
        if self.is_all:
            return other
        if other.is_all:
            return self
        out = []
        for a in self.ranges:
            for b in other.ranges:
                c = a.intersect(b)
                if c is not None:
                    out.append(c)
        return ValueSet(tuple(out))

    def union(self, other: "ValueSet") -> "ValueSet":
        if self.is_all or other.is_all:
            return ValueSet.all()
        return ValueSet(self.ranges + other.ranges)

    def points(self) -> Optional[list]:
        """The discrete values when every range is a point, else None."""
        if self.is_all or any(not r.is_point for r in self.ranges):
            return None
        return [r.low for r in self.ranges]


@dataclass(frozen=True)
class Domain:
    """ValueSet + NULL admissibility (reference: spi/predicate/Domain.java:41)."""

    values: ValueSet = field(default_factory=ValueSet.all)
    null_allowed: bool = False

    @staticmethod
    def all() -> "Domain":
        return Domain(ValueSet.all(), True)

    @staticmethod
    def none() -> "Domain":
        return Domain(ValueSet.none(), False)

    @staticmethod
    def single_value(v) -> "Domain":
        return Domain(ValueSet.of([v]), False)

    @staticmethod
    def only_null() -> "Domain":
        return Domain(ValueSet.none(), True)

    @property
    def is_all(self) -> bool:
        return self.values.is_all and self.null_allowed

    @property
    def is_none(self) -> bool:
        return self.values.is_none and not self.null_allowed

    def contains_value(self, v) -> bool:
        if v is None:
            return self.null_allowed
        return self.values.contains_value(v)

    def intersect(self, other: "Domain") -> "Domain":
        return Domain(self.values.intersect(other.values),
                      self.null_allowed and other.null_allowed)

    def union(self, other: "Domain") -> "Domain":
        return Domain(self.values.union(other.values),
                      self.null_allowed or other.null_allowed)


@dataclass(frozen=True)
class TupleDomain:
    """Per-column conjunction of domains (reference:
    spi/predicate/TupleDomain.java:56).  ``domains`` maps column name ->
    Domain; a column absent from the map is unconstrained.  ``is_none``
    marks a provably empty relation."""

    domains: dict[str, Domain] = field(default_factory=dict)
    is_none: bool = False

    @staticmethod
    def all() -> "TupleDomain":
        return TupleDomain({})

    @staticmethod
    def none() -> "TupleDomain":
        return TupleDomain({}, True)

    @property
    def is_all(self) -> bool:
        return not self.is_none and not self.domains

    def domain(self, column: str) -> Domain:
        return self.domains.get(column, Domain.all())

    def intersect(self, other: "TupleDomain") -> "TupleDomain":
        if self.is_none or other.is_none:
            return TupleDomain.none()
        out = dict(self.domains)
        for col, d in other.domains.items():
            nd = out[col].intersect(d) if col in out else d
            if nd.is_none:
                return TupleDomain.none()
            out[col] = nd
        return TupleDomain(out)

    def column_wise_union(self, other: "TupleDomain") -> "TupleDomain":
        """OR of tuple domains, exact only per shared column (the reference's
        columnWiseUnion — a sound over-approximation)."""
        if self.is_none:
            return other
        if other.is_none:
            return self
        out = {}
        for col in set(self.domains) & set(other.domains):
            out[col] = self.domains[col].union(other.domains[col])
        return TupleDomain(out)

    def overlaps_stats(self, mins: dict, maxs: dict,
                       has_null: Optional[dict] = None) -> bool:
        """Can any row with the given per-column [min, max] (+ null flags)
        satisfy this tuple domain?  False => the batch/split is prunable."""
        if self.is_none:
            return False
        for col, dom in self.domains.items():
            if col not in mins or col not in maxs:
                continue
            nullable = bool(has_null.get(col)) if has_null else True
            if mins[col] is None:  # all-NULL column stats
                if not dom.null_allowed:
                    return False
                continue
            if not dom.values.overlaps_range(mins[col], maxs[col]) and not (
                    dom.null_allowed and nullable):
                return False
        return True
