"""SPI layer: types, columnar batches, memory accounting, connector contract.

The re-expression of ``core/trino-spi`` (Page/Block/Type + connector SPI) in
array-first terms — see the module docstrings for the design mapping.
"""

from .types import (  # noqa: F401
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    REAL,
    SMALLINT,
    TIMESTAMP,
    TINYINT,
    UNKNOWN,
    VARCHAR,
    DecimalType,
    Type,
    common_super_type,
    is_integral,
    is_numeric,
    is_string,
    parse_type,
)
from .batch import Column, ColumnBatch, encode_strings, unify_dictionaries  # noqa: F401
from .memory import (  # noqa: F401
    AggregatedMemoryContext,
    ExceededMemoryLimitError,
    LocalMemoryContext,
    MemoryPool,
)
from .connector import (  # noqa: F401
    ColumnSchema,
    Connector,
    ConnectorPageSink,
    ConnectorPageSource,
    Split,
    TableSchema,
    TableStatistics,
)
from .errors import (  # noqa: F401
    EXTERNAL,
    INSUFFICIENT_RESOURCES,
    INTERNAL,
    USER,
    Backoff,
    ErrorCode,
    TrinoError,
    classify,
    is_retryable_type,
    lookup_code,
)
