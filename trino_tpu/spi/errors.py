"""Error classification + deterministic backoff (the resilience vocabulary).

Mirrors the reference's error taxonomy (spi/StandardErrorCode.java:31,
spi/ErrorType.java:17) and the airlift ``Backoff`` used by
operator/HttpPageBufferClient.java:355: every failure the coordinator acts
on carries an :class:`ErrorCode` whose :class:`ErrorType` decides
*retryability* —

- ``USER``                    the query itself is wrong (syntax, division by
                              zero, bad cast); retrying re-runs the same bug,
                              so these NEVER retry anywhere;
- ``INTERNAL``                an engine bug or injected fault; retryable
                              (reference FTE retries internal task failures);
- ``EXTERNAL``                the world outside the engine failed (worker
                              unreachable, page transport timeout, remote
                              host gone); retryable;
- ``INSUFFICIENT_RESOURCES``  memory/admission pressure; retryable (the FTE
                              scheduler grows the memory budget on retry).

``classify()`` maps arbitrary exceptions onto :class:`TrinoError` so the
worker can report ``error_type`` in its status JSON and the coordinator's
``retry_policy="QUERY"`` loop can decide fail-fast vs re-run without parsing
message strings.  :class:`Backoff` bounds how long an unreachable peer is
re-polled before it surfaces as a classified EXTERNAL failure.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

__all__ = [
    "USER", "INTERNAL", "EXTERNAL", "INSUFFICIENT_RESOURCES", "ERROR_TYPES",
    "ErrorCode", "TrinoError", "Backoff",
    "GENERIC_USER_ERROR", "SUBQUERY_MULTIPLE_ROWS",
    "GENERIC_INTERNAL_ERROR", "REMOTE_TASK_ERROR",
    "REMOTE_HOST_GONE", "PAGE_TRANSPORT_TIMEOUT", "PAGE_TRANSPORT_ERROR",
    "EXCEEDED_MEMORY_LIMIT_CODE", "NO_NODES_AVAILABLE",
    "QUERY_QUEUE_FULL", "QUERY_QUEUED_TIMEOUT", "CLUSTER_OUT_OF_MEMORY",
    "EXCEEDED_GLOBAL_MEMORY_LIMIT",
    "classify", "is_retryable_type", "lookup_code",
]

USER = "USER"
INTERNAL = "INTERNAL"
EXTERNAL = "EXTERNAL"
INSUFFICIENT_RESOURCES = "INSUFFICIENT_RESOURCES"
ERROR_TYPES = (USER, INTERNAL, EXTERNAL, INSUFFICIENT_RESOURCES)

# only USER errors are deterministic re-failures; everything else names a
# condition a re-run can escape (reference: ErrorType retry semantics in
# execution/scheduler/faulttolerant + coordinator query retries)
_RETRYABLE_TYPES = frozenset({INTERNAL, EXTERNAL, INSUFFICIENT_RESOURCES})


def is_retryable_type(error_type: Optional[str]) -> bool:
    return error_type in _RETRYABLE_TYPES


@dataclass(frozen=True)
class ErrorCode:
    """(name, numeric code, type) — the StandardErrorCode.java:31 triple.
    Code blocks follow the reference: USER < 0x1_0000, INTERNAL from
    0x1_0000, INSUFFICIENT_RESOURCES from 0x2_0000, EXTERNAL from 0x3_0000."""

    name: str
    code: int
    error_type: str

    def is_retryable(self) -> bool:
        return is_retryable_type(self.error_type)


GENERIC_USER_ERROR = ErrorCode("GENERIC_USER_ERROR", 0x0000, USER)
SYNTAX_ERROR = ErrorCode("SYNTAX_ERROR", 0x0001, USER)
DIVISION_BY_ZERO = ErrorCode("DIVISION_BY_ZERO", 0x0008, USER)
# a scalar subquery yielding >1 row is the query's own cardinality bug
# (reference: StandardErrorCode SUBQUERY_MULTIPLE_ROWS) — USER, never retried
SUBQUERY_MULTIPLE_ROWS = ErrorCode("SUBQUERY_MULTIPLE_ROWS", 0x0019, USER)
# admission rejections are USER on purpose: re-submitting an identical query
# into the same full queue re-fails identically, so the retry_policy=QUERY
# loop must never burn attempts on them (reference: StandardErrorCode
# QUERY_QUEUE_FULL / EXCEEDED_TIME_LIMIT family)
QUERY_QUEUE_FULL = ErrorCode("QUERY_QUEUE_FULL", 0x0009, USER)
QUERY_QUEUED_TIMEOUT = ErrorCode("QUERY_QUEUED_TIMEOUT", 0x000A, USER)
GENERIC_INTERNAL_ERROR = ErrorCode("GENERIC_INTERNAL_ERROR", 0x1_0000, INTERNAL)
EXCEEDED_MEMORY_LIMIT_CODE = ErrorCode(
    "EXCEEDED_LOCAL_MEMORY_LIMIT", 0x2_0000, INSUFFICIENT_RESOURCES)
NO_NODES_AVAILABLE = ErrorCode(
    "NO_NODES_AVAILABLE", 0x2_0001, INSUFFICIENT_RESOURCES)
# OOM-killer victims: INSUFFICIENT_RESOURCES, so an INTERNAL workload killed
# to relieve cluster pressure is eligible for a retry_policy=QUERY re-run
# once the pressure clears (reference: ClusterMemoryManager.java:90 +
# LowMemoryKiller)
CLUSTER_OUT_OF_MEMORY = ErrorCode(
    "CLUSTER_OUT_OF_MEMORY", 0x2_0002, INSUFFICIENT_RESOURCES)
EXCEEDED_GLOBAL_MEMORY_LIMIT = ErrorCode(
    "EXCEEDED_GLOBAL_MEMORY_LIMIT", 0x2_0003, INSUFFICIENT_RESOURCES)
REMOTE_TASK_ERROR = ErrorCode("REMOTE_TASK_ERROR", 0x3_0000, EXTERNAL)
PAGE_TRANSPORT_ERROR = ErrorCode("PAGE_TRANSPORT_ERROR", 0x3_0001, EXTERNAL)
PAGE_TRANSPORT_TIMEOUT = ErrorCode(
    "PAGE_TRANSPORT_TIMEOUT", 0x3_0002, EXTERNAL)
REMOTE_HOST_GONE = ErrorCode("REMOTE_HOST_GONE", 0x3_0003, EXTERNAL)

_CODES = {c.name: c for c in (
    GENERIC_USER_ERROR, SYNTAX_ERROR, DIVISION_BY_ZERO,
    SUBQUERY_MULTIPLE_ROWS, QUERY_QUEUE_FULL, QUERY_QUEUED_TIMEOUT,
    GENERIC_INTERNAL_ERROR, EXCEEDED_MEMORY_LIMIT_CODE, NO_NODES_AVAILABLE,
    CLUSTER_OUT_OF_MEMORY, EXCEEDED_GLOBAL_MEMORY_LIMIT,
    REMOTE_TASK_ERROR, PAGE_TRANSPORT_ERROR, PAGE_TRANSPORT_TIMEOUT,
    REMOTE_HOST_GONE,
)}

_FALLBACK_BY_TYPE = {
    USER: GENERIC_USER_ERROR,
    INTERNAL: GENERIC_INTERNAL_ERROR,
    EXTERNAL: REMOTE_TASK_ERROR,
    INSUFFICIENT_RESOURCES: EXCEEDED_MEMORY_LIMIT_CODE,
}


def lookup_code(name: Optional[str],
                error_type: Optional[str] = None) -> ErrorCode:
    """Wire form -> ErrorCode: by name when registered, else the type's
    generic code (unknown wire values degrade to INTERNAL, retryable —
    never to a silent USER fail-fast)."""
    if name and name in _CODES:
        return _CODES[name]
    return _FALLBACK_BY_TYPE.get(error_type, GENERIC_INTERNAL_ERROR)


class TrinoError(RuntimeError):
    """An exception that knows its ErrorCode; ``remote_host`` names the
    worker implicated in an EXTERNAL/remote failure so the coordinator's
    query-retry loop can blacklist it for the re-run."""

    def __init__(self, code: ErrorCode, message: str,
                 remote_host: Optional[str] = None):
        super().__init__(f"{code.name}: {message}")
        self.code = code
        self.remote_host = remote_host

    @property
    def error_type(self) -> str:
        return self.code.error_type

    def is_retryable(self) -> bool:
        return self.code.is_retryable()


# exception classes from upper layers, matched by NAME so the SPI does not
# import the analyzer/executor packages it underpins
_USER_ERROR_CLASS_NAMES = frozenset({
    "AnalysisError",     # sql/analyzer.py (ValueError subclass)
    "ParseError",        # sql/parser.py
    "QueryError",        # ops/expr.py deferred lane errors (DIVISION_BY_ZERO)
    "PatternSyntaxError",  # exec/row_pattern.py MATCH_RECOGNIZE pattern text
})
_NETWORK_ERROR_TYPES = (ConnectionError, TimeoutError)


def classify(exc: BaseException) -> TrinoError:
    """Wrap an arbitrary exception as a classified TrinoError (identity on
    an already-classified one).  The mapping mirrors the reference's
    ``toFailure``/StandardErrorCode defaults: known user-facing classes →
    USER, memory pressure → INSUFFICIENT_RESOURCES, network trouble →
    EXTERNAL, everything unrecognized → GENERIC_INTERNAL_ERROR."""
    if isinstance(exc, TrinoError):
        return exc
    from .memory import ExceededMemoryLimitError

    msg = f"{type(exc).__name__}: {exc}"
    if isinstance(exc, ExceededMemoryLimitError):
        return TrinoError(EXCEEDED_MEMORY_LIMIT_CODE, msg)
    name = type(exc).__name__
    if name in _USER_ERROR_CLASS_NAMES:
        if "DIVISION_BY_ZERO" in str(exc):
            return TrinoError(DIVISION_BY_ZERO, msg)
        return TrinoError(GENERIC_USER_ERROR, msg)
    import urllib.error

    if isinstance(exc, (urllib.error.URLError, *_NETWORK_ERROR_TYPES)):
        return TrinoError(PAGE_TRANSPORT_ERROR, msg)
    return TrinoError(GENERIC_INTERNAL_ERROR, msg)


class Backoff:
    """Deterministic exponential backoff with a failure-duration budget
    (reference: the airlift Backoff inside HttpPageBufferClient — min/max
    delay doubling, ``maxFailureDuration`` deciding when a flaky peer is
    declared failed).

    No jitter on purpose: delays are a pure function of the failure count,
    so fault drills on the CPU mesh are reproducible.  ``clock`` is
    injectable for tests."""

    def __init__(self, min_delay_s: float = 0.05, max_delay_s: float = 2.0,
                 max_failure_duration_s: float = 120.0,
                 clock: Callable[[], float] = time.monotonic):
        self.min_delay_s = float(min_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.max_failure_duration_s = float(max_failure_duration_s)
        self._clock = clock
        self.failure_count = 0
        self._first_failure: Optional[float] = None
        self._ready_at: float = 0.0

    @property
    def delay_s(self) -> float:
        """Current delay: min_delay * 2^(failures-1), capped at max_delay."""
        if self.failure_count == 0:
            return 0.0
        return min(self.max_delay_s,
                   self.min_delay_s * (2.0 ** (self.failure_count - 1)))

    def failure(self) -> bool:
        """Record one failure; returns True once failures have persisted
        past ``max_failure_duration_s`` (measured from the FIRST failure of
        the current streak, requiring at least two observations — one
        transient blip never trips the budget)."""
        now = self._clock()
        if self._first_failure is None:
            self._first_failure = now
        self.failure_count += 1
        self._ready_at = now + self.delay_s
        return (self.failure_count > 1
                and now - self._first_failure >= self.max_failure_duration_s)

    def success(self) -> None:
        self.failure_count = 0
        self._first_failure = None
        self._ready_at = 0.0

    def ready(self) -> bool:
        """False while the current delay gate is still closed."""
        return self._clock() >= self._ready_at

    @property
    def failure_duration_s(self) -> float:
        if self._first_failure is None:
            return 0.0
        return self._clock() - self._first_failure
