"""Connector SPI — the vertical plug-in boundary.

Mirrors the minimal contract called out in SURVEY §2.3 from
``core/trino-spi/src/main/java/io/trino/spi/connector``:

- :class:`ConnectorMetadata`  (tables, columns, stats)        — ConnectorMetadata.java
- :class:`ConnectorSplitManager` → :class:`Split` batches     — ConnectorSplitManager.java,
  ConnectorSplitSource.java:31 (async ``getNextBatch`` becomes a generator)
- :class:`ConnectorPageSource` (reads)                        — ConnectorPageSource.java:24-59
- :class:`ConnectorPageSink` (writes)                         — ConnectorPageSink.java:62-79
- optional bucketing via ``bucket_count``/``bucket_of``       — ConnectorNodePartitioningProvider.java

TPU-first addition: ``ConnectorMetadata.column_dictionary`` exposes the
table-global sorted dictionary for a string column so scans across splits
share one code space (see spi/batch.py docstring).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import numpy as np

from .batch import ColumnBatch
from .types import Type

__all__ = [
    "ColumnSchema",
    "TableSchema",
    "TableStatistics",
    "Split",
    "ConnectorPageSource",
    "ConnectorPageSink",
    "Connector",
]


@dataclass(frozen=True)
class ColumnSchema:
    name: str
    type: Type


@dataclass(frozen=True)
class TableSchema:
    name: str
    columns: tuple[ColumnSchema, ...]

    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]

    def column_type(self, name: str) -> Type:
        for c in self.columns:
            if c.name == name:
                return c.type
        raise KeyError(name)


@dataclass(frozen=True)
class TableStatistics:
    """Coarse stats for the cost model (mirrors spi/statistics/TableStatistics)."""

    row_count: float = float("nan")
    # per-column distinct-value estimates
    ndv: dict[str, float] = field(default_factory=dict)


@dataclass(frozen=True)
class Split:
    """A schedulable unit of table data (mirrors spi/connector/ConnectorSplit).

    ``info`` is connector-private (e.g. part index for the tpch generator).
    ``addresses`` optionally pins the split to hosts (locality)."""

    catalog: str
    table: str
    info: Any
    weight: float = 1.0
    addresses: tuple[str, ...] = ()


class ConnectorPageSource:
    """Pull-based reader for one split (mirrors ConnectorPageSource.java)."""

    def get_next_batch(self) -> Optional[ColumnBatch]:
        raise NotImplementedError

    def is_finished(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        pass


class ConnectorPageSink:
    """Writer for one task (mirrors ConnectorPageSink.java).

    ``append`` may signal backpressure by returning False (caller yields);
    ``finish`` returns commit fragments handed to the coordinator commit."""

    def append(self, batch: ColumnBatch) -> bool:
        raise NotImplementedError

    def finish(self) -> list[Any]:
        return []

    def abort(self) -> None:
        pass


class Connector:
    """One catalog's implementation.  Subset of spi/Plugin + Connector*."""

    name: str = "connector"

    # --- metadata ---------------------------------------------------------
    def list_tables(self) -> list[str]:
        raise NotImplementedError

    def get_table_schema(self, table: str) -> TableSchema:
        raise NotImplementedError

    def get_table_statistics(self, table: str) -> TableStatistics:
        stats = getattr(self, "_analyzed_stats", {}).get(table)
        return stats if stats is not None else TableStatistics()

    def set_analyzed_statistics(self, table: str,
                                stats: TableStatistics) -> None:
        """ANALYZE writes collected stats here; connectors whose
        get_table_statistics overrides should consult them first
        (reference: the engine-computed stats StatisticsWriterOperator
        hands back to ConnectorMetadata.finishStatisticsCollection)."""
        if not hasattr(self, "_analyzed_stats"):
            self._analyzed_stats = {}
        self._analyzed_stats[table] = stats

    def get_procedures(self) -> dict:
        """name -> callable(**kwargs) (reference:
        spi/procedure/Procedure.java; invoked by CALL)."""
        return {}

    def data_version(self, table: str) -> Optional[Any]:
        """Opaque token that changes whenever the table's data changes
        (the caching plane's invalidation currency: result-cache keys and
        MV staleness both compare these).  None means *unversioned* —
        reads of this table are never result-cached (the right answer for
        volatile sources like the system connector).  Immutable sources
        return a constant (tpch: the scale factor)."""
        return None


    def column_dictionary(self, table: str, column: str) -> Optional[np.ndarray]:
        """Table-global sorted dictionary for a string column, if known."""
        return None

    # --- reads ------------------------------------------------------------
    def get_splits(self, table: str, splits_per_node: int, node_count: int) -> list[Split]:
        raise NotImplementedError

    def create_page_source(self, split: Split, columns: Sequence[str],
                           constraint=None) -> ConnectorPageSource:
        """``constraint`` is an advisory spi/predicate.TupleDomain the
        connector MAY use to skip data (batches/splits); it need not enforce
        it (mirrors ConnectorPageSourceProvider.createPageSource receiving a
        dynamicFilter/TupleDomain it can use for pruning)."""
        raise NotImplementedError

    # --- transactions -----------------------------------------------------
    def begin_transaction(self):
        """Open a connector-private transaction handle (mirrors
        Connector.beginTransaction -> ConnectorTransactionHandle).  Default:
        autocommit-only connectors return None."""
        return None

    def commit_transaction(self, handle) -> None:
        pass

    def rollback_transaction(self, handle) -> None:
        pass

    # --- writes -----------------------------------------------------------
    def create_table(self, schema: TableSchema) -> None:
        raise NotImplementedError("connector does not support CREATE TABLE")

    def create_page_sink(self, table: str) -> ConnectorPageSink:
        raise NotImplementedError("connector does not support writes")

    def finish_insert(self, table: str, fragments: list[Any]) -> None:
        pass

    def drop_table(self, table: str) -> None:
        raise NotImplementedError
