"""Hierarchical memory accounting (mirrors ``lib/trino-memory-context``).

Reference: AggregatedMemoryContext.java / LocalMemoryContext and
core/trino-main ``memory/MemoryPool.java:44`` (``reserve:130`` /
``reserveRevocable:163``).  The TPU engine accounts two pools per worker:
HBM (device) and host RAM; spill tiers move reservations between them.

Semantics kept from the reference:
- a Local context's ``set_bytes`` deltas roll up through parent Aggregated
  contexts into the pool;
- *revocable* memory is tracked separately and can be reclaimed by asking the
  owning operator to spill (see exec/revoking.py);
- exceeding the pool limit raises :class:`ExceededMemoryLimitError`
  (the per-node OOM); the CLUSTER-level view — aggregation of these pools
  across queries/workers plus the low-memory killer — lives in
  execution/resource_manager.py ClusterMemoryManager.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = [
    "ExceededMemoryLimitError",
    "MemoryPool",
    "AggregatedMemoryContext",
    "LocalMemoryContext",
]


class ExceededMemoryLimitError(RuntimeError):
    def __init__(self, pool: str, requested: int, limit: int):
        super().__init__(
            f"Query exceeded per-node memory limit of {limit} bytes in pool "
            f"{pool} (requested {requested} additional bytes)"
        )
        self.pool = pool


class MemoryPool:
    """Per-worker byte pool (one for HBM, one for host RAM)."""

    def __init__(self, name: str, max_bytes: int):
        self.name = name
        self.max_bytes = max_bytes
        self.reserved = 0
        self.reserved_revocable = 0

    def reserve(self, delta: int, revocable: bool = False) -> None:
        if delta > 0 and self.reserved + self.reserved_revocable + delta > self.max_bytes:
            if not revocable:
                raise ExceededMemoryLimitError(self.name, delta, self.max_bytes)
        if revocable:
            self.reserved_revocable += delta
        else:
            self.reserved += delta

    def free(self, delta: int, revocable: bool = False) -> None:
        self.reserve(-delta, revocable)

    @property
    def free_bytes(self) -> int:
        return self.max_bytes - self.reserved - self.reserved_revocable


class AggregatedMemoryContext:
    """Sums children; roots into a MemoryPool."""

    def __init__(self, pool: Optional[MemoryPool] = None,
                 parent: Optional["AggregatedMemoryContext"] = None,
                 revocable: bool = False):
        self._pool = pool
        self._parent = parent
        self._revocable = revocable
        self._closed = False
        self.reserved = 0

    def new_child(self) -> "AggregatedMemoryContext":
        return AggregatedMemoryContext(parent=self, revocable=self._revocable)

    def new_local(self, tag: str = "") -> "LocalMemoryContext":
        return LocalMemoryContext(self, tag)

    def _update(self, delta: int) -> None:
        if delta == 0:
            return
        if self._closed:
            raise RuntimeError("memory context used after close")
        # reserve in the pool first so failures don't corrupt accounting
        if self._parent is not None:
            self._parent._update(delta)
        elif self._pool is not None:
            self._pool.reserve(delta, self._revocable)
        self.reserved += delta

    def close(self) -> None:
        """Free this subtree's reservation.  Children must already be closed
        (or simply abandoned); further use of this context or any child
        raises, preventing double-frees from driving the pool negative."""
        if self._closed:
            return
        self._update(-self.reserved)
        self._closed = True


class LocalMemoryContext:
    def __init__(self, parent: AggregatedMemoryContext, tag: str = ""):
        self._parent = parent
        self.tag = tag
        self.reserved = 0

    def set_bytes(self, new_bytes: int) -> None:
        delta = new_bytes - self.reserved
        self._parent._update(delta)
        self.reserved = new_bytes

    def close(self) -> None:
        if self.reserved:
            self.set_bytes(0)
