"""Central registry of every ``TRINO_TPU_*`` environment knob.

The engine grew ~45 env knobs across five PR generations, each declared
nowhere but its read site — so a typo'd read silently returns the default,
an operator cannot enumerate what is tunable, and docs drift freely.  This
module is the single source of truth: every knob's name, type, default,
and one-line doc, in one table.

Three consumers hold the registry honest:

- the ``knob-registry`` tpulint rule rejects any ``TRINO_TPU_*`` string
  literal in the tree that is not declared here (catching misspellings
  and undeclared additions statically — the declarations below are pure
  literals precisely so the linter can read them without importing jax);
- ``docs/KNOBS.md`` is *generated* from this table
  (``python -m tools.analysis --write-knob-docs``) and the ``knob-docs``
  rule fails when the committed file drifts from the registry;
- the typed accessors below (:func:`get_str` & friends) raise
  :class:`KeyError` on an undeclared name, so even dynamically-built knob
  reads cannot bypass the registry at runtime.

Reading through the accessors is recommended but not required — existing
``os.environ.get("TRINO_TPU_X", ...)`` sites stay valid as long as the
literal is declared.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

__all__ = ["Knob", "KNOBS", "declared", "knob", "get_str", "get_int",
           "get_float", "get_bool"]


@dataclass(frozen=True)
class Knob:
    """One declared environment knob.  ``default`` is the *string* form as
    the environment would carry it ("" = unset, code-side fallback applies);
    ``type`` is documentation plus accessor validation, one of
    ``str | int | float | bool | enum | json | path``."""

    name: str
    type: str
    default: str
    doc: str
    choices: Optional[tuple] = None


# NOTE for editors: declarations must stay PURE LITERALS — the tpulint
# knob-registry rule and the KNOBS.md generator read this file with ast,
# not import, so a computed default would be invisible to both.
_DECLARATIONS = (
    Knob("TRINO_TPU_ADAPTIVE", "enum", "auto",
         "Adaptive query execution (runtime join-distribution switching, "
         "skew-aware repartitioning); 0 is bit-for-bit legacy.",
         choices=("auto", "1", "0")),
    Knob("TRINO_TPU_AUTOSCALE", "bool", "0",
         "Elastic worker autoscaling: a controller watches admission queue "
         "pressure and cluster memory and grows or drains the worker fleet "
         "through the zero-loss shutdown protocol."),
    Knob("TRINO_TPU_AUTOSCALE_IDLE_ROUNDS", "int", "3",
         "Consecutive pressure-free controller rounds before the "
         "autoscaler drains one worker down toward the floor."),
    Knob("TRINO_TPU_AUTOSCALE_INTERVAL_S", "float", "5",
         "Autoscaler controller cadence (seconds between policy rounds)."),
    Knob("TRINO_TPU_AUTOSCALE_MAX_WORKERS", "int", "4",
         "Autoscaler ceiling: the controller never grows the worker fleet "
         "past this."),
    Knob("TRINO_TPU_AUTOSCALE_MIN_WORKERS", "int", "1",
         "Autoscaler floor: the controller never drains the worker fleet "
         "below this."),
    Knob("TRINO_TPU_AUTOSCALE_QUEUE_S", "float", "0.5",
         "Scale-up trigger: admission queued-seconds accumulated per "
         "controller round at or above this means queue pressure."),
    Knob("TRINO_TPU_BLACKLIST_PATH", "path", "",
         "Shared durable cluster-blacklist file (append-only JSONL).  When "
         "set, every coordinator in the fleet appends its strikes here and "
         "merges peers' entries on read (TTL-decayed) instead of keeping "
         "process-local state; unset keeps the per-coordinator journal "
         "persistence."),
    Knob("TRINO_TPU_BLACKLIST_THRESHOLD", "float", "2",
         "Failure score at or above which a worker enters the cross-query "
         "cluster blacklist."),
    Knob("TRINO_TPU_BLACKLIST_TTL_S", "float", "300",
         "Cluster-blacklist entry TTL; scores decay to zero over this "
         "window."),
    Knob("TRINO_TPU_BROADCAST_ROW_LIMIT", "int", "2000000",
         "Static planner threshold: a join build side estimated at or "
         "below this many rows is broadcast instead of repartitioned."),
    Knob("TRINO_TPU_BROADCAST_THRESHOLD_BYTES", "int", "33554432",
         "Adaptive activation-barrier threshold: observed build bytes "
         "below this flip a repartitioned join to broadcast (and above, "
         "the reverse)."),
    Knob("TRINO_TPU_CLUSTER_MEMORY_BYTES", "int", "",
         "Cluster-wide reserved-memory cap enforced by the low-memory "
         "killer; unset disables the cap."),
    Knob("TRINO_TPU_COALESCE_TARGET_ROWS", "int", "65536",
         "Scan-ingest batch coalescing target row count."),
    Knob("TRINO_TPU_COMPILE_CACHE_DIR", "path", "",
         "Directory for JAX's persistent on-disk compile cache; unset "
         "leaves the on-disk cache off."),
    Knob("TRINO_TPU_DRAIN_TIMEOUT_S", "float", "300",
         "Graceful-drain budget: a SHUTTING_DOWN worker abandons "
         "unfinished tasks and exits with code 9 past this."),
    Knob("TRINO_TPU_ENCODED_EXEC", "enum", "auto",
         "Compressed execution: operators consume dictionary codes, RLE "
         "runs, and lazy columns directly (decode at most once per "
         "query); 0 is bit-for-bit legacy expand-at-scan.",
         choices=("auto", "1", "0")),
    Knob("TRINO_TPU_EXCHANGE_STALL_S", "float", "1800",
         "Exchange take() stall watchdog: a source that produces nothing "
         "for this long fails the take with PAGE_TRANSPORT_TIMEOUT."),
    Knob("TRINO_TPU_EXEC_CACHE", "bool", "1",
         "Tier B executable-registry kill switch; 0 restores the legacy "
         "unbounded per-site memos."),
    Knob("TRINO_TPU_EXEC_CACHE_ENTRIES", "int", "256",
         "LRU capacity (entries) of each registered executable memo."),
    Knob("TRINO_TPU_EXEC_WARM", "bool", "1",
         "Replay exec_warm.json (journaled executable memo keys) on the "
         "worker boot path."),
    Knob("TRINO_TPU_FUSED_CAP", "int", "8192",
         "Fused-stage FINAL combine capacity (groups per task); overflow "
         "falls back to the legacy collective path for that query."),
    Knob("TRINO_TPU_FUSED_STAGE", "enum", "auto",
         "Whole-stage GSPMD compilation of PARTIAL->shuffle->FINAL seams; "
         "0 is bit-for-bit legacy collectives.",
         choices=("auto", "1", "0")),
    Knob("TRINO_TPU_HA", "bool", "0",
         "Horizontally-scaled HA control plane: the coordinator registers "
         "a heartbeated lease in TRINO_TPU_HA_DIR, owns queries by "
         "consistent hash, and claims dead peers' WAL directories; 0 is "
         "bit-for-bit single-coordinator legacy."),
    Knob("TRINO_TPU_HA_DIR", "path", "",
         "Shared cluster directory for the coordinator fleet (lease files, "
         "claim markers, per-coordinator query-state WAL roots); required "
         "when TRINO_TPU_HA=1."),
    Knob("TRINO_TPU_HA_HEARTBEAT_S", "float", "2",
         "Coordinator lease renewal cadence; must be well under the lease "
         "TTL."),
    Knob("TRINO_TPU_HA_LEASE_TTL_S", "float", "10",
         "Coordinator lease expiry: a lease not renewed for this long is "
         "dead and a peer may claim its WAL directory."),
    Knob("TRINO_TPU_HA_NODE_ID", "str", "",
         "Stable coordinator identity in the fleet directory (also "
         "suffixes the per-coordinator journal file); unset derives "
         "host-pid."),
    Knob("TRINO_TPU_HA_ROUTE_RETRY_S", "float", "15",
         "Front-tier retry-and-rehash budget: how long a routed request "
         "keeps probing live coordinators while the owner is mid-failover "
         "before reporting the query still QUEUED."),
    Knob("TRINO_TPU_HASH_IMPL", "enum", "auto",
         "Grouping/join hash index implementation.",
         choices=("auto", "pallas", "sort")),
    Knob("TRINO_TPU_HASH_INTERPRET", "bool", "0",
         "Run the Pallas hash kernels in interpret mode (CPU-only "
         "environments and kernel debugging)."),
    Knob("TRINO_TPU_HBO", "enum", "auto",
         "History-based optimization: the cost model prefers journaled "
         "per-fingerprint observed stats (rows, build bytes, partial-agg "
         "groups) over estimate_rows, and queries record plan_stats at "
         "completion; 0 disables both sides bit-for-bit.",
         choices=("auto", "1", "0")),
    Knob("TRINO_TPU_HBO_ROWS_PER_TASK", "int", "250000",
         "History-driven task fan-out: observed fragment rows divided by "
         "this sets the task count (capped at the worker count) for "
         "fragments whose fingerprint has history."),
    Knob("TRINO_TPU_INTERNAL_SECRET", "str", "",
         "Shared secret authenticating intra-cluster HTTP "
         "(coordinator<->worker); auto-generated per cluster boot when "
         "unset."),
    Knob("TRINO_TPU_JOIN_REORDER_DP_LIMIT", "int", "6",
         "Largest inner-join cluster (leaf relation count) the iterative "
         "optimizer enumerates exhaustively (left-deep dynamic "
         "programming); bigger clusters use the greedy ordering.  0 "
         "disables enumeration."),
    Knob("TRINO_TPU_JOURNAL", "bool", "1",
         "Durable query journal (JSONL EventListener); 0 disables."),
    Knob("TRINO_TPU_JOURNAL_DIR", "path", "",
         "Journal directory; unset uses a per-uid tempdir."),
    Knob("TRINO_TPU_JOURNAL_FILES", "int", "3",
         "Rotated journal generations kept."),
    Knob("TRINO_TPU_JOURNAL_MAX_BYTES", "int", "4194304",
         "Journal rotate threshold per file."),
    Knob("TRINO_TPU_LEGACY_EXPAND", "bool", "0",
         "1 restores the legacy per-run join expand (pre padded "
         "single-fetch)."),
    Knob("TRINO_TPU_MESH_SHAPE", "str", "",
         "Mesh-shape override for resident-plan programs (\"8\" or "
         "\"2x4\"); the dimension product caps the mesh width a plan may "
         "claim.  Unset sizes the mesh from the stage task count."),
    Knob("TRINO_TPU_OOM_POLICY", "enum", "largest_query",
         "Victim selection policy for the cluster low-memory killer.",
         choices=("largest_query", "lowest_priority", "youngest")),
    Knob("TRINO_TPU_OPTIMIZER", "enum", "iterative",
         "Logical optimizer implementation: iterative is the "
         "memo/fixpoint rule engine (planner/iterative/); legacy is the "
         "bit-for-bit single-pass rewrite pipeline.",
         choices=("iterative", "legacy")),
    Knob("TRINO_TPU_PALLAS", "bool", "1",
         "Master switch for Pallas kernels; 0 forces the jnp fallbacks."),
    Knob("TRINO_TPU_PLAN_CACHE", "bool", "1",
         "Tier A fingerprinted logical-plan cache; 0 disables (checked "
         "per lookup)."),
    Knob("TRINO_TPU_PLAN_CACHE_ENTRIES", "int", "256",
         "Plan-cache LRU capacity (entries)."),
    Knob("TRINO_TPU_PREFETCH", "bool", "1",
         "Async scan ingest (ordered multi-split prefetch); 0 is the "
         "bit-for-bit synchronous legacy path, 1 forces it on even on "
         "single-core hosts."),
    Knob("TRINO_TPU_PREFETCH_QUEUE_BYTES", "int", "268435456",
         "Prefetch queue byte bound (backpressure)."),
    Knob("TRINO_TPU_PREFETCH_QUEUE_DEPTH", "int", "8",
         "Prefetch queue depth in coalesced batches."),
    Knob("TRINO_TPU_PREFETCH_THREADS", "int", "-1",
         "Prefetch decode threads; -1 auto-tunes from host cores "
         "(cpu_count-1 capped at 4; 0 on single-core hosts)."),
    Knob("TRINO_TPU_PROFILE", "enum", "default",
         "Flight-recorder level: default is a clock read + tuple store "
         "with zero hot syncs; full brackets operators with "
         "block_until_ready for true device time.",
         choices=("off", "default", "full")),
    Knob("TRINO_TPU_PROFILE_RING", "int", "4096",
         "Per-thread profiler event-ring capacity."),
    Knob("TRINO_TPU_QUERY_DEFAULT_MEMORY", "int", "67108864",
         "Admission fallback peak-memory estimate for queries with no "
         "journaled plan-fingerprint history."),
    Knob("TRINO_TPU_QUERY_MAX_MEMORY", "int", "0",
         "Per-query reserved-memory ceiling; exceeding it fails the query "
         "EXCEEDED_MEMORY_LIMIT.  0 = unlimited."),
    Knob("TRINO_TPU_QUERY_STATE", "bool", "1",
         "Write-ahead query-state log for retry_policy=TASK queries "
         "(coordinator crash recovery); 0 disables logging and recovery."),
    Knob("TRINO_TPU_QUERY_STATE_DIR", "path", "",
         "Query-state WAL directory; unset uses a per-uid tempdir next to "
         "the query journal."),
    Knob("TRINO_TPU_RESIDENT_MAX_FRAGMENTS", "int", "8",
         "Largest fragment count one resident-plan program may absorb; "
         "bigger coalesced subtrees stay on the fused/legacy path."),
    Knob("TRINO_TPU_RESIDENT_PLAN", "enum", "auto",
         "Whole-query GSPMD compilation (one program per maximal "
         "TPU-resident plan); 0 keeps the task-per-worker fused/legacy "
         "path bit-for-bit.",
         choices=("auto", "1", "0")),
    Knob("TRINO_TPU_RESOURCE_GROUPS", "json", "",
         "Hierarchical resource-group tree (weights, concurrency and "
         "queue limits, selectors) as JSON; unset uses one flat default "
         "group."),
    Knob("TRINO_TPU_RESULT_CACHE", "bool", "1",
         "Tier C versioned result cache; 0 disables (checked per "
         "lookup)."),
    Knob("TRINO_TPU_RESULT_CACHE_BYTES", "int", "67108864",
         "Result-cache LRU byte budget."),
    Knob("TRINO_TPU_SINK_MAX_BYTES", "int", "268435456",
         "Per-sink buffered-bytes cap (backpressure bound on output "
         "buffers)."),
    Knob("TRINO_TPU_SKEW_FACTOR", "float", "2.0",
         "Adaptive skew threshold: a join key heavier than this multiple "
         "of the mean partition weight is split across probe tasks."),
    Knob("TRINO_TPU_SPECULATION", "bool", "0",
         "Leaf-stage straggler speculation for retry_policy=QUERY "
         "streaming queries."),
    Knob("TRINO_TPU_SPECULATION_NONLEAF", "bool", "0",
         "Extend streaming straggler speculation to non-leaf stages by "
         "teeing producer pages into the durable spool (requires "
         "speculation on)."),
    Knob("TRINO_TPU_SPOOL_DIR", "path", "",
         "Base directory for durable FTE spool roots; unset uses the "
         "system tempdir."),
    Knob("TRINO_TPU_SPOOL_MAX_BYTES", "int", "1073741824",
         "Spool retention byte budget: the GC reclaims expired/leaked "
         "roots oldest-first once retained spools exceed this."),
    Knob("TRINO_TPU_SPOOL_TTL_S", "float", "3600",
         "Retention TTL for unreleased spool roots (crashed or abandoned "
         "queries); the boot sweep reclaims roots idle past this."),
    Knob("TRINO_TPU_STAGE_DEVICE", "bool", "1",
         "Double-buffered device staging of coalesced scan batches; 0 "
         "leaves batches on host until the operator touches them."),
    Knob("TRINO_TPU_SYNC_FREE", "bool", "1",
         "Sync-free probe/expand hot loop; 0 is the legacy per-batch "
         "host-sync path."),
    Knob("TRINO_TPU_TEST_BOOT_FAIL", "bool", "0",
         "Test-only: worker processes exit at boot to exercise the boot "
         "timeout path."),
    Knob("TRINO_TPU_TPCH_VECTOR_DECODE", "bool", "1",
         "Vectorized TPC-H string decode via vocab/code tables; 0 keeps "
         "the legacy per-row decode for bench baselines."),
)

KNOBS: dict = {k.name: k for k in _DECLARATIONS}

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")


def declared(name: str) -> bool:
    return name in KNOBS


def knob(name: str) -> Knob:
    try:
        return KNOBS[name]
    except KeyError:
        raise KeyError(
            f"undeclared TRINO_TPU knob {name!r} — declare it in "
            f"trino_tpu/spi/knobs.py (the registry is the single source "
            f"of truth; see docs/KNOBS.md)") from None


def get_str(name: str) -> str:
    k = knob(name)
    return os.environ.get(k.name, k.default)


def get_int(name: str) -> Optional[int]:
    raw = get_str(name).strip()
    return int(raw) if raw else None


def get_float(name: str) -> Optional[float]:
    raw = get_str(name).strip()
    return float(raw) if raw else None


def get_bool(name: str) -> bool:
    raw = get_str(name).strip().lower()
    if raw in _FALSE or raw == "":
        return False
    return raw in _TRUE or raw not in _FALSE
