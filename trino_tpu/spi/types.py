"""Type system for the TPU-native engine.

Mirrors the role of Trino's ``core/trino-spi/src/main/java/io/trino/spi/type``
(``Type``, ``BigintType``, ``VarcharType``, ``DecimalType`` ...) but is designed
array-first: every type declares a fixed-width *storage dtype* so that a column
of any type is representable as a single fixed-shape device array (plus an
optional validity bitmask and, for character types, a host-side dictionary).

Key divergences from the JVM design (deliberate, TPU-first):

- VARCHAR/CHAR are always dictionary encoded: the device sees ``int32`` codes
  into a host-side *sorted* dictionary, so ``<``/``>`` comparisons and
  ORDER BY on the codes are order-correct (see spi/batch.py). This replaces
  Trino's ``VariableWidthBlock`` (reference: spi/block/VariableWidthBlock.java).
- DECIMAL(p<=18, s) is a scaled int64 ("short decimal", mirrors
  io.trino.spi.type.DecimalType's long path); arithmetic uses explicit
  rescaling helpers.  p>18 is rejected for now (reference Int128 path:
  spi/type/Int128Math.java).
- DATE is int32 days since 1970-01-01, TIMESTAMP is int64 microseconds
  (mirrors io.trino.spi.type.DateType / TimestampType storage).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from functools import total_ordering

import numpy as np

__all__ = [
    "Type",
    "BOOLEAN",
    "TINYINT",
    "SMALLINT",
    "INTEGER",
    "BIGINT",
    "REAL",
    "DOUBLE",
    "VARCHAR",
    "DATE",
    "TIMESTAMP",
    "DecimalType",
    "ArrayType",
    "RowType",
    "MapType",
    "UNKNOWN",
    "parse_type",
    "common_super_type",
    "is_numeric",
    "is_integral",
    "is_string",
]


@total_ordering
@dataclass(frozen=True)
class Type:
    """A SQL type with a fixed-width array storage representation."""

    name: str
    storage_dtype: np.dtype
    # rank used for implicit-coercion decisions (higher wins); -1 = no coercion
    _coercion_rank: int = -1

    def __str__(self) -> str:  # pragma: no cover - debug
        return self.name

    def __lt__(self, other: "Type") -> bool:
        return self.name < other.name

    @property
    def is_dictionary_encoded(self) -> bool:
        return (self.name in ("varchar", "char")
                or isinstance(self, (ArrayType, RowType, MapType))
                or (isinstance(self, DecimalType) and self.precision > 18))

    def zero_value(self):
        """Neutral fill value for masked-out slots."""
        return np.zeros((), dtype=self.storage_dtype)[()]


@dataclass(frozen=True)
class DecimalType(Type):
    """DECIMAL(p,s).  p<=18 is a scaled int64 ("short decimal" — mirrors
    io.trino.spi.type.DecimalType's long path).  p>18 (the reference's
    Int128 path, spi/type/Int128Math.java) is *dictionary-encoded*: the
    device sees int32 codes into a host-side SORTED dictionary of python
    scaled ints, so comparisons/ORDER BY/GROUP BY/joins run on codes
    (order-correct by construction) and exact arithmetic happens via limb
    decomposition (exec/kernels.decimal_limbs) or host dictionary
    transforms — the TPU has no native int128 and 64-bit lanes are
    emulated, so wide-integer vector arithmetic would be a poor fit."""

    precision: int = 18
    scale: int = 0

    def __init__(self, precision: int = 18, scale: int = 0):
        if precision > 38:
            raise ValueError(f"decimal({precision},{scale}): max precision 38")
        object.__setattr__(self, "name", f"decimal({precision},{scale})")
        object.__setattr__(
            self, "storage_dtype",
            np.dtype(np.int32) if precision > 18 else np.dtype(np.int64))
        object.__setattr__(self, "_coercion_rank", 40)
        object.__setattr__(self, "precision", precision)
        object.__setattr__(self, "scale", scale)

    @property
    def is_long(self) -> bool:
        return self.precision > 18

    def scale_factor(self) -> int:
        return 10**self.scale


@dataclass(frozen=True)
class RowType(Type):
    """ROW(name type, ...) (reference: spi/type/RowType.java).  Same
    dictionary-encoded stance as ARRAY: row *values* are python tuples in a
    host-side dictionary, the device sees int32 codes; field access is a
    host table + device gather."""

    fields: tuple = ()  # ((name|None, Type), ...)

    def __init__(self, fields):
        fields = tuple((n, t) for n, t in fields)
        inner = ", ".join(
            (f"{n} {t.name}" if n else t.name) for n, t in fields)
        object.__setattr__(self, "name", f"row({inner})")
        object.__setattr__(self, "storage_dtype", np.dtype(np.int32))
        object.__setattr__(self, "_coercion_rank", -1)
        object.__setattr__(self, "fields", fields)

    def field_index(self, name: str) -> int:
        for i, (n, _) in enumerate(self.fields):
            if n is not None and n.lower() == name.lower():
                return i
        raise KeyError(f"row has no field {name!r}")


@dataclass(frozen=True)
class MapType(Type):
    """MAP(K, V) (reference: spi/type/MapType.java).  Values are host-side
    dictionaries of canonical tuples of (key, value) pairs sorted by key;
    the device sees int32 codes (equality/grouping on codes, map functions
    as host transforms + gathers)."""

    key: "Type" = None
    value: "Type" = None

    def __init__(self, key: "Type", value: "Type"):
        object.__setattr__(self, "name", f"map({key.name}, {value.name})")
        object.__setattr__(self, "storage_dtype", np.dtype(np.int32))
        object.__setattr__(self, "_coercion_rank", -1)
        object.__setattr__(self, "key", key)
        object.__setattr__(self, "value", value)


@dataclass(frozen=True)
class ArrayType(Type):
    """ARRAY(T) (reference: spi/type/ArrayType.java).  TPU-first stance
    mirrors VARCHAR: array *values* live in a host-side dictionary of python
    tuples and the device sees int32 codes, so grouping/equality/joins run
    on codes while array functions (cardinality/element_at/contains) are
    host dictionary transforms + device gathers — the chip never touches
    nested layouts.  UNNEST re-expands on host (row expansion is inherently
    dynamic-shape)."""

    element: "Type" = None

    def __init__(self, element: "Type"):
        object.__setattr__(self, "name", f"array({element.name})")
        object.__setattr__(self, "storage_dtype", np.dtype(np.int32))
        object.__setattr__(self, "_coercion_rank", -1)
        object.__setattr__(self, "element", element)


BOOLEAN = Type("boolean", np.dtype(np.bool_), 0)
TINYINT = Type("tinyint", np.dtype(np.int8), 10)
SMALLINT = Type("smallint", np.dtype(np.int16), 11)
INTEGER = Type("integer", np.dtype(np.int32), 12)
BIGINT = Type("bigint", np.dtype(np.int64), 13)
REAL = Type("real", np.dtype(np.float32), 50)
DOUBLE = Type("double", np.dtype(np.float64), 51)
VARCHAR = Type("varchar", np.dtype(np.int32))  # dictionary codes
DATE = Type("date", np.dtype(np.int32))
TIMESTAMP = Type("timestamp", np.dtype(np.int64))  # microseconds
UNKNOWN = Type("unknown", np.dtype(np.bool_))  # type of NULL literal

_INTEGRAL = {TINYINT.name, SMALLINT.name, INTEGER.name, BIGINT.name}
_NUMERIC_RANKED = [TINYINT, SMALLINT, INTEGER, BIGINT, REAL, DOUBLE]


def is_integral(t: Type) -> bool:
    return t.name in _INTEGRAL


def is_numeric(t: Type) -> bool:
    return t.name in _INTEGRAL or t.name in (REAL.name, DOUBLE.name) or isinstance(t, DecimalType)


def is_string(t: Type) -> bool:
    return t.name in ("varchar", "char")


def common_super_type(a: Type, b: Type) -> Type | None:
    """Least common type for implicit coercion (mirrors
    io.trino.type.TypeCoercion.getCommonSuperType)."""
    if a == b:
        return a
    if a == UNKNOWN:
        return b
    if b == UNKNOWN:
        return a
    if is_numeric(a) and is_numeric(b):
        da, db = isinstance(a, DecimalType), isinstance(b, DecimalType)
        if da and db:
            scale = max(a.scale, b.scale)
            ip = max(a.precision - a.scale, b.precision - b.scale)
            # derived precision only widens into the long (dictionary) path
            # when an INPUT is already long: short-decimal expressions keep
            # their proven int64 kernels
            cap = 38 if (a.precision > 18 or b.precision > 18) else 18
            return DecimalType(min(cap, ip + scale), scale)
        if da or db:
            dec, other = (a, b) if da else (b, a)
            if other.name in (DOUBLE.name, REAL.name):
                return DOUBLE
            # integral + decimal -> decimal wide enough for the integral
            return DecimalType(max(dec.precision, 18), dec.scale)
        ra = a._coercion_rank
        rb = b._coercion_rank
        return a if ra >= rb else b
    if is_string(a) and is_string(b):
        return VARCHAR
    if {a.name, b.name} == {DATE.name, TIMESTAMP.name}:
        return TIMESTAMP
    if isinstance(a, ArrayType) and isinstance(b, ArrayType):
        e = common_super_type(a.element, b.element)
        return ArrayType(e) if e is not None else None
    if isinstance(a, RowType) and isinstance(b, RowType):
        if len(a.fields) != len(b.fields):
            return None
        fields = []
        for (an, at), (bn, bt) in zip(a.fields, b.fields):
            ft = common_super_type(at, bt)
            if ft is None:
                return None
            fields.append((an or bn, ft))
        return RowType(fields)
    if isinstance(a, MapType) and isinstance(b, MapType):
        k = common_super_type(a.key, b.key)
        v = common_super_type(a.value, b.value)
        return MapType(k, v) if k is not None and v is not None else None
    return None


def parse_type(text: str) -> Type:
    t = text.strip().lower()
    simple = {
        "boolean": BOOLEAN,
        "tinyint": TINYINT,
        "smallint": SMALLINT,
        "int": INTEGER,
        "integer": INTEGER,
        "bigint": BIGINT,
        "real": REAL,
        "float": REAL,
        "double": DOUBLE,
        "date": DATE,
        "timestamp": TIMESTAMP,
        "varchar": VARCHAR,
        "char": VARCHAR,
        "string": VARCHAR,
        "unknown": UNKNOWN,
    }
    if t in simple:
        return simple[t]
    if t.startswith("varchar(") or t.startswith("char("):
        return VARCHAR
    if t.startswith("decimal(") or t.startswith("numeric("):
        inner = t[t.index("(") + 1 : t.rindex(")")]
        parts = [p.strip() for p in inner.split(",")]
        prec = int(parts[0])
        scale = int(parts[1]) if len(parts) > 1 else 0
        return DecimalType(prec, scale)
    if t in ("decimal", "numeric"):
        return DecimalType(18, 0)
    if t.startswith("array(") and t.endswith(")"):
        return ArrayType(parse_type(t[len("array("):-1]))
    if t.startswith("array<") and t.endswith(">"):
        return ArrayType(parse_type(t[len("array<"):-1]))
    if t.startswith("map(") and t.endswith(")"):
        parts = _split_top(t[len("map("):-1])
        if len(parts) != 2:
            raise ValueError(f"map needs two type arguments: {text!r}")
        return MapType(parse_type(parts[0]), parse_type(parts[1]))
    if t.startswith("row(") and t.endswith(")"):
        fields = []
        for p in _split_top(t[len("row("):-1]):
            p = p.strip()
            # "name type" or bare "type"
            bits = p.split(None, 1)
            if len(bits) == 2:
                try:
                    fields.append((None, parse_type(p)))  # e.g. "decimal(2, 1)"
                except ValueError:
                    fields.append((bits[0], parse_type(bits[1])))
            else:
                fields.append((None, parse_type(p)))
        return RowType(fields)
    raise ValueError(f"unknown type: {text!r}")


def _split_top(s: str) -> list[str]:
    """Split on commas at paren depth 0 (type-argument lists)."""
    out, depth, start = [], 0, 0
    for i, ch in enumerate(s):
        if ch in "(<":
            depth += 1
        elif ch in ")>":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(s[start:i])
            start = i + 1
    if s[start:].strip():
        out.append(s[start:])
    return out


_EPOCH = datetime.date(1970, 1, 1)


def date_to_days(d: datetime.date | str) -> int:
    if isinstance(d, str):
        d = datetime.date.fromisoformat(d.strip())
    return (d - _EPOCH).days


def days_to_date(days: int) -> datetime.date:
    return _EPOCH + datetime.timedelta(days=int(days))
