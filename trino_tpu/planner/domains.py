"""Filter predicate -> TupleDomain extraction (pushdown framework).

The role of the reference's DomainTranslator (sql/planner/DomainTranslator
.java: fromPredicate) feeding PushPredicateIntoTableScan: walk a Filter's
conjuncts over a TableScan and derive per-column Domains from the
deterministic comparisons.  The result is ADVISORY (enforced=false): the
Filter stays in the plan for exactness, the scan uses the TupleDomain to
prune batches/splits and mask rows before they are padded and shipped to
the device.

Literal values convert to STORAGE space (decimal -> scaled int, date ->
epoch days, timestamp -> micros) so connectors compare against raw column
arrays; strings stay python str (compared through the dictionary)."""

from __future__ import annotations

from typing import Optional

from ..spi.batch import _to_days, _to_micros, _to_scaled_int
from ..spi.predicate import Domain, Range, TupleDomain, ValueSet
from ..spi.types import DATE, TIMESTAMP, ArrayType, DecimalType, Type, is_string
from ..sql.ir import Call, InputRef, Literal, RowExpression

__all__ = ["extract_tuple_domain", "storage_value"]


def _domain_comparable(t: Type) -> bool:
    """Only scalar types participate in Domain ranges.  Array (and other
    nested) literals would put python tuples into Ranges that zone-map stats
    then compare against stringified dictionary entries — bail out so those
    predicates stay in the exact Filter."""
    return not isinstance(t, ArrayType)


def storage_value(t: Type, v):
    """Python literal -> storage-space comparable (matches Column.from_values)."""
    if v is None:
        return None
    if isinstance(t, DecimalType):
        return _to_scaled_int(v, t.scale)
    if t == DATE:
        return _to_days(v)
    if t == TIMESTAMP:
        return _to_micros(v)
    if is_string(t):
        return str(v)
    if t.name == "boolean":
        return bool(v)
    return v


def _column_literal(c: Call) -> Optional[tuple[InputRef, object, bool]]:
    """Match (InputRef, Literal) or (Literal, InputRef); bool = flipped."""
    a, b = c.args
    if isinstance(a, InputRef) and isinstance(b, Literal) and _domain_comparable(a.type):
        return a, storage_value(a.type, b.value), False
    if isinstance(b, InputRef) and isinstance(a, Literal) and _domain_comparable(b.type):
        return b, storage_value(b.type, a.value), True
    return None


_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}


def _conjunct_domain(c: RowExpression) -> Optional[tuple[int, Domain]]:
    """One conjunct -> (channel, Domain), or None if not expressible."""
    if not isinstance(c, Call):
        return None
    name = c.name
    if name in ("eq", "lt", "le", "gt", "ge"):
        m = _column_literal(c)
        if m is None:
            return None
        ref, v, flipped = m
        if v is None:
            return ref.index, Domain.none()  # x <cmp> NULL is never true
        if flipped and name in _FLIP:
            name = _FLIP[name]
        if name == "eq":
            return ref.index, Domain(ValueSet.of([v]), False)
        if name == "lt":
            return ref.index, Domain(ValueSet((Range(None, False, v, False),)), False)
        if name == "le":
            return ref.index, Domain(ValueSet((Range(None, False, v, True),)), False)
        if name == "gt":
            return ref.index, Domain(ValueSet((Range(v, False, None, False),)), False)
        return ref.index, Domain(ValueSet((Range(v, True, None, False),)), False)
    if name == "$in":
        col = c.args[0]
        if not isinstance(col, InputRef) or not _domain_comparable(col.type):
            return None
        vals = []
        for a in c.args[1:]:
            if not isinstance(a, Literal):
                return None
            sv = storage_value(col.type, a.value)
            if sv is not None:
                vals.append(sv)
        return col.index, Domain(ValueSet.of(vals), False)
    if name == "$is_null" and isinstance(c.args[0], InputRef):
        return c.args[0].index, Domain.only_null()
    if (name == "$not" and isinstance(c.args[0], Call)
            and c.args[0].name == "$is_null"
            and isinstance(c.args[0].args[0], InputRef)):
        return c.args[0].args[0].index, Domain(ValueSet.all(), False)
    if name == "$or":
        # single-column OR: union the arm domains (x = 1 OR x IN (3, 4))
        arms = [_conjunct_domain(a) for a in c.args]
        if any(a is None for a in arms):
            return None
        chans = {ch for ch, _ in arms}
        if len(chans) != 1:
            return None
        dom = arms[0][1]
        for _, d in arms[1:]:
            dom = dom.union(d)
        return arms[0][0], dom
    return None


def _split_and(e: RowExpression) -> list[RowExpression]:
    if isinstance(e, Call) and e.name == "$and":
        out = []
        for a in e.args:
            out.extend(_split_and(a))
        return out
    return [e]


def extract_tuple_domain(predicate: RowExpression,
                         channel_to_column: dict[int, str]) -> TupleDomain:
    """Derive the TupleDomain a Filter implies over named scan columns.
    Conjuncts that are not simple column-vs-literal comparisons are ignored
    (sound: the domain only widens)."""
    td = TupleDomain.all()
    for c in _split_and(predicate):
        m = _conjunct_domain(c)
        if m is None:
            continue
        ch, dom = m
        col = channel_to_column.get(ch)
        if col is None:
            continue
        td = td.intersect(TupleDomain({col: dom}))
        if td.is_none:
            return td
    return td
