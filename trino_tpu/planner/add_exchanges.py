"""AddExchanges: place REMOTE exchange boundaries + split aggregations.

The distribution-planning pass (reference: sql/planner/optimizations/
AddExchanges.java:138, chooses SystemPartitioningHandle.java:48-57
partitionings).  Transforms a single-node plan into a distributed one:

- ``Aggregate(SINGLE)`` → ``Aggregate(FINAL) ∘ Exchange(REPARTITION keys)
  ∘ Aggregate(PARTIAL)`` — the classic two-phase aggregation.  The PARTIAL
  step emits mergeable state columns (``avg`` expands to sum+count, scale
  folded in so states are scale-free); distinct aggregates cannot pre-
  aggregate, so they repartition raw rows and aggregate SINGLE after.
- global ``Aggregate`` (no keys) → FINAL after ``Exchange(GATHER)``.
- ``Join(BROADCAST)`` → build side wrapped in ``Exchange(BROADCAST)``
  (BroadcastOutputBuffer path); ``Join(PARTITIONED)`` → both sides hash-
  repartitioned on the join keys (FIXED_HASH_DISTRIBUTION).
- ``Sort`` → per-task sort + order-preserving ``MERGE`` gather (no
  coordinator re-sort; MergeOperator.java:46); ``TopN`` → partial TopN +
  ``MERGE`` + final ``Limit``; ``Limit/DistinctLimit`` → partial on
  workers, final above a ``GATHER``.
- ``Output``/``TableWriter`` root runs single (coordinator gather).

Leaf fragments stay SOURCE-partitioned (split-driven).
"""

from __future__ import annotations

from typing import Optional

from ..spi.types import BIGINT, DOUBLE, DecimalType, Type
from .plan import (
    Aggregate,
    AggCall,
    DistinctLimit,
    Exchange,
    Filter,
    GroupId,
    Join,
    Limit,
    MatchRecognize,
    Output,
    PlanNode,
    Project,
    Replicate,
    SemiJoin,
    Sort,
    TableFunctionScan,
    TableScan,
    TableWriter,
    TopN,
    Union,
    Unnest,
    Values,
    Window,
)

__all__ = ["add_exchanges", "partial_agg_layout",
           "rewrite_join_distribution"]


def partial_agg_layout(aggs, input_types) -> list[tuple[str, Type, int]]:
    """Per original AggCall: list of (state_fn, state_type, width) describing
    the PARTIAL output columns.  avg → [(sum,f64),(count,i64)] with the
    decimal scale folded into the sum state."""
    from ..sql.analyzer import STAT_AGGS

    out = []
    for a in aggs:
        if a.fn == "avg":
            out.append([("avg_sum", DOUBLE), ("avg_count", BIGINT)])
        elif a.fn in STAT_AGGS:
            out.append([("stat_sum", DOUBLE), ("stat_sumsq", DOUBLE),
                        ("stat_count", BIGINT)])
        elif a.fn == "count":
            out.append([("count", BIGINT)])
        else:
            t = a.type
            out.append([(a.fn, t)])
    return out


def add_exchanges(root: PlanNode, writer_tasks: int = 1) -> PlanNode:
    """``writer_tasks > 1`` plans INSERT/CTAS with parallel writers fed by a
    ROUND_ROBIN exchange (the SCALED_WRITER_* partitionings planned by
    estimate; SkewedPartitionRebalancer-style runtime growth is a later
    round — see SystemPartitioningHandle.java:48-57)."""
    return _visit(root, single=True, writer_tasks=writer_tasks)


def _exchange(node: PlanNode, kind: str, keys=()) -> Exchange:
    return Exchange(node.output_names, node.output_types, node, kind,
                    "REMOTE", tuple(keys))


def rewrite_join_distribution(root: PlanNode, join: Join,
                              new_distribution: str,
                              new_left: Optional[PlanNode] = None
                              ) -> PlanNode:
    """Runtime PARTITIONED<->REPLICATED rewrite used by the adaptive
    execution plane (execution/adaptive.py): return ``root`` with the
    exact node ``join`` (identity match) replaced by a copy carrying
    ``new_distribution`` (and, for a broadcast->partitioned flip,
    ``new_left`` — the probe subtree cut into its own fragment and
    re-entered as a RemoteSource).  Only legal on plan trees whose
    consuming stage has not been activated yet; the static planning path
    never calls this."""
    from dataclasses import replace as _replace

    def walk(node: PlanNode) -> PlanNode:
        if node is join:
            return _replace(node,
                            left=node.left if new_left is None else new_left,
                            distribution=new_distribution)
        kids = node.children
        if not kids:
            return node
        new_kids = [walk(c) for c in kids]
        if all(a is b for a, b in zip(kids, new_kids)):
            return node
        if isinstance(node, Union):
            return _replace(node, sources=tuple(new_kids))
        if len(kids) == 1:
            return _replace(node, source=new_kids[0])
        if hasattr(node, "left"):
            return _replace(node, left=new_kids[0], right=new_kids[1])
        return _replace(node, source=new_kids[0], filter_source=new_kids[1])

    return walk(root)


def _visit(node: PlanNode, single: bool, writer_tasks: int = 1) -> PlanNode:
    """Rewrite bottom-up.  ``single`` = the parent requires this subtree's
    output to arrive at one task (root stage)."""

    if isinstance(node, TableWriter) and writer_tasks > 1:
        from dataclasses import replace as _replace

        src = _visit(node.source, single=False)
        rr = Exchange(src.output_names, src.output_types, src,
                      "ROUND_ROBIN", "REMOTE", ())
        # writer tasks each emit one BIGINT row count ("rows"); note the
        # optimizer's generic remap leaves TableWriter.output_names pointing
        # at the SOURCE columns, so the writer contract is restated here
        writer = _replace(node, source=rr,
                          output_names=("rows",), output_types=(BIGINT,))
        gathered = _exchange(writer, "GATHER")
        # TableFinish: sum the per-writer row counts
        # (reference: operator/TableFinishOperator.java:51)
        return Aggregate(("rows",), (BIGINT,), gathered,
                         (), (AggCall("sum", 0, BIGINT),))

    if isinstance(node, Aggregate):
        return _split_aggregate(node, single)

    if isinstance(node, Join):
        left = _visit(node.left, single=False)
        right = _visit(node.right, single=False)
        if node.distribution == "PARTITIONED" and node.left_keys:
            left = _exchange(left, "REPARTITION", node.left_keys)
            right = _exchange(right, "REPARTITION", node.right_keys)
        elif node.join_type in ("RIGHT", "FULL"):
            # keyless outer joins collapse to one task: a broadcast build
            # would emit unmatched build rows once per task
            left = _exchange(left, "GATHER")
            right = _exchange(right, "GATHER")
        else:
            right = _exchange(right, "BROADCAST")
        out = Join(node.output_names, node.output_types, left, right,
                   node.join_type, node.left_keys, node.right_keys,
                   node.residual, node.distribution)
        return _gather_if(out, single)

    if isinstance(node, SemiJoin):
        src = _visit(node.source, single=False)
        filt = _visit(node.filter_source, single=False)
        filt = _exchange(filt, "BROADCAST")
        out = SemiJoin(node.output_names, node.output_types, src, filt,
                       node.source_keys, node.filter_keys, node.negated,
                       node.residual, node.null_aware)
        return _gather_if(out, single)

    if isinstance(node, MatchRecognize):
        src = _visit(node.source, single=False)
        if node.partition_channels:
            src = _exchange(src, "REPARTITION", node.partition_channels)
        else:
            src = _exchange(src, "GATHER")
        out = _replace_source(node, src)
        return _gather_if(out, single and bool(node.partition_channels))

    if isinstance(node, Window):
        src = _visit(node.source, single=False)
        if node.partition_keys:
            # rows of one partition must colocate: hash-repartition on the
            # partition keys (reference: AddExchanges window distribution)
            src = _exchange(src, "REPARTITION", node.partition_keys)
            out = _replace_source(node, src)
            return _gather_if(out, single)
        src = _exchange(src, "GATHER")
        return _replace_source(node, src)

    if isinstance(node, Sort):
        # order-preserving distributed sort: sort per task, MERGE-gather
        # the pre-sorted streams (reference: MergeOperator.java:46; the
        # previous shape — gather then re-sort everything — is the
        # degenerate fallback this replaces)
        src = _visit(node.source, single=False)
        partial = Sort(node.output_names, node.output_types, src, node.keys)
        return Exchange(node.output_names, node.output_types, partial,
                        "MERGE", "REMOTE", (), node.keys)

    if isinstance(node, TopN):
        src = _visit(node.source, single=False)
        partial = TopN(node.output_names, node.output_types, src,
                       node.count, node.keys)
        merged = Exchange(node.output_names, node.output_types, partial,
                          "MERGE", "REMOTE", (), node.keys)
        return Limit(node.output_names, node.output_types, merged, node.count)

    if isinstance(node, Limit):
        src = _visit(node.source, single=False)
        partial = Limit(node.output_names, node.output_types, src, node.count)
        gathered = _exchange(partial, "GATHER")
        return Limit(node.output_names, node.output_types, gathered, node.count)

    if isinstance(node, DistinctLimit):
        src = _visit(node.source, single=False)
        partial = DistinctLimit(node.output_names, node.output_types, src,
                                node.count)
        gathered = _exchange(partial, "GATHER")
        return DistinctLimit(node.output_names, node.output_types, gathered,
                             node.count)

    if isinstance(node, Output):
        src = _visit(node.source, single=True, writer_tasks=writer_tasks)
        return _replace_source(node, src)

    if isinstance(node, TableWriter):
        src = _visit(node.source, single=True)
        return _replace_source(node, src)

    if isinstance(node, (Filter, Project, Replicate, GroupId, Unnest)):
        src = _visit(node.source, single=single)
        return _replace_source(node, src)

    if isinstance(node, Union):
        # each input stays in the union fragment: tasks union their own
        # split shares; any required global dedup sits above as an Aggregate.
        # A static (Values-only) input would be replayed identically by every
        # task of a multi-task union fragment, so it gets its own SINGLE
        # fragment via a GATHER edge.
        from dataclasses import replace as _replace

        srcs = []
        for s in node.sources:
            v = _visit(s, single=False)
            if not _has_task_varying_source(v):
                v = _exchange(v, "GATHER")
            srcs.append(v)
        return _gather_if(_replace(node, sources=tuple(srcs)), single)

    if isinstance(node, (TableScan, Values, TableFunctionScan)):
        return _gather_if(node, single)

    if isinstance(node, Exchange):  # already placed (LOCAL exchanges later)
        return _replace_source(node, _visit(node.source, single=False))

    raise NotImplementedError(f"add_exchanges: {type(node).__name__}")


def _has_task_varying_source(node: PlanNode) -> bool:
    """True when the subtree's output differs per task (scans split by task;
    exchange edges deliver per-task partitions).  Values-only subtrees are
    task-invariant: every task would produce identical copies."""
    if isinstance(node, (TableScan, Exchange)):
        return True
    return any(_has_task_varying_source(c) for c in node.children)


def _replace_source(node, src):
    from dataclasses import replace

    return replace(node, source=src)


def _gather_if(node: PlanNode, single: bool) -> PlanNode:
    if single:
        return _exchange(node, "GATHER")
    return node


def _split_aggregate(node: Aggregate, single: bool) -> PlanNode:
    src = _visit(node.source, single=False)
    nk = len(node.group_keys)
    has_distinct = any(a.distinct for a in node.aggregates)

    def _long_dec_avg(a) -> bool:
        # AVG over decimal(>18): the partial avg state is a scale-free f64
        # sum, which would lose the wide decimal's exactness across the
        # exchange — run it SINGLE at the consumer (SUM keeps its exact
        # limb path through PARTIAL/FINAL: the state is itself a long
        # decimal)
        from ..spi.types import DecimalType

        if a.fn != "avg" or a.arg < 0:
            return False
        t = node.source.output_types[a.arg]
        return isinstance(t, DecimalType) and t.precision > 18

    if has_distinct or any(_long_dec_avg(a) for a in node.aggregates):
        # distinct can't pre-aggregate: repartition raw rows on the group
        # keys (or gather when global), aggregate SINGLE at the consumer
        if nk:
            src = _exchange(src, "REPARTITION", node.group_keys)
        else:
            src = _exchange(src, "GATHER")
        out = Aggregate(node.output_names, node.output_types, src,
                        node.group_keys, node.aggregates, "SINGLE")
        return _gather_if(out, single and nk > 0)

    # ---- PARTIAL ----------------------------------------------------------
    layouts = partial_agg_layout(node.aggregates, src.output_types)
    p_names = [src.output_names[c] for c in node.group_keys]
    p_types = [src.output_types[c] for c in node.group_keys]
    for i, states in enumerate(layouts):
        for j, (fn, t) in enumerate(states):
            p_names.append(f"_s{i}_{j}")
            p_types.append(t)
    partial = Aggregate(tuple(p_names), tuple(p_types), src,
                        node.group_keys, node.aggregates, "PARTIAL")

    # ---- exchange ---------------------------------------------------------
    if nk:
        ex = _exchange(partial, "REPARTITION", tuple(range(nk)))
    else:
        ex = _exchange(partial, "GATHER")

    # ---- FINAL: same call list; args point at the first state channel -----
    f_calls = []
    ch = nk
    for a, states in zip(node.aggregates, layouts):
        f_calls.append(AggCall(a.fn, ch, a.type, False))
        ch += len(states)
    final = Aggregate(node.output_names, node.output_types, ex,
                      tuple(range(nk)), tuple(f_calls), "FINAL")
    return _gather_if(final, single and nk > 0)
