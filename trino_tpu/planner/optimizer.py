"""Plan optimizer: cross-join flattening + stats-greedy join ordering,
filter pushdown, join distribution choice, column pruning.

The deliberately small stand-in for sql/planner/PlanOptimizers' 228 iterative
rules (reference: iterative/rule/ReorderJoins.java,
DetermineJoinDistributionType.java, PushPredicateIntoTableScan.java,
PruneUnreferencedOutputs.java).  Rules operate on channel indices, so every
rewrite returns (new_node, mapping old-channel -> new-channel) and parents
remap their expressions — the moral equivalent of Trino's symbol mapper.

Join ordering: comma/CROSS-join clusters under a Filter are flattened into a
join graph; the spine starts at the largest estimated relation and greedily
joins the smallest connected relation next (build sides stay small); every
available equality edge becomes a hash-join key, including cycle-closing
edges (Q5's c_nationkey = s_nationkey).
"""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

from ..connectors.catalog import Catalog
from ..spi.types import BOOLEAN
from ..sql.ir import Call, InputRef, Literal, RowExpression, walk
from .plan import (
    Aggregate,
    DistinctLimit,
    Exchange,
    Filter,
    GroupId,
    Join,
    Limit,
    MatchRecognize,
    Output,
    PlanNode,
    Project,
    Replicate,
    SemiJoin,
    Sort,
    TableFunctionScan,
    TableScan,
    TableWriter,
    TopN,
    Union,
    Unnest,
    Values,
    Window,
)

__all__ = ["optimize", "estimate_rows", "optimizer_mode", "final_passes"]

_BROADCAST_LIMIT = 2_000_000  # build rows below this replicate to every task

# Damped selectivity of one extra equality join clause whose NDV is unknown
# (Trino's UNKNOWN_FILTER_COEFFICIENT idiom): before the fix, every clause
# past the first contributed selectivity 1.0, so stacked conjuncts never
# tightened a join estimate at all.
_EXTRA_JOIN_CLAUSE_SEL = 0.9


def optimizer_mode() -> str:
    """iterative | legacy (TRINO_TPU_OPTIMIZER; legacy is the bit-for-bit
    single-pass pipeline below)."""
    from ..spi import knobs

    mode = knobs.get_str("TRINO_TPU_OPTIMIZER").strip().lower()
    return mode if mode in ("iterative", "legacy") else "iterative"


def optimize(root: PlanNode, catalog: Catalog) -> PlanNode:
    if optimizer_mode() == "iterative":
        from .iterative import optimize_iterative

        return optimize_iterative(root, catalog)
    return _optimize_legacy(root, catalog)


def _optimize_legacy(root: PlanNode, catalog: Catalog) -> PlanNode:
    node, mapping = _rewrite(root, catalog)
    assert mapping == list(range(len(node.output_types))), "root remap escaped"
    return final_passes(node, catalog)


def final_passes(node: PlanNode, catalog: Catalog) -> PlanNode:
    """Mapping-free tail passes both optimizer modes share: column pruning,
    advisory scan constraints, LIMIT-into-scan."""
    node = _prune(node, set(range(len(node.output_types))))[0]
    node = _attach_scan_constraints(node)
    node = _push_limit_into_scan(node, catalog)
    return node


def _push_limit_into_scan(node: PlanNode, catalog: Catalog) -> PlanNode:
    """LIMIT over a (projected) scan lets the scan stop opening further
    splits once the bound is satisfied (reference: iterative/rule/
    PushLimitIntoTableScan.java; the engine Limit stays for exactness).
    Planning is side-effect free: the bound travels on the TableScan node,
    never as connector state."""
    from dataclasses import replace as _replace

    def pushable_scan(n: PlanNode) -> Optional[TableScan]:
        # only row-preserving hops between Limit and scan
        if isinstance(n, TableScan):
            return n if n.constraint is None else None
        if isinstance(n, Project):
            return pushable_scan(n.source)
        return None

    def walk(n: PlanNode) -> PlanNode:
        kids = tuple(walk(c) for c in n.children)
        if kids != tuple(n.children):
            n = _replace_children(n, kids)
        if isinstance(n, Limit):
            scan = pushable_scan(n.source)
            if scan is not None:
                cap = (min(scan.limit, n.count) if scan.limit is not None
                       else n.count)

                def set_limit(m: PlanNode) -> PlanNode:
                    if isinstance(m, TableScan):
                        return _replace(m, limit=cap)
                    return _replace_children(
                        m, tuple(set_limit(c) for c in m.children))

                n = _replace(n, source=set_limit(n.source))
        return n

    def _replace_children(n: PlanNode, kids) -> PlanNode:
        names = [f.name for f in n.__dataclass_fields__.values()]
        if "source" in names and len(kids) == 1:
            return _replace(n, source=kids[0])
        if "left" in names and len(kids) == 2:
            return _replace(n, left=kids[0], right=kids[1])
        if "sources" in names:
            return _replace(n, sources=tuple(kids))
        return n

    return walk(node)


def _attach_scan_constraints(node: PlanNode) -> PlanNode:
    """Final pass: Filter directly over TableScan derives an advisory
    TupleDomain on the scan (planner/domains.py; reference:
    PushPredicateIntoTableScan.java with enforced=false — the Filter stays)."""
    from .domains import extract_tuple_domain

    if isinstance(node, Filter) and isinstance(node.source, TableScan):
        scan = node.source
        td = extract_tuple_domain(
            node.predicate,
            {i: scan.columns[i] for i in range(len(scan.columns))})
        if not td.is_all:
            return replace(node, source=replace(scan, constraint=td))
        return node
    kids = node.children
    if not kids:
        return node
    new_kids = [_attach_scan_constraints(c) for c in kids]
    if all(a is b for a, b in zip(kids, new_kids)):
        return node
    if isinstance(node, Union):
        return replace(node, sources=tuple(new_kids))
    if len(kids) == 1:
        return replace(node, source=new_kids[0])
    return (replace(node, left=new_kids[0], right=new_kids[1])
            if hasattr(node, "left")
            else replace(node, source=new_kids[0], filter_source=new_kids[1]))


# --------------------------------------------------------------------------
# helpers


def _remap_expr(e: RowExpression, mapping: list[Optional[int]]) -> RowExpression:
    if isinstance(e, InputRef):
        new = mapping[e.index]
        assert new is not None, f"channel #{e.index} pruned but referenced"
        return InputRef(e.type, new)
    if isinstance(e, Call):
        return Call(e.type, e.name, tuple(_remap_expr(a, mapping) for a in e.args))
    return e


def _refs(e: RowExpression) -> set[int]:
    return {x.index for x in walk(e) if isinstance(x, InputRef)}


def _channel_ndv(node: PlanNode, ch: int, catalog: Catalog) -> Optional[float]:
    """Distinct-value estimate for an output channel, traced down identity
    projections/filters to a TableScan column (the NDV half of Trino's
    StatsCalculator — cost/ScalarStatsCalculator + table stats)."""
    while True:
        if isinstance(node, TableScan):
            stats = catalog.connector(node.catalog).get_table_statistics(node.table)
            return stats.ndv.get(node.columns[ch])
        if isinstance(node, Filter):
            node = node.source
            continue
        if isinstance(node, Project):
            e = node.expressions[ch]
            if isinstance(e, InputRef):
                node, ch = node.source, e.index
                continue
            return None
        if isinstance(node, Join):
            lw = len(node.left.output_types)
            if ch < lw:
                node = node.left
            else:
                node, ch = node.right, ch - lw
            continue
        if isinstance(node, SemiJoin):
            if ch < len(node.source.output_types):
                node = node.source
                continue
            return None
        return None


def _conjunct_selectivity(c: RowExpression, source: PlanNode,
                          catalog: Catalog) -> float:
    """Per-predicate selectivity from column NDV when available (mirrors
    cost/FilterStatsCalculator's equality/range rules), 0.3 fallback."""
    if isinstance(c, Call) and c.name == "eq":
        for a, b in (c.args, reversed(c.args)):
            if isinstance(a, InputRef) and isinstance(b, Literal):
                ndv = _channel_ndv(source, a.index, catalog)
                if ndv:
                    return 1.0 / ndv
        return 0.1
    if isinstance(c, Call) and c.name == "$in":
        col = c.args[0]
        if isinstance(col, InputRef):
            ndv = _channel_ndv(source, col.index, catalog)
            if ndv:
                return min(1.0, (len(c.args) - 1) / ndv)
        return 0.2
    if isinstance(c, Call) and c.name in ("lt", "le", "gt", "ge"):
        return 0.4  # one-sided range (BETWEEN splits into two of these)
    if isinstance(c, Call) and c.name == "$like":
        return 0.25
    return 0.3


def estimate_rows(node: PlanNode, catalog: Catalog, history=None) -> float:
    if history is not None:
        observed = history.observed_rows(node)
        if observed is not None:
            return float(observed)
    if isinstance(node, TableScan):
        stats = catalog.connector(node.catalog).get_table_statistics(node.table)
        r = stats.row_count
        return r if r == r else 10_000.0  # NaN check
    if isinstance(node, Filter):
        sel = 1.0
        for c in _split_and(node.predicate):
            sel *= _conjunct_selectivity(c, node.source, catalog)
        return estimate_rows(node.source, catalog, history) * max(sel, 1e-9)
    if isinstance(node, Project):
        return estimate_rows(node.source, catalog, history)
    if isinstance(node, Aggregate):
        src = estimate_rows(node.source, catalog, history)
        if not node.group_keys:
            return 1.0
        groups = 1.0
        known = False
        for k in node.group_keys:
            ndv = _channel_ndv(node.source, k, catalog)
            if ndv:
                groups *= ndv
                known = True
        if known:
            return max(1.0, min(groups, src))
        return max(1.0, src * 0.1)
    if isinstance(node, Join):
        l = estimate_rows(node.left, catalog, history)
        r = estimate_rows(node.right, catalog, history)
        if not node.left_keys:
            return l * r if node.join_type == "CROSS" else l
        # |L ⋈ R| ≈ |L||R| / max(ndv(lk), ndv(rk)) (textbook equi-join)
        lnd = _channel_ndv(node.left, node.left_keys[0], catalog)
        rnd = _channel_ndv(node.right, node.right_keys[0], catalog)
        if lnd and rnd:
            out = max(1.0, l * r / max(lnd, rnd))
        else:
            out = max(l, r)
        # every equality clause past the first tightens the estimate; an
        # unknown-NDV clause is floored at the damped per-conjunct default
        # instead of the old implicit selectivity of 1.0
        for lk, rk in zip(node.left_keys[1:], node.right_keys[1:]):
            nd = max(_channel_ndv(node.left, lk, catalog) or 0.0,
                     _channel_ndv(node.right, rk, catalog) or 0.0)
            sel = max(1.0 / nd, _EXTRA_JOIN_CLAUSE_SEL) if nd \
                else _EXTRA_JOIN_CLAUSE_SEL
            out = max(1.0, out * sel)
        return out
    if isinstance(node, SemiJoin):
        return estimate_rows(node.source, catalog, history)
    if isinstance(node, (Sort,)):
        return estimate_rows(node.source, catalog, history)
    if isinstance(node, (TopN, Limit)):
        return float(getattr(node, "count", 1000))
    if isinstance(node, Values):
        return float(len(node.rows))
    if isinstance(node, Union):
        return sum(estimate_rows(s, catalog, history) for s in node.sources)
    if isinstance(node, GroupId):
        return estimate_rows(node.source, catalog, history) * max(1, len(node.sets))
    if isinstance(node, Unnest):
        return estimate_rows(node.source, catalog, history) * 3.0  # avg fan-out guess
    for c in node.children:
        return estimate_rows(c, catalog, history)
    return 1000.0


# --------------------------------------------------------------------------
# main rewrite (returns node + channel mapping old->new)


def _identity(node: PlanNode) -> list[int]:
    return list(range(len(node.output_types)))


def _rewrite(node: PlanNode, catalog: Catalog) -> tuple[PlanNode, list[int]]:
    if isinstance(node, Filter):
        return _rewrite_filter_cluster(node, catalog)
    if isinstance(node, Join) and node.join_type in ("CROSS", "INNER"):
        return _rewrite_filter_cluster(node, catalog)

    if isinstance(node, (Output,)):
        child, m = _rewrite(node.source, catalog)
        if m != list(range(len(child.output_types))):
            child = _restore_layout(child, m, node.source)
        return replace(node, source=child), _identity(node)

    if isinstance(node, Project):
        child, m = _rewrite(node.source, catalog)
        exprs = tuple(_remap_expr(e, m) for e in node.expressions)
        return replace(node, source=child, expressions=exprs), _identity(node)

    if isinstance(node, Aggregate):
        child, m = _rewrite(node.source, catalog)
        return (
            replace(
                node,
                source=child,
                group_keys=tuple(m[k] for k in node.group_keys),
                aggregates=tuple(
                    replace(a, arg=m[a.arg] if a.arg >= 0 else -1)
                    for a in node.aggregates
                ),
            ),
            _identity(node),
        )

    if isinstance(node, Join):  # LEFT / SINGLE
        left, lm = _rewrite(node.left, catalog)
        right, rm = _rewrite(node.right, catalog)
        lw_old = len(node.left.output_types)
        lw_new = len(left.output_types)
        mapping = [lm[i] for i in range(lw_old)] + [rm[i - lw_old] + lw_new
                                                   for i in range(lw_old, lw_old + len(rm))]
        names = tuple(left.output_names) + tuple(right.output_names)
        types = tuple(left.output_types) + tuple(right.output_types)
        residual = (_remap_expr(node.residual, mapping)
                    if node.residual is not None else None)
        out = replace(
            node, output_names=names, output_types=types, left=left, right=right,
            left_keys=tuple(lm[k] for k in node.left_keys),
            right_keys=tuple(rm[k] for k in node.right_keys),
            residual=residual,
            distribution=_choose_distribution(right, catalog, node.join_type),
        )
        return out, mapping

    if isinstance(node, SemiJoin):
        src, sm = _rewrite(node.source, catalog)
        filt, fm = _rewrite(node.filter_source, catalog)
        sw_old = len(node.source.output_types)
        sw_new = len(src.output_types)
        mapping = [sm[i] for i in range(sw_old)] + [sw_new]  # mark at end
        residual = None
        if node.residual is not None:
            # residual layout: source ++ filter channels
            rmap = sm + [fm[i] + sw_new for i in range(len(fm))]
            residual = _remap_expr(node.residual, rmap)
        names = tuple(src.output_names) + (node.output_names[-1],)
        types = tuple(src.output_types) + (BOOLEAN,)
        out = replace(
            node, output_names=names, output_types=types,
            source=src, filter_source=filt,
            source_keys=tuple(sm[k] for k in node.source_keys),
            filter_keys=tuple(fm[k] for k in node.filter_keys),
            residual=residual,
        )
        return out, mapping

    if isinstance(node, (Sort, TopN, Limit, TableWriter, Exchange,
                         DistinctLimit, Replicate)):
        child, m = _rewrite(node.source, catalog)
        kwargs = dict(source=child, output_names=child.output_names,
                      output_types=child.output_types)
        if isinstance(node, (Sort, TopN)):
            kwargs["keys"] = tuple(replace(k, channel=m[k.channel]) for k in node.keys)
        if isinstance(node, Exchange):
            kwargs["partition_keys"] = tuple(m[k] for k in node.partition_keys)
        if isinstance(node, Replicate):
            kwargs["count_channel"] = m[node.count_channel]
        return replace(node, **kwargs), m

    if isinstance(node, GroupId):
        child, m = _rewrite(node.source, catalog)
        out = replace(node, source=child,
                      key_channels=tuple(m[c] for c in node.key_channels),
                      passthrough=tuple(m[c] for c in node.passthrough))
        return out, _identity(node)

    if isinstance(node, Unnest):
        child, m = _rewrite(node.source, catalog)
        out = replace(node, source=child,
                      replicate=tuple(m[c] for c in node.replicate),
                      unnest_channels=tuple(m[c] for c in node.unnest_channels))
        return out, _identity(node)

    if isinstance(node, MatchRecognize):
        child, m = _rewrite(node.source, catalog)
        if m != list(range(len(child.output_types))):
            child = _restore_layout(child, m, node.source)
        return replace(node, source=child), _identity(node)

    if isinstance(node, Window):
        child, m = _rewrite(node.source, catalog)
        sw_old = len(node.source.output_types)
        sw_new = len(child.output_types)
        funcs = tuple(
            replace(f, args=tuple(m[a] for a in f.args))
            for f in node.functions)
        names = tuple(child.output_names) + tuple(
            node.output_names[sw_old + j] for j in range(len(funcs)))
        types = tuple(child.output_types) + tuple(f.type for f in funcs)
        out = replace(
            node, output_names=names, output_types=types, source=child,
            partition_keys=tuple(m[k] for k in node.partition_keys),
            order_keys=tuple(replace(k, channel=m[k.channel])
                             for k in node.order_keys),
            functions=funcs)
        mapping = [m[i] for i in range(sw_old)] + [
            sw_new + j for j in range(len(funcs))]
        return out, mapping

    if isinstance(node, Union):
        new_sources = []
        for s in node.sources:
            child, m = _rewrite(s, catalog)
            if m != list(range(len(child.output_types))):
                child = _restore_layout(child, m, s)
            new_sources.append(child)
        return replace(node, sources=tuple(new_sources)), _identity(node)

    if isinstance(node, (TableScan, Values, TableFunctionScan)):
        return node, _identity(node)

    raise NotImplementedError(f"optimizer: {type(node).__name__}")


def _restore_layout(child: PlanNode, mapping: list[int], original: PlanNode) -> PlanNode:
    exprs = tuple(InputRef(t, mapping[i]) for i, t in enumerate(original.output_types))
    return Project(tuple(original.output_names), tuple(original.output_types),
                   child, exprs)


def _choose_distribution(build: PlanNode, catalog: Catalog,
                         join_type: str = "INNER", history=None) -> str:
    # RIGHT/FULL must partition: a broadcast build would emit its unmatched
    # rows once per task (reference: DetermineJoinDistributionType.java —
    # right/full joins cannot use REPLICATED)
    if join_type in ("RIGHT", "FULL"):
        return "PARTITIONED"
    import os

    # override hook for mis-estimation drills: force a wrong static choice
    # and let the adaptive plane (execution/adaptive.py) correct it at the
    # activation barrier from OBSERVED bytes
    limit = int(os.environ.get("TRINO_TPU_BROADCAST_ROW_LIMIT",
                               str(_BROADCAST_LIMIT)) or _BROADCAST_LIMIT)
    if history is not None:
        stats = history.stats_for(build)
        if stats is not None:
            # observed build bytes against the same threshold the adaptive
            # activation barrier uses — the plan-time version of its flip
            if stats.bytes is not None:
                from ..execution.adaptive import broadcast_threshold_bytes

                return ("BROADCAST"
                        if stats.bytes <= broadcast_threshold_bytes(None)
                        else "PARTITIONED")
            if stats.rows is not None:
                return ("BROADCAST" if stats.rows <= limit
                        else "PARTITIONED")
    return ("BROADCAST" if estimate_rows(build, catalog, history) <= limit
            else "PARTITIONED")


# --------------------------------------------------------------------------
# cross-join cluster flattening


def _shift(e: RowExpression, by: int) -> RowExpression:
    if isinstance(e, InputRef):
        return InputRef(e.type, e.index + by)
    if isinstance(e, Call):
        return Call(e.type, e.name, tuple(_shift(a, by) for a in e.args))
    return e


def _flatten(node: PlanNode, catalog: Catalog):
    """Collect cluster leaves with their ORIGINAL channel offsets."""
    leaves: list[tuple[PlanNode, list[int]]] = []
    conjuncts: list[RowExpression] = []

    def go(n: PlanNode, offset: int) -> int:
        """Returns width of n's original layout; appends leaves/conjuncts."""
        if isinstance(n, Join) and n.join_type in ("CROSS", "INNER"):
            lw = go(n.left, offset)
            rw = go(n.right, offset + lw)
            for lk, rk in zip(n.left_keys, n.right_keys):
                conjuncts.append(Call(BOOLEAN, "eq", (
                    InputRef(n.left.output_types[lk], offset + lk),
                    InputRef(n.right.output_types[rk], offset + lw + rk))))
            if n.residual is not None:
                conjuncts.append(_shift(n.residual, offset))
            return lw + rw
        leaf, m = _rewrite(n, catalog)
        leaves.append((leaf, offset, m))
        return len(n.output_types)

    total = go(node, 0)
    return leaves, conjuncts, total


def _hoist_common_or(e: RowExpression) -> list[RowExpression]:
    """(A ∧ X) ∨ (A ∧ Y) → [A, X ∨ Y] — extract conjuncts common to every
    OR arm (reference: sql/planner/iterative/rule/... ExtractCommonPredicates
    ExpressionRewriter; Kleene 3VL is distributive, so this is exact).  The
    unlocked equality conjuncts turn Q19-style OR-of-ANDs cross joins into
    hash joins."""
    if not (isinstance(e, Call) and e.name == "$or"):
        return [e]
    arms = [_split_and(a) for a in e.args]
    common = [t for t in arms[0]
              if all(any(t == u for u in arm) for arm in arms[1:])]
    if not common:
        return [e]
    reduced = [[t for t in arm if t not in common] for arm in arms]
    out = list(common)
    if all(reduced):  # an empty remainder makes the OR vacuous given common
        out.append(Call(BOOLEAN, "$or",
                        tuple(_conjoin(r) for r in reduced)))
    return out


def _rewrite_filter_cluster(node: PlanNode, catalog: Catalog):
    if isinstance(node, Filter):
        cluster_root = node.source
        preds = [p for c in _split_and(node.predicate)
                 for p in _hoist_common_or(c)]
    else:
        cluster_root = node
        preds = []
    if not (isinstance(cluster_root, Join)
            and cluster_root.join_type in ("CROSS", "INNER")):
        # plain filter over a non-join child
        child, m = _rewrite(cluster_root, catalog)
        if not isinstance(node, Filter):
            return child, m
        pred = _conjoin([_remap_expr(p, m) for p in preds])
        out = Filter(child.output_names, child.output_types, child, pred)
        return out, m

    leaves, conjuncts, total_width = _flatten(cluster_root, catalog)
    conjuncts = conjuncts + preds

    # original channel -> (leaf idx, local channel through leaf's mapping)
    chan_leaf: dict[int, tuple[int, int]] = {}
    for li, (leaf, offset, m) in enumerate(leaves):
        for local_old, local_new in enumerate(m):
            chan_leaf[offset + local_old] = (li, local_new)

    def leaf_of(e: RowExpression) -> Optional[int]:
        ls = {chan_leaf[i][0] for i in _refs(e)}
        return ls.pop() if len(ls) == 1 else None

    # push single-leaf conjuncts into the leaf
    leaf_nodes = [leaf for (leaf, _, _) in leaves]
    leaf_filters: list[list[RowExpression]] = [[] for _ in leaves]
    edges: list[tuple[int, int, RowExpression, RowExpression]] = []
    residual: list[RowExpression] = []
    for c in conjuncts:
        refs = _refs(c)
        involved = {chan_leaf[i][0] for i in refs}
        if len(involved) == 1:
            li = involved.pop()
            local = _remap_to_leaf(c, chan_leaf, li)
            leaf_filters[li].append(local)
        elif (isinstance(c, Call) and c.name == "eq" and len(involved) == 2
              and _single_leaf(c.args[0], chan_leaf) is not None
              and _single_leaf(c.args[1], chan_leaf) is not None):
            a, b = c.args
            la, lb = _single_leaf(a, chan_leaf), _single_leaf(b, chan_leaf)
            edges.append((la, lb,
                          _remap_to_leaf(a, chan_leaf, la),
                          _remap_to_leaf(b, chan_leaf, lb)))
        else:
            residual.append(c)

    for li, filters in enumerate(leaf_filters):
        if filters:
            leaf = leaf_nodes[li]
            leaf_nodes[li] = Filter(leaf.output_names, leaf.output_types,
                                    leaf, _conjoin(filters))

    est = [estimate_rows(l, catalog) for l in leaf_nodes]

    # greedy: spine = largest; next = the connected relation with the
    # SMALLEST ESTIMATED JOIN OUTPUT (|A><B| ~ |A|*|B| / max key NDV —
    # cost/JoinStatsRule's core rule).  Size-only greediness exploded Q5 at
    # scale: customer joined the spine over the 25-value nationkey edge
    # (fan-out x6000) before orders made the custkey edge available.
    order = [max(range(len(leaf_nodes)), key=lambda i: est[i])]
    remaining = set(range(len(leaf_nodes))) - set(order)
    spine_est = est[order[0]]

    ndv_cache: dict[tuple[int, int], Optional[float]] = {}

    def _leaf_ndv(leaf: int, expr) -> Optional[float]:
        if not isinstance(expr, InputRef):
            return None
        key = (leaf, expr.index)
        if key not in ndv_cache:
            ndv_cache[key] = _channel_ndv(leaf_nodes[leaf], expr.index,
                                          catalog)
        return ndv_cache[key]

    def _edge_ndv(i: int) -> Optional[float]:
        """max(NDV) over BOTH endpoints of the best usable edge
        (|A><B| ~ |A|*|B| / max(ndv_A, ndv_B) — cost/JoinStatsRule)."""
        best: Optional[float] = None
        for (a, b, ea, eb) in edges:
            if a in order and b == i:
                se, ce = ea, eb
                sl = a
            elif b in order and a == i:
                se, ce = eb, ea
                sl = b
            else:
                continue
            nd = max((x for x in (_leaf_ndv(i, ce), _leaf_ndv(sl, se))
                      if x), default=None)
            if nd:
                best = max(best or 0.0, nd)
        return best

    # key expressions must be channels; all edge endpoint exprs that are
    # plain InputRefs can be used directly, others appended via projection.
    while remaining:
        connected = [
            i for i in remaining
            if any((a in order and b == i) or (b in order and a == i)
                   for (a, b, _, _) in edges)
        ]
        if connected:

            def out_est(i: int) -> float:
                nd = _edge_ndv(i)
                if nd:
                    return spine_est * est[i] / max(nd, 1.0)
                # keyed join with unknown NDV: PK-FK-ish assumption
                return max(spine_est, est[i])

            outs = {i: out_est(i) for i in connected}
            pick = min(connected, key=lambda i: (outs[i], est[i]))
            spine_est = max(outs[pick], 1.0)
        else:
            pick = min(remaining, key=lambda i: est[i])
            spine_est = spine_est * max(est[pick], 1.0)  # cross join
        order.append(pick)
        remaining.discard(pick)

    # build the tree left-deep; track mapping (leaf idx, local ch) -> spine ch
    spine = leaf_nodes[order[0]]
    pos: dict[tuple[int, int], int] = {
        (order[0], i): i for i in range(len(spine.output_types))
    }
    used_edges = set()
    for step in range(1, len(order)):
        li = order[step]
        right = leaf_nodes[li]
        lkeys, rkeys = [], []
        for ei, (a, b, ea, eb) in enumerate(edges):
            if ei in used_edges:
                continue
            if a in order[:step] and b == li:
                sa, rb = ea, eb
            elif b in order[:step] and a == li:
                sa, rb = eb, ea
                a, b = b, a
            else:
                continue
            used_edges.add(ei)
            # spine-side expr: remap leaf-local -> spine channels
            sa_spine = _remap_leaf_to_spine(sa, a, pos)
            lkeys.append(sa_spine)
            rkeys.append(rb)
        lch, spine = _exprs_as_channels(lkeys, spine)
        rch, right = _exprs_as_channels(rkeys, right)
        names = tuple(spine.output_names) + tuple(right.output_names)
        types = tuple(spine.output_types) + tuple(right.output_types)
        sw = len(spine.output_types)
        jt = "INNER" if lch else "CROSS"
        spine = Join(names, types, spine, right, jt, tuple(lch), tuple(rch),
                     None, distribution=_choose_distribution(right, catalog))
        for i in range(len(right.output_types)):
            pos[(li, i)] = sw + i

    # residual conjuncts over the final spine
    if residual:
        def remap_residual(e: RowExpression) -> RowExpression:
            if isinstance(e, InputRef):
                li, local = chan_leaf[e.index]
                return InputRef(e.type, pos[(li, local)])
            if isinstance(e, Call):
                return Call(e.type, e.name, tuple(remap_residual(a) for a in e.args))
            return e
        spine = Filter(spine.output_names, spine.output_types, spine,
                       _conjoin([remap_residual(r) for r in residual]))

    # overall mapping: original concat channel -> spine channel
    mapping = []
    for i in range(total_width):
        li, local = chan_leaf.get(i, (None, None))
        mapping.append(pos.get((li, local)) if li is not None else None)
    return spine, mapping


def _remap_leaf_to_spine(e: RowExpression, leaf_idx: int,
                         pos: dict[tuple[int, int], int]) -> RowExpression:
    if isinstance(e, InputRef):
        return InputRef(e.type, pos[(leaf_idx, e.index)])
    if isinstance(e, Call):
        return Call(e.type, e.name,
                    tuple(_remap_leaf_to_spine(a, leaf_idx, pos) for a in e.args))
    return e


def _single_leaf(e: RowExpression, chan_leaf) -> Optional[int]:
    ls = {chan_leaf[i][0] for i in _refs(e)}
    return ls.pop() if len(ls) == 1 else None


def _remap_to_leaf(e: RowExpression, chan_leaf, li: int) -> RowExpression:
    if isinstance(e, InputRef):
        l, local = chan_leaf[e.index]
        assert l == li
        return InputRef(e.type, local)
    if isinstance(e, Call):
        return Call(e.type, e.name,
                    tuple(_remap_to_leaf(a, chan_leaf, li) for a in e.args))
    return e


def _exprs_as_channels(exprs: list[RowExpression], node: PlanNode):
    chans, extra, names = [], [], []
    for e in exprs:
        if isinstance(e, InputRef):
            chans.append(e.index)
        else:
            chans.append(len(node.output_types) + len(extra))
            extra.append(e)
            names.append(f"_jk{len(node.output_types) + len(extra) - 1}")
    if extra:
        base = [InputRef(t, i) for i, t in enumerate(node.output_types)]
        node = Project(tuple(node.output_names) + tuple(names),
                       tuple(node.output_types) + tuple(e.type for e in extra),
                       node, tuple(base + extra))
    return chans, node


def _split_and(e: RowExpression) -> list[RowExpression]:
    if isinstance(e, Call) and e.name == "$and":
        out = []
        for a in e.args:
            out.extend(_split_and(a))
        return out
    return [e]


def _conjoin(terms: list[RowExpression]) -> RowExpression:
    if len(terms) == 1:
        return terms[0]
    return Call(BOOLEAN, "$and", tuple(terms))


# --------------------------------------------------------------------------
# column pruning


def _prune(node: PlanNode, needed: set[int]) -> tuple[PlanNode, list[Optional[int]]]:
    """Drop unused output channels bottom-up.  Returns (node, mapping
    old-channel -> new-channel or None if dropped)."""

    def key_mapping(kept: list[int], width: int) -> list[Optional[int]]:
        m: list[Optional[int]] = [None] * width
        for new, old in enumerate(kept):
            m[old] = new
        return m

    if isinstance(node, Output):
        child, m = _prune(node.source, set(range(len(node.source.output_types))))
        assert all(x is not None for x in m)
        return replace(node, source=child), list(range(len(node.output_types)))

    if isinstance(node, Project):
        kept = sorted(needed)
        if not kept and node.expressions:
            # a zero-column batch cannot carry its row count (the padded
            # live-mask model needs at least one array): keep the cheapest
            # channel for count(*)-style consumers (the reference's pruning
            # keeps a smallest column for the same reason)
            kept = [0]
        child_needed = set()
        for i in kept:
            child_needed |= _refs(node.expressions[i])
        child, cm = _prune(node.source, child_needed)
        exprs = tuple(_remap_expr(node.expressions[i], cm) for i in kept)
        out = Project(tuple(node.output_names[i] for i in kept),
                      tuple(node.output_types[i] for i in kept), child, exprs)
        return out, key_mapping(kept, len(node.output_types))

    if isinstance(node, Filter):
        child_needed = set(needed) | _refs(node.predicate)
        child, cm = _prune(node.source, child_needed)
        pred = _remap_expr(node.predicate, cm)
        out = Filter(child.output_names, child.output_types, child, pred)
        return out, cm

    if isinstance(node, TableScan):
        kept = sorted(needed)
        if not kept:
            kept = [0]  # keep one channel for row counting
        out = TableScan(tuple(node.output_names[i] for i in kept),
                        tuple(node.output_types[i] for i in kept),
                        node.catalog, node.table,
                        tuple(node.columns[i] for i in kept))
        return out, key_mapping(kept, len(node.output_types))

    if isinstance(node, (Values, TableFunctionScan)):
        return node, list(range(len(node.output_types)))

    if isinstance(node, Aggregate):
        nk = len(node.group_keys)
        kept_aggs = [i for i in range(len(node.aggregates))
                     if (nk + i) in needed]
        child_needed = set(node.group_keys)
        for i in kept_aggs:
            if node.aggregates[i].arg >= 0:
                child_needed.add(node.aggregates[i].arg)
        child, cm = _prune(node.source, child_needed)
        aggs = tuple(
            replace(node.aggregates[i],
                    arg=cm[node.aggregates[i].arg] if node.aggregates[i].arg >= 0 else -1)
            for i in kept_aggs)
        keys = tuple(cm[k] for k in node.group_keys)
        kept = list(range(nk)) + [nk + i for i in kept_aggs]
        out = Aggregate(tuple(node.output_names[i] for i in kept),
                        tuple(node.output_types[i] for i in kept),
                        child, keys, aggs, node.step)
        return out, key_mapping(kept, len(node.output_types))

    if isinstance(node, Join):
        lw = len(node.left.output_types)
        left_needed = {i for i in needed if i < lw} | set(node.left_keys)
        right_needed = {i - lw for i in needed if i >= lw} | set(node.right_keys)
        if node.residual is not None:
            for r in _refs(node.residual):
                (left_needed if r < lw else right_needed).add(r if r < lw else r - lw)
        left, lm = _prune(node.left, left_needed)
        right, rm = _prune(node.right, right_needed)
        lw_new = len(left.output_types)
        mapping: list[Optional[int]] = []
        for i in range(lw):
            mapping.append(lm[i])
        for i in range(len(node.right.output_types)):
            mapping.append(rm[i] + lw_new if rm[i] is not None else None)
        residual = (_remap_expr(node.residual, mapping)
                    if node.residual is not None else None)
        names = tuple(left.output_names) + tuple(right.output_names)
        types = tuple(left.output_types) + tuple(right.output_types)
        out = replace(node, output_names=names, output_types=types,
                      left=left, right=right,
                      left_keys=tuple(lm[k] for k in node.left_keys),
                      right_keys=tuple(rm[k] for k in node.right_keys),
                      residual=residual)
        return out, mapping

    if isinstance(node, SemiJoin):
        sw = len(node.source.output_types)
        src_needed = {i for i in needed if i < sw} | set(node.source_keys)
        filt_needed = set(node.filter_keys)
        if node.residual is not None:
            for r in _refs(node.residual):
                (src_needed if r < sw else filt_needed).add(r if r < sw else r - sw)
        src, sm = _prune(node.source, src_needed)
        filt, fm = _prune(node.filter_source, filt_needed)
        sw_new = len(src.output_types)
        mapping = [sm[i] for i in range(sw)] + [sw_new]
        residual = None
        if node.residual is not None:
            # residual layout: source channels ++ filter-source channels
            full = [sm[i] for i in range(sw)] + \
                   [fm[i] + sw_new if fm[i] is not None else None
                    for i in range(len(node.filter_source.output_types))]
            residual = _remap_expr(node.residual, full)
        names = tuple(src.output_names) + (node.output_names[-1],)
        types = tuple(src.output_types) + (BOOLEAN,)
        out = replace(node, output_names=names, output_types=types,
                      source=src, filter_source=filt,
                      source_keys=tuple(sm[k] for k in node.source_keys),
                      filter_keys=tuple(fm[k] for k in node.filter_keys),
                      residual=residual)
        return out, mapping

    if isinstance(node, (Sort, TopN)):
        child_needed = set(needed) | {k.channel for k in node.keys}
        child, cm = _prune(node.source, child_needed)
        keys = tuple(replace(k, channel=cm[k.channel]) for k in node.keys)
        out = replace(node, source=child, keys=keys,
                      output_names=child.output_names,
                      output_types=child.output_types)
        return out, cm

    if isinstance(node, GroupId):
        # every output is load-bearing for the Aggregate above (keys + gid
        # are its grouping keys; passthroughs its arguments): prune below only
        child_needed = set(node.key_channels) | set(node.passthrough)
        child, cm = _prune(node.source, child_needed)
        out = replace(node, source=child,
                      key_channels=tuple(cm[c] for c in node.key_channels),
                      passthrough=tuple(cm[c] for c in node.passthrough))
        return out, list(range(len(node.output_types)))

    if isinstance(node, Unnest):
        child_needed = set(node.replicate) | set(node.unnest_channels)
        child, cm = _prune(node.source, child_needed)
        out = replace(node, source=child,
                      replicate=tuple(cm[c] for c in node.replicate),
                      unnest_channels=tuple(cm[c] for c in node.unnest_channels))
        return out, list(range(len(node.output_types)))

    if isinstance(node, MatchRecognize):
        # DEFINE/MEASURES reference source columns BY NAME in the host
        # pattern engine: the full input layout must survive
        child, cm = _prune(node.source,
                           set(range(len(node.source.output_types))))
        return replace(node, source=child), list(range(len(node.output_types)))

    if isinstance(node, Window):
        sw = len(node.source.output_types)
        kept_fns = [j for j in range(len(node.functions)) if (sw + j) in needed]
        child_needed = ({i for i in needed if i < sw}
                        | set(node.partition_keys)
                        | {k.channel for k in node.order_keys})
        for j in kept_fns:
            child_needed |= set(node.functions[j].args)
        child, cm = _prune(node.source, child_needed)
        sw_new = len(child.output_types)
        funcs = tuple(
            replace(node.functions[j],
                    args=tuple(cm[a] for a in node.functions[j].args))
            for j in kept_fns)
        names = tuple(child.output_names) + tuple(
            node.output_names[sw + j] for j in kept_fns)
        types = tuple(child.output_types) + tuple(f.type for f in funcs)
        out = replace(node, output_names=names, output_types=types,
                      source=child,
                      partition_keys=tuple(cm[k] for k in node.partition_keys),
                      order_keys=tuple(replace(k, channel=cm[k.channel])
                                       for k in node.order_keys),
                      functions=funcs)
        mapping: list[Optional[int]] = [cm[i] for i in range(sw)]
        fn_map = {j: sw_new + newj for newj, j in enumerate(kept_fns)}
        for j in range(len(node.functions)):
            mapping.append(fn_map.get(j))
        return out, mapping

    if isinstance(node, Union):
        kept = sorted(needed) or [0]
        new_sources = []
        for s in node.sources:
            child, cm = _prune(s, set(kept))
            if [cm[i] for i in kept] != list(range(len(child.output_types))):
                # re-project so every source keeps the identical layout
                child = Project(
                    tuple(node.output_names[i] for i in kept),
                    tuple(node.output_types[i] for i in kept),
                    child,
                    tuple(InputRef(node.output_types[i], cm[i]) for i in kept))
            new_sources.append(child)
        out = Union(tuple(node.output_names[i] for i in kept),
                    tuple(node.output_types[i] for i in kept),
                    tuple(new_sources))
        m: list[Optional[int]] = [None] * len(node.output_types)
        for new, old in enumerate(kept):
            m[old] = new
        return out, m

    if isinstance(node, Replicate):
        child, cm = _prune(node.source, set(needed) | {node.count_channel})
        return replace(node, source=child,
                       output_names=child.output_names,
                       output_types=child.output_types,
                       count_channel=cm[node.count_channel]), cm

    if isinstance(node, (Limit, Exchange, TableWriter)):
        if isinstance(node, TableWriter):
            needed = set(range(len(node.source.output_types)))
        child, cm = _prune(node.source, needed if not isinstance(node, TableWriter)
                           else set(range(len(node.source.output_types))))
        kwargs = dict(source=child)
        if not isinstance(node, TableWriter):
            kwargs["output_names"] = child.output_names
            kwargs["output_types"] = child.output_types
        if isinstance(node, Exchange):
            kwargs["partition_keys"] = tuple(cm[k] for k in node.partition_keys)
        return replace(node, **kwargs), cm if not isinstance(node, TableWriter) \
            else list(range(len(node.output_types)))

    raise NotImplementedError(f"prune: {type(node).__name__}")
