"""AST -> logical plan.

The role of sql/planner/LogicalPlanner + QueryPlanner/RelationPlanner
(reference: sql/planner/LogicalPlanner.java:237 ``plan``, QueryPlanner.java,
RelationPlanner.java) including subquery planning: correlated scalar
aggregates, EXISTS and IN become joins/semi-joins here (Trino models them as
ApplyNode + TransformCorrelated* rules; we decorrelate directly while
translating, producing the same join shapes).

Channel discipline: every relation's fields map 1:1 to its plan node's output
channels; appends (subquery marks, scalar results) only ever add channels on
the right, so previously translated IR stays valid.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

from ..connectors.catalog import Catalog
from ..spi.types import BIGINT, BOOLEAN, Type, UNKNOWN
from ..sql import ast
from ..sql.analyzer import (
    AGG_FUNCTIONS,
    AggregateCollector,
    AnalysisError,
    Field,
    Scope,
    Translator,
    WindowCollector,
    agg_result_type,
    cast_to,
    rewrite_expr,
    split_conjuncts,
)
from ..sql.ir import Call, InputRef, Literal, OuterRef, RowExpression, walk
from .plan import (
    AggCall,
    Aggregate,
    CorrelatedJoin,
    Filter,
    GroupId,
    Join,
    Limit,
    MatchRecognize,
    Output,
    PlanNode,
    Project,
    Replicate,
    SemiJoin,
    Sort,
    SortKey,
    TableFunctionScan,
    TableScan,
    TableWriter,
    TopN,
    Union,
    Unnest,
    Values,
    Window,
    WindowFunc,
)

__all__ = ["LogicalPlanner", "RelationPlan"]


@dataclass
class RelationPlan:
    node: PlanNode
    qualifiers: list[Optional[str]]

    def scope(self, parent: Optional[Scope] = None) -> Scope:
        return Scope(
            [
                Field(n, t, q)
                for n, t, q in zip(
                    self.node.output_names, self.node.output_types, self.qualifiers
                )
            ],
            parent,
        )

    @property
    def width(self) -> int:
        return len(self.node.output_names)

    def append(self, exprs: list[RowExpression], names: list[str],
               quals: Optional[list[Optional[str]]] = None) -> "RelationPlan":
        """Identity projection plus extra computed channels on the right."""
        base = [
            InputRef(t, i) for i, t in enumerate(self.node.output_types)
        ]
        node = Project(
            tuple(self.node.output_names) + tuple(names),
            tuple(self.node.output_types) + tuple(e.type for e in exprs),
            self.node,
            tuple(base + exprs),
        )
        return RelationPlan(node, self.qualifiers + (quals or [None] * len(exprs)))


def _has_outer(e: RowExpression, level: int = 1) -> bool:
    return any(isinstance(x, OuterRef) and x.level >= level for x in walk(e))


def _shift_outer(e: RowExpression, by: int = -1) -> RowExpression:
    """Decrement OuterRef levels (when an expression moves one scope out)."""
    if isinstance(e, OuterRef):
        if e.level + by <= 0:
            return InputRef(e.type, e.index)
        return OuterRef(e.type, e.index, e.level + by)
    if isinstance(e, Call):
        return Call(e.type, e.name, tuple(_shift_outer(a, by) for a in e.args))
    return e


def _shift_inputs(e: RowExpression, by: int) -> RowExpression:
    if isinstance(e, InputRef):
        return InputRef(e.type, e.index + by)
    if isinstance(e, Call):
        return Call(e.type, e.name, tuple(_shift_inputs(a, by) for a in e.args))
    return e


def _conjoin(terms: list[RowExpression]) -> Optional[RowExpression]:
    terms = [t for t in terms if t is not None]
    if not terms:
        return None
    if len(terms) == 1:
        return terms[0]
    return Call(BOOLEAN, "$and", tuple(terms))


class LogicalPlanner:
    def __init__(self, catalog: Catalog, default_catalog: str = "tpch"):
        self.catalog = catalog
        self.default_catalog = default_catalog
        self._view_stack: set[str] = set()  # cycle detection for view inlining

    # ------------------------------------------------------------------ api
    def plan(self, stmt: ast.Statement) -> PlanNode:
        from ..sql.analyzer import SQL_FUNCTIONS

        SQL_FUNCTIONS.set(getattr(self.catalog, "sql_functions", {}))
        if isinstance(stmt, ast.QueryStatement):
            rel = self.plan_query(stmt.query, None, {})
            return Output(self.node_names(rel), rel.node.output_types, rel.node)
        if isinstance(stmt, ast.CreateTableAsSelect) or isinstance(stmt, ast.InsertInto):
            rel = self.plan_query(stmt.query, None, {})
            cat, table = self._split_table_name(stmt.table)
            writer = TableWriter(("rows",), (BIGINT,), rel.node, cat, table)
            return Output(("rows",), (BIGINT,), writer)
        raise AnalysisError(f"unsupported statement: {type(stmt).__name__}")

    def node_names(self, rel: RelationPlan) -> tuple[str, ...]:
        return tuple(rel.node.output_names)

    def _split_table_name(self, name: str) -> tuple[str, str]:
        parts = name.split(".")
        if len(parts) == 1:
            return self.default_catalog, parts[0]
        return parts[0], parts[-1]

    # ---------------------------------------------------------------- query
    def plan_query(self, q: ast.Query, outer: Optional[Scope],
                   ctes: dict[str, ast.Query]) -> RelationPlan:
        ctes = dict(ctes)
        for w in q.with_:
            if w.column_names:
                ctes[w.name] = replace(
                    w.query, body=_alias_body(w.query.body, w.column_names))
            else:
                ctes[w.name] = w.query
        rel, select_irs = self.plan_body(q.body, outer, ctes)

        # ORDER BY / LIMIT over the projected relation.  Keys not in the
        # select list become hidden channels appended to the projection and
        # pruned after the sort (Trino: QueryPlanner orderingScheme over
        # hidden symbols; SELECT DISTINCT forbids them per spec).
        if q.order_by:
            keys = []
            hidden: list[RowExpression] = []
            for item in q.order_by:
                try:
                    ch = self._order_channel(
                        item.expr, q.body, rel, select_irs, outer, ctes)
                except AnalysisError:
                    tr = self._select_context_translator(q.body, outer, ctes)
                    if tr is None:
                        raise
                    if isinstance(rel.node, Aggregate):
                        raise AnalysisError(
                            "for SELECT DISTINCT, ORDER BY expressions must "
                            f"appear in select list: {item.expr}")
                    hidden.append(tr(item.expr))
                    ch = -len(hidden)  # placeholder, resolved below
                nf = item.nulls_first
                if nf is None:
                    nf = not item.ascending  # SQL default: NULLS LAST asc
                keys.append(SortKey(ch, item.ascending, nf))
            base_width = rel.width
            if hidden:
                proj = rel.node
                if not isinstance(proj, Project):
                    raise AnalysisError(
                        f"ORDER BY expression not in select list: {q.order_by}")
                ext = Project(
                    tuple(proj.output_names) + tuple(
                        f"_ord{i}" for i in range(len(hidden))),
                    tuple(proj.output_types) + tuple(e.type for e in hidden),
                    proj.source,
                    tuple(proj.expressions) + tuple(hidden))
                rel = RelationPlan(ext, rel.qualifiers + [None] * len(hidden))
                keys = [
                    k if k.channel >= 0 else
                    SortKey(base_width + (-k.channel - 1), k.ascending,
                            k.nulls_first)
                    for k in keys
                ]
            if q.limit is not None:
                node = TopN(rel.node.output_names, rel.node.output_types,
                            rel.node, q.limit, tuple(keys))
            else:
                node = Sort(rel.node.output_names, rel.node.output_types,
                            rel.node, tuple(keys))
            rel = RelationPlan(node, rel.qualifiers)
            if hidden:  # prune the hidden sort channels
                prune = Project(
                    tuple(node.output_names[:base_width]),
                    tuple(node.output_types[:base_width]),
                    node,
                    tuple(InputRef(node.output_types[i], i)
                          for i in range(base_width)))
                rel = RelationPlan(prune, rel.qualifiers[:base_width])
        elif q.limit is not None:
            rel = RelationPlan(
                Limit(rel.node.output_names, rel.node.output_types, rel.node, q.limit),
                rel.qualifiers,
            )
        return rel

    def _order_channel(self, e: ast.Expr, spec: ast.QuerySpec, rel: RelationPlan,
                       select_irs: list[RowExpression], outer, ctes) -> int:
        # 1) name matches a select alias/output name
        if isinstance(e, ast.ColumnRef) and len(e.parts) == 1:
            names = rel.node.output_names
            if names.count(e.parts[0]) == 1:
                return names.index(e.parts[0])
        # 2) expression equal to a select item (translated in the same context)
        if isinstance(e, ast.IntLiteral):  # ORDER BY ordinal
            if 1 <= e.value <= len(select_irs):
                return e.value - 1
        tr = self._select_context_translator(spec, outer, ctes)
        if tr is not None:
            try:
                ir = tr(e)
            except AnalysisError:
                ir = None
            if ir is not None and ir in select_irs:
                return select_irs.index(ir)
        raise AnalysisError(f"ORDER BY expression not in select list: {e}")

    def _select_context_translator(self, spec, outer, ctes):
        ctx = getattr(self, "_last_select_ctx", None)
        if ctx is None or ctx[0] is not spec:
            return None
        _, translate = ctx
        return translate

    # ----------------------------------------------------------------- body
    def plan_body(self, body: ast.QueryBody, outer: Optional[Scope],
                  ctes: dict[str, ast.Query]) -> tuple[RelationPlan, list[RowExpression]]:
        if isinstance(body, ast.QuerySpec):
            return self.plan_spec(body, outer, ctes)
        if isinstance(body, ast.Query):  # parenthesized query term
            rel = self.plan_query(body, outer, ctes)
            return rel, [InputRef(t, i)
                         for i, t in enumerate(rel.node.output_types)]
        if isinstance(body, ast.SetOp):
            rel = self.plan_setop(body, outer, ctes)
            return rel, [InputRef(t, i)
                         for i, t in enumerate(rel.node.output_types)]
        if isinstance(body, ast.ValuesBody):
            rel = self.plan_values(body, outer, ctes)
            return rel, [InputRef(t, i)
                         for i, t in enumerate(rel.node.output_types)]
        raise AnalysisError(f"unsupported query body: {type(body).__name__}")

    def plan_values(self, body: ast.ValuesBody, outer, ctes) -> RelationPlan:
        """VALUES rows (reference: sql/tree/Values.java -> ValuesNode).
        Literal rows build a Values node directly; rows with computed
        expressions desugar to a UNION ALL of FROM-less selects."""
        width = len(body.rows[0])
        for row in body.rows:
            if len(row) != width:
                raise AnalysisError("VALUES rows have different column counts")
        dummy = RelationPlan(
            Values(("_row",), (BIGINT,), rows=((0,),)), [None])
        tr = Translator(dummy.scope(outer))
        rows_ir = [[tr.translate(e) for e in row] for row in body.rows]
        if all(isinstance(e, Literal) for r in rows_ir for e in r):
            from ..spi.types import common_super_type

            types: list[Type] = list(e.type for e in rows_ir[0])
            for r in rows_ir[1:]:
                for i in range(width):
                    c = common_super_type(types[i], r[i].type)
                    if c is None:
                        raise AnalysisError(
                            f"VALUES column {i + 1} type mismatch: "
                            f"{types[i]} vs {r[i].type}")
                    types[i] = c
            if any(t == UNKNOWN for t in types):
                raise AnalysisError(
                    "VALUES column is entirely NULL; add a CAST")
            names = tuple(f"_col{i}" for i in range(width))
            rows = tuple(tuple(e.value for e in r) for r in rows_ir)
            return RelationPlan(Values(names, tuple(types), rows),
                                [None] * width)
        # computed expressions: UNION ALL of single-row selects (plan_setop
        # performs the per-column coercions)
        def spec_of(row) -> ast.QueryBody:
            return ast.QuerySpec(tuple(ast.SelectItem(e) for e in row))

        acc: ast.QueryBody = spec_of(body.rows[0])
        for row in body.rows[1:]:
            acc = ast.SetOp("UNION", False, acc, spec_of(row))
        return self.plan_body(acc, outer, ctes)[0]

    def plan_setop(self, op: ast.SetOp, outer, ctes) -> RelationPlan:
        """UNION/INTERSECT/EXCEPT (reference: sql/planner/plan/
        SetOperationNode.java lowered per SetOperationNodeTranslator):
        UNION ALL -> Union; the distinct variants -> Union of marker-tagged
        inputs + group-by-all-channels counting each side + Filter.  Group-
        based lowering gives SQL set semantics (NULLs compare equal) for
        free because the grouping kernel treats NULL as one group."""
        left = self.plan_body(op.left, outer, ctes)[0]
        right = self.plan_body(op.right, outer, ctes)[0]
        if left.width != right.width:
            raise AnalysisError(
                f"{op.op} inputs have different column counts: "
                f"{left.width} vs {right.width}")
        from ..spi.types import common_super_type

        types = []
        for i, (lt, rt) in enumerate(zip(left.node.output_types,
                                         right.node.output_types)):
            c = common_super_type(lt, rt)
            if c is None:
                raise AnalysisError(
                    f"{op.op} column {i + 1} type mismatch: {lt} vs {rt}")
            types.append(c)
        names = tuple(left.node.output_names)
        sides = [_cast_side(left, types), _cast_side(right, types)]

        if op.op == "UNION":
            un = Union(names, tuple(types), tuple(s.node for s in sides))
            rel = RelationPlan(un, [None] * len(names))
            if op.distinct:
                agg = Aggregate(un.output_names, un.output_types, un,
                                tuple(range(len(names))), ())
                rel = RelationPlan(agg, [None] * len(names))
            return rel

        # INTERSECT / EXCEPT [ALL]: tag each side, count per group.  The
        # DISTINCT variants filter on the counts; the ALL variants replicate
        # each group min(l,r) / max(l-r, 0) times (multiset semantics).
        w = len(names)
        tagged = []
        for si, s in enumerate(sides):
            marks = [Literal(BIGINT, 1 if si == 0 else 0),
                     Literal(BIGINT, 1 if si == 1 else 0)]
            tagged.append(s.append(marks, ["_l", "_r"]).node)
        un = Union(names + ("_l", "_r"), tuple(types) + (BIGINT, BIGINT),
                   tuple(tagged))
        aggs = (AggCall("sum", w, BIGINT), AggCall("sum", w + 1, BIGINT))
        agg = Aggregate(names + ("_lc", "_rc"), tuple(types) + (BIGINT, BIGINT),
                        un, tuple(range(w)), aggs)
        lc = InputRef(BIGINT, w)
        rc = InputRef(BIGINT, w + 1)
        zero = Literal(BIGINT, 0)
        if not op.distinct:
            if op.op == "INTERSECT":
                count_ir = Call(BIGINT, "least", (lc, rc))
            else:  # EXCEPT ALL
                count_ir = Call(BIGINT, "greatest",
                                (Call(BIGINT, "subtract", (lc, rc)), zero))
            counted = Project(
                names + ("_n",), tuple(types) + (BIGINT,), agg,
                tuple(InputRef(t, i) for i, t in enumerate(types))
                + (count_ir,))
            repl = Replicate(counted.output_names, counted.output_types,
                             counted, w)
            proj = Project(names, tuple(types), repl,
                           tuple(InputRef(t, i) for i, t in enumerate(types)))
            return RelationPlan(proj, [None] * len(names))
        if op.op == "INTERSECT":
            pred = Call(BOOLEAN, "$and", (Call(BOOLEAN, "gt", (lc, zero)),
                                          Call(BOOLEAN, "gt", (rc, zero))))
        else:  # EXCEPT
            pred = Call(BOOLEAN, "$and", (Call(BOOLEAN, "gt", (lc, zero)),
                                          Call(BOOLEAN, "eq", (rc, zero))))
        filt = Filter(agg.output_names, agg.output_types, agg, pred)
        proj = Project(names, tuple(types), filt,
                       tuple(InputRef(t, i) for i, t in enumerate(types)))
        return RelationPlan(proj, [None] * len(names))

    # ----------------------------------------------------------------- spec
    def plan_spec(self, spec: ast.QuerySpec, outer: Optional[Scope],
                  ctes: dict[str, ast.Query]) -> tuple[RelationPlan, list[RowExpression]]:
        # FROM-less SELECT evaluates over one synthetic row (the reference
        # plans a single-row ValuesNode); the dummy channel is invisible to
        # SELECT * via star_width=0
        rel = (self.plan_relation(spec.from_, outer, ctes)
               if spec.from_ is not None
               else RelationPlan(Values(("_row",), (BIGINT,), rows=((0,),)), [None]))
        # capture the user-visible fields now: WHERE subquery handling appends
        # synthetic channels (_mark/_scalar/_key) that SELECT * must not see
        star_width = rel.width if spec.from_ is not None else 0

        # WHERE: plain conjuncts first (push down), then subquery conjuncts
        if spec.where is not None:
            conjuncts = split_conjuncts(spec.where)
            plain = [c for c in conjuncts if not _contains_subquery(c)]
            subq = [c for c in conjuncts if _contains_subquery(c)]
            if plain:
                tr = Translator(rel.scope(outer))
                pred = _conjoin([cast_to(tr.translate(c), BOOLEAN) for c in plain])
                rel = RelationPlan(
                    Filter(rel.node.output_names, rel.node.output_types, rel.node, pred),
                    rel.qualifiers,
                )
            for c in subq:
                rel = self._plan_subquery_conjunct(rel, c, outer, ctes)

        has_group = bool(spec.group_by)
        collector = AggregateCollector()
        wcollector = WindowCollector()
        rewrite: dict[RowExpression, RowExpression] = {}
        scope = rel.scope(outer)
        tr = Translator(scope, aggregates=collector, windows=wcollector)
        select_items = self._expand_stars(spec, rel, star_width)
        select_irs = [tr.translate(it.expr) for it in select_items]
        having_ir = None
        having_subqueries: list[tuple[ast.Expr, RelationPlan]] = []
        if spec.having is not None:
            # two-phase: translate now against the pre-agg scope (collecting
            # aggregates); subqueries become $subq markers planned standalone
            # for their type, attached above the Aggregate afterwards
            def stash_cb(node: ast.Expr) -> RowExpression:
                if isinstance(node, ast.ScalarSubquery):
                    sub = self.plan_query(node.query, None, ctes)
                    if sub.width != 1:
                        raise AnalysisError("scalar subquery must return one column")
                    having_subqueries.append((node, sub))
                    return Call(sub.node.output_types[0], "$subq",
                                (Literal(BIGINT, len(having_subqueries) - 1),))
                raise AnalysisError(
                    f"unsupported subquery in HAVING: {type(node).__name__}")

            htr = Translator(scope, aggregates=collector, subquery_cb=stash_cb)
            having_ir = _conjoin(
                [cast_to(htr.translate(c), BOOLEAN)
                 for c in split_conjuncts(spec.having)])

        has_aggs = bool(collector.calls)
        covered_check = None
        gs_ctx = None  # (group_irs, set_list, gid channel or None)
        if has_group or has_aggs:
            group_irs, set_list = self._expand_grouping(
                spec.group_by, select_items, rel, outer)
            grouping_calls = [
                x for e in (select_irs + ([having_ir] if having_ir is not None else []))
                for x in walk(e)
                if isinstance(x, Call) and x.name == "$grouping"]
            if len(set_list) > 1 or grouping_calls:
                rel, rewrite, gid_ch = self._plan_grouping_sets(
                    rel, group_irs, set_list, collector, outer)
                rewrite.update(self._grouping_mask_rewrites(
                    grouping_calls, group_irs, set_list, gid_ch))
                gs_ctx = (group_irs, set_list, gid_ch)
            else:
                rel, rewrite = self._plan_aggregation(
                    rel, group_irs, collector, outer)
                gs_ctx = (group_irs, set_list, None)

            # validate BEFORE rewriting: every select subtree must be a
            # group-by expression, an aggregate placeholder, or composed of
            # those — a surviving bare InputRef references a pre-agg channel
            def covered(e: RowExpression) -> bool:
                if e in rewrite or isinstance(e, Literal):
                    return True
                if isinstance(e, Call):
                    return all(covered(a) for a in e.args)
                return False

            covered_check = covered
            for it, e in zip(select_items, select_irs):
                if not covered(e):
                    raise AnalysisError(
                        f"'{it.expr}' must be an aggregate expression or "
                        "appear in GROUP BY clause")
            select_irs = [rewrite_expr(e, rewrite) for e in select_irs]
            if having_ir is not None:
                having_ir = rewrite_expr(having_ir, rewrite)
                # attach stashed HAVING subqueries above the Aggregate
                for i, (node, sub) in enumerate(having_subqueries):
                    names = tuple(rel.node.output_names) + (f"_scalar{rel.width}",)
                    types = tuple(rel.node.output_types) + (sub.node.output_types[0],)
                    jn = Join(names, types, rel.node, sub.node, "SINGLE", (), (), None)
                    rel = RelationPlan(jn, rel.qualifiers + [None])
                    marker = Call(sub.node.output_types[0], "$subq",
                                  (Literal(BIGINT, i),))
                    having_ir = rewrite_expr(
                        having_ir,
                        {marker: InputRef(types[-1], rel.width - 1)})
                rel = RelationPlan(
                    Filter(rel.node.output_names, rel.node.output_types,
                           rel.node, having_ir),
                    rel.qualifiers,
                )
        elif spec.having is not None:
            raise AnalysisError("HAVING requires aggregation")

        # window functions: evaluated after aggregation/HAVING, before
        # DISTINCT and ORDER BY (reference: sql/planner/QueryPlanner window
        # planning order)
        win_rewrite: dict[RowExpression, RowExpression] = {}
        if wcollector.calls:
            rel, win_rewrite = self._plan_windows(
                rel, wcollector, rewrite,
                require_covered=(has_group or has_aggs))
            select_irs = [rewrite_expr(e, win_rewrite) for e in select_irs]

        # SELECT projection
        names = []
        for i, it in enumerate(select_items):
            if it.alias:
                names.append(it.alias)
            elif isinstance(it.expr, ast.ColumnRef):
                names.append(it.expr.parts[-1])
            else:
                names.append(f"_col{i}")
        proj = Project(tuple(names), tuple(e.type for e in select_irs),
                       rel.node, tuple(select_irs))
        out = RelationPlan(proj, [None] * len(names))
        if spec.distinct:
            agg = Aggregate(proj.output_names, proj.output_types, proj,
                            tuple(range(len(names))), ())
            out = RelationPlan(agg, [None] * len(names))

        # stash context for ORDER BY expression matching.  ORDER BY hidden
        # channels run through the same coverage validation as select items:
        # an uncovered pre-aggregation reference must error, never silently
        # index a post-aggregation channel.
        planned_agg_count = len(collector.calls)

        def translate_in_select_ctx(e: ast.Expr) -> RowExpression:
            t = Translator(scope, aggregates=collector, windows=wcollector)
            ir = t.translate(e)
            if len(collector.calls) != planned_agg_count:
                raise AnalysisError(
                    f"ORDER BY aggregate not in select list: {e}")
            if has_group or has_aggs:
                # ORDER BY may carry grouping() calls not present in the
                # select list: give them the same $grouping_mask rewrite
                extra: dict = {}
                gcalls = [x for x in walk(ir)
                          if isinstance(x, Call) and x.name == "$grouping"]
                if gcalls:
                    g_irs, s_list, gid = gs_ctx
                    if gid is None:
                        # single grouping set: grouping() is constant 0
                        for x in gcalls:
                            for a in x.args:
                                if a not in g_irs:
                                    raise AnalysisError(
                                        "grouping() arguments must appear "
                                        "in GROUP BY")
                            extra[x] = Literal(BIGINT, 0)
                    else:
                        extra = self._grouping_mask_rewrites(
                            gcalls, g_irs, s_list, gid)
                if covered_check is not None and not covered_check(ir):
                    raise AnalysisError(
                        f"'{e}' must be an aggregate expression or appear "
                        "in GROUP BY clause")
                ir = rewrite_expr(ir, {**rewrite, **extra})
            if win_rewrite:
                ir = rewrite_expr(ir, win_rewrite)
            return ir

        self._last_select_ctx = (spec, translate_in_select_ctx)
        return out, select_irs

    def _expand_stars(self, spec: ast.QuerySpec, rel: RelationPlan,
                      star_width: int) -> list[ast.SelectItem]:
        out = []
        for it in spec.select:
            if it.expr is not None:
                out.append(it)
                continue
            for name, qual in list(zip(rel.node.output_names, rel.qualifiers))[:star_width]:
                if it.star_prefix is None or it.star_prefix == qual:
                    out.append(ast.SelectItem(ast.ColumnRef((name,)), None))
        if not out:
            raise AnalysisError("SELECT * matched no columns")
        return out

    # ---------------------------------------------------------- aggregation
    def _plan_aggregation(self, rel: RelationPlan, group_irs, collector, outer):
        """Pre-project group keys + agg args, emit Aggregate, return rewrite
        map for post-agg expressions."""
        pre_exprs: list[RowExpression] = []
        pre_names: list[str] = []

        def channel_of(e: RowExpression) -> int:
            if isinstance(e, InputRef):
                return e.index
            for j, pe in enumerate(pre_exprs):
                if pe == e:
                    return rel.width + j
            pre_exprs.append(e)
            pre_names.append(f"_expr{len(pre_exprs)}")
            return rel.width + len(pre_exprs) - 1

        key_channels = [channel_of(g) for g in group_irs]
        agg_calls = []
        for fn, arg, distinct, out_t in collector.calls:
            ch = channel_of(arg) if arg is not None else -1
            agg_calls.append(AggCall(fn, ch, out_t, distinct))
        src = rel
        if pre_exprs:
            src = rel.append(pre_exprs, pre_names)
        names = tuple(
            [src.node.output_names[c] for c in key_channels]
            + [f"_agg{j}" for j in range(len(agg_calls))]
        )
        types = tuple(
            [src.node.output_types[c] for c in key_channels]
            + [a.type for a in agg_calls]
        )
        agg = Aggregate(names, types, src.node, tuple(key_channels), tuple(agg_calls))
        quals = [src.qualifiers[c] for c in key_channels] + [None] * len(agg_calls)
        out = RelationPlan(agg, quals)
        rewrite: dict[RowExpression, RowExpression] = {}
        for i, g in enumerate(group_irs):
            rewrite[g] = InputRef(g.type, i)
        for j, (fn, arg, distinct, out_t) in enumerate(collector.calls):
            placeholder = Call(out_t, "$aggref", (Literal(BIGINT, j),))
            rewrite[placeholder] = InputRef(out_t, len(key_channels) + j)
        return out, rewrite

    # ------------------------------------------------------- grouping sets
    def _expand_grouping(self, group_by, select_items, rel, outer):
        """Expand GROUP BY elements (exprs, ROLLUP, CUBE, GROUPING SETS) into
        (group_irs, sets): the ordered distinct grouping columns as IR, and
        one tuple of column indices per grouping set.  Multiple elements
        combine by cross product (SQL:2016 7.9; reference:
        StatementAnalyzer.analyzeGroupBy computing the set product)."""

        def resolve(g: ast.Expr) -> ast.Expr:
            # GROUP BY <ordinal> resolves to the select item's expression
            if isinstance(g, ast.IntLiteral):
                if not 1 <= g.value <= len(select_items):
                    raise AnalysisError(
                        f"GROUP BY position {g.value} is not in select list")
                return select_items[g.value - 1].expr
            return g

        element_sets: list[list[tuple[ast.Expr, ...]]] = []
        for el in group_by:
            if isinstance(el, ast.Rollup):
                exprs = [resolve(e) for e in el.exprs]
                element_sets.append(
                    [tuple(exprs[:k]) for k in range(len(exprs), -1, -1)])
            elif isinstance(el, ast.Cube):
                exprs = [resolve(e) for e in el.exprs]
                subsets = [
                    tuple(e for i, e in enumerate(exprs) if mask & (1 << i))
                    for mask in range(1 << len(exprs))]
                subsets.sort(key=len, reverse=True)
                element_sets.append(subsets)
            elif isinstance(el, ast.GroupingSets):
                element_sets.append(
                    [tuple(resolve(e) for e in s) for s in el.sets])
            else:
                element_sets.append([(resolve(el),)])
        combined: list[tuple[ast.Expr, ...]] = [()]
        for sets in element_sets:
            combined = [c + s for c in combined for s in sets]

        tr = Translator(rel.scope(outer))
        group_irs: list[RowExpression] = []
        index: dict[RowExpression, int] = {}
        set_list: list[tuple[int, ...]] = []
        for s in combined:
            idxs: list[int] = []
            for e in s:
                ir = tr.translate(e)
                if ir not in index:
                    index[ir] = len(group_irs)
                    group_irs.append(ir)
                if index[ir] not in idxs:
                    idxs.append(index[ir])
            set_list.append(tuple(idxs))
        return group_irs, set_list

    def _plan_grouping_sets(self, rel, group_irs, set_list, collector, outer):
        """GroupId + Aggregate keyed on (all grouping columns, $groupid)
        (reference: sql/planner/QueryPlanner.planGroupingSets building
        GroupIdNode).  Returns (relation, rewrite, groupid channel in the
        aggregation output)."""
        pre_exprs: list[RowExpression] = []
        pre_names: list[str] = []

        def channel_of(e: RowExpression) -> int:
            if isinstance(e, InputRef):
                return e.index
            for j, pe in enumerate(pre_exprs):
                if pe == e:
                    return rel.width + j
            pre_exprs.append(e)
            pre_names.append(f"_expr{len(pre_exprs)}")
            return rel.width + len(pre_exprs) - 1

        key_channels = [channel_of(g) for g in group_irs]
        agg_specs = []
        for fn, arg, distinct, out_t in collector.calls:
            ch = channel_of(arg) if arg is not None else -1
            agg_specs.append((fn, ch, distinct, out_t))
        src = rel
        if pre_exprs:
            src = rel.append(pre_exprs, pre_names)

        # aggregation arguments pass through un-nulled copies: a grouping
        # column that is also an aggregate argument must keep its values
        pass_chs: list[int] = []
        for _, ch, _, _ in agg_specs:
            if ch >= 0 and ch not in pass_chs:
                pass_chs.append(ch)
        nk = len(key_channels)
        g_names = tuple(
            [src.node.output_names[c] for c in key_channels]
            + [src.node.output_names[c] for c in pass_chs]
            + ["$groupid"])
        g_types = tuple(
            [src.node.output_types[c] for c in key_channels]
            + [src.node.output_types[c] for c in pass_chs]
            + [BIGINT])
        gid_node = GroupId(g_names, g_types, src.node,
                           tuple(key_channels), tuple(pass_chs),
                           tuple(set_list))

        agg_calls = []
        for fn, ch, distinct, out_t in agg_specs:
            new_ch = nk + pass_chs.index(ch) if ch >= 0 else -1
            agg_calls.append(AggCall(fn, new_ch, out_t, distinct))
        gkeys = tuple(range(nk)) + (nk + len(pass_chs),)
        a_names = tuple(
            list(g_names[:nk]) + ["$groupid"]
            + [f"_agg{j}" for j in range(len(agg_calls))])
        a_types = tuple(
            list(g_types[:nk]) + [BIGINT] + [a.type for a in agg_calls])
        agg = Aggregate(a_names, a_types, gid_node, gkeys, tuple(agg_calls))
        out = RelationPlan(agg, [None] * len(a_names))
        rewrite: dict[RowExpression, RowExpression] = {}
        for i, g in enumerate(group_irs):
            rewrite[g] = InputRef(g.type, i)
        for j, (fn, arg, distinct, out_t) in enumerate(collector.calls):
            placeholder = Call(out_t, "$aggref", (Literal(BIGINT, j),))
            rewrite[placeholder] = InputRef(out_t, nk + 1 + j)
        return out, rewrite, nk

    def _grouping_mask_rewrites(self, grouping_calls, group_irs, set_list,
                                gid_ch):
        """Map each $grouping(cols…) marker onto a $grouping_mask(gid,
        mask-per-set…) gather (reference: planner/GroupingOperationRewriter:
        grouping() = bitmask of arguments absent from the row's set, first
        argument = most significant bit)."""
        out: dict[RowExpression, RowExpression] = {}
        for x in grouping_calls:
            if x in out:
                continue
            idxs = []
            for a in x.args:
                try:
                    idxs.append(group_irs.index(a))
                except ValueError:
                    raise AnalysisError(
                        "grouping() arguments must appear in GROUP BY")
            n = len(idxs)
            masks = []
            for s in set_list:
                m = 0
                for pos, gi in enumerate(idxs):
                    if gi not in s:
                        m |= 1 << (n - 1 - pos)
                masks.append(m)
            out[x] = Call(
                BIGINT, "$grouping_mask",
                tuple([InputRef(BIGINT, gid_ch)]
                      + [Literal(BIGINT, m) for m in masks]))
        return out

    # -------------------------------------------------------------- windows
    def _plan_windows(self, rel: RelationPlan, wcollector: WindowCollector,
                      agg_rewrite: dict, require_covered: bool):
        """Emit Window nodes (one per distinct (partition, order) spec so each
        gets exactly one sort) and return the $winref -> channel rewrite."""

        def covered(e: RowExpression) -> bool:
            if e in agg_rewrite or isinstance(e, Literal):
                return True
            if isinstance(e, Call):
                return all(covered(a) for a in e.args)
            return False

        def prep(e: RowExpression) -> RowExpression:
            if require_covered and not covered(e):
                raise AnalysisError(
                    f"'{e}' in window specification must be an aggregate "
                    "expression or appear in GROUP BY clause")
            return rewrite_expr(e, agg_rewrite)

        groups: dict = {}
        group_order: list = []
        for idx, spec in enumerate(wcollector.calls):
            partition = tuple(prep(p) for p in spec.partition)
            order = tuple(
                (prep(k.expr), k.ascending, k.nulls_first) for k in spec.order)
            args = tuple(prep(a) for a in spec.args)
            key = (partition, order)
            if key not in groups:
                groups[key] = []
                group_order.append(key)
            groups[key].append((idx, spec, args))

        win_rewrite: dict[RowExpression, RowExpression] = {}
        for key in group_order:
            partition, order = key
            calls = groups[key]
            pending: list[RowExpression] = []

            def channel_of(e: RowExpression) -> int:
                if isinstance(e, InputRef):
                    return e.index
                for j, pe in enumerate(pending):
                    if pe == e:
                        return rel.width + j
                pending.append(e)
                return rel.width + len(pending) - 1

            pch = [channel_of(p) for p in partition]
            okeys = [SortKey(channel_of(oe), asc, nf)
                     for (oe, asc, nf) in order]
            funcs = []
            for _idx, spec, args in calls:
                ach = tuple(channel_of(a) for a in args)
                funcs.append(WindowFunc(spec.fn, ach, spec.type,
                                        spec.offset, spec.frame))
            if pending:
                rel = rel.append(
                    pending, [f"_wk{rel.width + j}"
                              for j in range(len(pending))])
            base = rel.width
            names = tuple(rel.node.output_names) + tuple(
                f"_win{base + j}" for j in range(len(calls)))
            types = tuple(rel.node.output_types) + tuple(
                spec.type for (_i, spec, _a) in calls)
            node = Window(names, types, rel.node, tuple(pch), tuple(okeys),
                          tuple(funcs))
            rel = RelationPlan(node, rel.qualifiers + [None] * len(calls))
            for j, (idx, spec, _args) in enumerate(calls):
                placeholder = Call(spec.type, "$winref",
                                   (Literal(BIGINT, idx),))
                win_rewrite[placeholder] = InputRef(spec.type, base + j)
        return rel, win_rewrite

    # ------------------------------------------------------------ relations
    def plan_relation(self, r: ast.Relation, outer: Optional[Scope],
                      ctes: dict[str, ast.Query]) -> RelationPlan:
        if isinstance(r, ast.Table):
            if r.name in ctes:
                rel = self.plan_query(ctes[r.name], None, ctes)
                qual = r.alias or r.name
                return RelationPlan(rel.node, [qual] * rel.width)
            # views resolve by UNQUALIFIED name only: a qualified reference
            # (catalog.table) always names the real table, so a view can
            # never shadow another catalog's table
            vname = r.name if "." not in r.name else None
            view = self.catalog.views.get(vname) if vname else None
            if view is not None:
                if vname in self._view_stack:
                    raise AnalysisError(
                        f"view is recursive: {vname}")
                qual = r.alias or vname
                if view.materialized and view.backing is not None:
                    # read the last refresh's backing table
                    bcat, btable = view.backing
                    schema = self.catalog.connector(bcat).get_table_schema(
                        btable)
                    cols = tuple(c.name for c in schema.columns)
                    types = tuple(c.type for c in schema.columns)
                    node = TableScan(cols, types, bcat, btable, cols)
                    return RelationPlan(node, [qual] * len(cols))
                # plain view: inline the defining query (the reference
                # expands views during analysis — StatementAnalyzer views)
                self._view_stack.add(vname)
                try:
                    rel = self.plan_query(view.query, None, {})
                finally:
                    self._view_stack.discard(vname)
                return RelationPlan(rel.node, [qual] * rel.width)
            cat, table, schema = self.catalog.resolve_table(r.name, self.default_catalog)
            cols = tuple(c.name for c in schema.columns)
            types = tuple(c.type for c in schema.columns)
            node = TableScan(cols, types, cat, table, cols)
            qual = r.alias or table
            return RelationPlan(node, [qual] * len(cols))
        if isinstance(r, ast.SubqueryRelation):
            rel = self.plan_query(r.query, outer, ctes)
            node = rel.node
            if r.column_names is not None:
                if len(r.column_names) != rel.width:
                    raise AnalysisError(
                        f"column alias list has {len(r.column_names)} names "
                        f"but relation has {rel.width} columns")
                node = replace(node, output_names=tuple(r.column_names))
            return RelationPlan(node, [r.alias] * rel.width)
        if isinstance(r, ast.TableFunctionRelation):
            return self._plan_table_function(r, outer)
        if isinstance(r, ast.MatchRecognizeRelation):
            return self._plan_match_recognize(r, outer, ctes)
        if isinstance(r, ast.UnnestRelation):
            return self._plan_unnest(None, r, outer, ctes)
        if isinstance(r, ast.Join):
            return self.plan_join(r, outer, ctes)
        raise AnalysisError(f"unsupported relation: {type(r).__name__}")

    def _plan_match_recognize(self, r: ast.MatchRecognizeRelation,
                              outer, ctes) -> RelationPlan:
        """MATCH_RECOGNIZE -> MatchRecognize node (reference:
        RelationPlanner.visitPatternRecognitionRelation).  Output = partition
        columns ++ measures; measure types from host inference (the pattern
        engine evaluates python values)."""
        from ..exec.match_recognize import infer_measure_type
        from ..exec.row_pattern import parse_pattern, pattern_labels

        src = self.plan_relation(r.input, outer, ctes)
        tr = Translator(src.scope(outer))

        def channel_of(e: ast.Expr) -> int:
            ir = tr.translate(e)
            if not isinstance(ir, InputRef):
                raise AnalysisError(
                    "MATCH_RECOGNIZE partition/order keys must be columns")
            return ir.index

        pch = tuple(channel_of(e) for e in r.partition_by)
        okeys = tuple((channel_of(s.expr), s.ascending) for s in r.order_by)
        # validate pattern + labels now (parse errors surface at plan time)
        labels = set(pattern_labels(parse_pattern(r.pattern)))
        for lbl, _ in r.defines:
            if lbl.upper() not in labels:
                raise AnalysisError(
                    f"DEFINE label {lbl} not used in PATTERN")
        schema = {n.lower(): t for n, t in
                  zip(src.node.output_names, src.node.output_types)}
        names = tuple([src.node.output_names[c] for c in pch]
                      + [m[1] for m in r.measures])
        types = tuple([src.node.output_types[c] for c in pch]
                      + [infer_measure_type(m[0], schema)
                         for m in r.measures])
        node = MatchRecognize(names, types, src.node, pch, okeys,
                              r.pattern, tuple(r.defines),
                              tuple(r.measures), r.skip_past)
        return RelationPlan(node, [r.alias] * len(names))

    def _plan_table_function(self, r: ast.TableFunctionRelation,
                             outer) -> RelationPlan:
        """TABLE(fn(args)): bind constant arguments, fix the schema
        (reference: ConnectorTableFunction.analyze -> TableFunctionAnalysis)."""
        fn = self.catalog.table_functions.get(r.name)
        if fn is None:
            raise AnalysisError(f"table function not registered: {r.name}")
        dummy = RelationPlan(
            Values(("_row",), (BIGINT,), rows=((0,),)), [None])
        tr = Translator(dummy.scope(outer))
        arg_vals = []
        for a in r.args:
            ir = tr.translate(a)
            if not isinstance(ir, Literal):
                raise AnalysisError(
                    f"table function {r.name} arguments must be constants")
            arg_vals.append(ir.value)
        try:
            bound = fn.bind(arg_vals)
        except ValueError as e:
            raise AnalysisError(str(e))
        names = tuple(bound.names)
        if r.column_names is not None:
            if len(r.column_names) != len(names):
                raise AnalysisError(
                    f"column alias list has {len(r.column_names)} names "
                    f"but {r.name} produces {len(names)} columns")
            names = tuple(r.column_names)
        node = TableFunctionScan(names, tuple(bound.types), r.name, bound)
        return RelationPlan(node, [r.alias] * len(names))

    def _plan_unnest(self, left: Optional[RelationPlan],
                     u: ast.UnnestRelation, outer, ctes) -> RelationPlan:
        """UNNEST as a relation (reference: RelationPlanner.planJoinUnnest /
        plan(Unnest)): lateral — array arguments see the left relation's
        columns; standalone UNNEST runs over one synthetic row and emits
        only the element columns."""
        from ..spi.types import ArrayType

        standalone = left is None
        if standalone:
            left = RelationPlan(
                Values(("_row",), (BIGINT,), rows=((0,),)), [None])
        orig_width = left.width
        tr = Translator(left.scope(outer))
        irs = [tr.translate(e) for e in u.exprs]
        for ir in irs:
            if not isinstance(ir.type, ArrayType):
                raise AnalysisError("UNNEST argument must be an array")
        chans, left = _as_channels(irs, left)
        replicate = () if standalone else tuple(range(orig_width))

        n_el = len(irs)
        el_names = [f"_unnest{i}" for i in range(n_el)]
        ord_name = "ordinality"
        if u.column_names:
            expect = n_el + (1 if u.ordinality else 0)
            if len(u.column_names) != expect:
                raise AnalysisError(
                    f"UNNEST column alias list has {len(u.column_names)} "
                    f"names but produces {expect} columns")
            el_names = list(u.column_names[:n_el])
            if u.ordinality:
                ord_name = u.column_names[-1]
        names = tuple([left.node.output_names[c] for c in replicate]
                      + el_names + ([ord_name] if u.ordinality else []))
        types = tuple([left.node.output_types[c] for c in replicate]
                      + [ir.type.element for ir in irs]
                      + ([BIGINT] if u.ordinality else []))
        node = Unnest(names, types, left.node, replicate, tuple(chans),
                      u.ordinality)
        quals = ([left.qualifiers[c] for c in replicate]
                 + [u.alias] * (n_el + (1 if u.ordinality else 0)))
        return RelationPlan(node, quals)

    def plan_join(self, j: ast.Join, outer, ctes) -> RelationPlan:
        if isinstance(j.right, ast.UnnestRelation):
            # lateral CROSS JOIN UNNEST(left.col)
            if j.join_type not in ("CROSS", "INNER") or j.condition is not None:
                raise AnalysisError(
                    "only CROSS JOIN UNNEST (no condition) is supported")
            left = self.plan_relation(j.left, outer, ctes)
            return self._plan_unnest(left, j.right, outer, ctes)
        left = self.plan_relation(j.left, outer, ctes)
        right = self.plan_relation(j.right, outer, ctes)
        names = tuple(left.node.output_names) + tuple(right.node.output_names)
        types = tuple(left.node.output_types) + tuple(right.node.output_types)
        quals = left.qualifiers + right.qualifiers
        if j.join_type == "CROSS" or j.condition is None:
            node = Join(names, types, left.node, right.node, "CROSS", (), (), None)
            return RelationPlan(node, quals)
        combined = Scope(
            [Field(n, t, q) for n, t, q in zip(names, types, quals)], outer)
        tr = Translator(combined)
        conjuncts = [cast_to(tr.translate(c), BOOLEAN)
                     for c in split_conjuncts(j.condition)]
        lw = left.width
        lkeys, rkeys, residual = [], [], []
        for c in conjuncts:
            sides = _classify_sides(c, lw)
            if (isinstance(c, Call) and c.name == "eq" and sides == "both"
                    and _classify_sides(c.args[0], lw) in ("left", "right")
                    and _classify_sides(c.args[1], lw) in ("left", "right")
                    and _classify_sides(c.args[0], lw) != _classify_sides(c.args[1], lw)):
                a, b = c.args
                if _classify_sides(a, lw) == "right":
                    a, b = b, a
                lkeys.append(a)
                rkeys.append(_shift_inputs(b, -lw))
            else:
                residual.append(c)
        # key expressions must be plain channels: append projections if needed
        lch, left = _as_channels(lkeys, left)
        rch, right = _as_channels(rkeys, right)
        names = tuple(left.node.output_names) + tuple(right.node.output_names)
        types = tuple(left.node.output_types) + tuple(right.node.output_types)
        quals = left.qualifiers + right.qualifiers
        res = _conjoin(residual) if residual else None
        node = Join(names, types, left.node, right.node, j.join_type,
                    tuple(lch), tuple(rch), res)
        return RelationPlan(node, quals)

    # ------------------------------------------------------------ subqueries
    def _plan_subquery_conjunct(self, rel: RelationPlan, c: ast.Expr, outer, ctes,
                                agg_rewrite=None) -> RelationPlan:
        holder = {"rel": rel}

        def cb(node):
            new_rel, ir = self._handle_subquery(holder["rel"], node, outer, ctes)
            holder["rel"] = new_rel
            return ir

        collector = agg_rewrite[0] if agg_rewrite else None
        tr = Translator(holder["rel"].scope(outer), aggregates=collector,
                        subquery_cb=cb)
        ir = cast_to(tr.translate(c), BOOLEAN)
        if agg_rewrite:
            ir = rewrite_expr(ir, agg_rewrite[1])
        out = holder["rel"]
        return RelationPlan(
            Filter(out.node.output_names, out.node.output_types, out.node, ir),
            out.qualifiers,
        )

    def _handle_subquery(self, rel: RelationPlan, node: ast.Expr, outer, ctes):
        if isinstance(node, ast.InSubquery):
            return self._plan_in_subquery(rel, node, outer, ctes)
        if isinstance(node, ast.Exists):
            return self._plan_exists(rel, node, outer, ctes)
        if isinstance(node, ast.ScalarSubquery):
            return self._plan_scalar_subquery(rel, node, outer, ctes)
        raise AnalysisError(f"unsupported subquery form: {type(node).__name__}")

    def _plan_in_subquery(self, rel: RelationPlan, node: ast.InSubquery, outer, ctes):
        sub = self.plan_query(node.query, None, ctes)
        if sub.width != 1:
            raise AnalysisError("IN subquery must return one column")
        operand = Translator(rel.scope(outer)).translate(node.operand)
        if isinstance(operand, InputRef):
            src, s_ch = rel, operand.index
        else:
            src = rel.append([operand], ["_in_key"])
            s_ch = src.width - 1
        mark_name = f"_mark{src.width}"
        names = tuple(src.node.output_names) + (mark_name,)
        types = tuple(src.node.output_types) + (BOOLEAN,)
        from .optimizer import optimizer_mode
        if optimizer_mode() == "iterative":
            # leave a CorrelatedJoin placeholder for the decorrelate rules
            # (TransformCorrelatedInPredicate lowers it to this SemiJoin)
            sj: PlanNode = CorrelatedJoin(names, types, src.node, sub.node,
                                          "in", (s_ch,), (0,))
        else:
            sj = SemiJoin(names, types, src.node, sub.node, (s_ch,), (0,),
                          negated=False, residual=None, null_aware=True)
        new_rel = RelationPlan(sj, src.qualifiers + [None])
        mark = InputRef(BOOLEAN, new_rel.width - 1)
        ir = Call(BOOLEAN, "$not", (mark,)) if node.negated else mark
        return new_rel, ir

    def _plan_exists(self, rel: RelationPlan, node: ast.Exists, outer, ctes):
        spec = node.query.body
        if spec.group_by or spec.having:
            raise AnalysisError("EXISTS subquery with aggregation not supported")
        inner = (self.plan_relation(spec.from_, None, ctes)
                 if spec.from_ is not None else None)
        if inner is None:
            raise AnalysisError("EXISTS requires FROM")
        inner_filters: list[RowExpression] = []
        corr_pairs: list[tuple[RowExpression, RowExpression]] = []
        residuals: list[RowExpression] = []
        if spec.where is not None:
            scope = inner.scope(rel.scope(outer))
            tr = Translator(scope)
            for c in split_conjuncts(spec.where):
                ir = cast_to(tr.translate(c), BOOLEAN)
                if not _has_outer(ir):
                    inner_filters.append(ir)
                elif (isinstance(ir, Call) and ir.name == "eq"
                      and _is_outer_only(ir.args[0]) != _is_outer_only(ir.args[1])):
                    a, b = ir.args
                    if _is_outer_only(b):
                        a, b = b, a
                    # a: outer side, b: inner side
                    if _has_outer(b):
                        residuals.append(ir)
                    else:
                        corr_pairs.append((_shift_outer(a), b))
                else:
                    residuals.append(ir)
        if inner_filters:
            pred = _conjoin(inner_filters)
            inner = RelationPlan(
                Filter(inner.node.output_names, inner.node.output_types,
                       inner.node, pred), inner.qualifiers)
        if not corr_pairs and not residuals:
            raise AnalysisError("uncorrelated EXISTS not supported yet")
        src = rel
        s_chs, f_chs = [], []
        src_append, inner_append = [], []
        for outer_e, inner_e in corr_pairs:
            if isinstance(outer_e, InputRef):
                s_chs.append(outer_e.index)
            else:
                src_append.append(outer_e)
                s_chs.append(None)
            if isinstance(inner_e, InputRef):
                f_chs.append(inner_e.index)
            else:
                inner_append.append(inner_e)
                f_chs.append(None)
        if src_append:
            base = src.width
            src = src.append(src_append, [f"_k{base+i}" for i in range(len(src_append))])
            it = iter(range(base, base + len(src_append)))
            s_chs = [c if c is not None else next(it) for c in s_chs]
        if inner_append:
            base = inner.width
            inner = inner.append(inner_append,
                                 [f"_k{base+i}" for i in range(len(inner_append))])
            it = iter(range(base, base + len(inner_append)))
            f_chs = [c if c is not None else next(it) for c in f_chs]
        residual_ir = None
        if residuals:
            # over source channels ++ inner channels
            sw = src.width
            def remap(e: RowExpression) -> RowExpression:
                if isinstance(e, OuterRef) and e.level == 1:
                    return InputRef(e.type, e.index)
                if isinstance(e, InputRef):
                    return InputRef(e.type, e.index + sw)
                if isinstance(e, Call):
                    return Call(e.type, e.name, tuple(remap(a) for a in e.args))
                return e
            residual_ir = _conjoin([remap(r) for r in residuals])
        mark_name = f"_mark{src.width}"
        names = tuple(src.node.output_names) + (mark_name,)
        types = tuple(src.node.output_types) + (BOOLEAN,)
        sj = SemiJoin(names, types, src.node, inner.node,
                      tuple(s_chs), tuple(f_chs), negated=False,
                      residual=residual_ir, null_aware=False)
        new_rel = RelationPlan(sj, src.qualifiers + [None])
        mark = InputRef(BOOLEAN, new_rel.width - 1)
        ir = Call(BOOLEAN, "$not", (mark,)) if node.negated else mark
        return new_rel, ir

    def _plan_scalar_subquery(self, rel: RelationPlan, node: ast.ScalarSubquery,
                              outer, ctes):
        spec = node.query.body
        # detect correlation by planning the WHERE against a chained scope
        corr = self._try_correlated_scalar(rel, node.query, outer, ctes)
        if corr is not None:
            return corr
        sub = self.plan_query(node.query, None, ctes)
        if sub.width != 1:
            raise AnalysisError("scalar subquery must return one column")
        names = tuple(rel.node.output_names) + (f"_scalar{rel.width}",)
        types = tuple(rel.node.output_types) + (sub.node.output_types[0],)
        # single-row broadcast join (EnforceSingleRow + cross join in Trino)
        jn = Join(names, types, rel.node, sub.node, "SINGLE", (), (), None)
        new_rel = RelationPlan(jn, rel.qualifiers + [None])
        return new_rel, InputRef(types[-1], new_rel.width - 1)

    def _try_correlated_scalar(self, rel: RelationPlan, q: ast.Query, outer, ctes):
        spec = q.body
        if (spec.group_by or spec.having or q.order_by or q.limit is not None
                or spec.from_ is None or len(spec.select) != 1):
            return None
        inner = self.plan_relation(spec.from_, None, ctes)
        if spec.where is None:
            return None
        scope = inner.scope(rel.scope(outer))
        tr = Translator(scope)
        inner_filters, corr_pairs = [], []
        for c in split_conjuncts(spec.where):
            ir = cast_to(tr.translate(c), BOOLEAN)
            if not _has_outer(ir):
                inner_filters.append(ir)
            elif (isinstance(ir, Call) and ir.name == "eq"
                  and _is_outer_only(ir.args[0]) != _is_outer_only(ir.args[1])
                  and not (_has_outer(ir.args[0]) and _has_outer(ir.args[1]))):
                a, b = ir.args
                if _is_outer_only(b):
                    a, b = b, a
                corr_pairs.append((_shift_outer(a), b))
            else:
                raise AnalysisError(f"unsupported correlated predicate: {c}")
        if not corr_pairs:
            return None
        # aggregate the inner by its correlation keys
        collector = AggregateCollector()
        sel_tr = Translator(inner.scope(), aggregates=collector)
        sel_ir = sel_tr.translate(spec.select[0].expr)
        if not collector.calls:
            raise AnalysisError("correlated scalar subquery must aggregate")
        if inner_filters:
            inner = RelationPlan(
                Filter(inner.node.output_names, inner.node.output_types,
                       inner.node, _conjoin(inner_filters)), inner.qualifiers)
        group_irs = [b for (_, b) in corr_pairs]
        # zero-row marker: count(*) is non-NULL for every real group, so the
        # LEFT join null-extends it to NULL exactly when an outer row matched
        # zero inner rows — lets us restore each aggregate's zero-row value
        # for ANY select expression (Trino: TransformCorrelatedScalarAggregation
        # aggregates over the null-extended join for the same effect).
        mark_idx = collector.add("count", None, False, BIGINT)
        agg_rel, rewrite = self._plan_aggregation(inner, group_irs, collector, None)
        value_ir = rewrite_expr(sel_ir, rewrite)
        nkeys = len(group_irs)
        mark_ch = nkeys + mark_idx
        value_rel = agg_rel.append([value_ir], ["_scalar_value"])
        # prune to keys + value + marker
        keep = list(range(nkeys)) + [value_rel.width - 1, mark_ch]
        proj = Project(
            tuple(value_rel.node.output_names[i] for i in keep),
            tuple(value_rel.node.output_types[i] for i in keep),
            value_rel.node,
            tuple(InputRef(value_rel.node.output_types[i], i) for i in keep),
        )
        # outer-side keys as channels
        outer_keys = [a for (a, _) in corr_pairs]
        och, src = _as_channels(outer_keys, rel)
        names = tuple(src.node.output_names) + proj.output_names
        types = tuple(src.node.output_types) + proj.output_types
        from .optimizer import optimizer_mode
        if optimizer_mode() == "iterative":
            # placeholder for TransformCorrelatedScalarSubquery, which
            # lowers to exactly the LEFT join the legacy branch builds
            jn: PlanNode = CorrelatedJoin(names, types, src.node, proj,
                                          "scalar_agg", tuple(och),
                                          tuple(range(nkeys)))
        else:
            jn = Join(names, types, src.node, proj, "LEFT",
                      tuple(och), tuple(range(nkeys)), None)
        new_rel = RelationPlan(jn, src.qualifiers + [None] * (nkeys + 2))
        value_ref: RowExpression = InputRef(types[-2], new_rel.width - 2)
        mark_ref = InputRef(BIGINT, new_rel.width - 1)
        # Restore the select expression's zero-row value: substitute every
        # aggref with its value over zero rows (count -> 0, everything else ->
        # NULL) and switch on the marker, so e.g. coalesce(sum(x), 0) yields 0
        # (not NULL) for outer rows with no matches while a genuine NULL value
        # on a matched group (all-NULL sum) is preserved.
        aggrefs = [x for x in walk(sel_ir)
                   if isinstance(x, Call) and x.name == "$aggref"]
        subst: dict[RowExpression, RowExpression] = {}
        for a in aggrefs:
            fn = collector.calls[a.args[0].value][0]
            subst[a] = Literal(a.type, 0 if fn == "count" else None)
        default_expr = rewrite_expr(sel_ir, subst)
        if default_expr == Literal(value_ref.type, None):
            return new_rel, value_ref
        ir: RowExpression = Call(
            value_ref.type, "$if",
            (Call(BOOLEAN, "$is_null", (mark_ref,)), default_expr, value_ref))
        return new_rel, ir


def _alias_body(body: ast.QueryBody, colnames: tuple[str, ...]) -> ast.QueryBody:
    """Apply WITH-clause column aliases; a set operation takes its output
    names from its leftmost input (SQL spec 7.13)."""
    if isinstance(body, ast.QuerySpec):
        return replace(body, select=tuple(
            replace(s, alias=cn) for s, cn in zip(body.select, colnames)))
    if isinstance(body, ast.SetOp):
        return replace(body, left=_alias_body(body.left, colnames))
    if isinstance(body, ast.Query):
        return replace(body, body=_alias_body(body.body, colnames))
    return body


def _cast_side(rel: RelationPlan, types: list) -> RelationPlan:
    """Project a set-op input so its channel types match the unified types."""
    if list(rel.node.output_types) == list(types):
        return rel
    exprs = tuple(
        cast_to(InputRef(t0, i), t)
        for i, (t0, t) in enumerate(zip(rel.node.output_types, types)))
    node = Project(tuple(rel.node.output_names), tuple(types), rel.node, exprs)
    return RelationPlan(node, list(rel.qualifiers))


def _index_of(ir, irs):
    return irs.index(ir) if ir in irs else None


def _contains_subquery(e: ast.Expr) -> bool:
    if isinstance(e, (ast.InSubquery, ast.Exists, ast.ScalarSubquery)):
        return True
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        if isinstance(v, ast.Expr) and _contains_subquery(v):
            return True
        if isinstance(v, tuple):
            for x in v:
                if isinstance(x, ast.Expr) and _contains_subquery(x):
                    return True
                if isinstance(x, ast.WhenClause):
                    if _contains_subquery(x.condition) or _contains_subquery(x.result):
                        return True
    return False


def _classify_sides(e: RowExpression, left_width: int) -> str:
    sides = set()
    for x in walk(e):
        if isinstance(x, InputRef):
            sides.add("left" if x.index < left_width else "right")
        elif isinstance(x, OuterRef):
            sides.add("outer")
    if sides == {"left"}:
        return "left"
    if sides == {"right"}:
        return "right"
    if not sides:
        return "none"
    return "both"


def _is_outer_only(e: RowExpression) -> bool:
    has_outer = False
    for x in walk(e):
        if isinstance(x, InputRef):
            return False
        if isinstance(x, OuterRef):
            has_outer = True
    return has_outer


def _as_channels(exprs: list[RowExpression], rel: RelationPlan):
    """Return ([channel...], possibly-extended relation) for key expressions."""
    chans = []
    to_append, names = [], []
    for e in exprs:
        if isinstance(e, InputRef):
            chans.append(e.index)
        else:
            chans.append(rel.width + len(to_append))
            to_append.append(e)
            names.append(f"_key{rel.width + len(to_append) - 1}")
    if to_append:
        rel = rel.append(to_append, names)
    return chans, rel
