"""History-based optimization: journaled runtime truth fed back into the
cost model (reference: the HBO design Trino/Presto ship as
HistoryBasedPlanStatisticsCalculator — observed plan-node statistics
keyed by a logical plan fingerprint, consulted before estimates).

Two halves, both here so the fingerprint definition cannot drift:

- **Recording** (:func:`record_query_stats`): at the end of a successful
  distributed query the runner hands over its fragments, stages and the
  adaptive controller; every fragment's observed output (sink
  ``rows_enqueued``/``bytes_enqueued``, adaptive staging counters, probe
  heavy-hitter share) is written to the PR 11 query journal as one
  ``plan_stats`` record keyed by each fragment root's *logical
  fingerprint*.
- **Reading** (:class:`HistoryProvider`): ``estimate_rows`` and the
  iterative optimizer's reorder/distribution rules look observed stats up
  by the same fingerprint; a hit replaces the estimate.  The provider's
  table is memoized on the journal file-set signature (the
  ``seeded_peak`` pattern), so steady-state planning costs a few stat()
  calls.

The fingerprint is **row-equivalence** hashing, not structural hashing:
two plan shapes that must produce the same row stream hash equal, so a
stat recorded against the *executed* plan (post-prune, post-fragmentation,
adaptively flipped) still matches the *candidate* subtree the optimizer
is costing on the next run.  Concretely:

- expressions render by channel **name**, never index (names are assigned
  once at translation and survive pruning/projection);
- Project / Sort / Output / Exchange are transparent (row-preserving);
- TableScan keys on (catalog, table) only — columns, advisory constraint
  and pushed limit are row-irrelevant or derived;
- Aggregate ignores the step: FINAL is transparent-to-source, so the
  plan-time SINGLE aggregation and the executed PARTIAL->shuffle->FINAL
  chain share one fingerprint;
- INNER/CROSS joins hash their sides and key pairs orderless, so the
  run-1 order and the reordered run-2 plan (and BROADCAST vs PARTITIONED)
  share one fingerprint;
- RemoteSource substitutes the producer fragment's fingerprint.

Misses degrade to estimates; history can change plans, never results.
Plan-cache poisoning is prevented by :func:`history_epoch`, a digest of
the plan_stats corpus mixed into the Tier A key (caching/plan_cache.py).
"""

from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..spi import knobs
from ..sql.ir import Call, InputRef, Literal, OuterRef, RowExpression
from .plan import (
    Aggregate,
    DistinctLimit,
    Exchange,
    Filter,
    GroupId,
    Join,
    Limit,
    Output,
    PlanNode,
    Project,
    RemoteSource,
    SemiJoin,
    Sort,
    TableScan,
    TopN,
    Union,
    Unnest,
    Values,
    Window,
)

__all__ = [
    "NodeStats", "HistoryProvider", "hbo_enabled", "provider_if_enabled",
    "history_epoch", "logical_fingerprint", "fragment_fingerprints",
    "record_query_stats",
]


def hbo_enabled() -> bool:
    return knobs.get_str("TRINO_TPU_HBO").strip().lower() not in ("0", "off")


# ---------------------------------------------------------------- fingerprint


def _render(e: RowExpression, names: tuple) -> str:
    """Name-based expression rendering: stable across channel remapping."""
    if isinstance(e, InputRef):
        return names[e.index] if e.index < len(names) else f"#{e.index}"
    if isinstance(e, Literal):
        return f"lit:{e.value!r}"
    if isinstance(e, Call):
        return f"{e.name}({','.join(_render(a, names) for a in e.args)})"
    if isinstance(e, OuterRef):
        return f"outer:{e.index}"
    return repr(e)


def _digest(parts: tuple) -> str:
    return hashlib.sha1(repr(parts).encode("utf-8")).hexdigest()[:16]


def logical_fingerprint(node: PlanNode,
                        resolve: Optional[Callable[[int], str]] = None) -> str:
    """Row-equivalence fingerprint of a plan subtree.  ``resolve`` maps a
    RemoteSource's fragment id to the producer fragment's fingerprint
    (record side); plan-time trees have no RemoteSource."""

    def fp(n: PlanNode) -> str:
        if isinstance(n, (Project, Sort, Output, Exchange)):
            return fp(n.source)
        if isinstance(n, TableScan):
            return _digest(("scan", n.catalog, n.table))
        if isinstance(n, Filter):
            from .optimizer import _split_and

            names = n.source.output_names
            conjuncts = tuple(sorted(
                _render(c, names) for c in _split_and(n.predicate)))
            return _digest(("filter", conjuncts, fp(n.source)))
        if isinstance(n, Aggregate):
            if n.step == "FINAL":
                return fp(n.source)
            names = n.source.output_names
            keys = tuple(sorted(names[k] for k in n.group_keys))
            aggs = tuple(sorted(
                (a.fn, names[a.arg] if a.arg >= 0 else "*", a.distinct)
                for a in n.aggregates))
            return _digest(("agg", keys, aggs, fp(n.source)))
        if isinstance(n, Join):
            lnames = n.left.output_names
            rnames = n.right.output_names
            pairs = tuple(sorted(
                tuple(sorted((lnames[l], rnames[r])))
                for l, r in zip(n.left_keys, n.right_keys)))
            residual = ""
            if n.residual is not None:
                residual = _render(n.residual, tuple(lnames) + tuple(rnames))
            sides = (fp(n.left), fp(n.right))
            if n.join_type in ("INNER", "CROSS"):
                # orderless: the reordered plan keeps the fingerprint
                sides = tuple(sorted(sides))
            return _digest(("join", n.join_type, pairs, residual) + sides)
        if isinstance(n, SemiJoin):
            snames = n.source.output_names
            fnames = n.filter_source.output_names
            pairs = tuple((snames[s], fnames[f])
                          for s, f in zip(n.source_keys, n.filter_keys))
            residual = ""
            if n.residual is not None:
                residual = _render(n.residual, tuple(snames) + tuple(fnames))
            return _digest(("semijoin", n.negated, n.null_aware, pairs,
                            residual, fp(n.source), fp(n.filter_source)))
        if isinstance(n, Limit):
            return _digest(("limit", n.count, fp(n.source)))
        if isinstance(n, TopN):
            keys = tuple((n.source.output_names[k.channel], k.ascending)
                         for k in n.keys)
            return _digest(("topn", n.count, keys, fp(n.source)))
        if isinstance(n, DistinctLimit):
            return _digest(("distinctlimit", n.count, fp(n.source)))
        if isinstance(n, Values):
            return _digest(("values", len(n.rows)))
        if isinstance(n, Union):
            return _digest(("union",) + tuple(sorted(fp(s)
                                                     for s in n.sources)))
        if isinstance(n, Window):
            names = n.source.output_names
            fns = tuple((f.fn, tuple(names[a] for a in f.args))
                        for f in n.functions)
            return _digest(("window",
                            tuple(names[k] for k in n.partition_keys),
                            fns, fp(n.source)))
        if isinstance(n, GroupId):
            return _digest(("groupid", n.sets, fp(n.source)))
        if isinstance(n, Unnest):
            return _digest(("unnest", n.unnest_channels, fp(n.source)))
        if isinstance(n, RemoteSource):
            if resolve is not None:
                return resolve(n.fragment_id)
            return _digest(("remote", n.fragment_id))
        # coarse default: type + children (TableWriter, Replicate, ...)
        return _digest((type(n).__name__,) + tuple(fp(c)
                                                   for c in n.children))

    return fp(node)


def fragment_fingerprints(fragments) -> dict:
    """Fingerprint every fragment root, resolving RemoteSources to their
    producer fragment's fingerprint (fragments form a DAG; iterate until
    all dependencies are available)."""
    fps: dict[int, str] = {}
    pending = list(fragments)
    while pending:
        rest = []
        for f in pending:
            try:
                fps[f.id] = logical_fingerprint(
                    f.root, resolve=lambda fid: fps[fid])
            except KeyError:
                rest.append(f)
        if len(rest) == len(pending):  # unresolvable — record what we have
            break
        pending = rest
    return fps


# ------------------------------------------------------------------- provider


@dataclass
class NodeStats:
    rows: Optional[int] = None
    bytes: Optional[int] = None
    groups: Optional[int] = None
    skew: Optional[float] = None


# (journal signature, table, epoch) memo — the seeded_peak pattern
_TABLE_CACHE: Optional[tuple] = None
_TABLE_LOCK = threading.Lock()


def _stats_table() -> tuple[dict, str]:
    """(fingerprint -> NodeStats, epoch) from the journal's plan_stats
    records, newest record winning per fingerprint; memoized on the
    journal file-set signature."""
    global _TABLE_CACHE
    from ..telemetry import journal

    j = journal.get_journal()
    if j is None:
        return {}, ""
    with _TABLE_LOCK:
        sig = journal._journal_signature(j)
        if _TABLE_CACHE is not None and _TABLE_CACHE[0] == sig:
            return _TABLE_CACHE[1], _TABLE_CACHE[2]
        table: dict[str, NodeStats] = {}
        h = hashlib.sha1()
        for rec in j.read(events=("plan_stats",)):
            nodes = rec.get("nodes")
            if not isinstance(nodes, dict):
                continue
            h.update(repr(sorted(nodes.items())).encode("utf-8"))
            for fp, st in nodes.items():
                if not isinstance(st, dict):
                    continue
                cur = table.setdefault(fp, NodeStats())
                for field_name in journal.PLAN_STATS_FIELDS:
                    v = st.get(field_name)
                    if v is not None:
                        setattr(cur, field_name, v)
        epoch = h.hexdigest()[:12] if table else ""
        _TABLE_CACHE = (sig, table, epoch)
        return table, epoch


def history_epoch() -> str:
    """Digest of the observed-stats corpus the planner would consult right
    now; mixed into the Tier A plan-cache key so history-driven plans
    never outlive the history that shaped them.  "" when HBO is off or
    no stats exist."""
    if not hbo_enabled():
        return ""
    try:
        return _stats_table()[1]
    except Exception:
        return ""


class HistoryProvider:
    """Per-planning view over the shared stats table (fresh instance per
    optimize call so lookup/hit counters are per-query for the trace)."""

    def __init__(self, table: dict):
        self.table = table
        self.lookups = 0
        self.hits = 0
        self._fp_cache: dict[int, str] = {}

    def fingerprint(self, node: PlanNode) -> str:
        key = id(node)
        fp = self._fp_cache.get(key)
        if fp is None:
            fp = logical_fingerprint(node)
            self._fp_cache[key] = fp
        return fp

    def stats_for(self, node: PlanNode) -> Optional[NodeStats]:
        self.lookups += 1
        st = self.table.get(self.fingerprint(node))
        if st is not None:
            self.hits += 1
        return st

    def observed_rows(self, node: PlanNode) -> Optional[float]:
        st = self.stats_for(node)
        if st is None:
            return None
        if st.rows is not None:
            return float(st.rows)
        if st.groups is not None:  # summed partial groups: upper bound
            return float(st.groups)
        return None


def provider_if_enabled() -> Optional[HistoryProvider]:
    """A fresh HistoryProvider when HBO is on and observed stats exist;
    None otherwise (planning falls back to estimates)."""
    if not hbo_enabled():
        return None
    try:
        table, _ = _stats_table()
    except Exception:
        return None
    if not table:
        return None
    return HistoryProvider(table)


def reset_for_test() -> None:
    global _TABLE_CACHE
    with _TABLE_LOCK:
        _TABLE_CACHE = None


# ------------------------------------------------------------------ recording


def _is_partial_agg_root(node: PlanNode) -> bool:
    while isinstance(node, (Exchange, Project, Output)):
        node = node.source
    return isinstance(node, Aggregate) and node.step == "PARTIAL"


def record_query_stats(fragments, stages, skip_fids, adaptive,
                       query_id: str, sql_fingerprint: str) -> int:
    """Write one plan_stats journal record for a finished distributed
    query.  ``stages`` maps fragment id -> stage (with sink ``buffers``);
    ``skip_fids`` holds fragments whose sinks bypassed the buffers (fused/
    resident/collective edges); ``adaptive`` (optional) supplies staging
    counters and skew for deferred producers.  Returns the number of
    fingerprints recorded; never raises into the query path."""
    from ..telemetry import journal

    if not hbo_enabled():
        return 0
    j = journal.get_journal()
    if j is None:
        return 0
    fps = fragment_fingerprints(fragments)
    observed = adaptive.observed_stats() if adaptive is not None else {}
    nodes: dict[str, dict] = {}
    for f in fragments:
        fp = fps.get(f.id)
        if fp is None:
            continue
        ob = observed.get(f.id)
        if ob is not None:
            rows, nbytes, skew = ob["rows"], ob["bytes"], ob.get("skew")
        else:
            if f.id in skip_fids:
                continue  # sink bypassed OutputBuffer: no counters
            st = stages.get(f.id)
            buffers = getattr(st, "buffers", None)
            if not buffers:
                continue
            rows = sum(b.rows_enqueued for b in buffers)
            nbytes = sum(b.bytes_enqueued for b in buffers)
            skew = None
            nparts = buffers[0].num_partitions
            if getattr(f, "output_kind", "") == "BROADCAST" and nparts > 1:
                # broadcast sinks enqueue every batch once per partition
                rows //= nparts
                nbytes //= nparts
        entry = nodes.setdefault(fp, {})
        if _is_partial_agg_root(f.root):
            entry["groups"] = int(rows)
        else:
            entry["rows"] = int(rows)
            entry["bytes"] = int(nbytes)
        if skew is not None:
            entry["skew"] = float(skew)
    if not nodes:
        return 0
    j.plan_stats(query_id, sql_fingerprint, nodes, ts=time.time())
    return len(nodes)
