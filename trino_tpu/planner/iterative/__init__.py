"""Iterative memo/fixpoint optimizer (reference: sql/planner/iterative/
IterativeOptimizer.java, Memo.java, Rule.java, matching/Pattern.java).

The package miniaturizes Trino's 228-rule engine to the channel-index
plan IR: a :class:`~trino_tpu.planner.iterative.memo.Memo` holds one
expression per group (Trino's Memo, not full Cascades), rules match
shapes through a small :mod:`pattern` DSL and return replacement
subtrees, and the :mod:`driver` explores groups to fixpoint in named
phases, recording every firing in a :class:`~trino_tpu.planner.
iterative.rule.Trace` that EXPLAIN surfaces.

``optimize_iterative`` is the entry point wired behind
``TRINO_TPU_OPTIMIZER=iterative`` in planner/optimizer.py.
"""

from .driver import IterativeOptimizer, default_phases, last_report, optimize_iterative
from .memo import GroupRef, Memo
from .pattern import Pattern
from .rule import Context, Rule, Trace

__all__ = [
    "Context", "GroupRef", "IterativeOptimizer", "Memo", "Pattern",
    "Rule", "Trace", "default_phases", "last_report", "optimize_iterative",
]
