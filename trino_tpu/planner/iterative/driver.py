"""Fixpoint driver (reference: sql/planner/iterative/IterativeOptimizer.java
exploreGroup/exploreNode/exploreChildren).

``IterativeOptimizer.run`` walks the memo top-down: apply rules at a
group until none fires, explore the children, and re-explore the group
if any child changed — exactly Trino's exploreGroup loop.  Rule sets run
in named phases (decorrelate -> simplify -> aggregations -> reorder ->
cleanup), each a full fixpoint pass over the memo.

``optimize_iterative`` is the planner entry point: it runs the phases,
then hands the extracted tree to the legacy final passes (column
pruning, scan-constraint attachment, limit-into-scan) that both
optimizer modes share, and publishes the firing trace for EXPLAIN.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..plan import CorrelatedJoin, PlanNode
from .memo import Memo
from .rule import Context, Trace

__all__ = ["IterativeOptimizer", "default_phases", "last_report",
           "optimize_iterative"]

_LAST = threading.local()


def last_report() -> Optional[Trace]:
    """Trace of the most recent iterative optimization on this thread
    (what EXPLAIN appends below the plan tree)."""
    return getattr(_LAST, "trace", None)


def default_phases():
    from .rules import aggregates, decorrelate, limits, prune, reorder, simplify
    return (
        ("decorrelate", (
            decorrelate.TransformCorrelatedScalarSubquery(),
            decorrelate.TransformCorrelatedInPredicate(),
        )),
        ("simplify", (
            simplify.RemoveTrivialFilters(),
            simplify.EvaluateZeroInput(),
            simplify.MergeAdjacentFilters(),
            simplify.MergeAdjacentProjects(),
            simplify.InlineProjections(),
            simplify.RemoveRedundantIdentityProjections(),
            limits.PushLimitThroughProject(),
            limits.PushLimitThroughSemiJoin(),
            limits.PushLimitThroughJoin(),
        )),
        ("aggregations", (
            aggregates.PushPartialAggregationThroughJoin(),
            aggregates.PushAggregationThroughOuterJoin(),
        )),
        ("reorder", (
            reorder.ReorderJoins(),
            reorder.DetermineJoinDistribution(),
        )),
        ("cleanup", (
            simplify.MergeAdjacentFilters(),
            simplify.MergeAdjacentProjects(),
            simplify.RemoveRedundantIdentityProjections(),
            prune.PruneJoinColumns(),
        )),
    )


class IterativeOptimizer:
    def __init__(self, phases=None, max_firings: int = 20_000):
        self.phases = phases if phases is not None else default_phases()
        self.max_firings = max_firings

    def run(self, root: PlanNode, ctx: Context) -> PlanNode:
        memo = Memo(root)
        ctx.memo = memo
        for phase_name, rules in self.phases:
            ctx.phase = phase_name
            self._explore_group(memo.root_group, rules, ctx)
        return memo.extract()

    def _explore_group(self, gid: int, rules, ctx: Context) -> bool:
        progress = self._explore_node(gid, rules, ctx)
        while self._explore_children(gid, rules, ctx):
            progress = True
            if not self._explore_node(gid, rules, ctx):
                break
        return progress

    def _explore_node(self, gid: int, rules, ctx: Context) -> bool:
        memo = ctx.memo
        node = memo.node(gid)
        progress = False
        changed = True
        while changed:
            changed = False
            for rule in rules:
                captures = (rule.pattern.match(node, ctx)
                            if rule.pattern is not None else {})
                if captures is None:
                    continue
                result = rule.apply(node, captures, ctx)
                if result is None or result is node:
                    continue
                # fixpoint safety net: a rule whose output extracts to the
                # same concrete tree did not make progress
                if memo.extract(result) == memo.extract(node):
                    continue
                ctx.firings += 1
                if ctx.firings > self.max_firings:
                    raise RuntimeError(
                        f"iterative optimizer exceeded {self.max_firings} "
                        f"rule firings (last: {rule.name}) — a rule is not "
                        f"reaching fixpoint")
                ctx.trace.fire(ctx.phase, rule.name, node)
                node = memo.replace_group(gid, result)
                progress = changed = True
                break  # restart the rule list against the new node
        return progress

    def _explore_children(self, gid: int, rules, ctx: Context) -> bool:
        progress = False
        for child in ctx.memo.child_groups(gid):
            if self._explore_group(child, rules, ctx):
                progress = True
        return progress


def _assert_decorrelated(node: PlanNode) -> None:
    if isinstance(node, CorrelatedJoin):
        raise AssertionError(
            "CorrelatedJoin survived the decorrelate phase — the "
            "TransformCorrelated* rules must be total")
    for c in node.children:
        _assert_decorrelated(c)


def optimize_iterative(root: PlanNode, catalog) -> PlanNode:
    """Full iterative pipeline: rule phases over the memo, then the
    shared legacy final passes; publishes the trace for EXPLAIN."""
    from .. import history as hbo
    from .. import optimizer as opt

    t0 = time.perf_counter()
    history = hbo.provider_if_enabled()
    ctx = Context(catalog=catalog, history=history, trace=Trace())
    out = IterativeOptimizer().run(root, ctx)
    _assert_decorrelated(out)
    out = opt.final_passes(out, catalog)
    ctx.trace.planning_ms = (time.perf_counter() - t0) * 1000.0
    if history is not None:
        ctx.trace.history_lookups = history.lookups
        ctx.trace.history_hits = history.hits
    _LAST.trace = ctx.trace

    try:
        from ...telemetry import metrics as m
        m.OPTIMIZER_RUNS.inc()
        m.OPTIMIZER_RULE_FIRINGS.inc(len(ctx.trace.fires))
        m.OPTIMIZER_PLANNING_MS.inc(ctx.trace.planning_ms)
        if history is not None:
            m.HBO_PLAN_LOOKUPS.inc(history.lookups)
            m.HBO_PLAN_HITS.inc(history.hits)
    except Exception:
        pass
    return out
