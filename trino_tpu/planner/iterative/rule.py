"""Rule base class, rule context, and the firing trace (reference:
sql/planner/iterative/Rule.java + Rule.Context, and the
IterativeOptimizer stats that EXPLAIN ANALYZE VERBOSE surfaces).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..plan import PlanNode

__all__ = ["Context", "Rule", "Trace"]


class Trace:
    """Append-only record of rule firings plus history-lookup counters;
    ``lines()`` renders the EXPLAIN trace block."""

    def __init__(self):
        self.fires: list[tuple[str, str, str]] = []  # (phase, rule, node)
        self.history_hits = 0
        self.history_lookups = 0
        self.planning_ms = 0.0

    def fire(self, phase: str, rule: str, node: PlanNode) -> None:
        self.fires.append((phase, rule, type(node).__name__))

    def fired(self, rule: str) -> int:
        return sum(1 for _, r, _ in self.fires if r == rule)

    def lines(self, timings: bool = True) -> list[str]:
        # plain EXPLAIN output stays timing-free (and so deterministic);
        # planning wall only renders under ANALYZE
        head = f"optimizer: iterative, {len(self.fires)} rule firings"
        if timings:
            head += f", {self.planning_ms:.1f}ms"
        out = [head]
        seen: dict[tuple[str, str], int] = {}
        order: list[tuple[str, str]] = []
        for phase, rule, _ in self.fires:
            key = (phase, rule)
            if key not in seen:
                order.append(key)
            seen[key] = seen.get(key, 0) + 1
        for phase, rule in order:
            out.append(f"  rule {rule} [{phase}] fired x{seen[(phase, rule)]}")
        if self.history_lookups:
            out.append(
                f"history: {'hit' if self.history_hits else 'miss'} "
                f"({self.history_hits}/{self.history_lookups} lookups)")
        return out


@dataclass
class Context:
    """What rules see: the catalog for stats, the optional
    HistoryProvider, the trace, and memo plumbing (resolve GroupRefs,
    extract concrete subtrees).  ``reordered`` holds id()s of join nodes
    a ReorderJoins firing produced, so the rule skips its own output."""

    catalog: object = None
    history: object = None
    trace: Trace = field(default_factory=Trace)
    memo: object = None
    phase: str = ""
    firings: int = 0
    reordered: set = field(default_factory=set)

    def resolve(self, node):
        if self.memo is not None:
            return self.memo.resolve(node)
        return node

    def extract(self, node):
        if self.memo is not None:
            return self.memo.extract(node)
        return node


class Rule:
    """One rewrite: ``pattern`` declares the shape, ``apply`` returns a
    replacement subtree or None (no change).  ``apply`` must preserve
    the matched node's output layout (names, types, channel order) —
    wrap in a restoring Project otherwise — and must reach fixpoint:
    re-applying to its own output must return None."""

    pattern = None

    @property
    def name(self) -> str:
        return type(self).__name__

    def apply(self, node: PlanNode, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        raise NotImplementedError
