"""Single-expression-per-group memo (reference: sql/planner/iterative/
Memo.java, GroupReference.java).

Trino's iterative memo is deliberately *not* a Cascades memo: each group
holds exactly one logical expression whose children are group references,
and a rule firing replaces the group's expression wholesale.  That is
what makes the fixpoint driver simple — no alternatives, no winners, just
the latest rewrite — while still giving structural sharing (identical
subtrees intern to one group) and O(1) subtree replacement.

Plan nodes are frozen dataclasses, so a group's representative is the
original node with its children swapped for :class:`GroupRef` leaves;
``extract`` materializes the concrete tree back out.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

from ..plan import CorrelatedJoin, Join, PlanNode, SemiJoin, Union

__all__ = ["GroupRef", "Memo", "with_children"]


@dataclass(frozen=True)
class GroupRef(PlanNode):
    """Leaf standing in for a memo group inside a representative node.
    Carries the group's output layout so layout-dependent rewrites work
    without resolving (mirrors GroupReference.java keeping outputs)."""

    group: int = -1

    def label(self) -> str:
        return f"GroupRef[{self.group}]"


def with_children(node: PlanNode, kids: tuple) -> PlanNode:
    """Rebuild ``node`` with ``kids`` as its children (same arity)."""
    if isinstance(node, Union):
        return replace(node, sources=tuple(kids))
    if isinstance(node, Join):
        return replace(node, left=kids[0], right=kids[1])
    if isinstance(node, SemiJoin):
        return replace(node, source=kids[0], filter_source=kids[1])
    if isinstance(node, CorrelatedJoin):
        return replace(node, source=kids[0], subquery=kids[1])
    if not kids:
        return node
    return replace(node, source=kids[0])


class Memo:
    """Groups are dense ints; ``node(gid)`` is the representative whose
    children are GroupRefs.  ``insert`` interns structurally-identical
    representatives to one group (dedup is best-effort: nodes holding
    unhashable payloads — e.g. MatchRecognize AST — get fresh groups)."""

    def __init__(self, root: PlanNode):
        self._nodes: dict[int, PlanNode] = {}
        self._interned: dict[PlanNode, int] = {}
        self._next = 0
        self.root_group = self.insert(root)

    def insert(self, node: PlanNode) -> int:
        if isinstance(node, GroupRef):
            return node.group
        kids = node.children
        if kids:
            refs = tuple(
                GroupRef(self.node(g).output_names, self.node(g).output_types,
                         group=g)
                for g in (self.insert(c) for c in kids))
            node = with_children(node, refs)
        try:
            gid = self._interned.get(node)
        except TypeError:  # unhashable payload — skip dedup
            gid = None
        if gid is not None:
            return gid
        gid = self._next
        self._next += 1
        self._nodes[gid] = node
        try:
            self._interned[node] = gid
        except TypeError:
            pass
        return gid

    def node(self, gid: int) -> PlanNode:
        return self._nodes[gid]

    def resolve(self, node_or_ref) -> PlanNode:
        """GroupRef -> its group's representative; concrete nodes pass
        through (the Lookup.resolve of Rule.Context)."""
        if isinstance(node_or_ref, GroupRef):
            return self._nodes[node_or_ref.group]
        return node_or_ref

    def replace_group(self, gid: int, node: PlanNode) -> PlanNode:
        """Point ``gid`` at a new representative (a rule's output; its
        concrete children are interned into child groups) and return it."""
        if isinstance(node, GroupRef):
            node = self._nodes[node.group]
        kids = node.children
        if kids and not all(isinstance(k, GroupRef) for k in kids):
            refs = tuple(
                k if isinstance(k, GroupRef) else GroupRef(
                    k.output_names, k.output_types, group=self.insert(k))
                for k in kids)
            node = with_children(node, refs)
        self._nodes[gid] = node
        return node

    def child_groups(self, gid: int) -> tuple[int, ...]:
        return tuple(k.group for k in self._nodes[gid].children)

    def extract(self, gid_or_node=None) -> PlanNode:
        """Materialize the concrete tree under a group (default: root)."""
        if gid_or_node is None:
            gid_or_node = self.root_group
        if isinstance(gid_or_node, GroupRef):
            gid_or_node = gid_or_node.group
        node = (self._nodes[gid_or_node] if isinstance(gid_or_node, int)
                else gid_or_node)
        kids = node.children
        if not kids:
            return node
        return with_children(node, tuple(self.extract(k) for k in kids))

    def group_count(self) -> int:
        return len(self._nodes)
