"""Tiny pattern DSL for rule matching (reference: sql/planner/iterative/
matching/Pattern.java + the typeOf(...).with(source().matching(...))
combinators rules declare their shapes with).

A pattern is a node-type test plus optional predicates and child
patterns.  Matching happens against memo representatives, so child
nodes are GroupRefs — the matcher resolves them through the rule
context before testing, and captures resolve to representatives (whose
own children are again GroupRefs; rules call ``ctx.extract`` when they
need a concrete subtree).

Match results are dicts of named captures; ``None`` means no match.
"""

from __future__ import annotations

from typing import Callable, Optional

__all__ = ["Pattern"]


class Pattern:
    """``Pattern(Filter)`` matches any Filter; ``.matching(pred)`` adds a
    predicate on the (resolved) node; ``.with_source(p, "inner")`` adds a
    child pattern whose resolved match lands in the capture dict under
    the given name (children are matched positionally)."""

    def __init__(self, node_type, *,
                 where: Optional[Callable] = None,
                 children: tuple = ()):
        self.node_type = node_type
        self.where = where
        self.children = children  # ((position, name, Pattern), ...)

    def matching(self, pred: Callable) -> "Pattern":
        prev = self.where
        where = pred if prev is None else (
            lambda node, ctx: prev(node, ctx) and pred(node, ctx))
        return Pattern(self.node_type, where=where, children=self.children)

    def with_child(self, position: int, name: str,
                   pattern: "Pattern") -> "Pattern":
        return Pattern(self.node_type, where=self.where,
                       children=self.children + ((position, name, pattern),))

    def with_source(self, pattern: "Pattern", name: str = "source") -> "Pattern":
        return self.with_child(0, name, pattern)

    def match(self, node, ctx) -> Optional[dict]:
        """Match ``node`` (a memo representative or concrete node),
        resolving children through ``ctx``; returns captures or None."""
        if not isinstance(node, self.node_type):
            return None
        if self.where is not None and not self.where(node, ctx):
            return None
        captures: dict = {}
        for position, name, child in self.children:
            kids = node.children
            if position >= len(kids):
                return None
            resolved = ctx.resolve(kids[position])
            sub = child.match(resolved, ctx)
            if sub is None:
                return None
            captures[name] = resolved
            captures.update(sub)
        return captures
