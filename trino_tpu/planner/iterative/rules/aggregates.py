"""Aggregation pushdown rules (reference: iterative/rule/
PushPartialAggregationThroughJoin.java,
PushAggregationThroughOuterJoin.java).

Both rules are the "eager aggregation" transform: pre-aggregate one join
input grouped by (its group keys ++ its join keys), join the compacted
side, then merge the partial states above.  Exactness: the join
duplicates each pre-aggregated state once per matching row of the other
side, and the merge functions (count->sum, sum->sum, min->min, max->max)
are exactly duplication-distributive under that grouping — no
count-scaling needed because the join keys are part of the inner
grouping.  min/max are duplication-insensitive outright."""

from __future__ import annotations

from typing import Optional

from ....sql.ir import Call, InputRef, Literal
from ...optimizer import estimate_rows
from ...plan import Aggregate, AggCall, Join, PlanNode, Project
from ..pattern import Pattern
from ..rule import Context, Rule

__all__ = ["PushAggregationThroughOuterJoin",
           "PushPartialAggregationThroughJoin"]

# pre-aggregation must actually compact the side it is pushed into
_COMPACTION_GATE = 0.5

_MERGE = {"count": "sum", "count_star": "sum", "sum": "sum",
          "min": "min", "max": "max"}


def _eligible(agg: Aggregate) -> bool:
    return (agg.step == "SINGLE" and agg.aggregates
            and not any(a.distinct for a in agg.aggregates))


def _worth_pushing(inner: Aggregate, side_concrete: PlanNode,
                   ctx: Context) -> bool:
    """Gate on the history-aware cost model: the inner aggregation must
    shrink its input, else the extra pass is pure overhead."""
    try:
        groups = estimate_rows(inner, ctx.catalog, ctx.history)
        rows = estimate_rows(side_concrete, ctx.catalog, ctx.history)
    except Exception:
        return False
    return groups < _COMPACTION_GATE * rows


class PushPartialAggregationThroughJoin(Rule):
    """Aggregate(G, aggs, InnerJoin(A, B)) with every aggregate argument
    on one side S -> merge-Aggregate over InnerJoin with S replaced by a
    pre-aggregation grouped by (G cap S) ++ S's join keys."""

    pattern = Pattern(Aggregate).matching(
        lambda n, ctx: _eligible(n)).with_source(
        Pattern(Join).matching(
            lambda n, ctx: n.join_type == "INNER" and n.residual is None),
        "join")

    def apply(self, node: Aggregate, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        join: Join = captures["join"]
        left, right = join.children
        lw = len(left.output_types)
        if any(a.fn not in _MERGE for a in node.aggregates):
            return None
        sides = {("left" if a.arg < lw else "right")
                 for a in node.aggregates if a.arg >= 0}
        if len(sides) > 1:
            return None
        side = sides.pop() if sides else "right"  # all count(*): either works
        side_ref = left if side == "left" else right
        if isinstance(ctx.resolve(side_ref), Aggregate):
            return None  # already compacted (and guards re-firing)

        G = node.group_keys
        sw = len(side_ref.output_types)
        base = 0 if side == "left" else lw
        g_side = [g - base for g in G if base <= g < base + sw]
        side_keys = join.left_keys if side == "left" else join.right_keys
        keys = sorted(set(g_side) | set(side_keys))
        key_pos = {k: i for i, k in enumerate(keys)}

        agg_names = tuple(node.output_names[len(G) + i]
                          for i in range(len(node.aggregates)))
        inner_aggs = tuple(
            AggCall(a.fn, (a.arg - base) if a.arg >= 0 else -1, a.type, False)
            for a in node.aggregates)
        inner_names = (tuple(side_ref.output_names[k] for k in keys)
                       + tuple(f"{n}$partial" for n in agg_names))
        inner_types = (tuple(side_ref.output_types[k] for k in keys)
                       + tuple(a.type for a in node.aggregates))
        inner = Aggregate(inner_names, inner_types, side_ref,
                          tuple(keys), inner_aggs, "SINGLE")
        if not _worth_pushing(
                Aggregate(inner_names, inner_types, ctx.extract(side_ref),
                          tuple(keys), inner_aggs, "SINGLE"),
                ctx.extract(side_ref), ctx):
            return None

        iw = len(inner_types)
        if side == "left":
            new_left, new_right = inner, right
            left_keys = tuple(key_pos[k] for k in join.left_keys)
            right_keys = join.right_keys
            remap = lambda g: (key_pos[g] if g < lw else iw + (g - lw))
            state_base = len(keys)
        else:
            new_left, new_right = left, inner
            left_keys = join.left_keys
            right_keys = tuple(key_pos[k] for k in join.right_keys)
            remap = lambda g: (g if g < lw else lw + key_pos[g - lw])
            state_base = lw + len(keys)
        join_names = (tuple(new_left.output_names)
                      + tuple(new_right.output_names))
        join_types = (tuple(new_left.output_types)
                      + tuple(new_right.output_types))
        new_join = Join(join_names, join_types, new_left, new_right,
                        "INNER", left_keys, right_keys, None,
                        join.distribution)

        merged = tuple(
            AggCall(_MERGE[a.fn], state_base + i, a.type, False)
            for i, a in enumerate(node.aggregates))
        return Aggregate(node.output_names, node.output_types, new_join,
                         tuple(remap(g) for g in G), merged, "SINGLE")


class PushAggregationThroughOuterJoin(Rule):
    """Aggregate(G subset-of probe, aggs over build, LeftJoin(A, B)) ->
    merge-Aggregate over LeftJoin(A, pre-aggregate(B by its join keys)),
    with COUNT columns coalesced to 0 above (an all-unmatched group
    yields a NULL merged state where the original counted 0)."""

    pattern = Pattern(Aggregate).matching(
        lambda n, ctx: _eligible(n)).with_source(
        Pattern(Join).matching(
            lambda n, ctx: n.join_type == "LEFT" and n.residual is None),
        "join")

    def apply(self, node: Aggregate, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        join: Join = captures["join"]
        left, right = join.children
        lw = len(left.output_types)
        if any(g >= lw for g in node.group_keys):
            return None
        if any(a.arg < lw or a.fn not in ("count", "sum", "min", "max")
               for a in node.aggregates):
            return None  # needs every argument on the null-extended side
        if isinstance(ctx.resolve(right), Aggregate):
            return None

        G = node.group_keys
        keys = sorted(set(join.right_keys))
        key_pos = {k: i for i, k in enumerate(keys)}
        agg_names = tuple(node.output_names[len(G) + i]
                          for i in range(len(node.aggregates)))
        inner_aggs = tuple(AggCall(a.fn, a.arg - lw, a.type, False)
                           for a in node.aggregates)
        inner_names = (tuple(right.output_names[k] for k in keys)
                       + tuple(f"{n}$partial" for n in agg_names))
        inner_types = (tuple(right.output_types[k] for k in keys)
                       + tuple(a.type for a in node.aggregates))
        inner = Aggregate(inner_names, inner_types, right,
                          tuple(keys), inner_aggs, "SINGLE")
        if not _worth_pushing(
                Aggregate(inner_names, inner_types, ctx.extract(right),
                          tuple(keys), inner_aggs, "SINGLE"),
                ctx.extract(right), ctx):
            return None

        join_names = tuple(left.output_names) + inner_names
        join_types = tuple(left.output_types) + inner_types
        new_join = Join(join_names, join_types, left, inner, "LEFT",
                        join.left_keys,
                        tuple(key_pos[k] for k in join.right_keys),
                        None, join.distribution)
        merged = tuple(
            AggCall(_MERGE[a.fn], lw + len(keys) + i, a.type, False)
            for i, a in enumerate(node.aggregates))
        agg = Aggregate(node.output_names, node.output_types, new_join,
                        G, merged, "SINGLE")
        exprs = [InputRef(t, i)
                 for i, t in enumerate(node.output_types[:len(G)])]
        for i, a in enumerate(node.aggregates):
            ref = InputRef(a.type, len(G) + i)
            if a.fn == "count":
                exprs.append(Call(a.type, "$coalesce",
                                  (ref, Literal(a.type, 0))))
            else:
                exprs.append(ref)
        return Project(node.output_names, node.output_types, agg,
                       tuple(exprs))
