"""Structural simplification rules (reference: iterative/rule/
MergeFilters.java, InlineProjections.java, MergeProjections (via
IterativeOptimizer's ProjectOffPushDown family),
RemoveRedundantIdentityProjections.java, RemoveTrivialFilters.java,
EvaluateEmptyIntersect / the *EmptyPlanNode family behind
EvaluateZeroInput semantics)."""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

from ....spi.types import BOOLEAN
from ....sql.ir import Call, InputRef, Literal, RowExpression
from ...optimizer import _conjoin, _split_and
from ...plan import (
    Aggregate,
    DistinctLimit,
    Filter,
    GroupId,
    Join,
    Limit,
    PlanNode,
    Project,
    Replicate,
    SemiJoin,
    Sort,
    TopN,
    Union,
    Unnest,
    Values,
    Window,
)
from ..pattern import Pattern
from ..rule import Context, Rule

__all__ = [
    "EvaluateZeroInput", "InlineProjections", "MergeAdjacentFilters",
    "MergeAdjacentProjects", "RemoveRedundantIdentityProjections",
    "RemoveTrivialFilters",
]


def _subst(e: RowExpression, inner: tuple) -> RowExpression:
    """Replace each InputRef by the inner projection's defining expr."""
    if isinstance(e, InputRef):
        return inner[e.index]
    if isinstance(e, Call):
        return Call(e.type, e.name, tuple(_subst(a, inner) for a in e.args))
    return e


def _trivial(e: RowExpression) -> bool:
    return isinstance(e, (InputRef, Literal))


def _ref_counts(exprs) -> dict[int, int]:
    counts: dict[int, int] = {}

    def go(e):
        if isinstance(e, InputRef):
            counts[e.index] = counts.get(e.index, 0) + 1
        elif isinstance(e, Call):
            for a in e.args:
                go(a)

    for e in exprs:
        go(e)
    return counts


class MergeAdjacentFilters(Rule):
    """Filter(p, Filter(q, X)) -> Filter(p AND q, X)."""

    pattern = Pattern(Filter).with_source(Pattern(Filter), "inner")

    def apply(self, node: Filter, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        inner: Filter = captures["inner"]
        pred = _conjoin(_split_and(inner.predicate)
                        + _split_and(node.predicate))
        return Filter(node.output_names, node.output_types,
                      inner.source, pred)


class MergeAdjacentProjects(Rule):
    """Project over a trivial Project (only channel renames/permutations
    and literals) composes into one Project."""

    pattern = Pattern(Project).with_source(Pattern(Project), "inner")

    def apply(self, node: Project, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        inner: Project = captures["inner"]
        if not all(_trivial(e) for e in inner.expressions):
            return None
        exprs = tuple(_subst(e, inner.expressions) for e in node.expressions)
        return Project(node.output_names, node.output_types,
                       inner.source, exprs)


class InlineProjections(Rule):
    """Project over a computing Project inlines when every computed inner
    channel is referenced at most once above (no work duplication —
    iterative/rule/InlineProjections.java's condition)."""

    pattern = Pattern(Project).with_source(Pattern(Project), "inner")

    def apply(self, node: Project, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        inner: Project = captures["inner"]
        if all(_trivial(e) for e in inner.expressions):
            return None  # MergeAdjacentProjects' case
        counts = _ref_counts(node.expressions)
        for i, e in enumerate(inner.expressions):
            if not _trivial(e) and counts.get(i, 0) > 1:
                return None
        exprs = tuple(_subst(e, inner.expressions) for e in node.expressions)
        return Project(node.output_names, node.output_types,
                       inner.source, exprs)


class RemoveRedundantIdentityProjections(Rule):
    """Identity Project (same channels, same names) collapses away."""

    pattern = Pattern(Project)

    def apply(self, node: Project, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        child = node.children[0]  # GroupRef carries the layout
        if len(node.expressions) != len(child.output_types):
            return None
        if tuple(node.output_names) != tuple(child.output_names):
            return None
        for i, e in enumerate(node.expressions):
            if not (isinstance(e, InputRef) and e.index == i):
                return None
        return child


class RemoveTrivialFilters(Rule):
    """Filter(TRUE) drops; Filter(FALSE/NULL) becomes an empty Values."""

    pattern = Pattern(Filter).matching(
        lambda n, ctx: isinstance(n.predicate, Literal))

    def apply(self, node: Filter, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        if node.predicate.value is True:
            return node.children[0]
        if node.predicate.value in (False, None):
            return Values(node.output_names, node.output_types, rows=())
        return None


def _is_empty(n: PlanNode) -> bool:
    return isinstance(n, Values) and not n.rows


def _empty_like(node: PlanNode) -> Values:
    return Values(node.output_names, node.output_types, rows=())


class EvaluateZeroInput(Rule):
    """Propagate empty relations (reference: the
    Remove/Evaluate-over-empty rule family — e.g.
    RemoveRedundantJoin / EvaluateZeroLimit semantics): an empty input
    makes row-preserving operators, grouped aggregations, and the
    affected join sides statically empty."""

    pattern = Pattern(PlanNode).matching(
        lambda n, ctx: any(_is_empty(ctx.resolve(c)) for c in n.children))

    def apply(self, node: PlanNode, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        kids = [ctx.resolve(c) for c in node.children]
        if isinstance(node, (Project, Filter, Sort, Limit, TopN,
                             DistinctLimit, Window, GroupId, Unnest,
                             Replicate)):
            return _empty_like(node)
        if isinstance(node, Aggregate):
            # a GLOBAL aggregate over zero rows still emits one row
            if node.group_keys and node.step == "SINGLE":
                return _empty_like(node)
            return None
        if isinstance(node, Join):
            left_empty, right_empty = _is_empty(kids[0]), _is_empty(kids[1])
            jt = node.join_type
            if ((jt in ("INNER", "CROSS") and (left_empty or right_empty))
                    or (jt in ("LEFT", "SINGLE") and left_empty)
                    or (jt == "RIGHT" and right_empty)
                    or (jt == "FULL" and left_empty and right_empty)):
                return _empty_like(node)
            return None
        if isinstance(node, SemiJoin):
            if _is_empty(kids[0]):
                return _empty_like(node)
            if _is_empty(kids[1]) and node.residual is None:
                # membership in the empty set is FALSE for every source
                # row (even NULL keys, null-aware or not)
                src = node.children[0]
                exprs = tuple(InputRef(t, i)
                              for i, t in enumerate(src.output_types))
                exprs = exprs + (Literal(BOOLEAN, False),)
                return Project(node.output_names, node.output_types,
                               src, exprs)
            return None
        if isinstance(node, Union):
            keep = [c for c, k in zip(node.children, kids)
                    if not _is_empty(k)]
            if len(keep) == len(kids):
                return None
            if not keep:
                return _empty_like(node)
            if len(keep) == 1:
                src = keep[0]
                exprs = tuple(InputRef(t, i)
                              for i, t in enumerate(src.output_types))
                return Project(node.output_names, node.output_types,
                               src, exprs)
            return replace(node, sources=tuple(keep))
        return None
