"""Limit pushdown rules (reference: iterative/rule/
PushLimitThroughProject.java, PushLimitThroughOuterJoin.java,
PushLimitThroughSemiJoin.java)."""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

from ...plan import Join, Limit, PlanNode, Project, SemiJoin
from ..pattern import Pattern
from ..rule import Context, Rule

__all__ = ["PushLimitThroughJoin", "PushLimitThroughProject",
           "PushLimitThroughSemiJoin"]


class PushLimitThroughProject(Rule):
    """Limit(Project(X)) -> Project(Limit(X)): projections are 1:1, so
    limiting below is identical and lets the limit keep sinking (and
    eventually fold into a TableScan)."""

    pattern = Pattern(Limit).with_source(Pattern(Project), "project")

    def apply(self, node: Limit, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        project: Project = captures["project"]
        src = project.children[0]
        inner = Limit(src.output_names, src.output_types, src, node.count)
        return replace(project, source=inner)


class PushLimitThroughSemiJoin(Rule):
    """Limit(SemiJoin(X, F)) -> SemiJoin(Limit(X), F): the semijoin emits
    exactly one output row per source row (a mark column), so the outer
    limit is subsumed by the pushed one."""

    pattern = Pattern(Limit).with_source(Pattern(SemiJoin), "semijoin")

    def apply(self, node: Limit, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        semijoin: SemiJoin = captures["semijoin"]
        src = semijoin.children[0]
        resolved = ctx.resolve(src)
        if isinstance(resolved, Limit) and resolved.count <= node.count:
            return None
        inner = Limit(src.output_names, src.output_types, src, node.count)
        return replace(semijoin, source=inner)


class PushLimitThroughJoin(Rule):
    """Limit(n, LeftJoin(A, B)) -> Limit(n, LeftJoin(Limit(n, A), B)):
    a left join emits at least one row per probe row, so n probe rows
    suffice; the outer limit stays to trim multi-match fan-out."""

    pattern = Pattern(Limit).with_source(
        Pattern(Join).matching(lambda n, ctx: n.join_type == "LEFT"),
        "join")

    def apply(self, node: Limit, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        join: Join = captures["join"]
        left = join.children[0]
        resolved = ctx.resolve(left)
        if isinstance(resolved, Limit) and resolved.count <= node.count:
            return None
        inner = Limit(left.output_names, left.output_types, left, node.count)
        return replace(node, source=replace(join, left=inner))
