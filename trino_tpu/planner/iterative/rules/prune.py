"""Column-pruning rules (reference: iterative/rule/
PruneJoinColumns.java / PruneJoinChildrenColumns.java).

The legacy ``_prune`` pass already narrows scans bottom-up; this rule
covers the shape it misses inside the memo — a Project over a Join that
carries channels no one above needs — by narrowing the join inputs with
identity sub-projections before the fragmenter materializes exchanges."""

from __future__ import annotations

from typing import Optional

from ....sql.ir import InputRef
from ...optimizer import _refs, _remap_expr
from ...plan import Join, PlanNode, Project
from ..pattern import Pattern
from ..rule import Context, Rule

__all__ = ["PruneJoinColumns"]


def _narrow(side, keep: list[int]) -> Project:
    names = tuple(side.output_names[i] for i in keep)
    types = tuple(side.output_types[i] for i in keep)
    exprs = tuple(InputRef(side.output_types[i], i) for i in keep)
    return Project(names, types, side, exprs)


class PruneJoinColumns(Rule):
    """Project(Join(A, B)) where some join output channels are dead:
    wrap the wide side(s) in identity projections over the live channels
    and remap keys/residual/projection accordingly."""

    pattern = Pattern(Project).with_source(Pattern(Join), "join")

    def apply(self, node: Project, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        join: Join = captures["join"]
        left, right = join.children
        lw = len(left.output_types)
        rw = len(right.output_types)

        needed: set[int] = set()
        for e in node.expressions:
            needed |= _refs(e)
        needed |= set(join.left_keys)
        needed |= {lw + k for k in join.right_keys}
        if join.residual is not None:
            needed |= _refs(join.residual)

        left_keep = sorted(i for i in needed if i < lw)
        right_keep = sorted(i - lw for i in needed if i >= lw)
        # zero-column relations are not representable; pin one channel
        if not left_keep:
            left_keep = [0]
        if not right_keep:
            right_keep = [0]
        if len(left_keep) == lw and len(right_keep) == rw:
            return None

        new_left = _narrow(left, left_keep) if len(left_keep) < lw else left
        new_right = (_narrow(right, right_keep)
                     if len(right_keep) < rw else right)
        lmap = {old: new for new, old in enumerate(left_keep)}
        rmap = {old: new for new, old in enumerate(right_keep)}
        nlw = len(left_keep)
        mapping = {}
        for old, new in lmap.items():
            mapping[old] = new
        for old, new in rmap.items():
            mapping[lw + old] = nlw + new

        join_names = tuple(new_left.output_names) + tuple(new_right.output_names)
        join_types = tuple(new_left.output_types) + tuple(new_right.output_types)
        residual = (_remap_expr(join.residual, mapping)
                    if join.residual is not None else None)
        new_join = Join(join_names, join_types, new_left, new_right,
                        join.join_type,
                        tuple(lmap[k] for k in join.left_keys),
                        tuple(rmap[k] for k in join.right_keys),
                        residual, join.distribution)
        exprs = tuple(_remap_expr(e, mapping) for e in node.expressions)
        return Project(node.output_names, node.output_types, new_join, exprs)
