"""Rule library for the iterative optimizer (reference:
sql/planner/iterative/rule/ — each module groups the miniatures of the
correspondingly-named Trino rules)."""
