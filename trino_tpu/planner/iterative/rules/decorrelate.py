"""Decorrelation rules (reference: iterative/rule/
TransformCorrelatedScalarSubquery.java,
TransformCorrelatedInPredicateToJoin.java).

The logical planner emits a :class:`CorrelatedJoin` placeholder when the
iterative optimizer is active; these rules lower it to the same join
shapes the legacy planner builds directly — but as rules, so the
subquery side participates in simplification/reordering first."""

from __future__ import annotations

from typing import Optional

from ...plan import CorrelatedJoin, Join, PlanNode, SemiJoin
from ..pattern import Pattern
from ..rule import Context, Rule

__all__ = ["TransformCorrelatedInPredicate",
           "TransformCorrelatedScalarSubquery"]


class TransformCorrelatedScalarSubquery(Rule):
    """Correlated scalar-aggregate subquery -> LEFT join on the
    correlation keys (the subquery side is already grouped by them, so
    at most one match per probe row)."""

    pattern = Pattern(CorrelatedJoin).matching(
        lambda n, ctx: n.kind == "scalar_agg")

    def apply(self, node: CorrelatedJoin, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        return Join(node.output_names, node.output_types,
                    node.children[0], node.children[1], "LEFT",
                    node.source_keys, node.subquery_keys, None)


class TransformCorrelatedInPredicate(Rule):
    """IN (subquery) -> null-aware SemiJoin producing the mark column."""

    pattern = Pattern(CorrelatedJoin).matching(
        lambda n, ctx: n.kind == "in")

    def apply(self, node: CorrelatedJoin, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        return SemiJoin(node.output_names, node.output_types,
                        node.children[0], node.children[1],
                        node.source_keys, node.subquery_keys,
                        negated=False, residual=None, null_aware=True)
