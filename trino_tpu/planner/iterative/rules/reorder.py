"""Join ordering and distribution rules (reference: iterative/rule/
ReorderJoins.java + JoinEnumerator, and
DetermineJoinDistributionType.java).

``ReorderJoins`` re-expresses the legacy optimizer's filter-cluster
machinery as a rule: flatten a maximal INNER/CROSS join cluster (with
the Filter above it, when present) into leaves + conjuncts, push
single-leaf conjuncts into their leaf, and rebuild a left-deep spine.
Two orderers share the expansion cost model (|A><B| ~ |A|*|B| /
max key NDV — cost/JoinStatsRule): exhaustive DP over connected
subsets when the cluster has at most TRINO_TPU_JOIN_REORDER_DP_LIMIT
leaves (JoinEnumerator's memoized search, minimizing the sum of
intermediate output estimates), and the legacy greedy otherwise.  Both
prefer history-observed row counts over catalog estimates when a
HistoryProvider is active — the "second run plans right" loop.

Unlike the legacy pass, leaves are NOT recursively rewritten here — the
driver explores nested groups with the same rule set; to keep a cluster
from being re-flattened at every nested join group, a firing records the
repr of every join subtree it produced and the rule skips those."""

from __future__ import annotations

from typing import Optional

from ....spi import knobs
from ....sql.ir import Call, InputRef, RowExpression
from ....spi.types import BOOLEAN
from ...optimizer import (
    _choose_distribution,
    _conjoin,
    _exprs_as_channels,
    _hoist_common_or,
    _refs,
    _remap_leaf_to_spine,
    _remap_to_leaf,
    _restore_layout,
    _shift,
    _single_leaf,
    _split_and,
    estimate_rows,
)
from ...plan import Filter, Join, PlanNode
from ..pattern import Pattern
from ..rule import Context, Rule

__all__ = ["DetermineJoinDistribution", "ReorderJoins"]


def _inner_join(n: PlanNode) -> bool:
    return isinstance(n, Join) and n.join_type in ("CROSS", "INNER")


def _cluster_top(n: PlanNode, ctx: Context) -> bool:
    if _inner_join(n):
        return True
    return isinstance(n, Filter) and _inner_join(ctx.resolve(n.source))


def _flatten_cluster(node: PlanNode):
    """Legacy _flatten without the recursive leaf rewrite: leaves stay
    whatever subtree the memo holds there (Filters included)."""
    leaves: list[tuple[PlanNode, int]] = []
    conjuncts: list[RowExpression] = []

    def go(n: PlanNode, offset: int) -> int:
        if _inner_join(n):
            lw = go(n.left, offset)
            rw = go(n.right, offset + lw)
            for lk, rk in zip(n.left_keys, n.right_keys):
                conjuncts.append(Call(BOOLEAN, "eq", (
                    InputRef(n.left.output_types[lk], offset + lk),
                    InputRef(n.right.output_types[rk], offset + lw + rk))))
            if n.residual is not None:
                conjuncts.append(_shift(n.residual, offset))
            return lw + rw
        leaves.append((n, offset))
        return len(n.output_types)

    total = go(node, 0)
    return leaves, conjuncts, total


def _dp_order(n: int, est: list[float], edges, out_est) -> list[int]:
    """Exhaustive left-deep enumeration: minimize the sum of intermediate
    join-output estimates PLUS build-side inputs, extending
    connected-first (cross joins only when nothing connects, like the
    greedy).  Charging each step for the relation it hashes is what keeps
    a big table from becoming a "cheap" build under a tiny probe spine —
    output estimates alone are orientation-blind (a 300-row spine probing
    a 24k-row build scores the same output as the reverse, but builds 80x
    the hash table, broadcast-replicated per task).  Deterministic
    tie-break on the order tuple."""
    # frozenset -> (cost, spine_est, order)
    best: dict[frozenset, tuple[float, float, tuple[int, ...]]] = {
        frozenset((i,)): (0.0, max(est[i], 1.0), (i,)) for i in range(n)
    }
    for _ in range(n - 1):
        nxt: dict[frozenset, tuple[float, float, tuple[int, ...]]] = {}
        for state, (cost, spine_est, order) in best.items():
            if len(order) != len(state):
                continue
            rest = [i for i in range(n) if i not in state]
            connected = [i for i in rest
                         if any((a in state and b == i)
                                or (b in state and a == i)
                                for (a, b, _, _) in edges)]
            for i in (connected or rest):
                oe = out_est(state, spine_est, i, bool(connected))
                cand = (cost + oe + max(est[i], 1.0), max(oe, 1.0),
                        order + (i,))
                ns = state | {i}
                cur = nxt.get(ns)
                if cur is None or (cand[0], cand[2]) < (cur[0], cur[2]):
                    nxt[ns] = cand
        best = nxt
    (_, _, order), = best.values() if len(best) == 1 else [
        min(best.values(), key=lambda v: (v[0], v[2]))]
    return list(order)


def _greedy_order(n: int, est: list[float], edges, out_est) -> list[int]:
    """The legacy greedy: spine = largest relation, then repeatedly the
    connected relation with the smallest estimated join output."""
    order = [max(range(n), key=lambda i: est[i])]
    remaining = set(range(n)) - set(order)
    spine_est = est[order[0]]
    while remaining:
        state = frozenset(order)
        connected = [i for i in sorted(remaining)
                     if any((a in state and b == i) or (b in state and a == i)
                            for (a, b, _, _) in edges)]
        if connected:
            outs = {i: out_est(state, spine_est, i, True) for i in connected}
            pick = min(connected, key=lambda i: (outs[i], est[i]))
            spine_est = max(outs[pick], 1.0)
        else:
            pick = min(remaining, key=lambda i: est[i])
            spine_est = spine_est * max(est[pick], 1.0)
        order.append(pick)
        remaining.discard(pick)
    return order


def _reorder_cluster(tree: PlanNode, ctx: Context) -> Optional[PlanNode]:
    catalog, history = ctx.catalog, ctx.history
    if isinstance(tree, Filter):
        cluster_root = tree.source
        preds = [p for c in _split_and(tree.predicate)
                 for p in _hoist_common_or(c)]
    else:
        cluster_root = tree
        preds = []
    if not _inner_join(cluster_root):
        return None

    leaves, conjuncts, total_width = _flatten_cluster(cluster_root)
    conjuncts = conjuncts + preds

    chan_leaf: dict[int, tuple[int, int]] = {}
    for li, (leaf, offset) in enumerate(leaves):
        for local in range(len(leaf.output_types)):
            chan_leaf[offset + local] = (li, local)

    leaf_nodes = [leaf for (leaf, _) in leaves]
    leaf_filters: list[list[RowExpression]] = [[] for _ in leaves]
    edges: list[tuple[int, int, RowExpression, RowExpression]] = []
    residual: list[RowExpression] = []
    for c in conjuncts:
        involved = {chan_leaf[i][0] for i in _refs(c)}
        if len(involved) == 1:
            li = involved.pop()
            leaf_filters[li].append(_remap_to_leaf(c, chan_leaf, li))
        elif (isinstance(c, Call) and c.name == "eq" and len(involved) == 2
              and _single_leaf(c.args[0], chan_leaf) is not None
              and _single_leaf(c.args[1], chan_leaf) is not None):
            a, b = c.args
            la, lb = _single_leaf(a, chan_leaf), _single_leaf(b, chan_leaf)
            edges.append((la, lb,
                          _remap_to_leaf(a, chan_leaf, la),
                          _remap_to_leaf(b, chan_leaf, lb)))
        else:
            residual.append(c)

    for li, filters in enumerate(leaf_filters):
        if filters:
            leaf = leaf_nodes[li]
            leaf_nodes[li] = Filter(leaf.output_names, leaf.output_types,
                                    leaf, _conjoin(filters))

    est = [estimate_rows(l, catalog, history) for l in leaf_nodes]

    from ...optimizer import _channel_ndv
    ndv_cache: dict[tuple[int, int], Optional[float]] = {}

    def _leaf_ndv(leaf: int, expr) -> Optional[float]:
        if not isinstance(expr, InputRef):
            return None
        key = (leaf, expr.index)
        if key not in ndv_cache:
            ndv_cache[key] = _channel_ndv(leaf_nodes[leaf], expr.index,
                                          catalog)
        return ndv_cache[key]

    def out_est(state: frozenset, spine_est: float, i: int,
                connected: bool) -> float:
        if not connected:
            return spine_est * max(est[i], 1.0)
        best: Optional[float] = None
        for (a, b, ea, eb) in edges:
            if a in state and b == i:
                se, ce, sl = ea, eb, a
            elif b in state and a == i:
                se, ce, sl = eb, ea, b
            else:
                continue
            nd = max((x for x in (_leaf_ndv(i, ce), _leaf_ndv(sl, se))
                      if x), default=None)
            if nd:
                best = max(best or 0.0, nd)
        if best:
            return spine_est * est[i] / max(best, 1.0)
        return max(spine_est, est[i])  # keyed, unknown NDV: PK-FK-ish

    n = len(leaf_nodes)
    dp_limit = knobs.get_int("TRINO_TPU_JOIN_REORDER_DP_LIMIT") or 0
    if 3 <= n <= dp_limit:
        order = _dp_order(n, est, edges, out_est)
    else:
        order = _greedy_order(n, est, edges, out_est)

    # build the tree left-deep; (leaf idx, local ch) -> spine ch
    spine = leaf_nodes[order[0]]
    pos: dict[tuple[int, int], int] = {
        (order[0], i): i for i in range(len(spine.output_types))
    }
    used_edges = set()
    for step in range(1, len(order)):
        li = order[step]
        right = leaf_nodes[li]
        lkeys, rkeys = [], []
        for ei, (a, b, ea, eb) in enumerate(edges):
            if ei in used_edges:
                continue
            if a in order[:step] and b == li:
                sa, rb = ea, eb
            elif b in order[:step] and a == li:
                sa, rb = eb, ea
                a, b = b, a
            else:
                continue
            used_edges.add(ei)
            lkeys.append(_remap_leaf_to_spine(sa, a, pos))
            rkeys.append(rb)
        lch, spine = _exprs_as_channels(lkeys, spine)
        rch, right = _exprs_as_channels(rkeys, right)
        names = tuple(spine.output_names) + tuple(right.output_names)
        types = tuple(spine.output_types) + tuple(right.output_types)
        sw = len(spine.output_types)
        jt = "INNER" if lch else "CROSS"
        spine = Join(names, types, spine, right, jt, tuple(lch), tuple(rch),
                     None,
                     distribution=_choose_distribution(right, catalog,
                                                       "INNER", history))
        for i in range(len(right.output_types)):
            pos[(li, i)] = sw + i

    if residual:
        def remap_residual(e: RowExpression) -> RowExpression:
            if isinstance(e, InputRef):
                li, local = chan_leaf[e.index]
                return InputRef(e.type, pos[(li, local)])
            if isinstance(e, Call):
                return Call(e.type, e.name,
                            tuple(remap_residual(a) for a in e.args))
            return e
        spine = Filter(spine.output_names, spine.output_types, spine,
                       _conjoin([remap_residual(r) for r in residual]))

    mapping = [pos[chan_leaf[i]] for i in range(total_width)]
    if mapping != list(range(len(tree.output_types))) \
            or tuple(spine.output_names) != tuple(tree.output_names):
        spine = _restore_layout(spine, mapping, tree)
    return spine


def _record_subtrees(node: PlanNode, seen: set) -> None:
    """Mark every join subtree (and the Filter atop one) of a rebuilt
    cluster so nested groups don't get re-flattened."""
    if isinstance(node, (Filter, Join)):
        seen.add(repr(node))
    for c in node.children:
        _record_subtrees(c, seen)


class ReorderJoins(Rule):
    pattern = Pattern((Filter, Join)).matching(_cluster_top)

    def apply(self, node: PlanNode, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        tree = ctx.extract(node)
        key = repr(tree)
        if key in ctx.reordered:
            return None
        out = _reorder_cluster(tree, ctx)
        ctx.reordered.add(key)
        if out is None:
            return None
        _record_subtrees(out, ctx.reordered)
        if out == tree:
            return None
        return out


class DetermineJoinDistribution(Rule):
    """Pick BROADCAST vs PARTITIONED for non-reorderable joins from
    history (observed build bytes/rows) or the estimate fallback —
    ReorderJoins already decides for the INNER/CROSS spines it builds."""

    pattern = Pattern(Join).matching(
        lambda n, ctx: n.join_type not in ("INNER", "CROSS"))

    def apply(self, node: Join, captures: dict,
              ctx: Context) -> Optional[PlanNode]:
        build = ctx.extract(node.right)
        dist = _choose_distribution(build, ctx.catalog, node.join_type,
                                    ctx.history)
        if dist == node.distribution:
            return None
        from dataclasses import replace
        return replace(node, distribution=dist)
