"""Logical plan nodes.

The subset of Trino's 66 node types (reference: sql/planner/plan/*.java —
TableScanNode, FilterNode, ProjectNode, AggregationNode, JoinNode,
SemiJoinNode, TopNNode, SortNode, LimitNode, ValuesNode, ExchangeNode,
OutputNode, TableWriterNode) the engine currently executes.  Every node's
output is a flat list of (name, type) channels; expressions inside a node
reference its input channels by index (InputRef), so plans need no symbol
table — the channel layout IS the contract (Trino uses named Symbols +
a SymbolAllocator; indices are the array-first equivalent).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..spi.types import BIGINT, BOOLEAN, DOUBLE, Type
from ..sql.ir import RowExpression

__all__ = [
    "PlanNode", "TableScan", "Filter", "Project", "AggCall", "Aggregate",
    "GroupId", "Unnest", "TableFunctionScan", "MatchRecognize",
    "Join", "SemiJoin", "CorrelatedJoin", "Sort", "SortKey", "TopN",
    "Limit", "Values",
    "Output", "Exchange", "RemoteSource", "TableWriter", "DistinctLimit",
    "Window", "WindowFunc", "Union", "Replicate", "plan_text",
]


@dataclass(frozen=True)
class PlanNode:
    output_names: tuple[str, ...]
    output_types: tuple[Type, ...]

    @property
    def children(self) -> tuple["PlanNode", ...]:
        return ()

    def label(self) -> str:
        return type(self).__name__


@dataclass(frozen=True)
class TableScan(PlanNode):
    catalog: str = ""
    table: str = ""
    columns: tuple[str, ...] = ()  # connector column names, 1:1 with outputs
    # advisory TupleDomain from predicate pushdown (spi/predicate.py;
    # reference: PushPredicateIntoTableScan with enforced=false) — excluded
    # from eq/hash (it is derived state, and TupleDomain holds a dict)
    constraint: Optional[object] = field(default=None, compare=False)
    # LIMIT pushed into the scan (reference: PushLimitIntoTableScan +
    # ConnectorMetadata.applyLimit): the scan may stop reading splits after
    # this many rows; the engine Limit above re-enforces exactly
    limit: Optional[int] = None

    def label(self) -> str:
        c = ""
        if self.constraint is not None and not self.constraint.is_all:
            cols = sorted(self.constraint.domains)
            c = f" constraint={cols}"
        return f"TableScan[{self.catalog}.{self.table} {list(self.columns)}{c}]"


@dataclass(frozen=True)
class Filter(PlanNode):
    source: PlanNode = None
    predicate: RowExpression = None

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        return f"Filter[{self.predicate}]"


@dataclass(frozen=True)
class Project(PlanNode):
    source: PlanNode = None
    expressions: tuple[RowExpression, ...] = ()

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        return f"Project[{', '.join(f'{n}:={e}' for n, e in zip(self.output_names, self.expressions))}]"


@dataclass(frozen=True)
class AggCall:
    """One aggregate: fn in (count, sum, avg, min, max, count_star, any_value);
    arg is an input channel index (or -1 for count(*))."""

    fn: str
    arg: int
    type: Type
    distinct: bool = False


@dataclass(frozen=True)
class Aggregate(PlanNode):
    source: PlanNode = None
    group_keys: tuple[int, ...] = ()  # input channel indices
    aggregates: tuple[AggCall, ...] = ()
    # SINGLE for now; PARTIAL/FINAL appear when the fragmenter splits
    step: str = "SINGLE"

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        aggs = ", ".join(f"{a.fn}({'*' if a.arg < 0 else '#%d' % a.arg}{' distinct' if a.distinct else ''})"
                         for a in self.aggregates)
        return f"Aggregate[{self.step} keys={list(self.group_keys)} {aggs}]"


@dataclass(frozen=True)
class GroupId(PlanNode):
    """Grouping-sets row expansion (reference: sql/planner/plan/
    GroupIdNode.java, operator/GroupIdOperator.java:32): replicates every
    input row once per grouping set, nulling grouping columns absent from
    the set and appending a group-id column.  Output channels =
    [one copy per key_channels entry] ++ [passthrough channels (aggregation
    arguments, never nulled)] ++ [$groupid BIGINT].  ``sets`` holds, per
    grouping set, the indices into ``key_channels`` that remain live."""

    source: PlanNode = None
    key_channels: tuple[int, ...] = ()
    passthrough: tuple[int, ...] = ()
    sets: tuple[tuple[int, ...], ...] = ()

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        return (f"GroupId[keys={list(self.key_channels)} "
                f"sets={[list(s) for s in self.sets]}]")


@dataclass(frozen=True)
class Unnest(PlanNode):
    """Array row expansion (reference: sql/planner/plan/UnnestNode.java,
    operator/unnest/UnnestOperator.java:42).  Output channels =
    [``replicate`` source channels] ++ [one element column per
    ``unnest_channels`` array column] ++ [ordinality BIGINT when set].
    Standalone ``FROM UNNEST(...)`` uses an empty ``replicate``; the lateral
    CROSS JOIN UNNEST form replicates the left side's channels."""

    source: PlanNode = None
    replicate: tuple[int, ...] = ()
    unnest_channels: tuple[int, ...] = ()
    ordinality: bool = False

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        return (f"Unnest[{list(self.unnest_channels)}"
                + (" ordinality" if self.ordinality else "") + "]")


@dataclass(frozen=True)
class Join(PlanNode):
    """Equi-join with optional residual filter.  Output channels are
    left-columns ++ right-columns (probe side = left)."""

    left: PlanNode = None
    right: PlanNode = None
    join_type: str = "INNER"  # INNER | LEFT
    left_keys: tuple[int, ...] = ()
    right_keys: tuple[int, ...] = ()
    residual: Optional[RowExpression] = None  # over concatenated layout
    # execution strategy hint (optimizer): PARTITIONED | BROADCAST
    distribution: str = "BROADCAST"

    @property
    def children(self):
        return (self.left, self.right)

    def label(self) -> str:
        keys = ", ".join(f"#{l}=#{r}" for l, r in zip(self.left_keys, self.right_keys))
        res = f" residual={self.residual}" if self.residual else ""
        return f"Join[{self.join_type} {self.distribution} {keys}{res}]"


@dataclass(frozen=True)
class SemiJoin(PlanNode):
    """EXISTS/IN: keeps (semi) or drops (anti) source rows with a match in
    filter_source.  Output = source channels unchanged."""

    source: PlanNode = None
    filter_source: PlanNode = None
    source_keys: tuple[int, ...] = ()
    filter_keys: tuple[int, ...] = ()
    negated: bool = False  # anti join
    # residual over source-channels ++ filter-source-channels, evaluated
    # per candidate pair (correlated EXISTS with non-equi conjuncts, Q21)
    residual: Optional[RowExpression] = None
    null_aware: bool = False  # NOT IN NULL semantics

    @property
    def children(self):
        return (self.source, self.filter_source)

    def label(self) -> str:
        keys = ", ".join(f"#{l}~#{r}" for l, r in zip(self.source_keys, self.filter_keys))
        return f"{'Anti' if self.negated else 'Semi'}Join[{keys}{' residual=' + str(self.residual) if self.residual else ''}]"


@dataclass(frozen=True)
class CorrelatedJoin(PlanNode):
    """Correlated-subquery placeholder (reference: sql/planner/plan/
    CorrelatedJoinNode.java).  The logical planner emits it only under the
    iterative optimizer; the decorrelation rules (planner/iterative/rules/
    decorrelate.py) lower it before any execution layer sees it.

    ``kind`` selects the decorrelated form:

    - ``scalar_agg`` — correlated scalar aggregate.  ``subquery`` is the
      pre-chewed keys+value+marker aggregation; output channels are
      source ++ subquery, and the node lowers to a LEFT equi-join on
      (source_keys, subquery_keys).
    - ``in`` — correlated IN-predicate membership.  Output channels are
      the source's plus one trailing BOOLEAN mark; the node lowers to a
      null-aware SemiJoin on (source_keys, subquery_keys).
    """

    source: PlanNode = None
    subquery: PlanNode = None
    kind: str = "scalar_agg"  # scalar_agg | in
    source_keys: tuple[int, ...] = ()
    subquery_keys: tuple[int, ...] = ()

    @property
    def children(self):
        return (self.source, self.subquery)

    def label(self) -> str:
        keys = ", ".join(f"#{l}~#{r}" for l, r in
                         zip(self.source_keys, self.subquery_keys))
        return f"CorrelatedJoin[{self.kind} {keys}]"


@dataclass(frozen=True)
class SortKey:
    channel: int
    ascending: bool = True
    nulls_first: bool = False


# default SQL frame: RANGE UNBOUNDED PRECEDING .. CURRENT ROW
DEFAULT_FRAME = ("RANGE", "UNBOUNDED_PRECEDING", None, "CURRENT", None)


@dataclass(frozen=True)
class WindowFunc:
    """One window function call: ``args`` are input channels (value column,
    then the lag/lead default channel when present); ``offset`` carries the
    constant lag/lead offset, ntile bucket count, or nth_value position."""

    fn: str
    args: tuple[int, ...]
    type: Type = None
    offset: int = 1
    frame: tuple = DEFAULT_FRAME


@dataclass(frozen=True)
class Window(PlanNode):
    """Window evaluation (reference: sql/planner/plan/WindowNode.java,
    operator/WindowOperator.java:69).  Output channels = every source channel
    followed by one channel per function."""

    source: PlanNode = None
    partition_keys: tuple[int, ...] = ()
    order_keys: tuple[SortKey, ...] = ()
    functions: tuple[WindowFunc, ...] = ()

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        fns = ", ".join(
            f"{f.fn}({', '.join('#%d' % a for a in f.args)})"
            for f in self.functions)
        keys = ", ".join(
            f"#{k.channel}{'' if k.ascending else ' desc'}"
            for k in self.order_keys)
        return (f"Window[partition={list(self.partition_keys)} "
                f"order=[{keys}] {fns}]")


@dataclass(frozen=True)
class MatchRecognize(PlanNode):
    """ONE ROW PER MATCH row-pattern recognition (reference:
    sql/planner/plan/PatternRecognitionNode.java:47).  Output channels =
    partition columns ++ measures.  DEFINE/MEASURES stay as AST expressions
    (evaluated by the host pattern engine; channel indices would buy
    nothing — pattern matching is inherently row-sequential)."""

    source: PlanNode = None
    partition_channels: tuple[int, ...] = ()
    order_keys: tuple[tuple[int, bool], ...] = ()  # (channel, ascending)
    pattern: str = ""
    defines: tuple = ()    # ((label, ast.Expr), ...)
    measures: tuple = ()   # ((ast.Expr, name), ...)
    skip_past: bool = True

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        return (f"MatchRecognize[PATTERN({self.pattern}) "
                f"partition={list(self.partition_channels)}]")


@dataclass(frozen=True)
class Sort(PlanNode):
    source: PlanNode = None
    keys: tuple[SortKey, ...] = ()

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        return "Sort[%s]" % ", ".join(
            f"#{k.channel}{'' if k.ascending else ' desc'}" for k in self.keys)


@dataclass(frozen=True)
class TopN(PlanNode):
    source: PlanNode = None
    count: int = 0
    keys: tuple[SortKey, ...] = ()

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        return f"TopN[{self.count}; %s]" % ", ".join(
            f"#{k.channel}{'' if k.ascending else ' desc'}" for k in self.keys)


@dataclass(frozen=True)
class Limit(PlanNode):
    source: PlanNode = None
    count: int = 0

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        return f"Limit[{self.count}]"


@dataclass(frozen=True)
class DistinctLimit(PlanNode):
    source: PlanNode = None
    count: Optional[int] = None  # None = plain DISTINCT

    @property
    def children(self):
        return (self.source,)


@dataclass(frozen=True)
class Union(PlanNode):
    """UNION ALL concatenation (reference: sql/planner/plan/UnionNode.java /
    SetOperationNode.java).  Every source's channels line up 1:1 with the
    output channels; INTERSECT/EXCEPT/UNION-DISTINCT are lowered by the
    planner to Union + marker counts + Aggregate + Filter (the
    SetOperationNodeTranslator strategy)."""

    sources: tuple[PlanNode, ...] = ()

    @property
    def children(self):
        return self.sources

    def label(self) -> str:
        return f"Union[{len(self.sources)} inputs]"


@dataclass(frozen=True)
class Replicate(PlanNode):
    """Emit each input row ``count_channel`` times (0 drops it).  The row-
    expansion piece of INTERSECT ALL / EXCEPT ALL lowering (reference:
    SetOperationNodeTranslator's mark/count strategy feeding row expansion)."""

    source: PlanNode = None
    count_channel: int = -1  # BIGINT input channel holding the repeat count

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        return f"Replicate[x#{self.count_channel}]"


@dataclass(frozen=True)
class Values(PlanNode):
    rows: tuple[tuple, ...] = ()

    def label(self) -> str:
        return f"Values[{len(self.rows)} rows]"


@dataclass(frozen=True)
class TableFunctionScan(PlanNode):
    """Leaf table-function invocation (reference: sql/planner/plan/
    TableFunctionNode.java executed by LeafTableFunctionOperator.java:41).
    ``bound`` is an spi.table_function.BoundTableFunction — excluded from
    eq/hash (it closes over a generator factory)."""

    name: str = ""
    bound: object = field(default=None, compare=False)

    def label(self) -> str:
        return f"TableFunctionScan[{self.name}]"


@dataclass(frozen=True)
class Output(PlanNode):
    source: PlanNode = None

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        return f"Output[{', '.join(self.output_names)}]"


@dataclass(frozen=True)
class Exchange(PlanNode):
    """Data redistribution boundary.  scope=REMOTE splits fragments
    (AddExchanges.java:138); scope=LOCAL repartitions between in-task
    pipelines (AddLocalExchanges.java:111).  kind=MERGE gathers pre-sorted
    per-task streams order-preservingly (``sort_keys``; the
    MergeOperator.java:46 edge)."""

    source: PlanNode = None
    kind: str = "GATHER"  # GATHER | REPARTITION | BROADCAST | MERGE
    scope: str = "REMOTE"  # REMOTE | LOCAL
    partition_keys: tuple[int, ...] = ()
    sort_keys: tuple["SortKey", ...] = ()

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        keys = f" keys={list(self.partition_keys)}" if self.partition_keys else ""
        return f"Exchange[{self.scope} {self.kind}{keys}]"


@dataclass(frozen=True)
class RemoteSource(PlanNode):
    """Reads a remote fragment's output inside a downstream fragment
    (mirrors sql/planner/plan/RemoteSourceNode.java).  ``fragment_id``
    names the producing fragment; ``kind`` echoes the exchange type
    (MERGE carries the producers' sort order in ``sort_keys``)."""

    fragment_id: int = -1
    kind: str = "GATHER"
    sort_keys: tuple["SortKey", ...] = ()

    def label(self) -> str:
        return f"RemoteSource[f{self.fragment_id} {self.kind}]"


@dataclass(frozen=True)
class TableWriter(PlanNode):
    source: PlanNode = None
    catalog: str = ""
    table: str = ""

    @property
    def children(self):
        return (self.source,)

    def label(self) -> str:
        return f"TableWriter[{self.catalog}.{self.table}]"


def plan_text(node: PlanNode, indent: int = 0) -> str:
    """EXPLAIN-style tree rendering."""
    lines = ["  " * indent + "- " + node.label()]
    for c in node.children:
        lines.append(plan_text(c, indent + 1))
    return "\n".join(lines)
