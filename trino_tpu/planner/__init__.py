"""Planner: logical plan nodes, AST->plan translation, optimizer rules,
fragmenter.  Re-expresses core/trino-main's sql/planner (66 node types,
228 iterative rules) as a deliberately small, growable rule set."""
