"""TPC-H data-generator connector (the v1 data source).

Mirrors ``plugin/trino-tpch`` (reference: TpchSplitManager.java:36 with
``splitsPerNode:40``, TpchPageSourceProvider) but generates columns with
vectorized numpy instead of row-at-a-time dbgen: every value is a pure
function of (table, column, row-key) through a splitmix64-style hash, so
generation is deterministic, order-independent, and split-parallel with no
shared RNG state.  Only projected columns are generated (the LazyBlock
equivalent — reference: spi/block/LazyBlock.java).

Fidelity: schemas, key structure (incl. the partsupp<->lineitem supplier
alignment Q9 needs, customers without orders for Q13/Q22, orderstatus and
totalprice consistent with each order's lineitems), official value
vocabularies, and the spec's date correlations are kept; textual comments are
template-generated with the predicate-relevant phrases ('special requests',
'Customer Complaints') injected at spec-like selectivities.  Numbers are NOT
bit-identical to dbgen — correctness tests diff against a sqlite oracle
loaded with the same generated data (SURVEY §4's H2-oracle pattern).

Scale: base cardinalities follow the spec (lineitem ~6M rows/SF).  Splits of
lineitem/orders are ranges of *orders* so each split carries whole orders.
"""

from __future__ import annotations

import datetime
import os
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from ..spi.batch import Column, ColumnBatch
from ..spi.connector import (
    ColumnSchema,
    Connector,
    ConnectorPageSource,
    Split,
    TableSchema,
    TableStatistics,
)
from ..spi.types import BIGINT, DATE, INTEGER, VARCHAR, DecimalType, Type

# --------------------------------------------------------------------------
# deterministic hashing (splitmix64 finalizer, vectorized)

_U = np.uint64


def _h64(x: np.ndarray, stream: int) -> np.ndarray:
    # stream constant folded in python ints (explicit mod-2^64 wraparound)
    z = x.astype(np.uint64) + _U((0x9E3779B97F4A7C15 * (stream * 2 + 1)) & (2**64 - 1))
    z = (z ^ (z >> _U(30))) * _U(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> _U(27))) * _U(0x94D049BB133111EB)
    return z ^ (z >> _U(31))


def _randint(keys: np.ndarray, stream: int, lo: int, hi: int) -> np.ndarray:
    """Uniform integer in [lo, hi] keyed by row id (inclusive)."""
    return (_h64(keys, stream) % _U(hi - lo + 1)).astype(np.int64) + lo


def _uniform(keys: np.ndarray, stream: int) -> np.ndarray:
    return (_h64(keys, stream) >> _U(11)).astype(np.float64) / float(1 << 53)


def _days(y: int, m: int, d: int) -> int:
    return (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days


_START = _days(1992, 1, 1)          # first orderdate
_END_ORDER = _days(1998, 8, 2)      # last orderdate (spec: 1998-12-31 - 151d)
_CUTOFF = _days(1995, 6, 17)        # currentdate for flags/status

# official nation list: (name, regionkey)
_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
_INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
_TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
_TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
_TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
_CONTAINER_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
_CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
_COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "indian", "ivory", "khaki", "lace", "lavender", "lawn", "lemon",
    "light", "lime", "linen", "magenta", "maroon", "medium", "metallic", "midnight",
    "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange", "orchid",
    "pale", "papaya", "peach", "peru", "pink", "plum", "powder", "puff",
    "purple", "red", "rose", "rosy", "royal", "saddle", "salmon", "sandy",
    "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring", "steel",
    "tan", "thistle", "tomato", "turquoise", "violet", "wheat", "white", "yellow",
]
_COMMENT_WORDS = [
    "carefully", "quickly", "furiously", "slyly", "blithely", "ironic",
    "final", "pending", "regular", "express", "bold", "even", "special",
    "silent", "unusual", "daring", "deposits", "requests", "packages",
    "instructions", "accounts", "foxes", "ideas", "theodolites", "pinto",
    "beans", "platelets", "asymptotes", "dependencies", "excuses", "sleep",
    "haggle", "nag", "wake", "cajole", "integrate", "detect", "among", "above",
]

_TABLES = ("region", "nation", "supplier", "customer", "part", "partsupp",
           "orders", "lineitem")


def _fmt_keyed(prefix: str, keys: np.ndarray, width: int = 9) -> np.ndarray:
    """'Prefix#000000001'-style vocabulary; zero-padding keeps lexical order ==
    numeric order, so these columns sort correctly as dictionary codes."""
    return np.array([f"{prefix}#{k:0{width}d}" for k in keys], dtype=object)


def _phones(nationkeys: np.ndarray, keys: np.ndarray) -> np.ndarray:
    a = _randint(keys, 101, 100, 999)
    b = _randint(keys, 102, 100, 999)
    c = _randint(keys, 103, 1000, 9999)
    codes = nationkeys + 10
    return np.array(
        [f"{cc}-{x}-{y}-{z}" for cc, x, y, z in zip(codes, a, b, c)], dtype=object
    )


def _comments(keys: np.ndarray, stream: int, phrase: Optional[str] = None,
              phrase_ppm: int = 0) -> np.ndarray:
    """Template comments from a small vocabulary (bounded dictionary); the
    given phrase is injected at ~phrase_ppm parts-per-million rows."""
    w = len(_COMMENT_WORDS)
    i1 = _h64(keys, stream * 7 + 1) % _U(w)
    i2 = _h64(keys, stream * 7 + 2) % _U(w)
    i3 = _h64(keys, stream * 7 + 3) % _U(w)
    out = np.array(
        [f"{_COMMENT_WORDS[a]} {_COMMENT_WORDS[b]} {_COMMENT_WORDS[c]}"
         for a, b, c in zip(i1, i2, i3)],
        dtype=object,
    )
    if phrase and phrase_ppm:
        hit = (_h64(keys, stream * 7 + 4) % _U(1_000_000)) < _U(phrase_ppm)
        if hit.any():
            mid = np.array([f"{_COMMENT_WORDS[a]} {phrase}" for a in i1[hit]],
                           dtype=object)
            out[hit] = mid
    return out


def _retail_price_cents(partkey: np.ndarray) -> np.ndarray:
    """Official spec formula (4.2.3): (90000 + pk/10 % 20001 + 100*(pk%1000))."""
    pk = partkey.astype(np.int64)
    return 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)


def _ps_suppkey(partkey: np.ndarray, j: np.ndarray, supp_count: int) -> np.ndarray:
    """Supplier j (0..3) of a part — the spec's alignment formula so that
    lineitem (partkey, suppkey) pairs always exist in partsupp (Q9)."""
    pk = partkey.astype(np.int64) - 1
    s = supp_count
    return 1 + (pk + j * (s // 4 + pk // s)) % s


# --------------------------------------------------------------------------
# per-order lineitem derivation (shared by orders and lineitem generators)


def _lines_per_order(orderkeys: np.ndarray) -> np.ndarray:
    return _randint(orderkeys, 11, 1, 7)


def _line_fields(okeys: np.ndarray, lineno: np.ndarray, orderdates: np.ndarray,
                 part_count: int, supp_count: int) -> dict[str, np.ndarray]:
    """Vectorized per-lineitem values keyed by (orderkey, linenumber)."""
    k = okeys.astype(np.uint64) * _U(8) + lineno.astype(np.uint64)
    quantity = _randint(k, 21, 1, 50)
    partkey = _randint(k, 22, 1, part_count)
    suppkey = _ps_suppkey(partkey, _randint(k, 23, 0, 3), supp_count)
    discount = _randint(k, 24, 0, 10)  # cents: 0.00 - 0.10
    tax = _randint(k, 25, 0, 8)
    extprice = quantity * _retail_price_cents(partkey)
    shipdate = orderdates + _randint(k, 26, 1, 121)
    commitdate = orderdates + _randint(k, 27, 30, 90)
    receiptdate = shipdate + _randint(k, 28, 1, 30)
    return dict(
        quantity=quantity, partkey=partkey, suppkey=suppkey,
        discount=discount, tax=tax, extprice=extprice,
        shipdate=shipdate, commitdate=commitdate, receiptdate=receiptdate,
    )


# --------------------------------------------------------------------------


@dataclass(frozen=True)
class _TableDef:
    name: str
    schema: TableSchema
    base_rows: int  # rows at SF=1 (0 = fixed-size table or derived)


def _schema(name: str, cols: list[tuple[str, Type]]) -> TableSchema:
    return TableSchema(name, tuple(ColumnSchema(n, t) for n, t in cols))


_DEC = DecimalType(15, 2)

SCHEMAS: dict[str, TableSchema] = {
    "region": _schema("region", [
        ("r_regionkey", BIGINT), ("r_name", VARCHAR), ("r_comment", VARCHAR)]),
    "nation": _schema("nation", [
        ("n_nationkey", BIGINT), ("n_name", VARCHAR),
        ("n_regionkey", BIGINT), ("n_comment", VARCHAR)]),
    "supplier": _schema("supplier", [
        ("s_suppkey", BIGINT), ("s_name", VARCHAR), ("s_address", VARCHAR),
        ("s_nationkey", BIGINT), ("s_phone", VARCHAR), ("s_acctbal", _DEC),
        ("s_comment", VARCHAR)]),
    "customer": _schema("customer", [
        ("c_custkey", BIGINT), ("c_name", VARCHAR), ("c_address", VARCHAR),
        ("c_nationkey", BIGINT), ("c_phone", VARCHAR), ("c_acctbal", _DEC),
        ("c_mktsegment", VARCHAR), ("c_comment", VARCHAR)]),
    "part": _schema("part", [
        ("p_partkey", BIGINT), ("p_name", VARCHAR), ("p_mfgr", VARCHAR),
        ("p_brand", VARCHAR), ("p_type", VARCHAR), ("p_size", BIGINT),
        ("p_container", VARCHAR), ("p_retailprice", _DEC), ("p_comment", VARCHAR)]),
    "partsupp": _schema("partsupp", [
        ("ps_partkey", BIGINT), ("ps_suppkey", BIGINT),
        ("ps_availqty", BIGINT), ("ps_supplycost", _DEC), ("ps_comment", VARCHAR)]),
    "orders": _schema("orders", [
        ("o_orderkey", BIGINT), ("o_custkey", BIGINT), ("o_orderstatus", VARCHAR),
        ("o_totalprice", _DEC), ("o_orderdate", DATE), ("o_orderpriority", VARCHAR),
        ("o_clerk", VARCHAR), ("o_shippriority", BIGINT), ("o_comment", VARCHAR)]),
    "lineitem": _schema("lineitem", [
        ("l_orderkey", BIGINT), ("l_partkey", BIGINT), ("l_suppkey", BIGINT),
        ("l_linenumber", BIGINT), ("l_quantity", _DEC), ("l_extendedprice", _DEC),
        ("l_discount", _DEC), ("l_tax", _DEC), ("l_returnflag", VARCHAR),
        ("l_linestatus", VARCHAR), ("l_shipdate", DATE), ("l_commitdate", DATE),
        ("l_receiptdate", DATE), ("l_shipinstruct", VARCHAR),
        ("l_shipmode", VARCHAR), ("l_comment", VARCHAR)]),
}

_BASE_ROWS = {
    "region": 5, "nation": 25, "supplier": 10_000, "customer": 150_000,
    "part": 200_000, "partsupp": 800_000, "orders": 1_500_000,
}


class TpchConnector(Connector):
    name = "tpch"

    def __init__(self, scale_factor: float = 0.01, batch_rows: int = 262_144):
        self.sf = scale_factor
        self.batch_rows = batch_rows
        self._dict_cache: dict[tuple[str, str], np.ndarray] = {}
        self._building: set[tuple[str, str]] = set()
        # vocab index -> sorted-dictionary code, per string column (the host
        # twin of _DeviceTpchGen._code_table): batch decode becomes ONE
        # integer gather instead of materializing python strings and binary-
        # searching an object array per row (GIL-bound, ~75% of decode time)
        self._code_tables: dict[tuple[str, str], tuple] = {}
        # TRINO_TPU_TPCH_VECTOR_DECODE=0 keeps the legacy string-materializing
        # decode — only useful as the bench baseline (bench.py --scan)
        self._vector_decode = os.environ.get(
            "TRINO_TPU_TPCH_VECTOR_DECODE", "1") != "0"

    def data_version(self, table: str):
        """Generated data is a pure function of the scale factor: a
        constant token makes repeated TPC-H reads result-cacheable
        forever within one configuration."""
        if table not in _TABLES:
            raise KeyError(f"tpch: no such table {table!r}")
        return f"sf={self.sf}"

    # ---- sizes ----------------------------------------------------------
    def row_count(self, table: str) -> int:
        if table in ("region", "nation"):
            return _BASE_ROWS[table]
        if table == "lineitem":
            # derived: sum of per-order line counts (exact; chunked to bound
            # temporary memory at large SF)
            n_orders = self.row_count("orders")
            total = 0
            for a in range(0, n_orders, 4_000_000):
                b = min(a + 4_000_000, n_orders)
                total += int(_lines_per_order(self._orderkeys(a, b)).sum())
            return total
        return max(1, int(_BASE_ROWS[table] * self.sf))

    def _orderkeys(self, start: int, stop: int) -> np.ndarray:
        return np.arange(start + 1, stop + 1, dtype=np.uint64)

    # ---- metadata -------------------------------------------------------
    def list_tables(self) -> list[str]:
        return list(_TABLES)

    def get_table_schema(self, table: str) -> TableSchema:
        if table not in SCHEMAS:
            raise KeyError(f"tpch: no such table {table!r}")
        return SCHEMAS[table]

    def get_table_statistics(self, table: str) -> TableStatistics:
        analyzed = getattr(self, "_analyzed_stats", {}).get(table)
        if analyzed is not None:
            return analyzed
        n = self.row_count(table)
        ndv: dict[str, float] = {}
        for c in SCHEMAS[table].columns:
            if c.name.endswith("key") and c.name[2:] != "shippriority":
                ndv[c.name] = float(n)
        for col, v in {
            "l_returnflag": 3, "l_linestatus": 2, "l_shipmode": 7,
            "o_orderpriority": 5, "c_mktsegment": 5, "n_name": 25,
            "r_name": 5, "p_brand": 25, "p_type": 150, "p_container": 40,
            "p_size": 50,
        }.items():
            if any(c.name == col for c in SCHEMAS[table].columns):
                ndv[col] = float(v)
        return TableStatistics(row_count=float(n), ndv=ndv)

    # ---- splits ---------------------------------------------------------
    def get_splits(self, table: str, splits_per_node: int, node_count: int) -> list[Split]:
        # lineitem splits range over *orders* so whole orders stay together
        n = self.row_count("orders" if table == "lineitem" else table)
        want = max(1, splits_per_node * node_count)
        n_splits = min(want, max(1, n // 4096)) if n > 8192 else 1
        bounds = np.linspace(0, n, n_splits + 1, dtype=np.int64)
        return [
            Split("tpch", table, (int(bounds[i]), int(bounds[i + 1])),
                  weight=float(bounds[i + 1] - bounds[i]))
            for i in range(n_splits)
            if bounds[i + 1] > bounds[i]
        ]

    def create_page_source(self, split: Split, columns: Sequence[str],
                           constraint=None) -> "_TpchPageSource":
        return _TpchPageSource(self, split, list(columns))

    # ---- dictionaries ---------------------------------------------------
    def column_dictionary(self, table: str, column: str) -> Optional[np.ndarray]:
        """Table-global sorted dictionary for a varchar column (cached)."""
        t = SCHEMAS[table].column_type(column)
        if not t.is_dictionary_encoded:
            return None
        key = (table, column)
        if key not in self._dict_cache:
            self._building.add(key)
            try:
                values = self._string_values(table, column)
            finally:
                self._building.discard(key)
            self._dict_cache[key] = np.unique(values)
        return self._dict_cache[key]

    # ---- generation -----------------------------------------------------
    def _string_values(self, table: str, column: str) -> np.ndarray:
        """All raw (unsorted) values for a string column — used to build the
        global dictionary.  Bounded vocabularies return the vocab directly."""
        fixed = {
            ("region", "r_name"): np.array(_REGIONS, object),
            ("nation", "n_name"): np.array([n for n, _ in _NATIONS], object),
            ("customer", "c_mktsegment"): np.array(_SEGMENTS, object),
            ("orders", "o_orderpriority"): np.array(_PRIORITIES, object),
            ("orders", "o_orderstatus"): np.array(["F", "O", "P"], object),
            ("lineitem", "l_shipmode"): np.array(_SHIPMODES, object),
            ("lineitem", "l_shipinstruct"): np.array(_INSTRUCTIONS, object),
            ("lineitem", "l_returnflag"): np.array(["A", "N", "R"], object),
            ("lineitem", "l_linestatus"): np.array(["F", "O"], object),
            ("part", "p_mfgr"): np.array(
                [f"Manufacturer#{i}" for i in range(1, 6)], object),
            ("part", "p_brand"): np.array(
                [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)], object),
            ("part", "p_type"): np.array(
                [f"{a} {b} {c}" for a in _TYPE_S1 for b in _TYPE_S2 for c in _TYPE_S3],
                object),
            ("part", "p_container"): np.array(
                [f"{a} {b}" for a in _CONTAINER_S1 for b in _CONTAINER_S2], object),
        }
        if (table, column) in fixed:
            return fixed[(table, column)]
        n = self.row_count(table)
        keys = np.arange(1, n + 1, dtype=np.uint64)
        batch = self._generate(table, [column], 0, n)
        # _generate returns dictionary-coded columns; decode via its dict
        col = batch.column(column)
        return col.dictionary[np.asarray(col.data)]

    def _dict_column(self, table: str, column: str, values: np.ndarray) -> Column:
        if (table, column) in self._building:
            # global dictionary under construction: local encoding suffices
            d, codes = np.unique(values, return_inverse=True)
            return Column(VARCHAR, codes.astype(np.int32), None, d)
        d = self.column_dictionary(table, column)
        codes = np.searchsorted(d, values).astype(np.int32)
        return Column(VARCHAR, codes, None, d)

    def _code_table(self, table: str, column: str, vocab) -> tuple:
        """(vocab-index -> code table, sorted dictionary), cached.  The
        dictionary is the DATA-derived one from column_dictionary — identical
        to the legacy decode, so small tables keep small dictionaries (nation
        comments: 25 entries, not the 59k vocab — dictionary-space ops like
        `||` depend on that).  Vocab entries absent from the data clip to an
        arbitrary valid code; by construction they never occur."""
        key = (table, column)
        cached = self._code_tables.get(key)
        if cached is None:
            values = np.asarray(vocab, dtype=object)
            d = self.column_dictionary(table, column)
            tab = np.searchsorted(d, values).astype(np.int32)
            np.clip(tab, 0, len(d) - 1, out=tab)
            cached = (tab, d)
            self._code_tables[key] = cached
        return cached

    def _vocab_column(self, table: str, column: str, idx: np.ndarray,
                      vocab: list[str]) -> Column:
        if (table, column) in self._building or not self._vector_decode:
            values = np.array(vocab, dtype=object)[np.asarray(idx, np.int64)]
            return self._dict_column(table, column, values)
        tab, d = self._code_table(table, column, vocab)
        return Column(VARCHAR, tab[np.asarray(idx, dtype=np.int64)], None, d)

    def _comment_column(self, table: str, column: str, keys: np.ndarray,
                        stream: int, phrase=None, phrase_ppm: int = 0) -> Column:
        """Comment column without materializing strings: the same splitmix
        index arithmetic as _device_comment_codes, mapped through the cached
        code table over _comment_vocab (pure ufunc work — releases the GIL,
        so prefetch threads genuinely parallelize the decode)."""
        if (table, column) in self._building or not self._vector_decode:
            return self._dict_column(
                table, column, _comments(keys, stream, phrase, phrase_ppm))
        w = len(_COMMENT_WORDS)
        keys = keys.astype(np.uint64)
        i1 = (_h64(keys, stream * 7 + 1) % _U(w)).astype(np.int64)
        i2 = (_h64(keys, stream * 7 + 2) % _U(w)).astype(np.int64)
        i3 = (_h64(keys, stream * 7 + 3) % _U(w)).astype(np.int64)
        idx = (i1 * w + i2) * w + i3
        if phrase and phrase_ppm:
            hit = (_h64(keys, stream * 7 + 4) % _U(1_000_000)) < _U(phrase_ppm)
            idx = np.where(hit, w * w * w + i1, idx)
        tab, d = self._code_table(table, column, _comment_vocab(phrase))
        return Column(VARCHAR, tab[idx], None, d)

    def _generate(self, table: str, columns: list[str], start: int, stop: int) -> ColumnBatch:
        gen = getattr(self, f"_gen_{table}")
        return gen(columns, start, stop)

    # region/nation -------------------------------------------------------
    def _gen_region(self, columns, start, stop):
        keys = np.arange(start, stop, dtype=np.int64)
        out = []
        for c in columns:
            if c == "r_regionkey":
                out.append(Column(BIGINT, keys))
            elif c == "r_name":
                out.append(self._vocab_column("region", "r_name", keys, _REGIONS))
            else:
                out.append(self._comment_column("region", "r_comment",
                                                 keys.astype(np.uint64), 1))
        return ColumnBatch(list(columns), out)

    def _gen_nation(self, columns, start, stop):
        keys = np.arange(start, stop, dtype=np.int64)
        out = []
        for c in columns:
            if c == "n_nationkey":
                out.append(Column(BIGINT, keys))
            elif c == "n_name":
                out.append(self._vocab_column(
                    "nation", "n_name", keys, [n for n, _ in _NATIONS]))
            elif c == "n_regionkey":
                out.append(Column(BIGINT, np.array(
                    [_NATIONS[k][1] for k in keys], dtype=np.int64)))
            else:
                out.append(self._comment_column("nation", "n_comment",
                                                 keys.astype(np.uint64), 2))
        return ColumnBatch(list(columns), out)

    # supplier ------------------------------------------------------------
    def _gen_supplier(self, columns, start, stop):
        keys = np.arange(start + 1, stop + 1, dtype=np.uint64)
        ik = keys.astype(np.int64)
        nk = _randint(keys, 31, 0, 24)
        out = []
        for c in columns:
            if c == "s_suppkey":
                out.append(Column(BIGINT, ik))
            elif c == "s_name":
                out.append(self._dict_column("supplier", "s_name",
                                             _fmt_keyed("Supplier", ik)))
            elif c == "s_address":
                out.append(self._dict_column("supplier", "s_address",
                                             _fmt_keyed("SAddr", ik)))
            elif c == "s_nationkey":
                out.append(Column(BIGINT, nk))
            elif c == "s_phone":
                out.append(self._dict_column("supplier", "s_phone", _phones(nk, keys)))
            elif c == "s_acctbal":
                out.append(Column(_DEC, _randint(keys, 32, -99999, 999999)))
            else:  # s_comment — 'Customer Complaints' at ~5 per 10k (Q16)
                out.append(self._comment_column(
                    "supplier", "s_comment", keys, 3,
                    "Customer foo Complaints", 500))
        return ColumnBatch(list(columns), out)

    # customer ------------------------------------------------------------
    def _gen_customer(self, columns, start, stop):
        keys = np.arange(start + 1, stop + 1, dtype=np.uint64)
        ik = keys.astype(np.int64)
        nk = _randint(keys, 41, 0, 24)
        out = []
        for c in columns:
            if c == "c_custkey":
                out.append(Column(BIGINT, ik))
            elif c == "c_name":
                out.append(self._dict_column("customer", "c_name",
                                             _fmt_keyed("Customer", ik)))
            elif c == "c_address":
                out.append(self._dict_column("customer", "c_address",
                                             _fmt_keyed("CAddr", ik)))
            elif c == "c_nationkey":
                out.append(Column(BIGINT, nk))
            elif c == "c_phone":
                out.append(self._dict_column("customer", "c_phone", _phones(nk, keys)))
            elif c == "c_acctbal":
                out.append(Column(_DEC, _randint(keys, 42, -99999, 999999)))
            elif c == "c_mktsegment":
                out.append(self._vocab_column("customer", "c_mktsegment",
                                              _randint(keys, 43, 0, 4), _SEGMENTS))
            else:
                out.append(self._comment_column("customer", "c_comment",
                                                 keys, 4))
        return ColumnBatch(list(columns), out)

    # part ----------------------------------------------------------------
    def _gen_part(self, columns, start, stop):
        keys = np.arange(start + 1, stop + 1, dtype=np.uint64)
        ik = keys.astype(np.int64)
        out = []
        mfgr = _randint(keys, 51, 1, 5)
        for c in columns:
            if c == "p_partkey":
                out.append(Column(BIGINT, ik))
            elif c == "p_name":
                w = len(_COLORS)
                i1 = _h64(keys, 52) % _U(w)
                i2 = _h64(keys, 53) % _U(w)
                i3 = _h64(keys, 54) % _U(w)
                names = np.array(
                    [f"{_COLORS[a]} {_COLORS[b]} {_COLORS[c2]}"
                     for a, b, c2 in zip(i1, i2, i3)], dtype=object)
                out.append(self._dict_column("part", "p_name", names))
            elif c == "p_mfgr":
                out.append(self._dict_column(
                    "part", "p_mfgr",
                    np.array([f"Manufacturer#{m}" for m in mfgr], object)))
            elif c == "p_brand":
                b2 = _randint(keys, 55, 1, 5)
                out.append(self._dict_column(
                    "part", "p_brand",
                    np.array([f"Brand#{m}{b}" for m, b in zip(mfgr, b2)], object)))
            elif c == "p_type":
                idx = _randint(keys, 56, 0, 149)
                vocab = [f"{a} {b} {c2}" for a in _TYPE_S1 for b in _TYPE_S2
                         for c2 in _TYPE_S3]
                out.append(self._vocab_column("part", "p_type", idx, vocab))
            elif c == "p_size":
                out.append(Column(BIGINT, _randint(keys, 57, 1, 50)))
            elif c == "p_container":
                idx = _randint(keys, 58, 0, 39)
                vocab = [f"{a} {b}" for a in _CONTAINER_S1 for b in _CONTAINER_S2]
                out.append(self._vocab_column("part", "p_container", idx, vocab))
            elif c == "p_retailprice":
                out.append(Column(_DEC, _retail_price_cents(ik)))
            else:
                out.append(self._comment_column("part", "p_comment", keys, 5))
        return ColumnBatch(list(columns), out)

    # partsupp ------------------------------------------------------------
    def _gen_partsupp(self, columns, start, stop):
        # row i -> (partkey = i//4 + 1, j = i%4)
        idx = np.arange(start, stop, dtype=np.int64)
        partkey = idx // 4 + 1
        j = idx % 4
        keys = idx.astype(np.uint64) + _U(1)
        supp_count = self.row_count("supplier")
        out = []
        for c in columns:
            if c == "ps_partkey":
                out.append(Column(BIGINT, partkey))
            elif c == "ps_suppkey":
                out.append(Column(BIGINT, _ps_suppkey(partkey, j, supp_count)))
            elif c == "ps_availqty":
                out.append(Column(BIGINT, _randint(keys, 61, 1, 9999)))
            elif c == "ps_supplycost":
                out.append(Column(_DEC, _randint(keys, 62, 100, 100000)))
            else:
                out.append(self._comment_column("partsupp", "ps_comment",
                                                 keys, 6))
        return ColumnBatch(list(columns), out)

    # orders --------------------------------------------------------------
    def _custkey_for_order(self, okeys: np.ndarray) -> np.ndarray:
        """Customers with custkey % 3 == 0 never order (Q13/Q22 shape)."""
        ncust = self.row_count("customer")
        eligible = ncust - ncust // 3
        r = _randint(okeys, 71, 0, max(eligible - 1, 0))
        # map 0..eligible-1 -> keys skipping multiples of 3: 1,2,4,5,7,8,...
        return (r // 2) * 3 + (r % 2) + 1

    def _order_lineitem_stats(self, okeys, orderdates):
        """(totalprice_cents, orderstatus codes) consistent with lineitems."""
        nlines = _lines_per_order(okeys)
        total = np.zeros(len(okeys), dtype=np.int64)
        all_f = np.ones(len(okeys), dtype=bool)
        all_o = np.ones(len(okeys), dtype=bool)
        for ln in range(1, 8):
            mask = nlines >= ln
            f = _line_fields(okeys, np.full(len(okeys), ln, np.uint64),
                             orderdates, self.row_count("part"),
                             self.row_count("supplier"))
            # charge = extprice * (1 - disc) * (1 + tax), rounded to cents
            charge = f["extprice"] * (100 - f["discount"]) * (100 + f["tax"])
            charge = (charge + 5000) // 10000
            total += np.where(mask, charge, 0)
            shipped = f["shipdate"] <= _CUTOFF
            all_f &= ~mask | shipped
            all_o &= ~mask | ~shipped
        status = np.where(all_f, 0, np.where(all_o, 1, 2))  # F / O / P
        return total, status

    def _gen_orders(self, columns, start, stop):
        okeys = self._orderkeys(start, stop)
        ik = okeys.astype(np.int64)
        orderdates = _randint(okeys, 72, _START, _END_ORDER)
        out = []
        total = status = None
        if "o_totalprice" in columns or "o_orderstatus" in columns:
            total, status = self._order_lineitem_stats(okeys, orderdates)
        for c in columns:
            if c == "o_orderkey":
                out.append(Column(BIGINT, ik))
            elif c == "o_custkey":
                out.append(Column(BIGINT, self._custkey_for_order(okeys)))
            elif c == "o_orderstatus":
                out.append(self._vocab_column("orders", "o_orderstatus",
                                              status, ["F", "O", "P"]))
            elif c == "o_totalprice":
                out.append(Column(_DEC, total))
            elif c == "o_orderdate":
                out.append(Column(DATE, orderdates.astype(np.int32)))
            elif c == "o_orderpriority":
                out.append(self._vocab_column("orders", "o_orderpriority",
                                              _randint(okeys, 73, 0, 4), _PRIORITIES))
            elif c == "o_clerk":
                clerks = _randint(okeys, 74, 1, max(1, int(1000 * self.sf)))
                out.append(self._dict_column("orders", "o_clerk",
                                             _fmt_keyed("Clerk", clerks)))
            elif c == "o_shippriority":
                out.append(Column(BIGINT, np.zeros(len(ik), dtype=np.int64)))
            else:  # o_comment — 'special ... requests' ~1.3% (Q13)
                out.append(self._comment_column(
                    "orders", "o_comment", okeys, 8,
                    "special foo requests", 13000))
        return ColumnBatch(list(columns), out)

    # lineitem ------------------------------------------------------------
    def _gen_lineitem(self, columns, start, stop):
        """start/stop are ORDER indices; emits all lineitems of those orders."""
        okeys1 = self._orderkeys(start, stop)
        nlines = _lines_per_order(okeys1)
        okeys = np.repeat(okeys1, nlines)
        # linenumbers 1..n per order
        lineno = (np.arange(len(okeys), dtype=np.int64)
                  - np.repeat(np.cumsum(nlines) - nlines, nlines) + 1).astype(np.uint64)
        orderdates = np.repeat(_randint(okeys1, 72, _START, _END_ORDER), nlines)
        f = _line_fields(okeys, lineno, orderdates,
                         self.row_count("part"), self.row_count("supplier"))
        k = okeys * _U(8) + lineno
        out = []
        for c in columns:
            if c == "l_orderkey":
                out.append(Column(BIGINT, okeys.astype(np.int64)))
            elif c == "l_partkey":
                out.append(Column(BIGINT, f["partkey"]))
            elif c == "l_suppkey":
                out.append(Column(BIGINT, f["suppkey"]))
            elif c == "l_linenumber":
                out.append(Column(BIGINT, lineno.astype(np.int64)))
            elif c == "l_quantity":
                out.append(Column(_DEC, f["quantity"] * 100))
            elif c == "l_extendedprice":
                out.append(Column(_DEC, f["extprice"]))
            elif c == "l_discount":
                out.append(Column(_DEC, f["discount"]))
            elif c == "l_tax":
                out.append(Column(_DEC, f["tax"]))
            elif c == "l_returnflag":
                returned = f["receiptdate"] <= _CUTOFF
                ra = _randint(k, 29, 0, 1)  # A or R when returned
                idx = np.where(returned, np.where(ra == 0, 0, 2), 1)  # A/N/R sorted
                out.append(self._vocab_column("lineitem", "l_returnflag", idx,
                                              ["A", "N", "R"]))
            elif c == "l_linestatus":
                idx = (f["shipdate"] > _CUTOFF).astype(np.int64)  # F=0, O=1
                out.append(self._vocab_column("lineitem", "l_linestatus", idx,
                                              ["F", "O"]))
            elif c == "l_shipdate":
                out.append(Column(DATE, f["shipdate"].astype(np.int32)))
            elif c == "l_commitdate":
                out.append(Column(DATE, f["commitdate"].astype(np.int32)))
            elif c == "l_receiptdate":
                out.append(Column(DATE, f["receiptdate"].astype(np.int32)))
            elif c == "l_shipinstruct":
                out.append(self._vocab_column("lineitem", "l_shipinstruct",
                                              _randint(k, 30, 0, 3), _INSTRUCTIONS))
            elif c == "l_shipmode":
                out.append(self._vocab_column("lineitem", "l_shipmode",
                                              _randint(k, 31, 0, 6), _SHIPMODES))
            else:
                out.append(self._comment_column("lineitem", "l_comment",
                                                 k, 9))
        return ColumnBatch(list(columns), out)


# --------------------------------------------------------------------------
# device-side generation (the staging fast path)
#
# Every value above is a pure integer function of the row key, so the hot
# tables can be generated ON the accelerator: the splitmix64 arithmetic runs
# as one jitted program and the columns are born in HBM.  Nothing but the
# (tiny or bounded) string dictionaries ever crosses the host<->device link —
# staging SF10 costs seconds instead of pushing ~6 GB through the device
# tunnel.  This is "data loading as compute": the TPU answer to the
# reference's dbgen-into-warmed-tables benchmark setup
# (testing/trino-benchto-benchmarks, plugin/trino-tpch).


def _comment_vocab(phrase: Optional[str] = None) -> np.ndarray:
    """Unsorted comment vocabulary: index a*w*w + b*w + c for the normal
    3-word template, then w**3 + a for the phrase variants."""
    w = len(_COMMENT_WORDS)
    base = [f"{_COMMENT_WORDS[a]} {_COMMENT_WORDS[b]} {_COMMENT_WORDS[c]}"
            for a in range(w) for b in range(w) for c in range(w)]
    if phrase:
        base += [f"{_COMMENT_WORDS[a]} {phrase}" for a in range(w)]
    return np.array(base, dtype=object)


def _device_comment_codes(keys, stream: int, phrase: Optional[str],
                          phrase_ppm: int):
    """Traced: unsorted-vocab index per row (host code table maps to the
    sorted dictionary afterwards)."""
    import jax.numpy as jnp

    w = len(_COMMENT_WORDS)
    i1 = (_h64(keys, stream * 7 + 1) % _U(w)).astype(jnp.int32)
    i2 = (_h64(keys, stream * 7 + 2) % _U(w)).astype(jnp.int32)
    i3 = (_h64(keys, stream * 7 + 3) % _U(w)).astype(jnp.int32)
    idx = i1 * (w * w) + i2 * w + i3
    if phrase and phrase_ppm:
        hit = (_h64(keys, stream * 7 + 4) % _U(1_000_000)) < _U(phrase_ppm)
        idx = jnp.where(hit, w * w * w + i1, idx)
    return idx


class _DeviceTpchGen:
    """Generates whole orders/lineitem tables as device-resident batches."""

    def __init__(self, conn: "TpchConnector"):
        self.conn = conn
        self._vocab_codes: dict = {}

    def _code_table(self, table: str, column: str, vocab) -> np.ndarray:
        """vocab index -> sorted-dictionary code (tiny host table)."""
        key = (table, column)
        if key not in self._vocab_codes:
            values = np.asarray(vocab, dtype=object)
            d = np.unique(values)
            self.conn._dict_cache[key] = d
            self._vocab_codes[key] = (
                np.searchsorted(d, values).astype(np.int32), d)
        return self._vocab_codes[key]

    def supports(self, table: str) -> bool:
        return table in ("orders", "lineitem")

    def generate(self, table: str, columns: Sequence[str]) -> ColumnBatch:
        import jax

        fn = getattr(self, f"_gen_{table}")
        cols = fn(list(columns))
        for c in cols:
            jax.block_until_ready(c.data)
        from ..spi.batch import pad_to_bucket

        return pad_to_bucket(ColumnBatch(list(columns), cols))

    # -- orders -----------------------------------------------------------
    def _gen_orders(self, columns: list[str]) -> list[Column]:
        import jax
        import jax.numpy as jnp

        conn = self.conn
        n = conn.row_count("orders")
        ncust = conn.row_count("customer")
        npart = conn.row_count("part")
        nsupp = conn.row_count("supplier")
        max_clerk = max(1, int(1000 * conn.sf))
        status_tab, _ = self._code_table(
            "orders", "o_orderstatus", ["F", "O", "P"])
        prio_tab, _ = self._code_table(
            "orders", "o_orderpriority", _PRIORITIES)
        comment_tab, _ = self._code_table(
            "orders", "o_comment", _comment_vocab("special foo requests"))
        clerk_vocab = _fmt_keyed("Clerk", np.arange(1, max_clerk + 1))
        self.conn._dict_cache[("orders", "o_clerk")] = clerk_vocab

        @jax.jit
        def prog(status_t, prio_t, comment_t):
            okeys = jnp.arange(1, n + 1, dtype=jnp.uint64)
            orderdates = _randint(okeys, 72, _START, _END_ORDER)
            total, status = _device_order_stats(okeys, orderdates,
                                                npart, nsupp)
            eligible = ncust - ncust // 3
            r = _randint(okeys, 71, 0, max(eligible - 1, 0))
            custkey = (r // 2) * 3 + (r % 2) + 1
            return dict(
                o_orderkey=okeys.astype(jnp.int64),
                o_custkey=custkey,
                o_orderstatus=status_t[status],
                o_totalprice=total,
                o_orderdate=orderdates.astype(jnp.int32),
                o_orderpriority=prio_t[_randint(okeys, 73, 0, 4)],
                o_clerk=(_randint(okeys, 74, 1, max_clerk) - 1
                         ).astype(jnp.int32),
                o_shippriority=jnp.zeros(n, jnp.int64),
                o_comment=comment_t[
                    _device_comment_codes(okeys, 8, "special foo requests",
                                          13000)],
            )

        vals = prog(jnp.asarray(status_tab), jnp.asarray(prio_tab),
                    jnp.asarray(comment_tab))
        dicts = {
            "o_orderstatus": self._vocab_codes[("orders", "o_orderstatus")][1],
            "o_orderpriority": self._vocab_codes[("orders", "o_orderpriority")][1],
            "o_clerk": clerk_vocab,
            "o_comment": self._vocab_codes[("orders", "o_comment")][1],
        }
        return [
            Column(SCHEMAS["orders"].column_type(c), vals[c],
                   None, dicts.get(c))
            for c in columns
        ]

    # -- lineitem ---------------------------------------------------------
    def _gen_lineitem(self, columns: list[str]) -> list[Column]:
        import jax
        import jax.numpy as jnp

        conn = self.conn
        n_orders = conn.row_count("orders")
        total = conn.row_count("lineitem")
        npart = conn.row_count("part")
        nsupp = conn.row_count("supplier")
        rf_tab, _ = self._code_table("lineitem", "l_returnflag", ["A", "N", "R"])
        ls_tab, _ = self._code_table("lineitem", "l_linestatus", ["F", "O"])
        si_tab, _ = self._code_table("lineitem", "l_shipinstruct", _INSTRUCTIONS)
        sm_tab, _ = self._code_table("lineitem", "l_shipmode", _SHIPMODES)
        cm_tab, _ = self._code_table("lineitem", "l_comment", _comment_vocab())

        @jax.jit
        def prog(rf_t, ls_t, si_t, sm_t, cm_t):
            okeys1 = jnp.arange(1, n_orders + 1, dtype=jnp.uint64)
            nlines = _lines_per_order(okeys1)
            ends = jnp.cumsum(nlines)
            row = jnp.arange(total, dtype=jnp.int64)
            oidx = jnp.searchsorted(ends, row, side="right")
            oidx = jnp.clip(oidx, 0, n_orders - 1)
            okeys = okeys1[oidx]
            lineno = (row - (ends - nlines)[oidx] + 1).astype(jnp.uint64)
            orderdates = _randint(okeys1, 72, _START, _END_ORDER)[oidx]
            f = _line_fields(okeys, lineno, orderdates, npart, nsupp)
            k = okeys * _U(8) + lineno
            returned = f["receiptdate"] <= _CUTOFF
            ra = _randint(k, 29, 0, 1)
            rf_idx = jnp.where(returned, jnp.where(ra == 0, 0, 2), 1)
            ls_idx = (f["shipdate"] > _CUTOFF).astype(jnp.int32)
            return dict(
                l_orderkey=okeys.astype(jnp.int64),
                l_partkey=f["partkey"],
                l_suppkey=f["suppkey"],
                l_linenumber=lineno.astype(jnp.int64),
                l_quantity=f["quantity"] * 100,
                l_extendedprice=f["extprice"],
                l_discount=f["discount"],
                l_tax=f["tax"],
                l_returnflag=rf_t[rf_idx],
                l_linestatus=ls_t[ls_idx],
                l_shipdate=f["shipdate"].astype(jnp.int32),
                l_commitdate=f["commitdate"].astype(jnp.int32),
                l_receiptdate=f["receiptdate"].astype(jnp.int32),
                l_shipinstruct=si_t[_randint(k, 30, 0, 3)],
                l_shipmode=sm_t[_randint(k, 31, 0, 6)],
                l_comment=cm_t[_device_comment_codes(k, 9, None, 0)],
            )

        vals = prog(jnp.asarray(rf_tab), jnp.asarray(ls_tab),
                    jnp.asarray(si_tab), jnp.asarray(sm_tab),
                    jnp.asarray(cm_tab))
        dicts = {c: self._vocab_codes[("lineitem", c)][1]
                 for c in ("l_returnflag", "l_linestatus", "l_shipinstruct",
                           "l_shipmode", "l_comment")}
        return [
            Column(SCHEMAS["lineitem"].column_type(c), vals[c],
                   None, dicts.get(c))
            for c in columns
        ]


def _device_order_stats(okeys, orderdates, npart: int, nsupp: int):
    """Traced twin of TpchConnector._order_lineitem_stats."""
    import jax.numpy as jnp

    n = okeys.shape[0]
    nlines = _lines_per_order(okeys)
    total = jnp.zeros(n, jnp.int64)
    all_f = jnp.ones(n, jnp.bool_)
    all_o = jnp.ones(n, jnp.bool_)
    for ln in range(1, 8):
        mask = nlines >= ln
        f = _line_fields(okeys, jnp.full(n, ln, jnp.uint64), orderdates,
                         npart, nsupp)
        charge = f["extprice"] * (100 - f["discount"]) * (100 + f["tax"])
        charge = (charge + 5000) // 10000
        total = total + jnp.where(mask, charge, 0)
        shipped = f["shipdate"] <= _CUTOFF
        all_f = all_f & (~mask | shipped)
        all_o = all_o & (~mask | ~shipped)
    status = jnp.where(all_f, 0, jnp.where(all_o, 1, 2))
    return total, status


def generate_table_device(conn: "TpchConnector", table: str,
                          columns: Sequence[str]) -> Optional[ColumnBatch]:
    """Device-resident generation of a hot table (orders/lineitem), or None
    when the table has no device path (callers fall back to the host
    generator).  Values are bit-identical to the host generator — both run
    the same splitmix64 integer arithmetic."""
    gen = _DeviceTpchGen(conn)
    if not gen.supports(table):
        return None
    return gen.generate(table, columns)


class _TpchPageSource(ConnectorPageSource):
    def __init__(self, conn: TpchConnector, split: Split, columns: list[str]):
        self.conn = conn
        self.split = split
        self.columns = columns
        self.pos, self.stop = split.info
        # order-ranged tables produce ~4x rows per order
        divisor = 4 if split.table == "lineitem" else 1
        self.step = max(1, conn.batch_rows // max(divisor, 1))

    def get_next_batch(self) -> Optional[ColumnBatch]:
        if self.pos >= self.stop:
            return None
        stop = min(self.pos + self.step, self.stop)
        batch = self.conn._generate(self.split.table, self.columns, self.pos, stop)
        self.pos = stop
        return batch

    def is_finished(self) -> bool:
        return self.pos >= self.stop
